"""JaxBackend — the TPU-batched CryptoBackend instance.

Routes Ed25519 batches through ed25519_jax.verify_full_kernel and VRF
batches through vrf_jax.vrf_verify_kernel (decompression, Elligator2 and
both Strauss ladders fused into one device call), with Montgomery batch
inversion on host for the final point compressions (one modular pow per
batch instead of one per point).

Batch sizes are padded to power-of-two buckets (min 128) so repeated calls
hit the jit cache instead of recompiling per shape.
"""
from __future__ import annotations

from . import ed25519_jax as EJ
from . import edwards as ed
from .backend import CryptoBackend


def _bucket(n: int, lo: int = 128) -> int:
    m = lo
    while m < n:
        m *= 2
    return m


def _pack_flat(parts):
    """Concatenate device arrays into one flat uint8 buffer ON DEVICE (an
    async jnp dispatch, no host transfer) so finish_window fetches a
    single array across the latency-bound link."""
    import jax.numpy as jnp
    flat = [p.reshape(-1) for p in parts]
    return flat[0] if len(flat) == 1 else jnp.concatenate(flat)


def batch_inverse(vals: list[int]) -> list[int]:
    """Montgomery trick: invert N field elements with one pow."""
    n = len(vals)
    out = [0] * n
    prefix = [1] * (n + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = prefix[i] * (v if v else 1) % ed.P
    inv_all = pow(prefix[n], ed.P - 2, ed.P)
    for i in range(n - 1, -1, -1):
        v = vals[i] if vals[i] else 1
        out[i] = prefix[i] * inv_all % ed.P
        inv_all = inv_all * v % ed.P
    return out


class JaxBackend(CryptoBackend):
    name = "jax-tpu"

    def __init__(self, min_bucket: int = 128):
        import jax  # fail here if jax unusable -> default_backend falls back
        self._devices = jax.devices()
        self.min_bucket = min_bucket

    def verify_ed25519_batch(self, reqs):
        if not reqs:
            return []
        vks = [r.vk for r in reqs]
        msgs = [r.msg for r in reqs]
        sigs = [r.sig for r in reqs]
        return EJ.batch_verify(vks, msgs, sigs,
                               pad_to=_bucket(len(reqs), self.min_bucket))

    def verify_vrf_batch(self, reqs):
        if not reqs:
            return []
        from . import vrf_jax
        oks, _betas = vrf_jax.batch_verify_vrf(
            [r.vk for r in reqs], [r.alpha for r in reqs],
            [r.proof for r in reqs],
            pad_to=_bucket(len(reqs), self.min_bucket))
        return oks

    def vrf_betas_batch(self, proofs):
        from . import vrf_jax
        return vrf_jax.batch_betas(
            proofs, pad_to=_bucket(len(proofs), self.min_bucket))

    def submit_window(self, reqs, next_beta_proofs=()):
        """Dispatch one replay window's whole device workload — the mixed
        Ed25519/VRF/KES verification of `reqs` AND the VRF betas the NEXT
        window's sequential pass will need — as async kernel calls whose
        results are packed on-device into ONE flat uint8 array, so the
        latency-bound host<->device link is crossed exactly once per
        window.  Returns an opaque state for finish_window."""
        import numpy as np

        import jax.numpy as jnp

        from . import vrf_jax
        ed_reqs, ed_owner, vrf_reqs, vrf_owner, n = self.split_mixed(reqs)
        parts = []
        ed_state = vrf_state = beta_state = None
        ne = nv = nb = 0
        if ed_reqs:
            ne = _bucket(len(ed_reqs), self.min_bucket)
            pad = ne - len(ed_reqs)
            arrays, parse_ok = EJ.prepare_bytes_batch(
                [r.vk for r in ed_reqs] + [b"\x00" * 32] * pad,
                [r.msg for r in ed_reqs] + [b""] * pad,
                [r.sig for r in ed_reqs] + [b"\x00" * 64] * pad)
            ed_state = (EJ.verify_kernel_full_submit(arrays), parse_ok)
            parts.append(ed_state[0].astype(jnp.uint8))
        if vrf_reqs:
            nv = _bucket(len(vrf_reqs), self.min_bucket)
            pad = nv - len(vrf_reqs)
            vrf_state = vrf_jax._submit(
                [r.vk for r in vrf_reqs] + [b"\x00" * 32] * pad,
                [r.alpha for r in vrf_reqs] + [b""] * pad,
                [r.proof for r in vrf_reqs] + [b"\x00" * 80] * pad, nv)
            parts.append(vrf_state[0].reshape(-1))
        beta_proofs = list(dict.fromkeys(next_beta_proofs))
        if beta_proofs:
            nb = _bucket(len(beta_proofs), self.min_bucket)
            padded = beta_proofs + [b"\x00" * 80] * (nb - len(beta_proofs))
            handle, decode_ok = vrf_jax._submit_betas(padded, nb)
            beta_state = (decode_ok,)
            parts.append(handle.reshape(-1))
        packed = _pack_flat(parts) if parts else None
        return {"packed": packed, "n": n,
                "ed": ed_state, "ed_owner": ed_owner, "ne": ne,
                "vrf": vrf_state, "vrf_owner": vrf_owner,
                "vrf_n": len(vrf_reqs), "nv": nv,
                "beta": beta_state, "beta_proofs": beta_proofs, "nb": nb}

    def finish_window(self, state):
        """Block on a submit_window dispatch (one transfer); returns
        (ok list aligned with the submitted reqs, {proof: beta} for the
        requested next-window proofs)."""
        import numpy as np
        out = [False] * state["n"]
        betas: dict = {}
        if state["packed"] is None:
            return out, betas
        flat = np.asarray(state["packed"])          # THE round trip
        off = 0
        if state["ed"] is not None:
            ed_ok = flat[off:off + state["ne"]]
            off += state["ne"]
            _handle, parse_ok = state["ed"]
            for k, i in enumerate(state["ed_owner"]):
                out[i] = bool(ed_ok[k]) and bool(parse_ok[k])
        if state["vrf"] is not None:
            rows = flat[off:off + state["nv"] * 130].reshape(-1, 130)
            off += state["nv"] * 130
            from . import vrf_jax
            _h, parse_ok, gamma_ok, s_ok, pf_arr = state["vrf"]
            oks, _b = vrf_jax._finish(rows, parse_ok, gamma_ok, s_ok,
                                      pf_arr, state["vrf_n"])
            for i, ok in zip(state["vrf_owner"], oks):
                out[i] = ok
        if state["beta"] is not None:
            rows = flat[off:off + state["nb"] * 33].reshape(-1, 33)
            from . import vrf_jax
            bs = vrf_jax._finish_betas(rows, state["beta"][0],
                                       len(state["beta_proofs"]))
            betas = dict(zip(state["beta_proofs"], bs))
        return out, betas

    def verify_mixed(self, reqs):
        """Fused mixed batch: one packed device transfer for the whole
        window (see submit_window)."""
        ok, _betas = self.finish_window(self.submit_window(reqs))
        return ok


