"""JaxBackend — the TPU-batched CryptoBackend instance.

Routes Ed25519 batches through ed25519_jax.verify_kernel and VRF batches
through dual_scalar_mult_kernel (U and V halves concatenated into one device
call), with Montgomery batch inversion on host for the final point
compressions (one modular pow per batch instead of one per point).

Batch sizes are padded to power-of-two buckets (min 128) so repeated calls
hit the jit cache instead of recompiling per shape.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import ed25519_jax as EJ
from . import edwards as ed
from . import field_jax as F
from . import vrf_ref
from .backend import CryptoBackend, CpuRefBackend


def _bucket(n: int, lo: int = 128) -> int:
    m = lo
    while m < n:
        m *= 2
    return m


def batch_inverse(vals: list[int]) -> list[int]:
    """Montgomery trick: invert N field elements with one pow."""
    n = len(vals)
    out = [0] * n
    prefix = [1] * (n + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = prefix[i] * (v if v else 1) % ed.P
    inv_all = pow(prefix[n], ed.P - 2, ed.P)
    for i in range(n - 1, -1, -1):
        v = vals[i] if vals[i] else 1
        out[i] = prefix[i] * inv_all % ed.P
        inv_all = inv_all * v % ed.P
    return out


class JaxBackend(CryptoBackend):
    name = "jax-tpu"

    def __init__(self, min_bucket: int = 128):
        import jax  # fail here if jax unusable -> default_backend falls back
        self._devices = jax.devices()
        self.min_bucket = min_bucket

    def verify_ed25519_batch(self, reqs):
        if not reqs:
            return []
        vks = [r.vk for r in reqs]
        msgs = [r.msg for r in reqs]
        sigs = [r.sig for r in reqs]
        return EJ.batch_verify(vks, msgs, sigs,
                               pad_to=_bucket(len(reqs), self.min_bucket))

    def verify_vrf_batch(self, reqs):
        if not reqs:
            return []
        n = len(reqs)
        # host half: decode, hash-to-curve, challenge decode
        items = []          # (j, s, c, Y, Gamma, H)
        valid = np.zeros(n, dtype=bool)
        for j, r in enumerate(reqs):
            Y = ed.decompress(r.vk) if len(r.vk) == 32 else None
            decoded = vrf_ref.decode_proof(r.proof)
            if Y is None or decoded is None:
                continue
            Gamma, c, s = decoded
            H = vrf_ref._hash_to_curve(r.vk, r.alpha)
            items.append((j, s, c, Y, Gamma, H))
            valid[j] = True
        if not items:
            return [False] * n
        m = _bucket(2 * len(items), self.min_bucket)
        # batch layout: [U half | V half | padding]
        p1, p2, abits, bbits = [], [], [], []
        for (_, s, c, Y, Gamma, H) in items:
            p1.append(ed.to_affine(ed.BASE))
            p2.append(_neg_affine(Y))
            abits.append(s)
            bbits.append(c)
        for (_, s, c, Y, Gamma, H) in items:
            p1.append(_affine(H))
            p2.append(_neg_affine(Gamma))
            abits.append(s)
            bbits.append(c)
        pad = m - len(p1)
        base_aff = ed.to_affine(ed.BASE)
        p1 += [base_aff] * pad
        p2 += [base_aff] * pad
        abits += [1] * pad
        bbits += [1] * pad
        arrays = _pack_points(p1) + _pack_points(p2) + (
            _pack_bits(abits), _pack_bits(bbits))
        X, Yc, Z = EJ.dual_scalar_mult_kernel(*[jnp.asarray(a)
                                                for a in arrays])
        xs = F.unpack(np.asarray(X))
        ys = F.unpack(np.asarray(Yc))
        zs = F.unpack(np.asarray(Z))
        zinv = batch_inverse(zs[:2 * len(items)])
        out = [False] * n
        k = len(items)
        for i, (j, s, c, Y, Gamma, H) in enumerate(items):
            U = ed.from_affine(xs[i] * zinv[i] % ed.P,
                               ys[i] * zinv[i] % ed.P)
            V = ed.from_affine(xs[k + i] * zinv[k + i] % ed.P,
                               ys[k + i] * zinv[k + i] % ed.P)
            out[j] = vrf_ref._hash_points(H, Gamma, U, V) == c
        return out


def _affine(p):
    if p[2] == 1:
        return p[0], p[1]
    return ed.to_affine(p)


def _neg_affine(p):
    x, y = _affine(p)
    return (ed.P - x) % ed.P, y


def _pack_points(pts):
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    ts = [p[0] * p[1] % ed.P for p in pts]
    return (F.pack(xs), F.pack(ys), F.pack(ts))


def _pack_bits(scalars):
    return np.stack([EJ._bits_msb_first(s) for s in scalars], axis=1)
