"""JaxBackend — the TPU-batched CryptoBackend instance.

Routes Ed25519 batches through the split-128 ladder kernels (half the
doubling chain via the per-key [2^128]A cache, ed25519_jax split-ladder
notes) and VRF batches through the packed vrf kernels (decompression,
Elligator2 and both Strauss ladders fused into one device call).  KES
hash paths run as one batched Blake2b-256 device check (blake2b_jax)
instead of per-item host hashing.

ALL device inputs travel as packed uint32 words — the r5 microbench
showed the tunneled host<->device link at ~20 MB/s, so the (256, N)
int32 bit rows of earlier rounds cost 4x more wall-clock in transfer
than the ladder kernel itself.  Unpacking is a tiny on-device XLA
prologue fused ahead of the Mosaic kernels.

Batch sizes are padded to power-of-two buckets (min 128) so repeated
calls hit the jit cache instead of recompiling per shape.

Kernel selection is MEASURED, not assumed: on a TPU the fused pallas
(Mosaic) kernels and the op-by-op XLA kernels are timed head-to-head
(median of 3) the first time each batch shape appears, and the winner is
cached per shape — run-to-run variance on a shared/tunneled chip is large
enough that a hardcoded choice was repeatedly wrong (VERDICT r3 "weak" #3).
"""
from __future__ import annotations

import sys
import time

import numpy as np

from . import blake2b_jax as B2
from . import ed25519_jax as EJ
from . import edwards as ed
from . import kes as kes_mod
from .backend import CryptoBackend, Ed25519Req, KesReq, VrfReq


# bump when kernel internals change enough that a persisted pallas-vs-XLA
# choice could be stale (the choices file is keyed by this revision)
_KERNEL_REV = "r5-split-words-1"


def _choice_cache_path() -> str:
    import os
    import tempfile
    d = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        tempfile.gettempdir(), "jax-ouro-cache")
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        d = tempfile.gettempdir()
    return os.path.join(d, f"ouro-kernel-choices-{_KERNEL_REV}.json")


def _load_choices() -> dict:
    """Persisted autotune outcomes (ADVICE r4): a production path hitting
    a shape some earlier process already measured skips the double
    compile + 6 timed dispatches entirely."""
    import json
    try:
        with open(_choice_cache_path()) as f:
            return {tuple(json.loads(k)): v for k, v in json.load(f).items()}
    except Exception:
        return {}


def _store_choice(key, use: bool) -> None:
    import json
    path = _choice_cache_path()
    try:
        cur = {}
        try:
            with open(path) as f:
                cur = json.load(f)
        except Exception:
            pass
        cur[json.dumps(list(key))] = use
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cur, f)
        import os
        os.replace(tmp, path)
    except Exception:
        pass


def _bucket(n: int, lo: int = 128) -> int:
    m = lo
    while m < n:
        m *= 2
    return m


def batch_inverse(vals: list[int]) -> list[int]:
    """Montgomery trick: invert N field elements with one pow."""
    n = len(vals)
    out = [0] * n
    prefix = [1] * (n + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = prefix[i] * (v if v else 1) % ed.P
    inv_all = pow(prefix[n], ed.P - 2, ed.P)
    for i in range(n - 1, -1, -1):
        v = vals[i] if vals[i] else 1
        out[i] = prefix[i] * inv_all % ed.P
        inv_all = inv_all * v % ed.P
    return out


def _pad_words(w: np.ndarray, m: int) -> np.ndarray:
    """Pad the lane axis of a words/sign array out to m columns."""
    n = w.shape[-1]
    if n == m:
        return w
    pad = [(0, 0)] * (w.ndim - 1) + [(0, m - n)]
    return np.pad(w, pad)


class JaxBackend(CryptoBackend):
    name = "jax-tpu"

    def __init__(self, min_bucket: int = 128, use_pallas: bool | None = None,
                 autotune: bool | None = None):
        import jax  # fail here if jax unusable -> default_backend falls back
        EJ._ensure_compile_cache()   # ladder compiles are minutes; cache
        self._devices = jax.devices()
        on_tpu = self._devices[0].platform == "tpu"
        if autotune is None:
            # measure pallas-vs-XLA per shape on a real chip UNLESS the
            # caller pinned the path explicitly; off-TPU pallas interpret
            # mode just re-runs the same jnp ops with extra overhead, so
            # XLA is always right there and measuring would waste compiles
            autotune = on_tpu and use_pallas is None
        if use_pallas is None:
            use_pallas = on_tpu
        self.use_pallas = use_pallas      # static fallback when not tuning
        self.autotune = autotune
        if use_pallas or autotune:
            from . import pallas_kernels as PK
            self._pk = PK
            min_bucket = max(min_bucket, PK.TILE)
        self.min_bucket = min_bucket
        self._composites: dict = {}   # (ne, nv, nb, nk, pallas) -> program
        # shape key -> bool (use pallas); seeded from the persisted
        # choices of earlier processes on the same machine (ADVICE r4) —
        # only when this instance is itself autotuning, so an explicitly
        # pinned use_pallas/autotune setting is never overridden by a
        # stale measurement file
        self._choice: dict = dict(_load_choices()) if autotune else {}

    # -- measured kernel selection ------------------------------------------
    def _pick(self, key, run_pallas, run_xla):
        """Return (use_pallas, cached_result) for this shape key.

        First time a shape appears under autotune: warm both paths (compile),
        then time 3 blocking reps each and keep the median winner.  The
        choice is cached for the backend's lifetime and logged, so perf
        claims can cite which kernel actually ran (VERDICT r3 next-step
        1d).  cached_result is the winner's last timed output — simple
        batch callers use it to skip an extra dispatch; the fused-window
        caller discards it (its composite re-runs once per shape, a
        one-time cost) and records its own "win" choice since the
        homogeneity vote may override a component's.  None afterwards.
        """
        use = self._choice.get(key)
        if use is not None:
            return use, None
        result = None
        if not self.autotune:
            use = self.use_pallas
        else:
            med = {}
            last = {}
            for flag, fn in ((True, run_pallas), (False, run_xla)):
                fn()                                    # warm / compile
                vals = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    last[flag] = fn()
                    vals.append(time.perf_counter() - t0)
                med[flag] = sorted(vals)[1]
            use = med[True] <= med[False]
            result = last[use]
            print(f"[jax_backend] autotune {key}: "
                  f"pallas {med[True] * 1e3:.0f}ms / "
                  f"xla {med[False] * 1e3:.0f}ms -> "
                  f"{'pallas' if use else 'xla'}",
                  file=sys.stderr, flush=True)
            _store_choice(key, use)
        self._choice[key] = use
        return use, result

    # -- host prep ----------------------------------------------------------
    def _prep_ed(self, reqs, m: int):
        """Packed-words prep + A128 assembly for an Ed25519 batch padded
        to m.  Returns (dev_args, parse_ok); keys the cache could not
        decompress are masked out of parse_ok (the kernels trust the
        cached affine x and skip the A square root)."""
        import jax.numpy as jnp
        pad = m - len(reqs)
        vks = [r.vk for r in reqs] + [b"\x00" * 32] * pad
        arrays, parse_ok = EJ.prepare_words_batch(
            vks,
            [r.msg for r in reqs] + [b""] * pad,
            [r.sig for r in reqs] + [b"\x00" * 64] * pad)
        Aw, _signA, Rw, signR, sw, kw = arrays
        xa, xw, yw, known = EJ.GLOBAL_A128_CACHE.assemble(vks)
        args = (jnp.asarray(Aw), jnp.asarray(xa),
                jnp.asarray(xw), jnp.asarray(yw),
                jnp.asarray(Rw), jnp.asarray(signR.reshape(1, -1)),
                jnp.asarray(sw), jnp.asarray(kw))
        return args, parse_ok & known

    def _ed_dispatch(self, args, m: int, use_pallas: bool):
        """Async-dispatch one prepared Ed25519 batch; (m,) int32 handle."""
        if use_pallas:
            return self._pk._ed25519_split_jit(*args, m).reshape(-1)
        Aw, xa, xw, yw, Rw, signR2, sw, kw = args
        return EJ.verify_full_split_words_kernel(
            Aw, xa, xw, yw, Rw, signR2[0], sw, kw)

    def verify_ed25519_batch(self, reqs):
        if not reqs:
            return []
        n = len(reqs)
        m = _bucket(n, self.min_bucket)
        args, parse_ok = self._prep_ed(reqs, m)
        use, ok = self._pick(
            ("ed", m),
            lambda: np.asarray(self._ed_dispatch(args, m, True)),
            lambda: np.asarray(self._ed_dispatch(args, m, False)))
        if ok is None:
            ok = np.asarray(self._ed_dispatch(args, m, use))
        return [bool(o) and bool(p)
                for o, p in zip(ok[:n], parse_ok[:n])]

    def _prep_vrf(self, reqs, m: int):
        import jax.numpy as jnp

        from . import vrf_jax
        pad = m - len(reqs)
        vks = [r.vk for r in reqs] + [b"\x00" * 32] * pad
        args, parse_ok, gamma_ok, s_ok, pf_arr = vrf_jax._prepare_words(
            vks,
            [r.alpha for r in reqs] + [b""] * pad,
            [r.proof for r in reqs] + [b"\x00" * 80] * pad)
        Yw, _signY, Gw, signG, rw, cw, sw = args
        xa, _x128, _y128, known = EJ.GLOBAL_A128_CACHE.assemble(vks)
        dev = (jnp.asarray(Yw), jnp.asarray(xa),
               jnp.asarray(Gw), jnp.asarray(signG.reshape(1, -1)),
               jnp.asarray(rw), jnp.asarray(cw), jnp.asarray(sw))
        return dev, (parse_ok & known, gamma_ok, s_ok, pf_arr)

    def _vrf_dispatch(self, dev, m: int, use_pallas: bool):
        from . import vrf_jax
        if use_pallas:
            return self._pk._vrf_verify_jit(*dev, m)
        Yw, xa, Gw, signG2, rw, cw, sw = dev
        return vrf_jax.vrf_verify_words_kernel(Yw, xa, Gw,
                                               signG2[0], rw, cw, sw)

    def verify_vrf_batch(self, reqs):
        if not reqs:
            return []
        from . import vrf_jax
        n = len(reqs)
        m = _bucket(n, self.min_bucket)
        dev, (parse_ok, gamma_ok, s_ok, pf_arr) = self._prep_vrf(reqs, m)
        use, rows = self._pick(
            ("vrf", m),
            lambda: np.asarray(self._vrf_dispatch(dev, m, True)),
            lambda: np.asarray(self._vrf_dispatch(dev, m, False)))
        if rows is None:
            rows = np.asarray(self._vrf_dispatch(dev, m, use))
        oks, _betas = vrf_jax._finish(rows, parse_ok, gamma_ok,
                                      s_ok, pf_arr, n)
        return oks

    # largest single gamma8 dispatch: bounds the set of compiled shapes
    # (a fresh pallas shape costs minutes through the AOT helper)
    BETA_CHUNK = 2048

    def _beta_dispatch(self, Gw, signG2, m: int, use_pallas: bool):
        from . import vrf_jax
        if use_pallas:
            return self._pk._gamma8_jit(Gw, signG2, m)
        return vrf_jax.gamma8_words_kernel(Gw, signG2[0])

    def vrf_betas_batch(self, proofs):
        from . import vrf_jax
        n = len(proofs)
        if n == 0:
            return []
        if n > self.BETA_CHUNK:
            out = []
            for off in range(0, n, self.BETA_CHUNK):
                out.extend(self.vrf_betas_batch(
                    proofs[off:off + self.BETA_CHUNK]))
            return out
        import jax.numpy as jnp
        m = _bucket(n, self.min_bucket)
        padded = list(proofs) + [b"\x00" * 80] * (m - n)
        (Gw, signG), decode_ok = vrf_jax._prepare_betas_words(padded)
        Gwd = jnp.asarray(Gw)
        signG2 = jnp.asarray(signG.reshape(1, -1))
        use, rows = self._pick(
            ("beta", m),
            lambda: np.asarray(self._beta_dispatch(Gwd, signG2, m, True)),
            lambda: np.asarray(self._beta_dispatch(Gwd, signG2, m, False)))
        if rows is None:
            rows = np.asarray(self._beta_dispatch(Gwd, signG2, m, use))
        return vrf_jax._finish_betas(np.asarray(rows), decode_ok, n)

    # -- mixed windows -------------------------------------------------------
    def _split_mixed_device(self, reqs):
        """Like CryptoBackend.split_mixed but hash-free: KES hash paths
        become device Blake2b jobs instead of host hashing (VERDICT r4
        missing #2).  Returns (ed_reqs, ed_owner, vrf_reqs, vrf_owner,
        kes_msgs, kes_expects, kes_job_owner, n)."""
        ed_reqs: list = []
        ed_owner: list[int] = []
        vrf_reqs: list = []
        vrf_owner: list[int] = []
        kes_msgs: list[bytes] = []
        kes_expects: list[bytes] = []
        kes_job_owner: list[int] = []
        for i, r in enumerate(reqs):
            if isinstance(r, Ed25519Req):
                ed_reqs.append(r)
                ed_owner.append(i)
            elif isinstance(r, VrfReq):
                vrf_reqs.append(r)
                vrf_owner.append(i)
            elif isinstance(r, KesReq):
                try:
                    sig = kes_mod.KesSig.from_bytes(r.depth, r.sig_bytes)
                except ValueError:
                    continue          # stays False
                walk = kes_mod.verify_walk(r.depth, r.vk, r.period, sig)
                if walk is None:
                    continue
                leaf_vk, leaf_sig, jobs = walk
                ed_reqs.append(Ed25519Req(leaf_vk, r.msg, leaf_sig))
                ed_owner.append(i)
                for msg, expect in jobs:
                    kes_msgs.append(msg)
                    kes_expects.append(expect)
                    kes_job_owner.append(i)
            else:
                raise TypeError(f"unknown proof request type {type(r)}")
        return (ed_reqs, ed_owner, vrf_reqs, vrf_owner,
                kes_msgs, kes_expects, kes_job_owner, len(reqs))

    def _prep_kes_hash(self, kes_msgs, kes_expects, m: int):
        import jax.numpy as jnp
        msgs = np.frombuffer(b"".join(kes_msgs), dtype=np.uint8)
        msgs = msgs.reshape(-1, 64)
        exps = np.frombuffer(b"".join(kes_expects), dtype=np.uint8)
        exps = exps.reshape(-1, 32)
        mw = _pad_words(B2.msg_words(msgs), m)
        ew = _pad_words(B2.digest_words(exps), m)
        return jnp.asarray(mw), jnp.asarray(ew)

    def _kes_dispatch(self, mw, ew, m: int, use_pallas: bool):
        if use_pallas:
            return self._pk._kes_hash_jit(mw, ew, m).reshape(-1)
        return B2.check_block64_jit(mw, ew)

    def _window_composite(self, ne: int, nv: int, nb: int, nk: int,
                          pallas: bool):
        """One jitted device program for a whole window: Ed25519 verify +
        VRF verify + next-window gamma8 betas + KES hash checks, results
        concatenated into the packed flat uint8 buffer on device.  ONE
        launch per window — separate dispatches each pay the accelerator
        tunnel's fixed launch latency (~150-200 ms), which dominated the
        replay.

        The program is HOMOGENEOUS (all ladder parts pallas or all XLA):
        mixing an op-by-op XLA ladder into a pallas composite made XLA's
        compile of the combined program pathological (>1h at replay
        shapes, vs minutes for either pure form), and only the chosen
        form is ever compiled."""
        key = (ne, nv, nb, nk, pallas)
        fn = self._composites.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from . import vrf_jax
        PK = getattr(self, "_pk", None)

        def call(ed_args, vrf_args, beta_args, kes_args):
            parts = []
            if ed_args is not None:
                if pallas:
                    ok = PK._ed25519_split_call(*ed_args, ne)
                else:
                    Aw, xa, xw, yw, Rw, signR2, sw, kw = ed_args
                    ok = EJ.verify_full_split_words_core(
                        Aw, xa, xw, yw, Rw, signR2[0], sw, kw)
                parts.append(ok.reshape(-1).astype(jnp.uint8))
            if vrf_args is not None:
                if pallas:
                    rows = PK._vrf_verify_call(*vrf_args, nv)
                else:
                    Yw, xa, Gw, sG2, rw, cw, sw = vrf_args
                    rows = vrf_jax.vrf_verify_words_core(
                        Yw, xa, Gw, sG2[0], rw, cw, sw)
                parts.append(rows.reshape(-1))
            if beta_args is not None:
                if pallas:
                    rows = PK._gamma8_call(*beta_args, nb)
                else:
                    bGw, bsG2 = beta_args
                    rows = vrf_jax.gamma8_words_core(bGw, bsG2[0])
                parts.append(rows.reshape(-1))
            if kes_args is not None:
                if pallas:
                    ok = PK._kes_hash_call(*kes_args, nk)
                else:
                    ok = B2.check_block64(*kes_args)
                parts.append(ok.reshape(-1).astype(jnp.uint8))
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        fn = jax.jit(call)
        self._composites[key] = fn
        return fn

    def submit_window(self, reqs, next_beta_proofs=()):
        """Dispatch one replay window's whole device workload — the mixed
        Ed25519/VRF/KES verification of `reqs` AND the VRF betas the NEXT
        window's sequential pass will need — as ONE fused device program
        whose results are packed into ONE flat uint8 array: the
        latency-bound host<->device link is crossed once per window, and
        the launch overhead is paid once instead of per kernel.  Returns
        an opaque state for finish_window."""
        import jax.numpy as jnp

        from . import vrf_jax
        (ed_reqs, ed_owner, vrf_reqs, vrf_owner,
         kes_msgs, kes_expects, kes_job_owner, n) = \
            self._split_mixed_device(reqs)
        beta_proofs = list(dict.fromkeys(next_beta_proofs))
        ed_state = vrf_state = beta_state = None
        ne = nv = nb = nk = 0
        ed_args = vrf_args = beta_args = kes_args = None
        if ed_reqs:
            ne = _bucket(len(ed_reqs), self.min_bucket)
            ed_args, parse_ok = self._prep_ed(ed_reqs, ne)
            ed_state = (None, parse_ok)
        if vrf_reqs:
            nv = _bucket(len(vrf_reqs), self.min_bucket)
            vrf_args, masks = self._prep_vrf(vrf_reqs, nv)
            vrf_state = (None,) + masks
        if beta_proofs:
            nb = _bucket(len(beta_proofs), self.min_bucket)
            padded = beta_proofs + [b"\x00" * 80] * (nb - len(beta_proofs))
            (Gw, signG), decode_ok = vrf_jax._prepare_betas_words(padded)
            beta_state = (decode_ok,)
            beta_args = (jnp.asarray(Gw),
                         jnp.asarray(signG.reshape(1, -1)))
        if kes_msgs:
            nk = _bucket(len(kes_msgs), self.min_bucket)
            kes_args = self._prep_kes_hash(kes_msgs, kes_expects, nk)
        if (ed_args is None and vrf_args is None and beta_args is None
                and kes_args is None):
            packed = None
        else:
            # per-component autotune (keys shared with the simple-batch
            # paths), then ONE fused composite for the winning combination
            use_ed = use_vrf = use_beta = use_kes = False
            if ed_args is not None:
                use_ed, _ = self._pick(
                    ("ed", ne),
                    lambda: np.asarray(self._ed_dispatch(ed_args, ne,
                                                         True)),
                    lambda: np.asarray(self._ed_dispatch(ed_args, ne,
                                                         False)))
            if vrf_args is not None:
                use_vrf, _ = self._pick(
                    ("vrf", nv),
                    lambda: np.asarray(self._vrf_dispatch(vrf_args, nv,
                                                          True)),
                    lambda: np.asarray(self._vrf_dispatch(vrf_args, nv,
                                                          False)))
            if beta_args is not None:
                use_beta, _ = self._pick(
                    ("beta", nb),
                    lambda: np.asarray(self._beta_dispatch(*beta_args, nb,
                                                           True)),
                    lambda: np.asarray(self._beta_dispatch(*beta_args, nb,
                                                           False)))
            if kes_args is not None:
                use_kes, _ = self._pick(
                    ("kesh", nk),
                    lambda: np.asarray(self._kes_dispatch(*kes_args, nk,
                                                          True)),
                    lambda: np.asarray(self._kes_dispatch(*kes_args, nk,
                                                          False)))
            # all-pallas unless every present LADDER component measured
            # XLA faster (see _window_composite on why no mixing); the
            # kes hash kernel is too small to swing the vote
            pallas_votes = [v for v, present in
                            ((use_ed, ed_args is not None),
                             (use_vrf, vrf_args is not None),
                             (use_beta, beta_args is not None)) if present]
            if pallas_votes:
                allp = any(pallas_votes)
            else:
                allp = use_kes
            win_key = ("win", ne, nv, nb, nk)
            if self._choice.get(win_key) != allp:
                self._choice[win_key] = allp
                if self.autotune:
                    print(f"[jax_backend] window composite {win_key[1:]}: "
                          f"{'pallas' if allp else 'xla'} (homogeneous; "
                          f"votes ed={use_ed} vrf={use_vrf} "
                          f"beta={use_beta} kesh={use_kes})",
                          file=sys.stderr, flush=True)
            packed = self._window_composite(ne, nv, nb, nk, allp)(
                ed_args, vrf_args, beta_args, kes_args)
        return {"packed": packed, "n": n,
                "ed": ed_state, "ed_owner": ed_owner, "ne": ne,
                "vrf": vrf_state, "vrf_owner": vrf_owner,
                "vrf_n": len(vrf_reqs), "nv": nv,
                "beta": beta_state, "beta_proofs": beta_proofs, "nb": nb,
                "kes_job_owner": kes_job_owner, "nk": nk,
                "kes_n": len(kes_msgs)}

    def finish_window(self, state):
        """Block on a submit_window dispatch (one transfer); returns
        (ok list aligned with the submitted reqs, {proof: beta} for the
        requested next-window proofs)."""
        out = [False] * state["n"]
        betas: dict = {}
        if state["packed"] is None:
            return out, betas
        flat = np.asarray(state["packed"])          # THE round trip
        off = 0
        if state["ed"] is not None:
            ed_ok = flat[off:off + state["ne"]]
            off += state["ne"]
            _handle, parse_ok = state["ed"]
            for k, i in enumerate(state["ed_owner"]):
                out[i] = bool(ed_ok[k]) and bool(parse_ok[k])
        if state["vrf"] is not None:
            rows = flat[off:off + state["nv"] * 130].reshape(-1, 130)
            off += state["nv"] * 130
            from . import vrf_jax
            _h, parse_ok, gamma_ok, s_ok, pf_arr = state["vrf"]
            oks, _b = vrf_jax._finish(rows, parse_ok, gamma_ok, s_ok,
                                      pf_arr, state["vrf_n"])
            for i, ok in zip(state["vrf_owner"], oks):
                out[i] = ok
        if state["beta"] is not None:
            rows = flat[off:off + state["nb"] * 33].reshape(-1, 33)
            off += state["nb"] * 33
            from . import vrf_jax
            bs = vrf_jax._finish_betas(rows, state["beta"][0],
                                       len(state["beta_proofs"]))
            betas = dict(zip(state["beta_proofs"], bs))
        if state["nk"]:
            kes_ok = flat[off:off + state["nk"]]
            # a KES request is valid only if its leaf Ed25519 check
            # passed (handled via ed_owner above) AND every hash-path
            # job checked out
            for k, i in enumerate(state["kes_job_owner"][:state["kes_n"]]):
                if not kes_ok[k]:
                    out[i] = False
        return out, betas

    def verify_kes_batch(self, reqs):
        """KES batch: leaf Ed25519 on the curve kernels + hash path on the
        Blake2b device kernel — no host hashing (VERDICT r4 missing #2)."""
        return self.verify_mixed(reqs)

    def verify_mixed(self, reqs):
        """Fused mixed batch: one packed device transfer for the whole
        window (see submit_window)."""
        ok, _betas = self.finish_window(self.submit_window(reqs))
        return ok
