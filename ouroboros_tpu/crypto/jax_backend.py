"""JaxBackend — the TPU-batched CryptoBackend instance.

Routes Ed25519 batches through ed25519_jax.verify_full_kernel and VRF
batches through vrf_jax.vrf_verify_kernel (decompression, Elligator2 and
both Strauss ladders fused into one device call), with Montgomery batch
inversion on host for the final point compressions (one modular pow per
batch instead of one per point).

Batch sizes are padded to power-of-two buckets (min 128) so repeated calls
hit the jit cache instead of recompiling per shape.

Kernel selection is MEASURED, not assumed: on a TPU the fused pallas
(Mosaic) kernels and the op-by-op XLA kernels are timed head-to-head
(median of 3) the first time each batch shape appears, and the winner is
cached per shape — run-to-run variance on a shared/tunneled chip is large
enough that a hardcoded choice was repeatedly wrong (VERDICT r3 "weak" #3).
"""
from __future__ import annotations

import sys
import time

from . import ed25519_jax as EJ
from . import edwards as ed
from .backend import CryptoBackend


def _bucket(n: int, lo: int = 128) -> int:
    m = lo
    while m < n:
        m *= 2
    return m


def batch_inverse(vals: list[int]) -> list[int]:
    """Montgomery trick: invert N field elements with one pow."""
    n = len(vals)
    out = [0] * n
    prefix = [1] * (n + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = prefix[i] * (v if v else 1) % ed.P
    inv_all = pow(prefix[n], ed.P - 2, ed.P)
    for i in range(n - 1, -1, -1):
        v = vals[i] if vals[i] else 1
        out[i] = prefix[i] * inv_all % ed.P
        inv_all = inv_all * v % ed.P
    return out


class JaxBackend(CryptoBackend):
    name = "jax-tpu"

    def __init__(self, min_bucket: int = 128, use_pallas: bool | None = None,
                 autotune: bool | None = None):
        import jax  # fail here if jax unusable -> default_backend falls back
        EJ._ensure_compile_cache()   # ladder compiles are minutes; cache
        self._devices = jax.devices()
        on_tpu = self._devices[0].platform == "tpu"
        if autotune is None:
            # measure pallas-vs-XLA per shape on a real chip UNLESS the
            # caller pinned the path explicitly; off-TPU pallas interpret
            # mode just re-runs the same jnp ops with extra overhead, so
            # XLA is always right there and measuring would waste compiles
            autotune = on_tpu and use_pallas is None
        if use_pallas is None:
            use_pallas = on_tpu
        self.use_pallas = use_pallas      # static fallback when not tuning
        self.autotune = autotune
        if use_pallas or autotune:
            from . import pallas_kernels as PK
            self._pk = PK
            min_bucket = max(min_bucket, PK.TILE)
        self.min_bucket = min_bucket
        self._composites: dict = {}   # (ne, nv, nb, pallas) -> window program
        self._choice: dict = {}       # shape key -> bool (use pallas)

    # -- measured kernel selection ------------------------------------------
    def _pick(self, key, run_pallas, run_xla):
        """Return (use_pallas, cached_result) for this shape key.

        First time a shape appears under autotune: warm both paths (compile),
        then time 3 blocking reps each and keep the median winner.  The
        choice is cached for the backend's lifetime and logged, so perf
        claims can cite which kernel actually ran (VERDICT r3 next-step
        1d).  cached_result is the winner's last timed output — simple
        batch callers use it to skip an extra dispatch; the fused-window
        caller discards it (its composite re-runs once per shape, a
        one-time cost) and records its own "win" choice since the
        homogeneity vote may override a component's.  None afterwards.
        """
        use = self._choice.get(key)
        if use is not None:
            return use, None
        result = None
        if not self.autotune:
            use = self.use_pallas
        else:
            med = {}
            last = {}
            for flag, fn in ((True, run_pallas), (False, run_xla)):
                fn()                                    # warm / compile
                vals = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    last[flag] = fn()
                    vals.append(time.perf_counter() - t0)
                med[flag] = sorted(vals)[1]
            use = med[True] <= med[False]
            result = last[use]
            print(f"[jax_backend] autotune {key}: "
                  f"pallas {med[True] * 1e3:.0f}ms / "
                  f"xla {med[False] * 1e3:.0f}ms -> "
                  f"{'pallas' if use else 'xla'}",
                  file=sys.stderr, flush=True)
        self._choice[key] = use
        return use, result

    # -- pallas runners (vrf_jax._submit/_submit_betas plug-ins) -----------
    def _ed_submit(self, arrays, use_pallas: bool):
        """Async-dispatch one prepared Ed25519 batch; (n,) int32 handle."""
        if not use_pallas:
            return EJ.verify_kernel_full_submit(arrays)
        import jax.numpy as jnp
        yA, signA, yR, signR, s_bits, k_bits = arrays
        return self._pk.ed25519_verify_pallas(
            jnp.asarray(yA), jnp.asarray(signA), jnp.asarray(yR),
            jnp.asarray(signR), jnp.asarray(s_bits), jnp.asarray(k_bits),
            yA.shape[1]).reshape(-1)

    def verify_ed25519_batch(self, reqs):
        if not reqs:
            return []
        import numpy as np
        n = len(reqs)
        m = _bucket(n, self.min_bucket)
        pad = m - n
        arrays, parse_ok = EJ.prepare_bytes_batch(
            [r.vk for r in reqs] + [b"\x00" * 32] * pad,
            [r.msg for r in reqs] + [b""] * pad,
            [r.sig for r in reqs] + [b"\x00" * 64] * pad)
        use, ok = self._pick(
            ("ed", m),
            lambda: np.asarray(self._ed_submit(arrays, True)),
            lambda: np.asarray(self._ed_submit(arrays, False)))
        if ok is None:
            ok = np.asarray(self._ed_submit(arrays, use))
        return [bool(o) and bool(p)
                for o, p in zip(ok[:n], parse_ok[:n])]

    def verify_vrf_batch(self, reqs):
        if not reqs:
            return []
        import numpy as np
        from . import vrf_jax
        n = len(reqs)
        m = _bucket(n, self.min_bucket)
        vks = [r.vk for r in reqs] + [b"\x00" * 32] * (m - n)
        alphas = [r.alpha for r in reqs] + [b""] * (m - n)
        proofs = [r.proof for r in reqs] + [b"\x00" * 80] * (m - n)
        args, parse_ok, gamma_ok, s_ok, pf_arr = vrf_jax._prepare(
            vks, alphas, proofs)
        use, rows = self._pick(
            ("vrf", m),
            lambda: np.asarray(self._pk.vrf_verify_pallas(*args)),
            lambda: np.asarray(vrf_jax._default_runner(*args)))
        if rows is None:
            runner = self._pk.vrf_verify_pallas if use \
                else vrf_jax._default_runner
            rows = runner(*args)
        oks, _betas = vrf_jax._finish(rows, parse_ok, gamma_ok,
                                      s_ok, pf_arr, n)
        return oks

    # largest single gamma8 dispatch: bounds the set of compiled shapes
    # (a fresh pallas shape costs minutes through the AOT helper)
    BETA_CHUNK = 2048

    def vrf_betas_batch(self, proofs):
        import numpy as np
        from . import vrf_jax
        n = len(proofs)
        if n == 0:
            return []
        if n > self.BETA_CHUNK:
            out = []
            for off in range(0, n, self.BETA_CHUNK):
                out.extend(self.vrf_betas_batch(
                    proofs[off:off + self.BETA_CHUNK]))
            return out
        m = _bucket(n, self.min_bucket)
        padded = list(proofs) + [b"\x00" * 80] * (m - n)
        (yG, signG), decode_ok = vrf_jax._prepare_betas(padded)
        import jax.numpy as jnp
        use, rows = self._pick(
            ("beta", m),
            lambda: np.asarray(self._pk.gamma8_pallas(yG, signG)),
            lambda: np.asarray(vrf_jax.gamma8_kernel(
                jnp.asarray(yG), jnp.asarray(signG))))
        if rows is None:
            if use:
                rows = self._pk.gamma8_pallas(yG, signG)
            else:
                rows = vrf_jax.gamma8_kernel(jnp.asarray(yG),
                                             jnp.asarray(signG))
        return vrf_jax._finish_betas(np.asarray(rows), decode_ok, n)

    def _window_composite(self, ne: int, nv: int, nb: int, pallas: bool):
        """One jitted device program for a whole window: Ed25519 verify +
        VRF verify + next-window gamma8 betas, results concatenated into
        the packed flat uint8 buffer on device.  ONE launch per window —
        separate dispatches each pay the accelerator tunnel's fixed launch
        latency (~150-200 ms), which dominated the replay.

        The program is HOMOGENEOUS (all parts pallas or all XLA): mixing
        an op-by-op XLA ladder into a pallas composite made XLA's compile
        of the combined program pathological (>1h at replay shapes, vs
        minutes for either pure form), and only the chosen form is ever
        compiled."""
        key = (ne, nv, nb, pallas)
        fn = self._composites.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from . import vrf_jax
        PK = getattr(self, "_pk", None)
        ed_p = vrf_p = beta_p = pallas

        def call(ed_args, vrf_args, beta_args):
            parts = []
            if ed_args is not None:
                if ed_p:
                    ok = PK._ed25519_verify_call(*ed_args, ne)
                else:
                    yA, signA2, yR, signR2, s_bits, k_bits = ed_args
                    ok = EJ.verify_full_core(yA, signA2[0], yR, signR2[0],
                                             s_bits, k_bits)
                parts.append(ok.reshape(-1).astype(jnp.uint8))
            if vrf_args is not None:
                if vrf_p:
                    rows = PK._vrf_verify_call(*vrf_args, nv)
                else:
                    yY, sY2, yG, sG2, r, cb, lob, hib = vrf_args
                    rows = vrf_jax.vrf_verify_core(yY, sY2[0], yG, sG2[0],
                                                   r, cb, lob, hib)
                parts.append(rows.reshape(-1))
            if beta_args is not None:
                if beta_p:
                    rows = PK._gamma8_call(*beta_args, nb)
                else:
                    byG, bsG2 = beta_args
                    rows = vrf_jax.gamma8_kernel(byG, bsG2[0])
                parts.append(rows.reshape(-1))
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        fn = jax.jit(call)
        self._composites[key] = fn
        return fn

    def submit_window(self, reqs, next_beta_proofs=()):
        """Dispatch one replay window's whole device workload — the mixed
        Ed25519/VRF/KES verification of `reqs` AND the VRF betas the NEXT
        window's sequential pass will need — as ONE fused device program
        whose results are packed into ONE flat uint8 array: the
        latency-bound host<->device link is crossed once per window, and
        the launch overhead is paid once instead of per kernel.  Returns
        an opaque state for finish_window."""
        import numpy as np

        import jax.numpy as jnp

        from . import vrf_jax
        ed_reqs, ed_owner, vrf_reqs, vrf_owner, n = self.split_mixed(reqs)
        beta_proofs = list(dict.fromkeys(next_beta_proofs))
        ed_state = vrf_state = beta_state = None
        ne = nv = nb = 0
        ed_args = vrf_args = beta_args = None
        if ed_reqs:
            ne = _bucket(len(ed_reqs), self.min_bucket)
            pad = ne - len(ed_reqs)
            arrays, parse_ok = EJ.prepare_bytes_batch(
                [r.vk for r in ed_reqs] + [b"\x00" * 32] * pad,
                [r.msg for r in ed_reqs] + [b""] * pad,
                [r.sig for r in ed_reqs] + [b"\x00" * 64] * pad)
            ed_state = (None, parse_ok)
            yA, signA, yR, signR, s_bits, k_bits = arrays
            ed_args = (jnp.asarray(yA),
                       jnp.asarray(signA.reshape(1, -1)),
                       jnp.asarray(yR),
                       jnp.asarray(signR.reshape(1, -1)),
                       jnp.asarray(s_bits), jnp.asarray(k_bits))
        if vrf_reqs:
            nv = _bucket(len(vrf_reqs), self.min_bucket)
            pad = nv - len(vrf_reqs)
            args, parse_ok, gamma_ok, s_ok, pf_arr = vrf_jax._prepare(
                [r.vk for r in vrf_reqs] + [b"\x00" * 32] * pad,
                [r.alpha for r in vrf_reqs] + [b""] * pad,
                [r.proof for r in vrf_reqs] + [b"\x00" * 80] * pad)
            vrf_state = (None, parse_ok, gamma_ok, s_ok, pf_arr)
            yY, signY, yG, signG, r_l, c_b, lo_b, hi_b = args
            vrf_args = (jnp.asarray(yY),
                        jnp.asarray(signY.reshape(1, -1)),
                        jnp.asarray(yG),
                        jnp.asarray(signG.reshape(1, -1)),
                        jnp.asarray(r_l), jnp.asarray(c_b),
                        jnp.asarray(lo_b), jnp.asarray(hi_b))
        if beta_proofs:
            nb = _bucket(len(beta_proofs), self.min_bucket)
            padded = beta_proofs + [b"\x00" * 80] * (nb - len(beta_proofs))
            (yG, signG), decode_ok = vrf_jax._prepare_betas(padded)
            beta_state = (decode_ok,)
            beta_args = (jnp.asarray(yG),
                         jnp.asarray(signG.reshape(1, -1)))
        if ed_args is None and vrf_args is None and beta_args is None:
            packed = None
        else:
            # per-component autotune (keys shared with the simple-batch
            # paths), then ONE fused composite for the winning combination
            use_ed = use_vrf = use_beta = False
            if ed_args is not None:
                use_ed, _ = self._pick(
                    ("ed", ne),
                    lambda: np.asarray(self._pk._ed25519_verify_jit(
                        *ed_args, ne)),
                    lambda: np.asarray(EJ.verify_full_kernel(
                        ed_args[0], ed_args[1][0], ed_args[2],
                        ed_args[3][0], ed_args[4], ed_args[5])))
            if vrf_args is not None:
                use_vrf, _ = self._pick(
                    ("vrf", nv),
                    lambda: np.asarray(self._pk._vrf_verify_jit(
                        *vrf_args, nv)),
                    lambda: np.asarray(vrf_jax.vrf_verify_kernel(
                        vrf_args[0], vrf_args[1][0], vrf_args[2],
                        vrf_args[3][0], *vrf_args[4:])))
            if beta_args is not None:
                use_beta, _ = self._pick(
                    ("beta", nb),
                    lambda: np.asarray(self._pk._gamma8_jit(
                        *beta_args, nb)),
                    lambda: np.asarray(vrf_jax.gamma8_kernel(
                        beta_args[0], beta_args[1][0])))
            # all-pallas unless every present component measured XLA
            # faster (see _window_composite on why no mixing); the
            # decision is recorded under a "win" key so perf reports can
            # cite what the composite ACTUALLY ran even when a component
            # vote disagreed
            pallas_votes = [v for v, present in
                            ((use_ed, ed_args is not None),
                             (use_vrf, vrf_args is not None),
                             (use_beta, beta_args is not None)) if present]
            allp = any(pallas_votes)
            win_key = ("win", ne, nv, nb)
            if self._choice.get(win_key) != allp:
                self._choice[win_key] = allp
                if self.autotune:
                    print(f"[jax_backend] window composite {win_key[1:]}: "
                          f"{'pallas' if allp else 'xla'} (homogeneous; "
                          f"votes ed={use_ed} vrf={use_vrf} "
                          f"beta={use_beta})",
                          file=sys.stderr, flush=True)
            packed = self._window_composite(ne, nv, nb, allp)(
                ed_args, vrf_args, beta_args)
        return {"packed": packed, "n": n,
                "ed": ed_state, "ed_owner": ed_owner, "ne": ne,
                "vrf": vrf_state, "vrf_owner": vrf_owner,
                "vrf_n": len(vrf_reqs), "nv": nv,
                "beta": beta_state, "beta_proofs": beta_proofs, "nb": nb}

    def finish_window(self, state):
        """Block on a submit_window dispatch (one transfer); returns
        (ok list aligned with the submitted reqs, {proof: beta} for the
        requested next-window proofs)."""
        import numpy as np
        out = [False] * state["n"]
        betas: dict = {}
        if state["packed"] is None:
            return out, betas
        flat = np.asarray(state["packed"])          # THE round trip
        off = 0
        if state["ed"] is not None:
            ed_ok = flat[off:off + state["ne"]]
            off += state["ne"]
            _handle, parse_ok = state["ed"]
            for k, i in enumerate(state["ed_owner"]):
                out[i] = bool(ed_ok[k]) and bool(parse_ok[k])
        if state["vrf"] is not None:
            rows = flat[off:off + state["nv"] * 130].reshape(-1, 130)
            off += state["nv"] * 130
            from . import vrf_jax
            _h, parse_ok, gamma_ok, s_ok, pf_arr = state["vrf"]
            oks, _b = vrf_jax._finish(rows, parse_ok, gamma_ok, s_ok,
                                      pf_arr, state["vrf_n"])
            for i, ok in zip(state["vrf_owner"], oks):
                out[i] = ok
        if state["beta"] is not None:
            rows = flat[off:off + state["nb"] * 33].reshape(-1, 33)
            from . import vrf_jax
            bs = vrf_jax._finish_betas(rows, state["beta"][0],
                                       len(state["beta_proofs"]))
            betas = dict(zip(state["beta_proofs"], bs))
        return out, betas

    def verify_mixed(self, reqs):
        """Fused mixed batch: one packed device transfer for the whole
        window (see submit_window)."""
        ok, _betas = self.finish_window(self.submit_window(reqs))
        return ok


