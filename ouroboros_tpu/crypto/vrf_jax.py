"""Batched ECVRF-ED25519-SHA512-Elligator2 verification on TPU.

The device-side analog of vrf_ref.verify (libsodium crypto_vrf_ietfdraft03,
the PraosVRF of Shelley/Protocol.hs:366-415): for a whole batch of proofs,

  host (numpy/hashlib, C-speed): byte parsing, canonical-y checks, the
      SHA-512s (Elligator input r, challenge recomputation, beta);
  device (one fused kernel): decompress Y and Gamma, the Elligator2 map in
      projective form (no inversions — the Legendre test and the square
      root run on numerator/denominator polynomials), cofactor clearing,
      [8]Gamma for beta, both Strauss-Shamir ladders U = [s]B - [c]Y,
      V = [s]H - [c]Gamma as one concatenated batch, then affine
      conversion via ONE batched inversion chain and on-device point
      compression to bytes.

The kernel returns a single (N, 130) uint8 array — compressed H, U, V,
[8]Gamma plus validity flags — because the host<->device link has high
fixed latency (~100ms/transfer on the tunneled device): one transfer per
batch, sized ~130 bytes/item, is the difference between 700/s and
thousands/s.

vrf_ref is the bit-exactness oracle; edge cases (non-square w fallback,
inv(0) = 0, failed decompression -> BASE) mirror its behavior via
branch-free selects.  The two measure-zero hash preimages where the
projective form would diverge from the reference (1 + 2r^2 = 0 and
u = -1) are explicitly selected to the reference's values.
"""
from __future__ import annotations

import hashlib

import numpy as np

import jax
import jax.numpy as jnp

from . import ed25519_jax as EJ
from . import edwards as ed
from . import field_jax as F
from .vrf_ref import PROOF_LEN, SUITE

_GX, _GY = ed.to_affine(ed.BASE)
# [2^128]B — compile-time constant for the split-scalar ladder
_B128 = ed.scalar_mult(1 << 128, ed.BASE)
_G2X, _G2Y = ed.to_affine(_B128)
_A = ed.A24                           # Montgomery A = 486662
# reference fallback for the measure-zero Elligator edge case 1+2r^2 == 0:
# host path yields u = -A, y = (-A-1)/(1-A)
_Y_W0 = (ed.P - _A - 1) * ed.inv((1 - _A) % ed.P) % ed.P


def _double_n(pt, n_doublings: int):
    return jax.lax.fori_loop(0, n_doublings,
                             lambda _, p: EJ.pt_double(p), pt)


def _triple_table_cached(P1, P1p, P2, n):
    """8-entry cached-form table over bit combinations lo + 2·hi + 4·c of
    Q += [lo]P1 + [hi]P1' + [c]P2 (4 extended adds + 7 to_cached muls)."""
    ident = EJ._identity_like(P1[0])
    t3 = EJ.pt_add(P1, P1p, n)
    t5 = EJ.pt_add(P1, P2, n)
    t6 = EJ.pt_add(P1p, P2, n)
    t7 = EJ.pt_add(t3, P2, n)
    ext = (P1, P1p, t3, P2, t5, t6, t7)
    return [EJ.ident_cached(P1[0])] + [EJ.to_cached(p, n) for p in ext]


def _triple_ladder_idx(P1, P1p, P2, idx_rows):
    """Q = [lo]P1 + [hi]P1' + [c]P2 in 128 iterations (all three scalars
    are < 2^128: the verification scalar s splits as s = hi*2^128 + lo
    with P1' = [2^128]P1, and the VRF challenge c is 16 bytes).  Halves
    the doubling chain of the naive 256-iteration dual ladder.
    idx_rows: (128, N) int32 digits lo + 2·hi + 4·c, MSB-first.
    Cached-form table adds (one fewer mul per iteration).  Points in
    full extended coordinates; returns projective (X, Y, Z)."""
    n = P1[0].shape[1]
    cach = _triple_table_cached(P1, P1p, P2, n)
    table = tuple(jnp.stack([t[c] for t in cach]) for c in range(4))
    ident = EJ._identity_like(P1[0])

    def body(i, Q):
        Q = EJ.pt_double(Q)
        idx = jax.lax.dynamic_index_in_dim(idx_rows, i, 0, keepdims=False)
        return EJ.pt_add_cached(Q, EJ._onehot_entry(table, idx, 8))

    Q = jax.lax.fori_loop(0, 128, body, ident)
    return Q[0], Q[1], Q[2]


def _triple_ladder_128(P1, P1p, P2, lo_bits, hi_bits, c_bits):
    """Bit-rows compatibility wrapper around _triple_ladder_idx."""
    return _triple_ladder_idx(P1, P1p, P2,
                              lo_bits + 2 * hi_bits + 4 * c_bits)


def _select(mask, a, b):
    return jnp.where(mask[None, :], a, b)


def _sqrt_ratio(u, v):
    """x with x^2 = u/v (RFC 8032 §5.1.3 candidate + twist), plus ok mask.
    x is the even-parity affine root — the sign-0 decompression choice."""
    v3 = F.mul(F.mul(v, v), v)
    v7 = F.mul(F.mul(v3, v3), v)
    xc = F.mul(F.mul(u, v3), EJ.pow_p58(F.mul(u, v7)))
    vx2 = F.mul(v, F.mul(xc, xc))
    root_direct = F.is_zero(F.sub(vx2, u))
    root_twist = F.is_zero(F.add(vx2, u))
    ok = jnp.logical_or(root_direct, root_twist)
    x = _select(root_direct, xc, F.mul(xc, F.const_batch(ed.SQRT_M1,
                                                         u.shape[1])))
    x = F.canon(x)
    # parity 0 (sign bit 0 of the compressed-with-sign-0 encoding)
    x_neg, _ = F._exact_scan(F.p_col(x.shape[1]) - x)
    return _select((x[0] & 1) == 1, x_neg, x), ok


def elligator2_fraction(r):
    """Projective Elligator2: r -> Edwards point, inversion-free.

    Host reference (vrf_ref._hash_to_curve): u = -A/(1+2r^2), flipped to
    -A-u when w = u(u^2+Au+1) is non-square; y = (u-1)/(u+1); decompress
    with sign 0.  Here u = U/W with W = 1+2r^2 and U = -A or -2Ar^2, so
    chi(w) = chi(-A * c1 * W) with c1 = W^2 - 2A^2 r^2 (w scaled by the
    square W^4), and y = (U-W)/(U+W) stays a fraction all the way into
    the sqrt ratio.  Returns extended (X, Y, Z, T) with Z = U+W."""
    n = r.shape[1]
    one = F.one_like(r)
    Ac = F.const_batch(_A, n)
    r2 = F.mul(r, r)
    two_r2 = F.add(r2, r2)
    W = F.add(two_r2, one)                      # 1 + 2r^2
    # c1 = W^2 - 2 A^2 r^2 ;  chi input = -A * c1 * W
    c1 = F.sub(F.mul(W, W), F.mul(F.mul(Ac, Ac), two_r2))
    chi_in = F.sub(r * 0, F.mul(Ac, F.mul(c1, W)))
    is_sq = F.is_zero(F.sub(EJ.pow_chi(chi_in), one))
    negA = F.sub(r * 0, Ac)
    U = _select(is_sq, negA, F.mul(negA, two_r2))   # -A  |  -2A r^2
    Yn = F.sub(U, W)
    Yd = F.add(U, W)
    # measure-zero reference edge cases (see module docstring)
    w_zero = F.is_zero(W)
    Yn = _select(w_zero, F.const_batch(_Y_W0, n), Yn)
    Yd = _select(w_zero, one, Yd)
    d_zero = F.is_zero(Yd)
    Yn = _select(d_zero, r * 0, Yn)
    Yd = _select(d_zero, one, Yd)
    # decompress y = Yn/Yd with sign 0: x^2 = (y^2-1)/(d y^2+1)
    Yn2 = F.mul(Yn, Yn)
    Yd2 = F.mul(Yd, Yd)
    u_num = F.sub(Yn2, Yd2)
    v_num = F.add(F.mul(F.const_batch(ed.D, n), Yn2), Yd2)
    x, ok = _sqrt_ratio(u_num, v_num)
    # x == 0 with sign 0 is fine; failure -> BASE (vrf_ref:37)
    X = _select(ok, F.mul(x, Yd), F.const_batch(_GX, n))
    Y = _select(ok, Yn, F.const_batch(_GY, n))
    Z = _select(ok, Yd, one)
    T = _select(ok, F.mul(x, Yn), F.const_batch(_GX * _GY % ed.P, n))
    return (X, Y, Z, T)


def _double3(pt):
    return EJ.pt_double(EJ.pt_double(EJ.pt_double(pt)))


_BYTE_W = None


def compress_device(x_aff, y_aff):
    """Affine limb coords -> (32, N) int32 byte values of the compressed
    encoding (y LE with the x-parity sign in bit 255)."""
    yc = F.canon(y_aff)
    xc = F.canon(x_aff)
    sign = xc[0] & 1
    shifts = jnp.arange(F.RADIX, dtype=jnp.int32)[None, :, None]
    bits = (yc[:, None, :] >> shifts) & 1            # (NLIMBS, RADIX, N)
    bits = bits.reshape(F.NLIMBS * F.RADIX, -1)[:256]
    w = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    byts = jnp.sum(bits.reshape(32, 8, -1) * w, axis=1)   # (32, N)
    return byts.at[31].add(sign << 7)


def vrf_verify_idx_core(yY, signY, yG, signG, r, idx_rows):
    """Full device half of batched VRF verification.

    idx_rows: (128, N) int32 joint digits lo + 2·hi + 4·c (MSB-first).
    Returns an (N, 130) uint8 array per item:
      [0:32]   compressed H        [32:64]  compressed U
      [64:96]  compressed V        [96:128] compressed [8]Gamma
      [128]    okY  [129]  okG
    """
    n = yY.shape[1]
    one = F.one_like(yY)
    xY, okY = EJ.device_decompress(yY, signY)
    xG, okG = EJ.device_decompress(yG, signG)
    H = _double3(elligator2_fraction(r))             # cofactor clearing
    G8 = _double3((xG, yG, one, F.mul(xG, yG)))      # for beta
    # ladder halves, split-scalar form (s = hi*2^128 + lo, c < 2^128):
    #   U = [lo]B + [hi]B' + [c](-Y)     with B' = [2^128]B (constant)
    #   V = [lo]H + [hi]H' + [c](-Gamma) with H' = [2^128]H (128 doubles)
    nYx = F.sub(yY * 0, xY)
    nGx = F.sub(yG * 0, xG)
    B = (F.const_batch(_GX, n), F.const_batch(_GY, n), one,
         F.const_batch(_GX * _GY % ed.P, n))
    Bp = (F.const_batch(_G2X, n), F.const_batch(_G2Y, n), one,
          F.const_batch(_G2X * _G2Y % ed.P, n))
    Hp = _double_n(H, 128)
    negY = (nYx, yY, one, F.mul(nYx, yY))
    negG = (nGx, yG, one, F.mul(nGx, yG))
    P1 = tuple(jnp.concatenate([B[c], H[c]], axis=1) for c in range(4))
    P1p = tuple(jnp.concatenate([Bp[c], Hp[c]], axis=1) for c in range(4))
    P2 = tuple(jnp.concatenate([negY[c], negG[c]], axis=1)
               for c in range(4))
    idx2 = jnp.concatenate([idx_rows, idx_rows], axis=1)
    UV = _triple_ladder_idx(P1, P1p, P2, idx2)
    # one inversion chain for every Z: [H | U | V | G8]
    Zall = jnp.concatenate([H[2], UV[2], G8[2]], axis=1)      # (NLIMBS, 4n)
    Zi = EJ.pow_inv(Zall)
    Xall = jnp.concatenate([H[0], UV[0], G8[0]], axis=1)
    Yall = jnp.concatenate([H[1], UV[1], G8[1]], axis=1)
    comp = compress_device(F.mul(Xall, Zi), F.mul(Yall, Zi))  # (32, 4n)
    rows = jnp.concatenate([comp[:, :n], comp[:, n:2 * n],
                            comp[:, 2 * n:3 * n], comp[:, 3 * n:],
                            okY.astype(jnp.int32)[None, :],
                            okG.astype(jnp.int32)[None, :]], axis=0)
    return rows.T.astype(jnp.uint8)                  # (n, 130)


def vrf_verify_core(yY, signY, yG, signG, r, c_bits, s_lo_bits, s_hi_bits):
    """Bit-rows compatibility form (parallel/sharded_verify wraps this)."""
    return vrf_verify_idx_core(yY, signY, yG, signG, r,
                               s_lo_bits + 2 * s_hi_bits + 4 * c_bits)


vrf_verify_kernel = jax.jit(vrf_verify_core)


def vrf_verify_idx_xy_core(yY, xY, yG, signG, r, idx_rows):
    """Cached-Y form: the pool key's affine x arrives from the A128Cache
    (pool keys repeat across a whole epoch of headers), skipping one of
    the two pow-chain decompressions.  Row 128 (okY) is constant-true —
    the host folds the cache's `known` mask into parse_ok instead."""
    n = yY.shape[1]
    one = F.one_like(yY)
    xG, okG = EJ.device_decompress(yG, signG)
    H = _double3(elligator2_fraction(r))
    G8 = _double3((xG, yG, one, F.mul(xG, yG)))
    nYx = F.sub(yY * 0, xY)
    nGx = F.sub(yG * 0, xG)
    B = (F.const_batch(_GX, n), F.const_batch(_GY, n), one,
         F.const_batch(_GX * _GY % ed.P, n))
    Bp = (F.const_batch(_G2X, n), F.const_batch(_G2Y, n), one,
          F.const_batch(_G2X * _G2Y % ed.P, n))
    Hp = _double_n(H, 128)
    negY = (nYx, yY, one, F.mul(nYx, yY))
    negG = (nGx, yG, one, F.mul(nGx, yG))
    P1 = tuple(jnp.concatenate([B[c], H[c]], axis=1) for c in range(4))
    P1p = tuple(jnp.concatenate([Bp[c], Hp[c]], axis=1) for c in range(4))
    P2 = tuple(jnp.concatenate([negY[c], negG[c]], axis=1)
               for c in range(4))
    idx2 = jnp.concatenate([idx_rows, idx_rows], axis=1)
    UV = _triple_ladder_idx(P1, P1p, P2, idx2)
    Zall = jnp.concatenate([H[2], UV[2], G8[2]], axis=1)
    Zi = EJ.pow_inv(Zall)
    Xall = jnp.concatenate([H[0], UV[0], G8[0]], axis=1)
    Yall = jnp.concatenate([H[1], UV[1], G8[1]], axis=1)
    comp = compress_device(F.mul(Xall, Zi), F.mul(Yall, Zi))
    ones = okG.astype(jnp.int32) * 0 + 1
    rows = jnp.concatenate([comp[:, :n], comp[:, n:2 * n],
                            comp[:, 2 * n:3 * n], comp[:, 3 * n:],
                            ones[None, :],
                            okG.astype(jnp.int32)[None, :]], axis=0)
    return rows.T.astype(jnp.uint8)


def _vrf_idx_rows(c_words, s_words):
    """(4, N) challenge words + (8, N) scalar words -> (128, N) digits."""
    rows = []
    for i in range(128):
        rows.append(F.bit_from_words(s_words, 127 - i)
                    + 2 * F.bit_from_words(s_words, 255 - i)
                    + 4 * F.bit_from_words(c_words, 127 - i))
    return jnp.stack(rows)


def vrf_verify_words_core(Yw, xYw, Gw, signG, rw, cw, sw):
    """Packed-words form: 256-bit inputs as (8, N) uint32 word rows (the
    challenge as (4, N)); unpacking happens on device; Y's affine x comes
    pre-resolved from the point cache.  Transfer-thin — see field_jax
    packed-I/O notes."""
    return vrf_verify_idx_xy_core(
        F.limbs_from_words(Yw), F.limbs_from_words(xYw),
        F.limbs_from_words(Gw), signG,
        F.limbs_from_words(rw), _vrf_idx_rows(cw, sw))


vrf_verify_words_kernel = jax.jit(vrf_verify_words_core)


# challenge preimage prefix bytes (suite || 0x02), a host constant hoisted
# out of the jitted fold body
_SUITE2 = np.frombuffer(SUITE + b"\x02", dtype=np.uint8)


def challenge_ok_device(rows, gamma_bytes, c_bytes):
    """Device-side ECVRF challenge verdict from the kernel's (N, 130)
    output rows: c == SHA512(suite || 0x02 || H || Gamma || U || V)[:16]
    (vrf_ref._hash_points order), folded with the rows' decompression
    flags.  Returns (N,) bool.

    This is the device analog of the host loop in `_finish` — with it,
    the fused window program ships ONE fold scalar instead of 130 bytes
    per proof (sha512_jax has the transfer arithmetic).

    `gamma_bytes` is (N, 32) uint8 (proof bytes 0:32, the compressed
    Gamma), `c_bytes` (N, 16) uint8 (proof bytes 32:48) — both
    host-known inputs; H, U, V stay on device."""
    from . import sha512_jax as S
    n = rows.shape[0]
    prefix = jnp.broadcast_to(jnp.asarray(_SUITE2), (n, 2))
    msg = jnp.concatenate(
        [prefix, rows[:, 0:32], gamma_bytes.astype(jnp.uint8),
         rows[:, 32:96]], axis=1)
    c_match = S.prefix16_eq(msg, 130, c_bytes)
    okY = rows[:, 128].astype(bool)
    okG = rows[:, 129].astype(bool)
    return c_match & okY & okG


def vrf_verify_fold_words_core(Yw, xYw, Gw, signG, rw, cw, sw,
                               gamma_bytes, c_bytes, valid):
    """Packed-words verify + on-device challenge fold: (N,) uint8
    verdicts (valid & challenge & decompression flags) — the
    transfer-thin verdict form (16 B -> 1 B per 130 B row)."""
    rows = vrf_verify_words_core(Yw, xYw, Gw, signG, rw, cw, sw)
    ok = challenge_ok_device(rows, gamma_bytes, c_bytes)
    return (ok & (valid != 0)).astype(jnp.uint8)


vrf_verify_fold_words_kernel = jax.jit(vrf_verify_fold_words_core)


@jax.jit
def gamma8_kernel(yG, signG):
    """[8]Gamma compressed, for batched beta derivation (proof_to_hash).
    Returns (N, 33) uint8: compressed [8]Gamma + ok flag."""
    n = yG.shape[1]
    one = F.one_like(yG)
    xG, okG = EJ.device_decompress(yG, signG)
    G8 = _double3((xG, yG, one, F.mul(xG, yG)))
    Zi = EJ.pow_inv(G8[2])
    comp = compress_device(F.mul(G8[0], Zi), F.mul(G8[1], Zi))
    rows = jnp.concatenate([comp, okG.astype(jnp.int32)[None, :]], axis=0)
    return rows.T.astype(jnp.uint8)


def gamma8_words_core(Gw, signG):
    """Packed-words form of gamma8_kernel (unpack on device)."""
    yG = F.limbs_from_words(Gw)
    one = F.one_like(yG)
    xG, okG = EJ.device_decompress(yG, signG)
    G8 = _double3((xG, yG, one, F.mul(xG, yG)))
    Zi = EJ.pow_inv(G8[2])
    comp = compress_device(F.mul(G8[0], Zi), F.mul(G8[1], Zi))
    rows = jnp.concatenate([comp, okG.astype(jnp.int32)[None, :]], axis=0)
    return rows.T.astype(jnp.uint8)


gamma8_words_kernel = jax.jit(gamma8_words_core)


def _prepare_betas_words(proofs):
    """Packed-words host parse of a gamma8 batch: ((Gw, signG), ok)."""
    pf_arr, pf_ok = EJ._bytes_rows(proofs, PROOF_LEN)
    signG = (pf_arr[:, 31] >> 7).astype(np.int32)
    okGc = EJ._y_canonical(pf_arr[:, :32])
    s_ok = EJ._scalar_lt_L(np.ascontiguousarray(pf_arr[:, 48:80]))
    g_clear = pf_arr[:, :32].copy()
    g_clear[:, 31] &= 0x7F
    return ((F.words_from_bytes_rows(g_clear), signG),
            pf_ok & okGc & s_ok)


# ---------------------------------------------------------------------------
# Host orchestration
# ---------------------------------------------------------------------------

def _bits128_from_le(rows: np.ndarray) -> np.ndarray:
    """(N, 16) little-endian scalar bytes -> (128, N) MSB-first int32
    bits (one 128-bit ladder half)."""
    bits = np.flip(np.unpackbits(rows, axis=1, bitorder="little"), axis=1)
    return np.ascontiguousarray(bits.T).astype(np.int32)


def _r_limbs(vks, alphas) -> np.ndarray:
    """Elligator2 inputs: r = SHA512(suite || 0x01 || vk || alpha)[:32] with
    the top bit masked (vrf_ref._hash_to_curve:25-27)."""
    rows = bytearray()
    for vk, alpha in zip(vks, alphas):
        rows += hashlib.sha512(SUITE + b"\x01" + vk + alpha).digest()[:32]
    arr = np.frombuffer(bytes(rows), dtype=np.uint8).reshape(len(vks), 32)
    arr = arr.copy()
    arr[:, 31] &= 0x7F
    limbs, _sign, _ok = EJ._decode_compressed(arr)
    return limbs


def _default_runner(Yw, xYw, Gw, signG, rw, cw, sw):
    return vrf_verify_words_kernel(
        jnp.asarray(Yw), jnp.asarray(xYw), jnp.asarray(Gw),
        jnp.asarray(signG), jnp.asarray(rw), jnp.asarray(cw),
        jnp.asarray(sw))


def _prepare(vks, alphas, proofs):
    """Host-side parse of one padded batch into kernel inputs.

    Returns (kernel_args, parse_ok, gamma_ok, s_ok, pf_arr); kernel_args
    is the 8-tuple the verify kernels take (limbs + sign vectors + bit
    rows), so callers can dispatch it themselves (e.g. fused into one
    per-window device program)."""
    vk_arr, vk_ok = EJ._bytes_rows(vks, 32)
    pf_arr, pf_ok = EJ._bytes_rows(proofs, PROOF_LEN)
    yY, signY, okYc = EJ._decode_compressed(vk_arr)
    yG, signG, okGc = EJ._decode_compressed(pf_arr[:, :32])
    s_rows = np.ascontiguousarray(pf_arr[:, 48:80])
    s_ok = EJ._scalar_lt_L(s_rows)
    gamma_ok = pf_ok & okGc
    parse_ok = vk_ok & okYc & gamma_ok & s_ok
    args = (yY, signY.astype(np.int32), yG, signG.astype(np.int32),
            _r_limbs(vks, alphas),
            _bits128_from_le(np.ascontiguousarray(pf_arr[:, 32:48])),  # c
            _bits128_from_le(np.ascontiguousarray(s_rows[:, :16])),    # lo
            _bits128_from_le(np.ascontiguousarray(s_rows[:, 16:])))    # hi
    return args, parse_ok, gamma_ok, s_ok, pf_arr


def _r_rows(vks, alphas) -> np.ndarray:
    """Elligator2 input byte rows: r = SHA512(suite || 0x01 || vk ||
    alpha)[:32] with the top bit masked (vrf_ref._hash_to_curve:25-27)."""
    rows = bytearray()
    for vk, alpha in zip(vks, alphas):
        rows += hashlib.sha512(SUITE + b"\x01" + vk + alpha).digest()[:32]
    arr = np.frombuffer(bytes(rows), dtype=np.uint8).reshape(len(vks), 32)
    arr = arr.copy()
    arr[:, 31] &= 0x7F
    return arr


def _prepare_words(vks, alphas, proofs):
    """Packed-words host prep (the transfer-thin analog of _prepare).

    Returns (kernel_args, parse_ok, gamma_ok, s_ok, pf_arr) with
    kernel_args = (Yw, signY, Gw, signG, rw, cw, sw) — uint32 word rows
    for vrf_verify_words_kernel / the pallas packed kernel."""
    vk_arr, vk_ok = EJ._bytes_rows(vks, 32)
    pf_arr, pf_ok = EJ._bytes_rows(proofs, PROOF_LEN)
    signY = (vk_arr[:, 31] >> 7).astype(np.int32)
    signG = (pf_arr[:, 31] >> 7).astype(np.int32)
    okYc = EJ._y_canonical(vk_arr)
    okGc = EJ._y_canonical(pf_arr[:, :32])
    s_rows = np.ascontiguousarray(pf_arr[:, 48:80])
    s_ok = EJ._scalar_lt_L(s_rows)
    gamma_ok = pf_ok & okGc
    parse_ok = vk_ok & okYc & gamma_ok & s_ok
    vk_clear = vk_arr.copy()
    vk_clear[:, 31] &= 0x7F
    g_clear = pf_arr[:, :32].copy()
    g_clear[:, 31] &= 0x7F
    c_rows = np.ascontiguousarray(pf_arr[:, 32:48])
    cw = np.ascontiguousarray(
        c_rows.reshape(-1, 4, 4).view(np.uint32)[:, :, 0].T)
    args = (F.words_from_bytes_rows(vk_clear), signY,
            F.words_from_bytes_rows(g_clear), signG,
            F.words_from_bytes_rows(_r_rows(vks, alphas)), cw,
            F.words_from_bytes_rows(s_rows))
    return args, parse_ok, gamma_ok, s_ok, pf_arr


def _submit(vks, alphas, proofs, m, runner=None):
    """Parse + dispatch one padded batch; returns (device handle, masks,
    proof rows).  Does not block — callers may pipeline.  `runner` swaps
    the kernel invocation (packed-words signature: Yw, xYw, Gw, signG,
    rw, cw, sw — e.g. pallas_kernels.vrf_verify_pallas).  Y's affine x
    is resolved through the global point cache; unknown/bad keys fold
    into parse_ok."""
    from .precompute import GLOBAL_PRECOMPUTE_CACHE
    args, parse_ok, gamma_ok, s_ok, pf_arr = _prepare_words(vks, alphas,
                                                            proofs)
    Yw, _signY, Gw, signG, rw, cw, sw = args
    xa, _x128, _y128, known = GLOBAL_PRECOMPUTE_CACHE.assemble(list(vks))
    handle = (runner or _default_runner)(Yw, xa, Gw, signG, rw, cw, sw)
    return handle, parse_ok & known, gamma_ok, s_ok, pf_arr


def _finish(handle, parse_ok, gamma_ok, s_ok, pf_arr, n):
    rows = np.asarray(handle)                        # ONE transfer
    okY = rows[:, 128].astype(bool)
    okG = rows[:, 129].astype(bool)
    oks: list[bool] = []
    betas: list = []
    for j in range(n):
        row = rows[j]
        # beta is total given a decodable proof (Gamma decodes, s < L) —
        # the decode_proof precondition of vrf_ref.proof_to_hash
        if gamma_ok[j] and s_ok[j] and okG[j]:
            betas.append(hashlib.sha512(
                SUITE + b"\x03" + row[96:128].tobytes()).digest())
        else:
            betas.append(None)
        if not (parse_ok[j] and okY[j] and okG[j]):
            oks.append(False)
            continue
        c_prime = hashlib.sha512(
            SUITE + b"\x02" + row[0:32].tobytes() + bytes(pf_arr[j, :32])
            + row[32:64].tobytes() + row[64:96].tobytes()).digest()[:16]
        oks.append(c_prime == bytes(pf_arr[j, 32:48]))
    return oks, betas


def batch_verify_vrf(vks, alphas, proofs,
                     pad_to: int | None = None) -> tuple[list, list]:
    """Batched VRF verify; returns (ok list[bool], beta list[bytes|None]).

    beta[j] is the VRF output hash (proof_to_hash) whenever the proof
    decodes — independent of overall verification success, matching
    vrf_ref.proof_to_hash's totality."""
    n = len(vks)
    if n == 0:
        return [], []
    m = pad_to if pad_to and pad_to >= n else n
    vks = list(vks) + [b"\x00" * 32] * (m - n)
    alphas = list(alphas) + [b""] * (m - n)
    proofs = list(proofs) + [b"\x00" * PROOF_LEN] * (m - n)
    handle, parse_ok, gamma_ok, s_ok, pf_arr = _submit(vks, alphas,
                                                       proofs, m)
    return _finish(handle, parse_ok, gamma_ok, s_ok, pf_arr, n)


def _prepare_betas(proofs):
    """Host-side parse of a gamma8 batch: ((yG, signG), decode_ok)."""
    pf_arr, pf_ok = EJ._bytes_rows(proofs, PROOF_LEN)
    yG, signG, okGc = EJ._decode_compressed(pf_arr[:, :32])
    s_ok = EJ._scalar_lt_L(np.ascontiguousarray(pf_arr[:, 48:80]))
    return (yG, signG.astype(np.int32)), pf_ok & okGc & s_ok


def _submit_betas(proofs, m, runner=None):
    """Parse + dispatch a gamma8 batch; returns (handle, decode_ok).
    `runner` takes the packed-words pair (Gw, signG)."""
    (Gw, signG), decode_ok = _prepare_betas_words(proofs)
    if runner is None:
        handle = gamma8_words_kernel(jnp.asarray(Gw), jnp.asarray(signG))
    else:
        handle = runner(Gw, signG)
    return handle, decode_ok


def _finish_betas(rows: np.ndarray, decode_ok, n: int) -> list:
    ok = rows[:, 32].astype(bool) & decode_ok
    return [hashlib.sha512(SUITE + b"\x03" + rows[j, :32].tobytes()).digest()
            if ok[j] else None
            for j in range(n)]


def batch_betas(proofs, pad_to: int | None = None) -> list:
    """Batched proof_to_hash: beta bytes per proof, None where the proof
    does not decode (vrf_ref.proof_to_hash raises there)."""
    n = len(proofs)
    if n == 0:
        return []
    m = pad_to if pad_to and pad_to >= n else n
    proofs = list(proofs) + [b"\x00" * PROOF_LEN] * (m - n)
    handle, decode_ok = _submit_betas(proofs, m)
    return _finish_betas(np.asarray(handle), decode_ok, n)
