"""crypto — batched verification primitives for the consensus hot path.

CPU reference implementations (edwards / ed25519_ref / vrf_ref / kes) +
batched JAX device kernels (field_jax / ed25519_jax / vrf_jax) behind the
CryptoBackend seam (backend.py).  See SURVEY.md §2 (crypto accounting) and
BASELINE.md (north-star workloads).
"""
from .backend import (
    CpuRefBackend, CryptoBackend, Ed25519Req, KesReq, OpensslBackend,
    VrfReq, default_backend, set_default_backend,
)

__all__ = [
    "CpuRefBackend", "CryptoBackend", "Ed25519Req", "KesReq",
    "OpensslBackend", "VrfReq", "default_backend", "set_default_backend",
]
