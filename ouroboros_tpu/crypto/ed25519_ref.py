"""RFC 8032 Ed25519 sign/verify — pure-Python CPU reference backend.

Role in the framework: the DSIGN algorithm of the consensus protocol stack
(reference seam: cardano-crypto-class DSIGNAlgorithm, pinned to Ed25519DSIGN
in Shelley/Protocol/Crypto.hs:15-23).  The batched TPU path
(ed25519_jax.py) must agree bit-for-bit with this module; tests also
cross-check against the OpenSSL implementation in `cryptography`.
"""
from __future__ import annotations

from . import edwards as ed
from .edwards import BASE, L, P


def _clamp(k: bytes) -> int:
    a = bytearray(k)
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


def secret_expand(sk: bytes) -> tuple[int, bytes]:
    """seed -> (secret scalar, nonce prefix)."""
    h = ed.sha512(sk)
    return _clamp(h[:32]), h[32:]


def public_key_pure(sk: bytes) -> bytes:
    a, _ = secret_expand(sk)
    return ed.compress(ed.scalar_mult(a, BASE))


def sign_pure(sk: bytes, msg: bytes) -> bytes:
    a, prefix = secret_expand(sk)
    vk = ed.compress(ed.scalar_mult(a, BASE))
    r = ed.sha512_int(prefix, msg) % L
    R = ed.compress(ed.scalar_mult(r, BASE))
    k = ed.sha512_int(R, vk, msg) % L
    s = (r + k * a) % L
    return R + int.to_bytes(s, 32, "little")


# Ed25519 signing is deterministic (RFC 8032), so the OpenSSL path emits
# byte-identical keys/signatures at C speed — the pure functions above
# remain the spec and the cross-check oracle (tests/test_crypto_ref.py).
try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey as _SslKey,
    )

    def public_key(sk: bytes) -> bytes:
        return _SslKey.from_private_bytes(sk).public_key()\
            .public_bytes_raw()

    def sign(sk: bytes, msg: bytes) -> bytes:
        return _SslKey.from_private_bytes(sk).sign(msg)
except Exception:                                  # pragma: no cover
    public_key = public_key_pure
    sign = sign_pure


def verify(vk: bytes, msg: bytes, sig: bytes) -> bool:
    """RFC 8032 verify: [s]B == R + [k]A  (cofactorless, as libsodium)."""
    if len(sig) != 64 or len(vk) != 32:
        return False
    A = ed.decompress(vk)
    R = ed.decompress(sig[:32])
    if A is None or R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = ed.sha512_int(sig[:32], vk, msg) % L
    sB = ed.scalar_mult(s, BASE)
    kA = ed.scalar_mult(k, A)
    return ed.pt_equal(sB, ed.pt_add(R, kA))


def verify_prepared(A, R, s: int, k: int) -> bool:
    """Verify from pre-decoded points/scalars (the shape the batched device
    kernel consumes: hashing+decompression on host, group math on device)."""
    sB = ed.scalar_mult(s, BASE)
    kA = ed.scalar_mult(k, A)
    return ed.pt_equal(sB, ed.pt_add(R, kA))
