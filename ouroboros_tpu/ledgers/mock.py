"""Mock UTxO ledger — the SimpleBlock ledger analog.

Reference: ouroboros-consensus-mock/src/Ouroboros/Consensus/Mock/Ledger/
{UTxO,State}.hs — transactions spend (txid, ix) inputs into (addr, amount)
outputs; applying a block updates the UTxO set.  We add Ed25519 witnesses
(one per spending address, signature over the tx id) so the mock exercises
the same body-crypto seam the reference's Shelley BBODY does
(Shelley/Ledger/Ledger.hs:279 witness multi-verify) — these are the
batchable body proofs.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..chain.block import Point, point_of
from ..consensus.ledger import LedgerError, LedgerRules
from ..crypto import ed25519_ref
from ..crypto.backend import Ed25519Req
from ..utils import cbor


@dataclass(frozen=True)
class TxIn:
    txid: bytes
    ix: int

    def encode(self):
        return [self.txid, self.ix]

    @classmethod
    def decode(cls, obj):
        return cls(bytes(obj[0]), int(obj[1]))


@dataclass(frozen=True)
class TxOut:
    addr: bytes                       # = Ed25519 vk of the owner
    amount: int

    def encode(self):
        return [self.addr, self.amount]

    @classmethod
    def decode(cls, obj):
        return cls(bytes(obj[0]), int(obj[1]))


@dataclass(frozen=True)
class Tx:
    inputs: tuple                     # TxIn
    outputs: tuple                    # TxOut
    witnesses: tuple = ()             # (vk, sig-over-txid) pairs

    _cache: dict = field(default_factory=dict, repr=False, hash=False,
                         compare=False)

    @property
    def txid(self) -> bytes:
        c = self._cache
        if "id" not in c:
            body = cbor.dumps([[i.encode() for i in self.inputs],
                               [o.encode() for o in self.outputs]])
            c["id"] = hashlib.blake2b(body, digest_size=32).digest()
        return c["id"]

    def encode(self):
        return [[i.encode() for i in self.inputs],
                [o.encode() for o in self.outputs],
                [[vk, sig] for vk, sig in self.witnesses]]

    @classmethod
    def decode(cls, obj):
        return cls(tuple(TxIn.decode(i) for i in obj[0]),
                   tuple(TxOut.decode(o) for o in obj[1]),
                   tuple((bytes(vk), bytes(sig)) for vk, sig in obj[2]))


def make_tx(inputs: Sequence[TxIn], outputs: Sequence[TxOut],
            signing_keys: Sequence[bytes]) -> Tx:
    """Build and witness a tx: one signature over the txid per signing key."""
    tx = Tx(tuple(inputs), tuple(outputs))
    wits = tuple((ed25519_ref.public_key(sk), ed25519_ref.sign(sk, tx.txid))
                 for sk in signing_keys)
    return Tx(tx.inputs, tx.outputs, wits)


@dataclass(frozen=True)
class MockLedgerState:
    utxo: tuple                       # sorted ((txid, ix, addr, amount), ...)
    slot: int                         # last applied slot (tick clock)
    tip: Point

    def utxo_dict(self) -> dict:
        return {(t, i): (a, m) for t, i, a, m in self.utxo}

    def state_hash(self) -> bytes:
        """Deterministic digest for replay-parity checks (BASELINE.md
        'byte-identical ChainDB state')."""
        enc = cbor.dumps([[t, i, a, m] for t, i, a, m in self.utxo]
                         + [self.slot, self.tip.encode()])
        return hashlib.blake2b(enc, digest_size=32).digest()


def _freeze(utxo: dict) -> tuple:
    return tuple(sorted((t, i, a, m)
                 for (t, i), (a, m) in utxo.items()))


class MockLedger(LedgerRules):
    """LedgerRules over MockLedgerState.

    genesis: {addr: amount} initial distribution (spendable as inputs of
    the all-zero txid)."""

    GENESIS_TXID = b"\x00" * 32

    def __init__(self, genesis: dict):
        self.genesis = dict(genesis)

    def initial_state(self) -> MockLedgerState:
        utxo = {(self.GENESIS_TXID, ix): (addr, amount)
                for ix, (addr, amount) in enumerate(
                    sorted(self.genesis.items()))}
        return MockLedgerState(_freeze(utxo), -1, Point.genesis())

    def tip(self, state: MockLedgerState) -> Point:
        return state.tip

    def tick(self, state: MockLedgerState, slot: int) -> MockLedgerState:
        return MockLedgerState(state.utxo, slot, state.tip)

    # -- structural application (shared by apply/reapply) --------------------
    def _apply_txs(self, state: MockLedgerState, block) -> MockLedgerState:
        utxo = state.utxo_dict()
        for tx in block.body:
            if len({(i.txid, i.ix) for i in tx.inputs}) != len(tx.inputs):
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} has duplicate inputs")
            spent = 0
            for i in tx.inputs:
                key = (i.txid, i.ix)
                if key not in utxo:
                    raise LedgerError(
                        f"missing input {i.txid.hex()[:12]}#{i.ix}")
                spent += utxo[key][1]
            if any(o.amount < 0 for o in tx.outputs):
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} has a negative output")
            produced = sum(o.amount for o in tx.outputs)
            if produced > spent:
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} produces {produced} > "
                    f"spends {spent}")
            for i in tx.inputs:
                del utxo[(i.txid, i.ix)]
            for ix, o in enumerate(tx.outputs):
                utxo[(tx.txid, ix)] = (o.addr, o.amount)
        return MockLedgerState(_freeze(utxo), state.slot, point_of(block))

    def check_tx_witnesses(self, state: MockLedgerState, tx: Tx) -> None:
        """Structural witness check: every spending address has a witness.
        (Signature validity itself is the batchable proof.)"""
        utxo = state.utxo_dict()
        witness_vks = {vk for vk, _ in tx.witnesses}
        for i in tx.inputs:
            key = (i.txid, i.ix)
            if key in utxo and utxo[key][0] not in witness_vks:
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} spends from "
                    f"{utxo[key][0].hex()[:12]} without a witness")

    def sequential_checks(self, ticked: MockLedgerState, block) -> None:
        for tx in block.body:
            self.check_tx_witnesses(ticked, tx)

    def apply_block(self, ticked: MockLedgerState, block,
                    backend=None) -> MockLedgerState:
        from ..crypto.backend import default_backend
        backend = backend or default_backend()
        self.sequential_checks(ticked, block)
        reqs = self.extract_proofs(ticked, block)
        if reqs:
            ok = backend.verify_ed25519_batch(reqs)
            if not all(ok):
                raise LedgerError(
                    f"invalid tx witness in block at slot {block.slot}")
        return self._apply_txs(ticked, block)

    def reapply_block(self, ticked: MockLedgerState, block) -> MockLedgerState:
        return self._apply_txs(ticked, block)

    def extract_proofs(self, ticked: MockLedgerState, block) -> list:
        return [Ed25519Req(vk=vk, msg=tx.txid, sig=sig)
                for tx in block.body for vk, sig in tx.witnesses]

    # -- tx-level interface for the mempool ----------------------------------
    def apply_tx(self, state: MockLedgerState, tx: Tx,
                 backend=None) -> MockLedgerState:
        """Validate one tx against `state` (mempool revalidation path)."""

        class _OneTxBlock:
            body = (tx,)
            slot = state.slot
            hash = state.tip.hash

            @property
            def header(self):
                return self
        blk = _OneTxBlock()
        self.check_tx_witnesses(state, tx)
        from ..crypto.backend import default_backend
        ok = (backend or default_backend()).verify_ed25519_batch(
            self.extract_proofs(state, blk))
        if not all(ok):
            raise LedgerError(f"tx {tx.txid.hex()[:12]}: bad witness")
        new = self._apply_txs(state, blk)
        return MockLedgerState(new.utxo, state.slot, state.tip)

    def tx_proofs(self, state: MockLedgerState, tx: Tx) -> list:
        """One tx's witness obligations (the batching-service admission
        seam): same requests apply_tx would verify inline."""
        return [Ed25519Req(vk=vk, msg=tx.txid, sig=sig)
                for vk, sig in tx.witnesses]

    def ledger_view(self, state: MockLedgerState):
        return None
