"""Ledger instantiations (the ouroboros-consensus-{mock,shelley,...} analog)."""
from .mock import MockLedger, MockLedgerState, Tx, TxIn, TxOut, make_tx

__all__ = ["MockLedger", "MockLedgerState", "Tx", "TxIn", "TxOut", "make_tx"]
