"""Sharded batched verification: shard_map over the window axis + psum.

Each device runs the Strauss ladder (crypto.ed25519_jax.verify_core) on its
shard of the proof window; a psum over the mesh axis aggregates the count of
fast-path-zero diffs (a device-side statistic; the exact accept decision
stays on host, crypto.ed25519_jax.finalize).  This is the multi-chip
"training step" of the framework: validation throughput scales linearly in
mesh size because the ladder needs no cross-example communication — the
collective rides ICI only for the final scalar.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crypto import ed25519_jax as EJ
from .mesh import WINDOW_AXIS

# jax.shard_map graduated from jax.experimental on newer jax; this tree
# must run on both (the container jax only ships the experimental name)
try:
    _shard_map = jax.shard_map
except AttributeError:                       # pragma: no cover - jax<0.5
    from jax.experimental.shard_map import shard_map as _shard_map


@functools.lru_cache(maxsize=8)
def build_sharded_verifier(mesh: Mesh):
    """Returns a jitted fn over sharded inputs:
    (yA, signA, yR, signR, s_bits, k_bits) -> (ok (N,), total_ok scalar).

    Inputs as in crypto.ed25519_jax.verify_full_kernel, batch axis sharded
    over the mesh's window axis; batch size must divide by mesh size.  The
    per-shard ladder needs no communication; the psum totals the accepted
    count over ICI.
    """
    axis = mesh.axis_names[0]
    spec2 = P(None, axis)
    spec1 = P(axis)

    def step(yA, signA, yR, signR, sb, kb):
        ok = EJ.verify_full_core(yA, signA, yR, signR, sb, kb)
        total = jax.lax.psum(jnp.sum(ok), axis)
        return ok, total

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=(spec2, spec1, spec2, spec1, spec2, spec2),
        out_specs=(spec1, P()))
    return jax.jit(mapped)


def sharded_batch_verify(vks, msgs, sigs, mesh: Mesh,
                         pad_to: int | None = None) -> list[bool]:
    """End-to-end sharded verify (host prep -> mesh kernel -> host accept)."""
    n = len(vks)
    if n == 0:
        return []
    d = mesh.devices.size
    m = pad_to if pad_to and pad_to >= n else n
    m = ((m + d - 1) // d) * d
    vks = list(vks) + [b"\x00" * 32] * (m - n)
    msgs = list(msgs) + [b""] * (m - n)
    sigs = list(sigs) + [b"\x00" * 64] * (m - n)
    arrays, parse_ok = EJ.prepare_bytes_batch(vks, msgs, sigs)
    fn = build_sharded_verifier(mesh)
    axis = mesh.axis_names[0]
    shard2 = NamedSharding(mesh, P(None, axis))
    shard1 = NamedSharding(mesh, P(axis))
    specs = [shard2, shard1, shard2, shard1, shard2, shard2]
    dev_arrays = [jax.device_put(a, s) for a, s in zip(arrays, specs)]
    ok, _total = fn(*dev_arrays)
    ok = np.asarray(ok)
    return [bool(o) and bool(p) for o, p in zip(ok[:n], parse_ok[:n])]


# ---------------------------------------------------------------------------
# Sharded VRF + the mesh-wide CryptoBackend
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=8)
def build_sharded_vrf(mesh: Mesh):
    """shard_map of crypto.vrf_jax.vrf_verify_core over the window axis:
    each device decompresses, maps Elligator2, and runs the split-scalar
    128-iteration ladders on its shard of the VRF batch — no cross-device
    communication (the proofs are independent), so throughput scales
    linearly over ICI."""
    from ..crypto import vrf_jax
    axis = mesh.axis_names[0]
    spec2 = P(None, axis)
    spec1 = P(axis)
    mapped = _shard_map(
        vrf_jax.vrf_verify_core, mesh=mesh,
        in_specs=(spec2, spec1, spec2, spec1, spec2, spec2, spec2, spec2),
        out_specs=P(axis, None))
    return jax.jit(mapped)


@functools.lru_cache(maxsize=8)
def build_sharded_gamma8(mesh: Mesh):
    from ..crypto import vrf_jax
    axis = mesh.axis_names[0]
    mapped = _shard_map(
        vrf_jax.gamma8_kernel.__wrapped__, mesh=mesh,
        in_specs=(P(None, axis), P(axis)),
        out_specs=P(axis, None))
    return jax.jit(mapped)


from ..crypto.backend import CryptoBackend


class ShardedJaxBackend(CryptoBackend):
    """CryptoBackend over a device mesh: Ed25519, VRF, and KES-leaf proof
    batches shard over the window axis (consensus/batch.py windows flow
    through the inherited verify_mixed unchanged — the batching seam is
    mesh-agnostic).

    The pipelined single-transfer path (submit_window/finish_window) is
    mesh-sharded too: one jitted program per window shape runs the Ed25519
    ladder + VRF ladders + next-window gamma8 with every batch sharded
    over the window axis, packing all results into ONE flat uint8 array —
    one launch and one host transfer per window regardless of mesh size
    (VERDICT r3 next-step 5; on a tunneled or multi-host link the fixed
    per-dispatch latency dominates exactly as on one chip).

    Cross-window precomputation cache threading: KES hash-path outcomes
    ride the shared cache (split_mixed_cached — one host Merkle walk per
    (pool, period) per process), and window input buffers are donated on
    real accelerators.  The Ed25519/VRF POINT entries are not consumed
    here yet: these mesh kernels run the bit-rows form and decompress on
    device; moving them to the packed-words/cached-x kernels (the
    single-chip forms) is the remaining step to key-free warm windows on
    a mesh."""

    def __init__(self, mesh: Mesh, min_bucket: int = 128):
        self.mesh = mesh
        self.name = f"jax-mesh-{mesh.devices.size}"
        self.min_bucket = min_bucket
        self._composites: dict = {}      # (ne, nv, nb) -> fused program
        # buffer donation for the per-window inputs (see JaxBackend):
        # fresh arrays every window, never read back -> donation-safe
        self._donate = mesh.devices.flat[0].platform in ("tpu", "gpu")

    def _pad(self, n: int) -> int:
        d = self.mesh.devices.size
        m = max(self.min_bucket, n)
        m = ((m + d - 1) // d) * d
        return m

    def verify_ed25519_batch(self, reqs):
        if not reqs:
            return []
        return sharded_batch_verify(
            [r.vk for r in reqs], [r.msg for r in reqs],
            [r.sig for r in reqs], self.mesh, pad_to=self._pad(len(reqs)))

    def _vrf_runner(self):
        fn = build_sharded_vrf(self.mesh)
        axis = self.mesh.axis_names[0]
        s2 = NamedSharding(self.mesh, P(None, axis))
        s1 = NamedSharding(self.mesh, P(axis))
        specs = (s2, s1, s2, s1, s2, s2, s2, s2)

        def run(*args):
            return fn(*(jax.device_put(np.asarray(a), s)
                        for a, s in zip(args, specs)))
        return run

    def verify_vrf_batch(self, reqs):
        # the mesh runners shard the limb/bit-rows kernel form, so prep
        # goes through vrf_jax._prepare directly (vrf_jax._submit moved
        # to the packed-words single-chip form in r5)
        if not reqs:
            return []
        from ..crypto import vrf_jax
        n = len(reqs)
        m = self._pad(n)
        vks = [r.vk for r in reqs] + [b"\x00" * 32] * (m - n)
        alphas = [r.alpha for r in reqs] + [b""] * (m - n)
        proofs = [r.proof for r in reqs] + [b"\x00" * 80] * (m - n)
        args, parse_ok, gamma_ok, s_ok, pf_arr = vrf_jax._prepare(
            vks, alphas, proofs)
        handle = self._vrf_runner()(*args)
        oks, _betas = vrf_jax._finish(handle, parse_ok, gamma_ok, s_ok,
                                      pf_arr, n)
        return oks

    def vrf_betas_batch(self, proofs):
        if not proofs:
            return []
        from ..crypto import vrf_jax
        n = len(proofs)
        m = self._pad(n)
        padded = list(proofs) + [b"\x00" * 80] * (m - n)
        fn = build_sharded_gamma8(self.mesh)
        axis = self.mesh.axis_names[0]
        s2 = NamedSharding(self.mesh, P(None, axis))
        s1 = NamedSharding(self.mesh, P(axis))
        (yG, signG), decode_ok = vrf_jax._prepare_betas(padded)
        handle = fn(jax.device_put(np.asarray(yG), s2),
                    jax.device_put(np.asarray(signG), s1))
        return vrf_jax._finish_betas(np.asarray(handle), decode_ok, n)

    # -- pipelined single-transfer window path ------------------------------

    def _window_composite(self, ne: int, nv: int, nb: int):
        """One jitted mesh program for a whole window (see
        crypto.jax_backend.JaxBackend._window_composite for the packed
        layout it must reproduce)."""
        key = (ne, nv, nb)
        fn = self._composites.get(key)
        if fn is not None:
            return fn
        from ..crypto import vrf_jax
        mesh = self.mesh
        axis = mesh.axis_names[0]
        spec2 = P(None, axis)
        spec1 = P(axis)

        ed_mapped = _shard_map(
            EJ.verify_full_core, mesh=mesh,
            in_specs=(spec2, spec1, spec2, spec1, spec2, spec2),
            out_specs=spec1) if ne else None
        vrf_mapped = _shard_map(
            vrf_jax.vrf_verify_core, mesh=mesh,
            in_specs=(spec2, spec1, spec2, spec1, spec2, spec2, spec2,
                      spec2),
            out_specs=P(axis, None)) if nv else None
        beta_mapped = _shard_map(
            vrf_jax.gamma8_kernel.__wrapped__, mesh=mesh,
            in_specs=(spec2, spec1),
            out_specs=P(axis, None)) if nb else None

        def call(ed_args, vrf_args, beta_args):
            parts = []
            if ed_args is not None:
                yA, signA2, yR, signR2, sb, kb = ed_args
                ok = ed_mapped(yA, signA2[0], yR, signR2[0], sb, kb)
                parts.append(ok.reshape(-1).astype(jnp.uint8))
            if vrf_args is not None:
                yY, sY2, yG, sG2, r, cb, lob, hib = vrf_args
                rows = vrf_mapped(yY, sY2[0], yG, sG2[0], r, cb, lob, hib)
                parts.append(rows.reshape(-1))
            if beta_args is not None:
                byG, bsG2 = beta_args
                parts.append(beta_mapped(byG, bsG2[0]).reshape(-1))
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        fn = jax.jit(call, donate_argnums=(0, 1, 2)) if self._donate \
            else jax.jit(call)
        from ..crypto.jax_backend import _compile_span_on_first_call
        fn = _compile_span_on_first_call(
            fn, f"sharded.composite({ne},{nv},{nb})"
                f"@mesh{len(self.mesh.devices.flat)}")
        self._composites[key] = fn
        return fn

    def prewarm_window(self, reqs, next_beta_proofs=()):
        """Run one full window for `reqs` NOW — compiling its sharded
        composite outside any timed/timeout-budgeted region — returning
        ``(wall_seconds, ok_vector)``: the seconds (dominated by XLA
        compile on a cold cache) plus the window's verdicts, so callers
        assert correctness on THIS run instead of paying a duplicate
        window for it.  MULTICHIP_r05 follow-up: a silent 4m25s compile
        inside the timed region turned into rc=124 with zero
        attribution; the dryrun now pre-warms and reports this number
        instead."""
        import time as _time
        from ..observe import spans as _ospans
        t0 = _time.perf_counter()
        with _ospans.span("sharded.prewarm", cat="compile"):
            ok, _ = self.finish_window(
                self.submit_window(reqs, next_beta_proofs))
        return _time.perf_counter() - t0, ok

    def submit_window(self, reqs, next_beta_proofs=()):
        """Mesh-sharded analog of JaxBackend.submit_window: same host
        prep, same packed result layout, batches sharded over the window
        axis.  Returns the opaque state finish_window consumes."""
        from ..observe import spans as _ospans
        with _ospans.span("window.submit", cat="dispatch"):
            return self._submit_window(reqs, next_beta_proofs)

    def _submit_window(self, reqs, next_beta_proofs=()):
        from ..crypto import vrf_jax
        # KES hash paths reduce on host here, but through the cross-
        # window outcome cache: a pool's per-period Merkle walk is
        # hashed once per process, not once per signature
        ed_reqs, ed_owner, vrf_reqs, vrf_owner, n = \
            self.split_mixed_cached(reqs)
        beta_proofs = list(dict.fromkeys(next_beta_proofs))
        ed_state = vrf_state = beta_state = None
        ne = nv = nb = 0
        ed_args = vrf_args = beta_args = None
        axis = self.mesh.axis_names[0]
        s2 = NamedSharding(self.mesh, P(None, axis))

        def put2(a):
            return jax.device_put(np.asarray(a), s2)

        if ed_reqs:
            ne = self._pad(len(ed_reqs))
            pad = ne - len(ed_reqs)
            arrays, parse_ok = EJ.prepare_bytes_batch(
                [r.vk for r in ed_reqs] + [b"\x00" * 32] * pad,
                [r.msg for r in ed_reqs] + [b""] * pad,
                [r.sig for r in ed_reqs] + [b"\x00" * 64] * pad)
            ed_state = (None, parse_ok)
            yA, signA, yR, signR, s_bits, k_bits = arrays
            ed_args = (put2(yA),
                       jax.device_put(signA.reshape(1, -1), s2),
                       put2(yR),
                       jax.device_put(signR.reshape(1, -1), s2),
                       put2(s_bits), put2(k_bits))
        if vrf_reqs:
            nv = self._pad(len(vrf_reqs))
            pad = nv - len(vrf_reqs)
            args, parse_ok, gamma_ok, s_ok, pf_arr = vrf_jax._prepare(
                [r.vk for r in vrf_reqs] + [b"\x00" * 32] * pad,
                [r.alpha for r in vrf_reqs] + [b""] * pad,
                [r.proof for r in vrf_reqs] + [b"\x00" * 80] * pad)
            vrf_state = (None, parse_ok, gamma_ok, s_ok, pf_arr)
            yY, signY, yG, signG, r_l, c_b, lo_b, hi_b = args
            vrf_args = (put2(yY),
                        jax.device_put(signY.reshape(1, -1), s2),
                        put2(yG),
                        jax.device_put(signG.reshape(1, -1), s2),
                        put2(r_l), put2(c_b), put2(lo_b), put2(hi_b))
        if beta_proofs:
            nb = self._pad(len(beta_proofs))
            padded = beta_proofs + [b"\x00" * 80] * (nb - len(beta_proofs))
            (yG, signG), decode_ok = vrf_jax._prepare_betas(padded)
            beta_state = (decode_ok,)
            beta_args = (put2(yG),
                         jax.device_put(signG.reshape(1, -1), s2))
        if ed_args is None and vrf_args is None and beta_args is None:
            packed = None
        else:
            packed = self._window_composite(ne, nv, nb)(
                ed_args, vrf_args, beta_args)
        return {"packed": packed, "n": n,
                "ed": ed_state, "ed_owner": ed_owner, "ne": ne,
                "vrf": vrf_state, "vrf_owner": vrf_owner,
                "vrf_n": len(vrf_reqs), "nv": nv,
                "beta": beta_state, "beta_proofs": beta_proofs, "nb": nb,
                # KES hash paths are reduced on host here
                # (split_mixed_cached); keys kept for the shared
                # finish_window
                "kes_checks": [], "nk": 0, "kes_n": 0}

    # identical packed layout -> identical host-side unpacking
    from ..crypto.jax_backend import JaxBackend as _JB
    finish_window = _JB.finish_window
    verify_mixed = _JB.verify_mixed
    del _JB
