"""Sharded batched verification: shard_map over the window axis + psum.

Each device runs the Strauss ladder (crypto.ed25519_jax.verify_core) on its
shard of the proof window; a psum over the mesh axis aggregates the count of
fast-path-zero diffs (a device-side statistic; the exact accept decision
stays on host, crypto.ed25519_jax.finalize).  This is the multi-chip
"training step" of the framework: validation throughput scales linearly in
mesh size because the ladder needs no cross-example communication — the
collective rides ICI only for the final scalar.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crypto import ed25519_jax as EJ
from .mesh import WINDOW_AXIS


@functools.lru_cache(maxsize=8)
def build_sharded_verifier(mesh: Mesh):
    """Returns a jitted fn over sharded inputs:
    (yA, signA, yR, signR, s_bits, k_bits) -> (ok (N,), total_ok scalar).

    Inputs as in crypto.ed25519_jax.verify_full_kernel, batch axis sharded
    over the mesh's window axis; batch size must divide by mesh size.  The
    per-shard ladder needs no communication; the psum totals the accepted
    count over ICI.
    """
    axis = mesh.axis_names[0]
    spec2 = P(None, axis)
    spec1 = P(axis)

    def step(yA, signA, yR, signR, sb, kb):
        ok = EJ.verify_full_core(yA, signA, yR, signR, sb, kb)
        total = jax.lax.psum(jnp.sum(ok), axis)
        return ok, total

    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(spec2, spec1, spec2, spec1, spec2, spec2),
        out_specs=(spec1, P()))
    return jax.jit(mapped)


def sharded_batch_verify(vks, msgs, sigs, mesh: Mesh,
                         pad_to: int | None = None) -> list[bool]:
    """End-to-end sharded verify (host prep -> mesh kernel -> host accept)."""
    n = len(vks)
    if n == 0:
        return []
    d = mesh.devices.size
    m = pad_to if pad_to and pad_to >= n else n
    m = ((m + d - 1) // d) * d
    vks = list(vks) + [b"\x00" * 32] * (m - n)
    msgs = list(msgs) + [b""] * (m - n)
    sigs = list(sigs) + [b"\x00" * 64] * (m - n)
    arrays, parse_ok = EJ.prepare_bytes_batch(vks, msgs, sigs)
    fn = build_sharded_verifier(mesh)
    axis = mesh.axis_names[0]
    shard2 = NamedSharding(mesh, P(None, axis))
    shard1 = NamedSharding(mesh, P(axis))
    specs = [shard2, shard1, shard2, shard1, shard2, shard2]
    dev_arrays = [jax.device_put(a, s) for a, s in zip(arrays, specs)]
    ok, _total = fn(*dev_arrays)
    ok = np.asarray(ok)
    return [bool(o) and bool(p) for o, p in zip(ok[:n], parse_ok[:n])]
