"""Sharded batched verification: shard_map over the window axis + psum.

Each device runs the Strauss ladder (crypto.ed25519_jax.verify_core) on its
shard of the proof window; a psum over the mesh axis aggregates the count of
fast-path-zero diffs (a device-side statistic; the exact accept decision
stays on host, crypto.ed25519_jax.finalize).  This is the multi-chip
"training step" of the framework: validation throughput scales linearly in
mesh size because the ladder needs no cross-example communication — the
collective rides ICI only for the final scalar.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crypto import ed25519_jax as EJ
from .mesh import WINDOW_AXIS

# jax.shard_map graduated from jax.experimental on newer jax; this tree
# must run on both (the container jax only ships the experimental name)
try:
    _shard_map = jax.shard_map
except AttributeError:                       # pragma: no cover - jax<0.5
    from jax.experimental.shard_map import shard_map as _shard_map


@functools.lru_cache(maxsize=8)
def build_sharded_verifier(mesh: Mesh):
    """Returns a jitted fn over sharded inputs:
    (yA, signA, yR, signR, s_bits, k_bits) -> (ok (N,), total_ok scalar).

    Inputs as in crypto.ed25519_jax.verify_full_kernel, batch axis sharded
    over the mesh's window axis; batch size must divide by mesh size.  The
    per-shard ladder needs no communication; the psum totals the accepted
    count over ICI.
    """
    axis = mesh.axis_names[0]
    spec2 = P(None, axis)
    spec1 = P(axis)

    def step(yA, signA, yR, signR, sb, kb):
        ok = EJ.verify_full_core(yA, signA, yR, signR, sb, kb)
        total = jax.lax.psum(jnp.sum(ok), axis)
        return ok, total

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=(spec2, spec1, spec2, spec1, spec2, spec2),
        out_specs=(spec1, P()))
    return jax.jit(mapped)


def sharded_batch_verify(vks, msgs, sigs, mesh: Mesh,
                         pad_to: int | None = None) -> list[bool]:
    """End-to-end sharded verify (host prep -> mesh kernel -> host accept)."""
    n = len(vks)
    if n == 0:
        return []
    d = mesh.devices.size
    m = pad_to if pad_to and pad_to >= n else n
    m = ((m + d - 1) // d) * d
    vks = list(vks) + [b"\x00" * 32] * (m - n)
    msgs = list(msgs) + [b""] * (m - n)
    sigs = list(sigs) + [b"\x00" * 64] * (m - n)
    arrays, parse_ok = EJ.prepare_bytes_batch(vks, msgs, sigs)
    fn = build_sharded_verifier(mesh)
    axis = mesh.axis_names[0]
    shard2 = NamedSharding(mesh, P(None, axis))
    shard1 = NamedSharding(mesh, P(axis))
    specs = [shard2, shard1, shard2, shard1, shard2, shard2]
    dev_arrays = [jax.device_put(a, s) for a, s in zip(arrays, specs)]
    ok, _total = fn(*dev_arrays)
    ok = np.asarray(ok)
    return [bool(o) and bool(p) for o, p in zip(ok[:n], parse_ok[:n])]


# ---------------------------------------------------------------------------
# Sharded VRF + the mesh-wide CryptoBackend
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=8)
def build_sharded_vrf(mesh: Mesh):
    """shard_map of crypto.vrf_jax.vrf_verify_core over the window axis:
    each device decompresses, maps Elligator2, and runs the split-scalar
    128-iteration ladders on its shard of the VRF batch — no cross-device
    communication (the proofs are independent), so throughput scales
    linearly over ICI."""
    from ..crypto import vrf_jax
    axis = mesh.axis_names[0]
    spec2 = P(None, axis)
    spec1 = P(axis)
    mapped = _shard_map(
        vrf_jax.vrf_verify_core, mesh=mesh,
        in_specs=(spec2, spec1, spec2, spec1, spec2, spec2, spec2, spec2),
        out_specs=P(axis, None))
    return jax.jit(mapped)


@functools.lru_cache(maxsize=8)
def build_sharded_gamma8(mesh: Mesh):
    from ..crypto import vrf_jax
    axis = mesh.axis_names[0]
    mapped = _shard_map(
        vrf_jax.gamma8_kernel.__wrapped__, mesh=mesh,
        in_specs=(P(None, axis), P(axis)),
        out_specs=P(axis, None))
    return jax.jit(mapped)


from ..crypto.backend import CryptoBackend  # noqa: F401  (re-export)
from ..crypto.jax_backend import JaxBackend


class ShardedJaxBackend(JaxBackend):
    """JaxBackend over a device mesh: the window path (submit_window /
    finish_window / verify_mixed and the fold=True verdict reduction) is
    INHERITED — only the fused window composite itself is replaced by a
    shard_map of the very same packed-words component cores over the
    window axis, and every batch input lands pre-sharded (`_dev`).

    Reusing the single-device composite body per shard is what makes the
    mesh path compile inside the multichip budget: the r5 mesh composite
    traced a mesh-wide monolith of the BIT-ROWS kernel forms (256-bit
    ladders over (256, N) rows), which XLA:CPU chewed on for 4m25s —
    the whole MULTICHIP_r05 rc=124.  The per-shard program here is the
    same split-ladder packed-words program the single-chip path compiles
    in seconds-to-a-minute, and its compiled executable persists in the
    XLA compile cache across processes (mesh.enable_compile_cache), so a
    warm container pays no compile at all.

    Inheriting the prep also threads the mesh path through the
    cross-window precomputation cache (crypto/precompute.py): pool-key
    decompression + split tables are served from cache, so warm mesh
    windows ship zero per-key device work — previously a single-chip-
    only property.  KES hash paths still reduce on host here (via the
    cached split), so the composite stays Ed25519+VRF+betas.

    The legacy bit-rows mesh API (sharded_batch_verify / verify_*_batch
    overrides below) is kept for the standalone-batch surface and its
    tests; the replay hot path never touches it."""

    def __init__(self, mesh: Mesh, min_bucket: int = 128):
        super().__init__(min_bucket=min_bucket, use_pallas=False,
                         autotune=False)
        self.mesh = mesh
        self.name = f"jax-mesh-{mesh.devices.size}"
        # buffer donation for the per-window inputs (see JaxBackend):
        # fresh arrays every window, never read back -> donation-safe
        self._donate = mesh.devices.flat[0].platform in ("tpu", "gpu")
        axis = mesh.axis_names[0]
        self._lane_sharding = NamedSharding(mesh, P(None, axis))

    def _pad(self, n: int) -> int:
        d = self.mesh.devices.size
        m = max(self.min_bucket, n)
        m = ((m + d - 1) // d) * d
        return m

    @property
    def n_shards(self) -> int:
        """padding_stats() reports lane occupancy per shard: _pad rounds
        every batch to a mesh multiple, so each device carries
        padded/n_shards lanes of which waste_frac are padding."""
        return int(self.mesh.devices.size)

    def _dev(self, a):
        # every window input is lane-axis-last: shard the lane axis
        return jax.device_put(np.asarray(a), self._lane_sharding)

    def _split_mixed_device(self, reqs):
        """Mesh windows reduce KES hash paths on host — through the
        cross-window outcome cache (one Merkle walk per (pool, period)
        per process) — so the sharded composite carries no Blake2b jobs.
        Same 8-tuple shape as the single-chip split, with empty KES
        slots."""
        ed_reqs, ed_owner, vrf_reqs, vrf_owner, n = \
            self.split_mixed_cached(reqs)
        return ed_reqs, ed_owner, vrf_reqs, vrf_owner, [], [], [], n

    def verify_ed25519_batch(self, reqs):
        if not reqs:
            return []
        return sharded_batch_verify(
            [r.vk for r in reqs], [r.msg for r in reqs],
            [r.sig for r in reqs], self.mesh, pad_to=self._pad(len(reqs)))

    def _vrf_runner(self):
        fn = build_sharded_vrf(self.mesh)
        axis = self.mesh.axis_names[0]
        s2 = NamedSharding(self.mesh, P(None, axis))
        s1 = NamedSharding(self.mesh, P(axis))
        specs = (s2, s1, s2, s1, s2, s2, s2, s2)

        def run(*args):
            return fn(*(jax.device_put(np.asarray(a), s)
                        for a, s in zip(args, specs)))
        return run

    def verify_vrf_batch(self, reqs):
        # the mesh runners shard the limb/bit-rows kernel form, so prep
        # goes through vrf_jax._prepare directly (vrf_jax._submit moved
        # to the packed-words single-chip form in r5)
        if not reqs:
            return []
        from ..crypto import vrf_jax
        n = len(reqs)
        m = self._pad(n)
        vks = [r.vk for r in reqs] + [b"\x00" * 32] * (m - n)
        alphas = [r.alpha for r in reqs] + [b""] * (m - n)
        proofs = [r.proof for r in reqs] + [b"\x00" * 80] * (m - n)
        args, parse_ok, gamma_ok, s_ok, pf_arr = vrf_jax._prepare(
            vks, alphas, proofs)
        handle = self._vrf_runner()(*args)
        oks, _betas = vrf_jax._finish(handle, parse_ok, gamma_ok, s_ok,
                                      pf_arr, n)
        return oks

    def vrf_betas_batch(self, proofs):
        if not proofs:
            return []
        from ..crypto import vrf_jax
        n = len(proofs)
        m = self._pad(n)
        padded = list(proofs) + [b"\x00" * 80] * (m - n)
        fn = build_sharded_gamma8(self.mesh)
        axis = self.mesh.axis_names[0]
        s2 = NamedSharding(self.mesh, P(None, axis))
        s1 = NamedSharding(self.mesh, P(axis))
        (yG, signG), decode_ok = vrf_jax._prepare_betas(padded)
        handle = fn(jax.device_put(np.asarray(yG), s2),
                    jax.device_put(np.asarray(signG), s1))
        return vrf_jax._finish_betas(np.asarray(handle), decode_ok, n)

    # -- pipelined single-transfer window path ------------------------------
    # submit_window / finish_window / verify_mixed / the fold=True path
    # are inherited from JaxBackend; only the composite is mesh-built.

    def _window_composite(self, ne: int, nv: int, nb: int, nk: int,
                          pallas: bool):
        """One jitted mesh program per window shape: shard_map of the
        SAME packed-words component cores the single-device composite
        fuses, each shard running the identical per-shard program, the
        results stitched into JaxBackend's flat uint8 layout (so
        finish_window and the fold program are shared verbatim).

        Tracing the per-shard body instead of a mesh-wide monolith is
        the compile-budget fix: XLA compiles one shard-sized program +
        the SPMD partitioning, not an N-lane super-program."""
        assert nk == 0, "mesh windows reduce KES on host"
        key = (ne, nv, nb, 0, False)
        fn = self._composites.get(key)
        if fn is not None:
            return fn
        from ..crypto import vrf_jax
        mesh = self.mesh
        axis = mesh.axis_names[0]
        s2 = P(None, axis)
        in_specs: list = []
        out_specs: list = []
        if ne:
            in_specs.append((s2,) * 8)
            out_specs.append(P(axis))
        if nv:
            in_specs.append((s2,) * 7)
            out_specs.append(P(axis, None))
        if nb:
            in_specs.append((s2,) * 2)
            out_specs.append(P(axis, None))

        def body(*present):
            i = 0
            outs = []
            if ne:
                Aw, xa, xw, yw, Rw, signR2, sw, kw = present[i]
                i += 1
                ok = EJ.verify_full_split_words_core(
                    Aw, xa, xw, yw, Rw, signR2[0], sw, kw)
                outs.append(ok.reshape(-1).astype(jnp.uint8))
            if nv:
                Yw, xa, Gw, sG2, rw, cw, sw_ = present[i]
                i += 1
                outs.append(vrf_jax.vrf_verify_words_core(
                    Yw, xa, Gw, sG2[0], rw, cw, sw_))
            if nb:
                bGw, bsG2 = present[i]
                i += 1
                outs.append(vrf_jax.gamma8_words_core(bGw, bsG2[0]))
            return tuple(outs)

        mapped = _shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                            out_specs=tuple(out_specs))

        def call(ed_args, vrf_args, beta_args, kes_args):
            present = [a for a in (ed_args, vrf_args, beta_args)
                       if a is not None]
            parts = [o.reshape(-1) for o in mapped(*present)]
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        fn = jax.jit(call, donate_argnums=(0, 1, 2, 3)) if self._donate \
            else jax.jit(call)
        from ..crypto.jax_backend import _compile_span_on_first_call
        fn = _compile_span_on_first_call(
            fn, f"sharded.composite({ne},{nv},{nb})"
                f"@mesh{len(self.mesh.devices.flat)}")
        self._composites[key] = fn
        return fn

    # prewarm_window is INHERITED from JaxBackend (ISSUE 11): the mesh
    # and single-device paths share the same compile-outside-timed-
    # regions contract, span name and return shape.
