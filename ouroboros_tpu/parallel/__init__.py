"""parallel — device-mesh scaling for the batched validation hot path.

The reference's parallelism is peers/threads/STM (SURVEY.md §2 "Parallelism
strategies"); its crypto hot path is strictly sequential.  Here the device
dimension is first-class: a window of independent proofs (the "sequence" of
headers being validated) is sharded over a jax.sharding.Mesh axis and each
chip runs the same branch-free ladder on its shard, with psum aggregation
over ICI.  No NCCL/MPI analog: collectives are XLA's.
"""
from .mesh import enable_compile_cache, log_compile_time, make_mesh
from .sharded_verify import (
    ShardedJaxBackend, build_sharded_verifier, sharded_batch_verify,
)

__all__ = ["ShardedJaxBackend", "enable_compile_cache",
           "log_compile_time", "make_mesh", "build_sharded_verifier",
           "sharded_batch_verify"]
