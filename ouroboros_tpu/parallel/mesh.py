"""Device mesh construction for sharded batch validation."""
from __future__ import annotations

import os
import sys
import tempfile
import time
from contextlib import contextmanager
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

from ..observe import metrics as _metrics
from ..observe import spans as _spans

WINDOW_AXIS = "window"   # the header-window (proof-batch) axis

# pre-bound (OBS002): log_compile_time is cold, but the handle is static
_LAST_COMPILE = _metrics.gauge("parallel.last_compile_secs", stable=False)


def enable_compile_cache(cache_dir: Optional[str] = None) -> str:
    """Point XLA at a persistent compilation cache (MULTICHIP_r05
    follow-up: the sharded ladder takes 4m+ to compile, which silently
    ate the whole multichip timeout budget on a cold container).  Safe
    to call repeatedly; returns the cache directory in effect.

    Uses the same default directory as bench.py so single-chip bench
    runs and mesh dryruns share compiled executables where shapes
    agree."""
    cache_dir = (cache_dir
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or os.path.join(tempfile.gettempdir(), "jax-ouro-cache"))
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # 0, not the default 1.0: the dryrun's tiny shapes compile in
        # under a second and would otherwise recompile on EVERY
        # container start without ever entering the persistent cache
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except (AttributeError, ValueError):
        pass    # older jax: the env var alone still enables the cache
    return cache_dir


@contextmanager
def log_compile_time(what: str, stream=None):
    """Wall-time a compile-heavy block and print one log line, so a
    multi-minute XLA compile shows up in the harness tail instead of
    looking like a hang until the timeout kills it.

    Also records a `compile` span and yields a result dict whose
    ``secs`` field carries the elapsed seconds after the block exits —
    callers that must REPORT compile cost (the multichip dryrun JSON)
    bind it: ``with log_compile_time(...) as ct: ...; ct["secs"]``."""
    stream = stream if stream is not None else sys.stderr
    out = {"what": what, "secs": None}
    t0 = time.perf_counter()
    print(f"[parallel] {what}: compiling...", file=stream, flush=True)
    span_cm = _spans.span(f"compile.{what}", cat="compile")
    span_cm.__enter__()
    try:
        yield out
    finally:
        span_cm.__exit__(None, None, None)
        out["secs"] = round(time.perf_counter() - t0, 3)
        _LAST_COMPILE.set(out["secs"])
        print(f"[parallel] {what}: done in {out['secs']:.1f}s",
              file=stream, flush=True)


def make_mesh(n_devices: Optional[int] = None,
              axis: str = WINDOW_AXIS) -> Mesh:
    """1-D mesh over the first n_devices devices.

    The framework's device-parallel dimension is the proof batch — the
    window of independent headers/tx-witnesses being validated (the
    sequence-parallel analog for a blockchain's 'sequence').  A 1-D mesh
    suffices because the ladder kernel has no cross-example communication;
    psum aggregation is the only collective.
    """
    # Honor JAX_PLATFORMS explicitly: some platform plugins (e.g. the axon
    # TPU tunnel) keep themselves as the default backend regardless, which
    # would silently ignore a requested virtual CPU mesh.
    plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() or None
    devs = jax.devices(plat) if plat else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    import numpy as np
    return Mesh(np.array(devs), (axis,))
