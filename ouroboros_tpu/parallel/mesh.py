"""Device mesh construction for sharded batch validation."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh


WINDOW_AXIS = "window"   # the header-window (proof-batch) axis


def make_mesh(n_devices: Optional[int] = None,
              axis: str = WINDOW_AXIS) -> Mesh:
    """1-D mesh over the first n_devices devices.

    The framework's device-parallel dimension is the proof batch — the
    window of independent headers/tx-witnesses being validated (the
    sequence-parallel analog for a blockchain's 'sequence').  A 1-D mesh
    suffices because the ladder kernel has no cross-example communication;
    psum aggregation is the only collective.
    """
    # Honor JAX_PLATFORMS explicitly: some platform plugins (e.g. the axon
    # TPU tunnel) keep themselves as the default backend regardless, which
    # would silently ignore a requested virtual CPU mesh.
    import os
    plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() or None
    devs = jax.devices(plat) if plat else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    import numpy as np
    return Mesh(np.array(devs), (axis,))
