"""ouroboros_tpu.observe — the unified observability layer.

Three parts, one seam (ISSUE 7):

- `metrics`: a process-wide registry of named counters/gauges/histograms
  with deterministic sorted snapshots.  The precompute cache stats, the
  autotuner's decision/frozen-write counters, subscription reconnects,
  watchdog firings and mux teardowns all live here.
- `spans`: hierarchical timing spans with explicit block_until_ready
  fencing, splitting every replay window into host-seq / dispatch /
  device / compile / sync phases.  Monotonic-clock only, sim-time aware
  (the same API yields virtual durations under simharness).
- `export`: Prometheus text exposition, chrome://tracing span dumps,
  and the typed-tracer-events -> JSONL bridge.
- `adapter`: NodeTracers -> metrics (typed protocol events count without
  string matching).
- `flight`: the always-on flight recorder — a bounded ring of recent
  spans/events/metric deltas, dumped as chrome-trace + JSONL on failure
  (ISSUE 9).
- `netmetrics`: bounded-cardinality per-peer network instruments — the
  `peer_label` LRU helper, labeled counters/gauges, and the mux traffic
  accounting (ISSUE 14).
- `propagation`: per-node block-propagation lifecycle timelines + the
  FleetTelemetry merge (time-to-adoption quantiles, per-edge delivery
  latency, partition healing) for chaos-fleet runs (ISSUE 14).
- `scrape` (imported on demand — it pulls the network stack): the live
  Prometheus scrape endpoint + periodic emitter over the project's own
  snocket/SDU transport.

Defaults: metric writes are ON (an enabled counter bump is one flag
read plus an int add) and span recording is OFF (spans allocate and
read clocks; the bench/tests enable them around regions they study).
Both layers are near-free when off — `spans.span()` returns a shared
null context manager, a gated metric write is a single flag read — and
`enable()/disable()` flip them together.  The migrated precompute/
autotune counters are `always=True`: they are load-bearing program
state (bench and tests assert on them) that the registry exports, not
observation that the flag may drop.
"""
from __future__ import annotations

from . import adapter, export, flight, metrics, netmetrics, propagation, \
    spans
from .adapter import counting_node_tracers, metrics_node_tracers
from .flight import FLIGHT, FlightRecorder
from .metrics import REGISTRY, Counter, Gauge, Histogram, MetricsRegistry
from .netmetrics import peer_label
from .propagation import FleetTelemetry, PropagationTracker
from .spans import RECORDER, Span, SpanRecorder, phase_totals, span

# NOTE: observe.scrape is deliberately NOT imported here — it pulls in
# the network stack (snocket/mux), which itself imports observe.metrics;
# consumers `from ouroboros_tpu.observe import scrape` on demand.

__all__ = [
    "FLIGHT", "FleetTelemetry", "FlightRecorder",
    "REGISTRY", "RECORDER", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "PropagationTracker", "Span", "SpanRecorder",
    "adapter", "counting_node_tracers", "disable", "enable", "enabled",
    "export", "flight", "metrics", "metrics_node_tracers", "netmetrics",
    "peer_label", "phase_totals", "propagation", "span", "spans",
]


def enable() -> None:
    """Turn on metrics writes and span recording."""
    metrics.REGISTRY.enable()
    spans.RECORDER.enable()


def disable() -> None:
    metrics.REGISTRY.disable()
    spans.RECORDER.disable()


def enabled() -> bool:
    return metrics.REGISTRY.enabled or spans.RECORDER.enabled
