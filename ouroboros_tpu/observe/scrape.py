"""Live metrics scrape endpoint + periodic emitter — over the project's
OWN network stack.

The reference ships no metrics server (SURVEY.md §5: "everything is
tracer events"); a production node serving millions of users needs its
queue-latency quantiles and replay progress observable WHILE it runs.
Rather than bolt on an HTTP stack, the endpoint speaks the mux SDU
framing over a Snocket bearer — the exact transport every mini-protocol
uses — which buys three properties for free:

- **one implementation, two interpreters**: under `SimSnocket` the whole
  request/response cycle is deterministic simulation (tested, race-
  explored); under `TcpSnocket`/`UnixSnocket` the SAME code serves real
  scrapes through network/socket_bearer.py;
- **sim-aware time**: the periodic emitter sleeps on the runtime clock,
  so tests see exact virtual emission times;
- **clean shutdown**: server/emitter are runtime threads with explicit
  `stop()` — cancel-and-join on every exit path, no leaked threads
  (asserted by tests and the bench --smoke scrape probe).

Wire format (protocol number 0x7A50, outside every mini-protocol's
range): the client sends one SDU whose payload is ``GET /metrics``; the
server replies with the Prometheus text exposition chunked into SDUs
and terminates with one empty-payload SDU.  Anything else closes the
connection.  `scrape()` is the matching client; tools/obsreport.py
``--live`` renders a scrape from the command line.
"""
from __future__ import annotations

from typing import Callable, Optional

from .. import simharness as sim
from ..network.mux import SDU
from ..network.snocket import Snocket
from . import export as _export
from . import metrics as _metrics

#: mux protocol number of the scrape endpoint (15-bit space; mini-
#: protocols live in 0..~20, so the top of the range is ours)
SCRAPE_PROTOCOL_NUM = 0x7A50
SCRAPE_REQUEST = b"GET /metrics"

_SCRAPES = _metrics.counter("observe.scrapes_served")
_EMITS = _metrics.counter("observe.emitter_ticks")


class ScrapeServer:
    """Serve `prometheus_text(registry)` to scrapers over a Snocket.

    Lifecycle: ``await start()`` binds + spawns the accept loop;
    ``await stop()`` closes the listener and cancel-joins the accept
    loop AND every in-flight connection handler — a handler blocked on
    a silent client must not outlive the server."""

    def __init__(self, snocket: Snocket, addr,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 include_unstable: bool = True):
        self.snocket = snocket
        self.addr = addr
        self.registry = (registry if registry is not None
                         else _metrics.REGISTRY)
        self.include_unstable = include_unstable
        self.listener = None
        self._accept_task = None
        self._conns: set = set()
        self._stopping = False

    async def start(self) -> "ScrapeServer":
        self.listener = await self.snocket.listen(self.addr)
        self._accept_task = sim.spawn(self._accept_loop(),
                                      label="scrape-accept")
        return self

    async def stop(self) -> None:
        self._stopping = True
        if self.listener is not None:
            self.listener.close()
        if self._accept_task is not None:
            await self._accept_task.cancel_wait()
        for conn in list(self._conns):
            await conn.cancel_wait()
        self._conns.clear()

    async def _accept_loop(self) -> None:
        while not self._stopping:
            bearer, remote = await self.listener.accept()
            # prune finished handlers so a long-lived endpoint holds
            # only live connections
            self._conns = {c for c in self._conns if not c.done}
            conn = sim.spawn(self._handle(bearer),
                             label=f"scrape-conn-{remote}")
            self._conns.add(conn)

    async def _handle(self, bearer) -> None:
        try:
            req = await bearer.read()
            if req.num != SCRAPE_PROTOCOL_NUM \
                    or req.payload != SCRAPE_REQUEST:
                return
            text = _export.prometheus_text(
                self.registry, include_unstable=self.include_unstable)
            await send_chunked(bearer, text.encode())
            _SCRAPES.inc()
        finally:
            close = getattr(bearer, "close", None)
            if close:
                close()


async def send_chunked(bearer, payload: bytes) -> None:
    """Chunk `payload` into SDUs sized to the bearer and terminate with
    one empty SDU (the end-of-exposition marker)."""
    chunk = min(getattr(bearer, "sdu_size", 12288), 0xFFFF - 8)
    for off in range(0, len(payload), chunk):
        await bearer.write(SDU(0, 0, SCRAPE_PROTOCOL_NUM,
                               payload[off:off + chunk]))
    await bearer.write(SDU(0, 0, SCRAPE_PROTOCOL_NUM, b""))


async def scrape(snocket: Snocket, addr) -> str:
    """Dial `addr` and fetch the exposition text (the Prometheus-scraper
    analog; parse with export.parse_prometheus_text)."""
    bearer = await snocket.connect(addr)
    try:
        await bearer.write(SDU(0, 0, SCRAPE_PROTOCOL_NUM, SCRAPE_REQUEST))
        chunks = []
        while True:
            sdu = await bearer.read()
            if not sdu.payload:
                break
            chunks.append(sdu.payload)
        return b"".join(chunks).decode()
    finally:
        close = getattr(bearer, "close", None)
        if close:
            close()


class PeriodicEmitter:
    """Emit a registry snapshot every `interval` runtime seconds.

    `emit(text)` receives the Prometheus exposition (default) or
    whatever `render(registry)` returns — e.g. a JSONL line per tick
    for a log pipeline.  Runs as a runtime thread on the active clock:
    exact virtual cadence under simharness, wall cadence in production.
    ``await stop()`` cancel-joins the thread — clean shutdown on every
    exit path."""

    def __init__(self, interval: float, emit: Callable[[str], None],
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 render: Optional[Callable] = None):
        self.interval = interval
        self.emit = emit
        self.registry = (registry if registry is not None
                         else _metrics.REGISTRY)
        self.render = render or _export.prometheus_text
        self._task = None
        self._stopping = False

    async def start(self) -> "PeriodicEmitter":
        self._task = sim.spawn(self._loop(), label="observe-emitter")
        return self

    async def _loop(self) -> None:
        while not self._stopping:
            await sim.sleep(self.interval)
            if self._stopping:
                return
            self.emit(self.render(self.registry))
            _EMITS.inc()

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            await self._task.cancel_wait()
            self._task = None
