"""Flight recorder — a bounded ring of the last moments before a failure.

The reference stack keeps an always-on tracer seam precisely so a crash
leaves evidence (SURVEY.md §5); the bench-scoped observe/ layer from
ISSUE 7 cannot play that role — spans are drained per rep and metric
history is a point-in-time snapshot.  This module is the crash-proof
analog: while ARMED, every span close, every instrument write and any
`note()`d typed event lands in one process-wide ring
(`collections.deque(maxlen=N)` — appends are GIL-atomic, so the
pipelined replay's producer and consumer record concurrently without a
lock), and a failure path dumps the ring as

- ``flight.trace.json`` — the span entries as chrome://tracing
  `trace_event` JSON (load via chrome://tracing or ui.perfetto.dev);
- ``flight.jsonl``      — every ring entry in arrival order, one JSON
  object per line, ``kind`` ∈ {span, metric, event} (a header line
  leads with the dump reason and entry count).

Cost model: DISARMED is one attribute read per instrument write and per
span close (`flight is None`); ARMED adds one tuple build + deque
append.  Nothing is formatted until `dump()`.

Clock discipline matches observe/spans.py: entry timestamps come from
`monotonic_now()`, i.e. the active runtime's VIRTUAL clock under
simharness — a seeded threadnet failure therefore dumps byte-identical
bytes on every replay of the same seed (golden-tested), and a
production failure dumps real monotonic time.

Wired failure paths: consensus/pipeline.py dumps on a ReplayResult
error or a producer crash; testing/threadnet.py dumps the chaos sim's
trace tail when a seeded chaos run raises.  Arming is explicit
(`FLIGHT.arm()`), typically around a long replay or a chaos sweep;
``OURO_FLIGHT_DIR`` overrides the dump directory.
"""
from __future__ import annotations

import json
import os
import tempfile
from collections import deque
from typing import List, Optional

from . import export as _export
from . import metrics as _metrics
from . import spans as _spans

#: dumps are load-bearing evidence: count them whether or not
#: observation is enabled
_DUMPS = _metrics.counter("observe.flight_dumps", always=True)


def default_dump_dir() -> str:
    return os.environ.get("OURO_FLIGHT_DIR") or os.path.join(
        tempfile.gettempdir(), "ouro-flight")


class FlightRecorder:
    """The bounded ring + its arm/dump lifecycle.  One process-wide
    instance (`FLIGHT`) hooks the global registry and span recorder;
    tests build private ones against private registries/recorders."""

    def __init__(self, capacity: int = 4096,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 recorder: Optional[_spans.SpanRecorder] = None):
        self.capacity = capacity
        self.armed = False
        self._reg = registry if registry is not None else _metrics.REGISTRY
        self._rec = recorder if recorder is not None else _spans.RECORDER
        self._ring: deque = deque(maxlen=capacity)
        self._was_rec_enabled = False

    # -- lifecycle -----------------------------------------------------------
    def arm(self, capacity: Optional[int] = None) -> "FlightRecorder":
        """Start recording.  Span recording is forced on while armed (a
        flight recorder without spans records nothing worth replaying);
        the recorder's prior state is restored on disarm.  Re-arming an
        armed recorder is a no-op state-wise — the ORIGINAL pre-arm
        recorder state survives, so nested arm/disarm pairs cannot leave
        span recording forced on forever."""
        if capacity is not None and capacity != self._ring.maxlen:
            self._ring = deque(self._ring, maxlen=capacity)
        if not self.armed:
            self._was_rec_enabled = self._rec.enabled
        self.armed = True
        self._rec.enabled = True
        self._reg.flight = self
        self._rec.flight = self
        return self

    def disarm(self) -> None:
        self.armed = False
        if self._reg.flight is self:
            self._reg.flight = None
        if self._rec.flight is self:
            self._rec.flight = None
        if not self._was_rec_enabled:
            self._rec.enabled = False

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)

    # -- recording hooks (called from metrics/spans while armed) -------------
    def span(self, sp: _spans.Span) -> None:
        self._ring.append(
            (sp.t1, "span", sp.name, sp.cat, sp.t0, sp.t1))

    def metric(self, name: str, op: str, v) -> None:
        self._ring.append((_spans.monotonic_now(), "metric", name, op, v))

    def note(self, event, t: Optional[float] = None) -> None:
        """Record one typed event (utils/tracer.py dataclass or any
        object — rendered through the typed JSONL schema at dump time).
        Pass `t` when the event carries its own clock reading (a sim
        trace tail noted AFTER the simulation exited must keep the
        virtual times it happened at, not the wall clock of the
        post-mortem — the byte-identical-replay contract)."""
        if self.armed:
            self._ring.append((_spans.monotonic_now() if t is None
                               else t, "event", event))

    def tracer(self):
        """A live Tracer feeding the ring — plug into NodeTracers to make
        protocol events part of the flight record."""
        from ..utils.tracer import Tracer
        return Tracer(self.note)

    # -- dumping -------------------------------------------------------------
    def entries(self) -> List[tuple]:
        return list(self._ring)

    def _spans_of(self, entries) -> List[_spans.Span]:
        out = []
        for e in entries:
            if e[1] == "span":
                sp = _spans.Span(e[2], e[3], e[4])
                sp.t1 = e[5]
                out.append(sp)
        return out

    def dump(self, dir_path: Optional[str] = None,
             reason: str = "") -> dict:
        """Write the ring to `dir_path` (default OURO_FLIGHT_DIR or a
        tmp-rooted ouro-flight/) as chrome-trace + JSONL; returns the
        paths.  The ring is snapshotted once so a concurrent recorder
        thread cannot tear the dump."""
        dir_path = dir_path or default_dump_dir()
        os.makedirs(dir_path, exist_ok=True)
        entries = self.entries()
        trace_path = os.path.join(dir_path, "flight.trace.json")
        _export.write_chrome_trace(trace_path, self._spans_of(entries))
        jsonl_path = os.path.join(dir_path, "flight.jsonl")
        with open(jsonl_path, "w") as f:
            f.write(json.dumps(
                {"kind": "flight", "reason": reason,
                 "entries": len(entries)},
                separators=(",", ":")) + "\n")
            for e in entries:
                f.write(json.dumps(self._record(e),
                                   separators=(",", ":")) + "\n")
        _DUMPS.inc()
        return {"dir": dir_path, "trace": trace_path, "jsonl": jsonl_path}

    @staticmethod
    def _record(e: tuple) -> dict:
        t, kind = round(e[0], 9), e[1]
        if kind == "span":
            return {"t": t, "kind": kind, "name": e[2], "cat": e[3],
                    "t0": round(e[4], 9), "t1": round(e[5], 9)}
        if kind == "metric":
            return {"t": t, "kind": kind, "name": e[2], "op": e[3],
                    "v": e[4]}
        rec = {"t": t, "kind": "event"}
        rec.update(_export.event_record(e[2]))
        return rec

    def dump_on_failure(self, reason: str) -> Optional[dict]:
        """The failure-path entry point: a no-op unless armed, so the
        error paths that call it (pipeline, threadnet) stay free in
        normal runs."""
        if not self.armed:
            return None
        return self.dump(reason=reason)


#: the process-wide flight recorder (hooks REGISTRY + RECORDER)
FLIGHT = FlightRecorder()
