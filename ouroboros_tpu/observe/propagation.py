"""Block-propagation lifecycle tracking + fleet-wide aggregation (ISSUE 14).

The only question that matters on an O(100)-node diffusion net is "did
the fleet converge, and how fast did a block propagate" — the reference
answers it with per-peer network tracers whose timestamps an offline
tool correlates.  Here each node keeps a :class:`PropagationTracker`: a
bounded per-block-hash timeline of lifecycle stages on the RUNTIME
clock (exact virtual times under simharness, monotonic host time in
production):

    header_seen    first ChainSync roll-forward carrying the header
    fetch_decided  BlockFetch decision logic assigned the block to a peer
    body_arrived   the block body landed from a BlockFetch response
    validated      the header passed batched validation
    adopted        chain selection made the block part of our chain

Each mark feeds the ``net.propagation.*`` stage-delta histograms and
(when a tracer is attached) emits a typed :class:`TraceBlockPropagation`
event, so the lifecycle is visible live on the scrape endpoint AND in
the typed event log.

:class:`FleetTelemetry` merges per-node timelines into the fleet
report: time-to-50%/95%-adoption quantiles, per-edge delivery latency
(receiver's first-header-seen minus the sender's adoption), partition
healing times (first cross-partition delivery after the window closes),
and the per-peer mux byte accounting from
:mod:`observe.netmetrics`.  Every aggregate is a pure sorted-order
function of the recorded virtual timestamps, so two replays of one
seeded chaos run produce byte-identical reports (the ISSUE 14
acceptance gate).
"""
from __future__ import annotations

import math
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from . import metrics as _metrics
from . import netmetrics as _net
from .spans import monotonic_now as _now

STAGES = ("header_seen", "fetch_decided", "body_arrived", "validated",
          "adopted")

# stage-delta histograms, pre-bound (OBS002); only recorded when BOTH
# endpoints of the pair were marked on this node
_STAGE_HISTS: Dict[Tuple[str, str], _metrics.Histogram] = {
    ("header_seen", "fetch_decided"):
        _metrics.latency_histogram("net.propagation.header_to_decided_secs"),
    ("fetch_decided", "body_arrived"):
        _metrics.latency_histogram("net.propagation.decided_to_body_secs"),
    ("header_seen", "validated"):
        _metrics.latency_histogram("net.propagation.header_to_validated_secs"),
    ("body_arrived", "adopted"):
        _metrics.latency_histogram("net.propagation.body_to_adopted_secs"),
    ("header_seen", "adopted"):
        _metrics.latency_histogram("net.propagation.header_to_adopted_secs"),
}
_BLOCKS_TRACKED = _metrics.counter("net.propagation.blocks_tracked",
                                   stable=False)


@dataclass(frozen=True)
class TraceBlockPropagation:
    """Typed tracer event: one lifecycle stage of one block on one node.
    `t` is the runtime-clock reading the stage was recorded at."""
    node: str
    stage: str
    hash: bytes
    t: float
    peer: Any = None


class PropagationTracker:
    """One node's per-block lifecycle timeline, keyed by block hash.

    Bounded: at most `cap` block hashes are tracked; the oldest entry is
    evicted when a new hash arrives at capacity (a long-lived node must
    not accumulate a timeline per historical block).  `mark` records the
    FIRST time a stage is reached — later duplicates are ignored, so
    `header_seen` really is first-header-seen even with many peers."""

    def __init__(self, node: str = "node", cap: int = 4096, tracer=None):
        self.node = node
        self.cap = cap
        self.tracer = tracer
        # hash -> {stage: (t, peer)}
        self.timeline: "OrderedDict[bytes, dict]" = OrderedDict()

    def mark(self, stage: str, h: bytes, peer=None,
             t: Optional[float] = None) -> bool:
        """Record `stage` for block `h` at `t` (default: now on the
        runtime clock).  True when the stage was newly recorded."""
        entry = self.timeline.get(h)
        if entry is None:
            if len(self.timeline) >= self.cap:
                self.timeline.popitem(last=False)
            entry = self.timeline[h] = {}
            _BLOCKS_TRACKED.inc()
        if stage in entry:
            return False
        t = _now() if t is None else t
        entry[stage] = (t, peer)
        for (a, b), hist in _STAGE_HISTS.items():
            if b == stage and a in entry:
                hist.observe(t - entry[a][0])
        tracer = self.tracer
        if tracer is not None and tracer.active:
            tracer.trace(TraceBlockPropagation(
                node=self.node, stage=stage, hash=h, t=t, peer=peer))
        return True

    def stage_time(self, h: bytes, stage: str) -> Optional[float]:
        rec = self.timeline.get(h, {}).get(stage)
        return rec[0] if rec is not None else None

    def stage_peer(self, h: bytes, stage: str):
        rec = self.timeline.get(h, {}).get(stage)
        return rec[1] if rec is not None else None


def _quantile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Deterministic nearest-rank quantile over an already-sorted list."""
    if not sorted_vals:
        return None
    i = max(0, math.ceil(q * len(sorted_vals)) - 1)
    return round(sorted_vals[i], 9)


def _dist(vals: List[float]) -> dict:
    vals = sorted(vals)
    return {"n": len(vals),
            "p50": _quantile(vals, 0.50),
            "p95": _quantile(vals, 0.95),
            "max": round(vals[-1], 9) if vals else None}


class FleetTelemetry:
    """Merge per-node :class:`PropagationTracker` timelines into the
    fleet report.  `partitions` are the run's scheduled partitions
    (objects with ``start``/``end``/``groups``) so healing times can be
    attributed to the window that caused them."""

    def __init__(self, partitions=()):
        self.partitions = tuple(partitions)
        self.trackers: "OrderedDict[str, PropagationTracker]" = OrderedDict()

    def tracker(self, node: str, cap: int = 4096,
                tracer=None) -> PropagationTracker:
        """Create (or return) the tracker for `node` and register it."""
        t = self.trackers.get(node)
        if t is None:
            t = self.trackers[node] = PropagationTracker(
                node=node, cap=cap, tracer=tracer)
        return t

    def attach(self, tracker: PropagationTracker) -> None:
        self.trackers[tracker.node] = tracker

    # -- delivery edges ------------------------------------------------------
    def _deliveries(self) -> List[tuple]:
        """(t_received, sender, receiver, hash) for every first-header
        delivery whose sender had already adopted the block — the
        cross-node propagation events edge latency and partition healing
        are computed from.  The receiver's ChainSync peer id is
        `receiver->sender` (the initiator dials the server it pulls
        headers from)."""
        out = []
        for receiver in sorted(self.trackers):
            tr = self.trackers[receiver]
            for h in tr.timeline:
                rec = tr.timeline[h].get("header_seen")
                if rec is None or rec[1] is None:
                    continue
                t, peer = rec
                peer = str(peer)
                sender = peer.split("->", 1)[1] if "->" in peer else peer
                out.append((t, sender, receiver, h))
        out.sort(key=lambda d: (d[0], d[1], d[2], d[3]))
        return out

    def _group_of(self, partition, node: str) -> Optional[int]:
        for i, g in enumerate(partition.groups):
            if node in g:
                return i
        return None

    # -- the report ----------------------------------------------------------
    def report(self) -> dict:
        """The fleet report: a plain JSON-safe dict, byte-identical (via
        ``json.dumps(..., sort_keys=True)``) across replays of one
        seeded run."""
        nodes = sorted(self.trackers)
        n = len(nodes)
        need_50 = math.ceil(0.5 * n) if n else 0
        need_95 = math.ceil(0.95 * n) if n else 0

        # -- adoption quantiles ---------------------------------------------
        all_hashes = sorted({h for tr in self.trackers.values()
                             for h in tr.timeline})
        per_block: List[dict] = []
        to_50: List[float] = []
        to_95: List[float] = []
        for h in all_hashes:
            times = sorted(t for t in
                           (tr.stage_time(h, "adopted")
                            for tr in self.trackers.values())
                           if t is not None)
            if not times:
                continue
            t0 = times[0]
            row = {"hash": h.hex(), "nodes_adopted": len(times),
                   "t_first_adopted": round(t0, 9),
                   "to_50": None, "to_95": None}
            if need_50 and len(times) >= need_50:
                row["to_50"] = round(times[need_50 - 1] - t0, 9)
                to_50.append(row["to_50"])
            if need_95 and len(times) >= need_95:
                row["to_95"] = round(times[need_95 - 1] - t0, 9)
                to_95.append(row["to_95"])
            per_block.append(row)
        per_block.sort(key=lambda r: (r["t_first_adopted"], r["hash"]))

        # -- per-edge delivery latency --------------------------------------
        deliveries = self._deliveries()
        edge_lat: Dict[str, List[float]] = {}
        for t, sender, receiver, h in deliveries:
            sender_tr = self.trackers.get(sender)
            if sender_tr is None:
                continue
            st = sender_tr.stage_time(h, "adopted")
            if st is None or t < st:
                continue
            edge_lat.setdefault(f"{sender}->{receiver}",
                                []).append(t - st)

        # -- partition healing ----------------------------------------------
        healing: List[dict] = []
        for p in self.partitions:
            healed: Optional[float] = None
            for t, sender, receiver, _h in deliveries:
                if t < p.end:
                    continue
                gs = self._group_of(p, sender)
                gr = self._group_of(p, receiver)
                if gs is not None and gr is not None and gs != gr:
                    healed = round(t - p.end, 9)
                    break
            healing.append({"start": p.start, "end": p.end,
                            "healed_after_secs": healed})

        return {
            "nodes": nodes,
            "adoption": {
                "blocks": len(per_block),
                "fully_adopted_blocks": sum(
                    1 for r in per_block if r["nodes_adopted"] == n),
                "time_to_50": _dist(to_50),
                "time_to_95": _dist(to_95),
                "per_block": per_block,
            },
            "per_edge_delivery": {
                edge: _dist(edge_lat[edge]) for edge in sorted(edge_lat)},
            "partitions": healing,
            "mux": _net.mux_accounting(),
        }

    def report_json(self) -> str:
        import json
        return json.dumps(self.report(), sort_keys=True,
                          separators=(",", ":"))
