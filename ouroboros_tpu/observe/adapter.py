"""NodeTracers -> metrics adapter.

The node's typed event stream (utils/tracer.py dataclasses) becomes
registry counters WITHOUT string matching: each event counts under
`node.<subsystem>.<EventTypeName>`, keyed by the event's CLASS — the
typed log schema is the metric schema.  Events carrying an `n` field
(e.g. TraceChainSyncEvent batches) count by that weight.

`metrics_node_tracers()` builds a NodeTracers bundle whose tracers do
only this; `counting(tracer)` wraps an existing tracer so the events
still reach their original sink (sim trace, JSONL bridge) and are
counted on the way through.
"""
from __future__ import annotations

from ..utils.tracer import NodeTracers, Tracer
from . import metrics as _metrics


def _emit_for(subsystem: str, reg=None):
    reg = reg or _metrics.registry()
    counters: dict = {}           # event class -> Counter (no re-lookup)

    def emit(ev) -> None:
        cls = type(ev)
        c = counters.get(cls)
        if c is None:
            c = reg.counter(f"node.{subsystem}.{cls.__name__}")
            counters[cls] = c
        c.inc(getattr(ev, "n", 1))
    return emit


def metrics_tracer(subsystem: str, reg=None) -> Tracer:
    """A Tracer counting each event under node.<subsystem>.<EventType>."""
    return Tracer(_emit_for(subsystem, reg))


def counting(subsystem: str, inner: Tracer, reg=None) -> Tracer:
    """Count events AND forward them to `inner` (tee)."""
    emit = _emit_for(subsystem, reg)
    if not inner.active:
        return Tracer(emit)

    def both(ev) -> None:
        emit(ev)
        inner.trace(ev)
    return Tracer(both)


def metrics_node_tracers(reg=None) -> NodeTracers:
    """The per-subsystem bundle, every subsystem counting into the
    registry (protocol events become metrics with zero string
    matching)."""
    return NodeTracers(chain_db=metrics_tracer("chaindb", reg),
                       forge=metrics_tracer("forge", reg),
                       fetch=metrics_tracer("fetch", reg),
                       chain_sync=metrics_tracer("chainsync", reg))


def counting_node_tracers(inner: NodeTracers, reg=None) -> NodeTracers:
    """Wrap an existing bundle: events still reach their sinks, and are
    counted on the way through."""
    return NodeTracers(chain_db=counting("chaindb", inner.chain_db, reg),
                       forge=counting("forge", inner.forge, reg),
                       fetch=counting("fetch", inner.fetch, reg),
                       chain_sync=counting("chainsync", inner.chain_sync,
                                           reg))
