"""Bounded-cardinality per-peer network instruments (ISSUE 14).

The diffusion stack's natural metric dimensions — peer addresses,
protocol numbers, connection labels — are RUNTIME values: a registry
series per raw peer string is an unbounded-cardinality bomb on an
O(100)-node chaos net with churn (every redial mints a new connection
tag).  This module is the one sanctioned way a dynamic value becomes
part of a metric name:

- :class:`BoundedLabels` — an LRU-tracked label domain with a hard cap:
  the first `cap` distinct values get their own (sanitised) label, every
  later NEW value collapses into the shared ``overflow`` bucket, so the
  registry's labeled-series count is bounded by construction.  Values
  already admitted keep resolving to their own label forever (replays of
  a seeded run resolve identically).
- :func:`peer_label` — the process-wide peer domain (`addr -> label`).
- :func:`labeled_counter` / :func:`labeled_gauge` — registry instruments
  named ``base{k="v",...}`` with every label VALUE routed through a
  per-(base, key) bounded domain.  `export.prometheus_text` renders
  these as real Prometheus labeled series.

ouro-lint rule OBS003 enforces the seam: a metric name built by
f-string/concat from runtime values anywhere else in the package is a
finding — route it through here instead.

Cost discipline (the bench --smoke disabled-observation probe): every
label resolution bumps :data:`LABEL_FORMATS` (an ``always`` counter, so
it counts even while observation is off) — call sites like the mux hot
path must therefore guard on ``registry.enabled`` BEFORE touching this
module, and the probe asserts the counter stayed flat with observation
disabled.  Labeled series are ``stable=False``: peer sets vary run to
run, so they live in the live exposition, never the deterministic
snapshot bench embeds.

:class:`MuxIO` is the mux's per-connection traffic accounting: registry
series per (peer, protocol-number) plus plain-int local totals that
:class:`observe.propagation.FleetTelemetry` folds into the fleet report
(local ints, not registry reads, so two seeded replays report
byte-identical per-peer accounting regardless of what else the process
observed).
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

from . import metrics as _metrics

#: label a NEW value maps to once its domain is full
OVERFLOW_LABEL = "overflow"
#: default domain cap — generous for an O(100)-node net, small enough
#: that a runaway label source cannot swamp a scrape
DEFAULT_LABEL_CAP = 256

#: every label resolution (sanitise + LRU probe) counts here, whether or
#: not observation is enabled (`always`) — the disabled-observation probe
#: asserts ZERO resolutions happen on the mux hot path with metrics off
LABEL_FORMATS = _metrics.counter("net.labels.formatted", always=True,
                                 stable=False)
#: new values refused by a full domain (collapsed into `overflow`)
LABEL_OVERFLOWS = _metrics.counter("net.labels.overflowed", always=True,
                                   stable=False)


def _sanitize(value: str) -> str:
    """A label value safe inside the exposition's quoted string and the
    whitespace-split parser: quotes/backslashes/braces/whitespace out."""
    out = []
    for ch in value:
        out.append("_" if ch in '"\\{}' or ch.isspace() else ch)
    return "".join(out)


class BoundedLabels:
    """One label domain: at most `cap` distinct values ever get their
    own label; later new values share the overflow bucket.  Lookup keeps
    LRU order purely as recency bookkeeping — entries are never evicted,
    because an evicted-then-readmitted value would mint a second
    registry series and the cardinality bound would be a fiction."""

    def __init__(self, cap: int = DEFAULT_LABEL_CAP,
                 overflow: str = OVERFLOW_LABEL):
        self.cap = cap
        self.overflow = overflow
        self.overflows = 0
        self._lru: "OrderedDict[object, str]" = OrderedDict()

    def get(self, value) -> str:
        LABEL_FORMATS.inc()
        lru = self._lru
        got = lru.get(value)
        if got is not None:
            lru.move_to_end(value)
            return got
        if len(lru) >= self.cap:
            self.overflows += 1
            LABEL_OVERFLOWS.inc()
            return self.overflow
        label = _sanitize(str(value))
        lru[value] = label
        return label

    def __len__(self) -> int:
        return len(self._lru)


#: the process-wide peer domain: every peer address / connection label
#: that becomes part of a metric name resolves through this one cap
PEER_LABELS = BoundedLabels()


def peer_label(addr) -> str:
    """The bounded label for a peer address (LRU cap + overflow bucket):
    THE helper every per-peer metric name must route through."""
    return PEER_LABELS.get(addr)


# per-(base, key) domains for labeled_counter/labeled_gauge values that
# did not already come through peer_label — any dynamic value entering a
# metric name is bounded, whichever door it used
_DOMAINS: Dict[Tuple[str, str], BoundedLabels] = {}


def _bounded_value(base: str, key: str, value) -> str:
    dom = _DOMAINS.get((base, key))
    if dom is None:
        dom = _DOMAINS[(base, key)] = BoundedLabels()
    return dom.get(value)


def _labeled_name(base: str, labels: Dict[str, str]) -> str:
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{base}{{{inner}}}"


def labeled_counter(base: str, reg: Optional[_metrics.MetricsRegistry]
                    = None, **labels) -> _metrics.Counter:
    """A counter named ``base{k="v",...}`` with every label value
    bounded.  stable=False: labeled series are live-exposition data, not
    part of the deterministic snapshot."""
    reg = reg if reg is not None else _metrics.REGISTRY
    name = _labeled_name(base, {k: _bounded_value(base, k, v)
                                for k, v in labels.items()})
    return reg.counter(name, stable=False)


def labeled_gauge(base: str, reg: Optional[_metrics.MetricsRegistry]
                  = None, **labels) -> _metrics.Gauge:
    """The gauge analog of :func:`labeled_counter`."""
    reg = reg if reg is not None else _metrics.REGISTRY
    name = _labeled_name(base, {k: _bounded_value(base, k, v)
                                for k, v in labels.items()})
    return reg.gauge(name, stable=False)


# ---------------------------------------------------------------------------
# Mux traffic accounting
# ---------------------------------------------------------------------------

#: MuxIO instances born since the last reset_run_scope() — the seam
#: FleetTelemetry reads per-peer totals from (mux objects themselves are
#: buried inside connection runners).  Bounded: a long-lived node with
#: connection churn must not accumulate an entry per historical
#: connection forever (the registry series already aggregate per edge).
MUX_IO: "deque[MuxIO]" = deque(maxlen=4096)


def reset_run_scope() -> None:
    """Start a fresh accounting scope (run_chaos_threadnet calls this at
    the top of every run so two replays of one seed fold identical
    MuxIO sets into their fleet reports)."""
    MUX_IO.clear()


def _edge_of(label: str) -> str:
    """The stable edge identity of a mux label: `node0->node1#2.mux-i`
    -> `node0->node1` (redials of one edge aggregate into one series)."""
    return label.split(".mux", 1)[0].split("#", 1)[0]


def _side_of(label: str) -> str:
    if label.endswith(".mux-r"):
        return "r"
    return "i"          # `.mux-i`, plain `.mux` dialers, anything else


class MuxIO:
    """Per-connection mux ingress/egress accounting.

    Registry series per (peer-edge, side, protocol-number), built lazily
    once per protocol (the per-SDU path is two dict probes + two bound
    counter incs); plain-int per-proto totals for the fleet report.
    Construct ONLY under a ``registry.enabled`` guard — construction
    formats labels."""

    __slots__ = ("label", "edge", "side", "ingress_bytes", "egress_bytes",
                 "ingress_sdus", "egress_sdus", "_in", "_out", "_reg")

    def __init__(self, label: str,
                 reg: Optional[_metrics.MetricsRegistry] = None):
        self.label = str(label)
        self.edge = _edge_of(self.label)
        self.side = _side_of(self.label)
        self.ingress_bytes: Dict[int, int] = {}
        self.egress_bytes: Dict[int, int] = {}
        self.ingress_sdus: Dict[int, int] = {}
        self.egress_sdus: Dict[int, int] = {}
        self._in: Dict[int, tuple] = {}
        self._out: Dict[int, tuple] = {}
        self._reg = reg
        MUX_IO.append(self)

    def _handles(self, table: Dict[int, tuple], num: int,
                 direction: str) -> tuple:
        h = table.get(num)
        if h is None:
            peer = peer_label(self.edge)
            kw = {"peer": peer, "side": self.side, "proto": str(num)}
            h = (labeled_counter(f"net.mux.{direction}_bytes",
                                 reg=self._reg, **kw),
                 labeled_counter(f"net.mux.{direction}_sdus",
                                 reg=self._reg, **kw))
            table[num] = h
        return h

    def ingress(self, num: int, nbytes: int) -> None:
        b, s = self._handles(self._in, num, "ingress")
        b.inc(nbytes)
        s.inc()
        self.ingress_bytes[num] = self.ingress_bytes.get(num, 0) + nbytes
        self.ingress_sdus[num] = self.ingress_sdus.get(num, 0) + 1

    def egress(self, num: int, nbytes: int) -> None:
        b, s = self._handles(self._out, num, "egress")
        b.inc(nbytes)
        s.inc()
        self.egress_bytes[num] = self.egress_bytes.get(num, 0) + nbytes
        self.egress_sdus[num] = self.egress_sdus.get(num, 0) + 1

    def totals(self) -> dict:
        """Deterministic per-connection summary (sorted proto keys)."""
        def tot(d):
            return sum(d.values())
        return {"edge": self.edge, "side": self.side,
                "ingress_bytes": tot(self.ingress_bytes),
                "egress_bytes": tot(self.egress_bytes),
                "ingress_sdus": tot(self.ingress_sdus),
                "egress_sdus": tot(self.egress_sdus),
                "by_proto": {str(n): {
                    "in_bytes": self.ingress_bytes.get(n, 0),
                    "out_bytes": self.egress_bytes.get(n, 0),
                    "in_sdus": self.ingress_sdus.get(n, 0),
                    "out_sdus": self.egress_sdus.get(n, 0)}
                    for n in sorted(set(self.ingress_bytes)
                                    | set(self.egress_bytes))}}


def mux_accounting() -> dict:
    """Per-(edge, side) traffic totals aggregated over every MuxIO born
    in the current run scope — redials of one edge merge.  Sorted keys
    throughout: two seeded replays yield byte-identical JSON."""
    agg: Dict[Tuple[str, str], dict] = {}
    for io in MUX_IO:
        key = (io.edge, io.side)
        cur = agg.get(key)
        t = io.totals()
        if cur is None:
            agg[key] = t
            continue
        for f in ("ingress_bytes", "egress_bytes",
                  "ingress_sdus", "egress_sdus"):
            cur[f] += t[f]
        for n, row in t["by_proto"].items():
            dst = cur["by_proto"].setdefault(
                n, {"in_bytes": 0, "out_bytes": 0,
                    "in_sdus": 0, "out_sdus": 0})
            for f in row:
                dst[f] += row[f]
    return {f"{edge}|{side}": agg[(edge, side)]
            for edge, side in sorted(agg)}
