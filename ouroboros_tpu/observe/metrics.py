"""Process-wide metrics registry — named counters/gauges/histograms with
deterministic snapshots.

The reference threads a contravariant `Tracer m a` through every
constructor but ships no metrics layer; our reproduction had outgrown
its ad-hoc equivalents (private counters in crypto/precompute.py and
crypto/autotune.py, one-off breakdowns printed by bench.py).  This
module is the one seam they all migrate into.

Design constraints, in order:

1. **Near-free when disabled.**  Every observational write goes through
   one flag read (`registry.enabled`); a disabled registry performs NO
   instrument writes at all — asserted by the bench --smoke probe via
   `data_writes`, which counts gated writes that actually landed.
2. **Deterministic snapshots.**  `snapshot()` returns instruments in
   sorted name order with values that are pure functions of the workload
   at a fixed seed (counts, not wall times), so two bench runs emit
   byte-identical `metrics` sections and the output stays diffable.
   Instruments that hold measured durations or other run-varying values
   are created with `stable=False` and excluded from `snapshot()`
   (they still appear in the Prometheus exposition, which is allowed to
   vary run to run).
3. **Functional counters stay functional.**  The migrated precompute /
   autotune counters are *load-bearing* — tests and bench assertions
   gate on them (warm windows do zero fills; frozen tuners reject
   writes).  Those are created with `always=True`: they count whether or
   not observation is enabled, and their writes are not charged to
   `data_writes` (they are program state that happens to be exported,
   not observation).

Instruments can exist unregistered (``Counter("x")``): per-instance
caches in tests get private counters with the same API while only the
process-wide singletons bind into the global registry — two fresh
`PrecomputeCache` instances never fight over one name.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Tuple


class Counter:
    """Monotonic-by-convention numeric cell.  `value` is read/write so
    migrated call sites using `cache.hits += 1` keep working through a
    property alias."""

    kind = "counter"
    __slots__ = ("name", "value", "always", "stable", "_reg")

    def __init__(self, name: str, reg: Optional["MetricsRegistry"] = None,
                 always: bool = False, stable: bool = True):
        self.name = name
        self.value = 0
        self.always = always
        self.stable = stable
        self._reg = reg

    def inc(self, n: int = 1) -> None:
        reg = self._reg
        if self.always:
            self.value += n
        elif reg is not None and reg.enabled:
            self.value += n
            reg.data_writes += 1
        else:
            return
        if reg is not None and reg.flight is not None:
            reg.flight.metric(self.name, "inc", n)

    def snapshot_value(self):
        return self.value


class Gauge:
    """Last-write-wins numeric cell."""

    kind = "gauge"
    __slots__ = ("name", "value", "always", "stable", "_reg")

    def __init__(self, name: str, reg: Optional["MetricsRegistry"] = None,
                 always: bool = False, stable: bool = True):
        self.name = name
        self.value = 0
        self.always = always
        self.stable = stable
        self._reg = reg

    def set(self, v) -> None:
        reg = self._reg
        if self.always:
            self.value = v
        elif reg is not None and reg.enabled:
            self.value = v
            reg.data_writes += 1
        else:
            return
        if reg is not None and reg.flight is not None:
            reg.flight.metric(self.name, "set", v)

    def snapshot_value(self):
        return self.value


# default buckets suit the quantities this repo observes (queue depths,
# batch sizes, retry counts) — powers of two up to a replay window
DEFAULT_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256,
                                      512, 1024, 2048, 4096)

# fixed log-spaced latency bucket edges: 1µs doubling up to ~134s.  ONE
# shared vocabulary for every duration histogram (queue waits, span
# phases, submit→drain, arrival gaps) so quantiles from any two
# instruments — or two runs — are comparable bucket for bucket, and the
# exposition stays byte-stable for a fixed workload.
LATENCY_BUCKETS: Tuple[float, ...] = tuple(1e-6 * 2 ** i for i in range(28))


def quantile_from_buckets(buckets: Tuple[float, ...], counts: List,
                          q: float) -> float:
    """Deterministic quantile from per-bucket counts (len(counts) ==
    len(buckets) + 1, the final cell being the +inf overflow).

    rank = q * total observations; the answer interpolates linearly
    inside the bucket containing that rank ([0, b0] for the first, the
    top edge for overflow — an unbounded bucket cannot be interpolated).
    Pure integer/float arithmetic on the counts: two histograms with
    identical counts yield byte-identical quantiles regardless of
    observation or creation order."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0
    for i, c in enumerate(counts[:-1]):
        prev, cum = cum, cum + c
        if c and cum >= rank:
            lo = buckets[i - 1] if i else 0.0
            hi = buckets[i]
            return round(lo + (hi - lo) * (rank - prev) / c, 9)
    return float(buckets[-1]) if buckets else 0.0


class Histogram:
    """Fixed-bucket histogram (cumulative counts on export, per the
    Prometheus convention; stored per-bucket so observe() is one index
    update).  `buckets=LATENCY_BUCKETS` makes it the log-bucket latency
    form with deterministic p50/p95/p99 via `quantiles()`."""

    kind = "histogram"
    __slots__ = ("name", "buckets", "counts", "total", "count", "always",
                 "stable", "_reg")

    def __init__(self, name: str, reg: Optional["MetricsRegistry"] = None,
                 buckets: Iterable[float] = DEFAULT_BUCKETS,
                 always: bool = False, stable: bool = True):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)   # +inf overflow
        self.total = 0.0
        self.count = 0
        self.always = always
        self.stable = stable
        self._reg = reg

    def _record(self, v) -> None:
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += v
        self.count += 1

    def observe(self, v) -> None:
        reg = self._reg
        if self.always:
            self._record(v)
        elif reg is not None and reg.enabled:
            self._record(v)
            reg.data_writes += 1
        else:
            return
        if reg is not None and reg.flight is not None:
            reg.flight.metric(self.name, "observe", v)

    def quantile(self, q: float) -> float:
        """Deterministic q-quantile (0 < q < 1) from the bucket counts —
        see quantile_from_buckets.  p50/p95/p99 of a latency histogram
        are pure functions of the observation multiset."""
        return quantile_from_buckets(self.buckets, self.counts, q)

    def quantiles(self) -> dict:
        """The {p50, p95, p99} triple every latency consumer wants."""
        return {"p50": self.quantile(0.50),
                "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def snapshot_value(self):
        # integers only (total may be float when observing floats; round
        # to a fixed precision so the snapshot stays byte-stable)
        return {"count": self.count,
                "sum": round(self.total, 9),
                "buckets": {repr(b): c for b, c in
                            zip(self.buckets, self.counts[:-1])},
                "overflow": self.counts[-1]}


class MetricsRegistry:
    """Name -> instrument map with idempotent creation and deterministic
    snapshots.  One process-wide instance lives at `observe.metrics
    .REGISTRY`; tests build private ones."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.data_writes = 0          # gated writes that landed (probe)
        self.flight = None            # armed FlightRecorder (observe/flight)
        self._instruments: Dict[str, object] = {}

    # -- creation (idempotent by name) ----------------------------------
    def _make(self, cls, name: str, **kw):
        inst = self._instruments.get(name)
        if inst is not None:
            if not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}")
            return inst
        inst = cls(name, reg=self, **kw)
        self._instruments[name] = inst
        return inst

    def counter(self, name: str, always: bool = False,
                stable: bool = True) -> Counter:
        return self._make(Counter, name, always=always, stable=stable)

    def gauge(self, name: str, always: bool = False,
              stable: bool = True) -> Gauge:
        return self._make(Gauge, name, always=always, stable=stable)

    def histogram(self, name: str, buckets: Iterable[float] = DEFAULT_BUCKETS,
                  always: bool = False, stable: bool = True) -> Histogram:
        return self._make(Histogram, name, buckets=buckets, always=always,
                          stable=stable)

    def get(self, name: str):
        return self._instruments.get(name)

    # -- enable/disable --------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    # -- snapshots --------------------------------------------------------
    def instruments(self) -> List[object]:
        return [self._instruments[k] for k in sorted(self._instruments)]

    def snapshot(self, include_unstable: bool = False) -> dict:
        """{name: value} in sorted name order.  Only `stable` instruments
        by default — the deterministic, diffable subset (bench emits this
        verbatim into its JSON).  Histograms render as nested dicts with
        repr'd bucket edges."""
        out = {}
        for name in sorted(self._instruments):
            inst = self._instruments[name]
            if inst.stable or include_unstable:
                out[name] = inst.snapshot_value()
        return out

    def snapshot_json(self, include_unstable: bool = False) -> str:
        """Canonical byte form of snapshot() (sorted keys, no spaces) —
        the thing two same-seed runs must agree on byte for byte."""
        return json.dumps(self.snapshot(include_unstable),
                          sort_keys=True, separators=(",", ":"))

    def reset(self) -> None:
        """Zero every instrument (tests); registration survives."""
        for inst in self._instruments.values():
            if isinstance(inst, Histogram):
                inst.counts = [0] * (len(inst.buckets) + 1)
                inst.total = 0.0
                inst.count = 0
            else:
                inst.value = 0
        self.data_writes = 0


# the process-wide registry: crypto caches, the autotuner, network
# counters and the span layer all bind into this one
REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return REGISTRY


def counter(name: str, always: bool = False, stable: bool = True) -> Counter:
    return REGISTRY.counter(name, always=always, stable=stable)


def gauge(name: str, always: bool = False, stable: bool = True) -> Gauge:
    return REGISTRY.gauge(name, always=always, stable=stable)


def histogram(name: str, buckets: Iterable[float] = DEFAULT_BUCKETS,
              always: bool = False, stable: bool = True) -> Histogram:
    return REGISTRY.histogram(name, buckets=buckets, always=always,
                              stable=stable)


def latency_histogram(name: str) -> Histogram:
    """A duration histogram on the shared LATENCY_BUCKETS vocabulary.
    Measured seconds vary run to run, so latency instruments are always
    `stable=False` — exported live (scrape/Prometheus) but excluded from
    the deterministic snapshot bench embeds.  Bind the handle ONCE at
    module/init scope: `observe()` through a fresh registry lookup on a
    hot path is the OBS002 lint."""
    return REGISTRY.histogram(name, buckets=LATENCY_BUCKETS, stable=False)


def enabled() -> bool:
    return REGISTRY.enabled
