"""Process-wide metrics registry — named counters/gauges/histograms with
deterministic snapshots.

The reference threads a contravariant `Tracer m a` through every
constructor but ships no metrics layer; our reproduction had outgrown
its ad-hoc equivalents (private counters in crypto/precompute.py and
crypto/autotune.py, one-off breakdowns printed by bench.py).  This
module is the one seam they all migrate into.

Design constraints, in order:

1. **Near-free when disabled.**  Every observational write goes through
   one flag read (`registry.enabled`); a disabled registry performs NO
   instrument writes at all — asserted by the bench --smoke probe via
   `data_writes`, which counts gated writes that actually landed.
2. **Deterministic snapshots.**  `snapshot()` returns instruments in
   sorted name order with values that are pure functions of the workload
   at a fixed seed (counts, not wall times), so two bench runs emit
   byte-identical `metrics` sections and the output stays diffable.
   Instruments that hold measured durations or other run-varying values
   are created with `stable=False` and excluded from `snapshot()`
   (they still appear in the Prometheus exposition, which is allowed to
   vary run to run).
3. **Functional counters stay functional.**  The migrated precompute /
   autotune counters are *load-bearing* — tests and bench assertions
   gate on them (warm windows do zero fills; frozen tuners reject
   writes).  Those are created with `always=True`: they count whether or
   not observation is enabled, and their writes are not charged to
   `data_writes` (they are program state that happens to be exported,
   not observation).

Instruments can exist unregistered (``Counter("x")``): per-instance
caches in tests get private counters with the same API while only the
process-wide singletons bind into the global registry — two fresh
`PrecomputeCache` instances never fight over one name.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Tuple


class Counter:
    """Monotonic-by-convention numeric cell.  `value` is read/write so
    migrated call sites using `cache.hits += 1` keep working through a
    property alias."""

    kind = "counter"
    __slots__ = ("name", "value", "always", "stable", "_reg")

    def __init__(self, name: str, reg: Optional["MetricsRegistry"] = None,
                 always: bool = False, stable: bool = True):
        self.name = name
        self.value = 0
        self.always = always
        self.stable = stable
        self._reg = reg

    def inc(self, n: int = 1) -> None:
        if self.always:
            self.value += n
            return
        reg = self._reg
        if reg is not None and reg.enabled:
            self.value += n
            reg.data_writes += 1

    def snapshot_value(self):
        return self.value


class Gauge:
    """Last-write-wins numeric cell."""

    kind = "gauge"
    __slots__ = ("name", "value", "always", "stable", "_reg")

    def __init__(self, name: str, reg: Optional["MetricsRegistry"] = None,
                 always: bool = False, stable: bool = True):
        self.name = name
        self.value = 0
        self.always = always
        self.stable = stable
        self._reg = reg

    def set(self, v) -> None:
        if self.always:
            self.value = v
            return
        reg = self._reg
        if reg is not None and reg.enabled:
            self.value = v
            reg.data_writes += 1

    def snapshot_value(self):
        return self.value


# default buckets suit the quantities this repo observes (queue depths,
# batch sizes, retry counts) — powers of two up to a replay window
DEFAULT_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256,
                                      512, 1024, 2048, 4096)


class Histogram:
    """Fixed-bucket histogram (cumulative counts on export, per the
    Prometheus convention; stored per-bucket so observe() is one index
    update)."""

    kind = "histogram"
    __slots__ = ("name", "buckets", "counts", "total", "count", "always",
                 "stable", "_reg")

    def __init__(self, name: str, reg: Optional["MetricsRegistry"] = None,
                 buckets: Iterable[float] = DEFAULT_BUCKETS,
                 always: bool = False, stable: bool = True):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)   # +inf overflow
        self.total = 0.0
        self.count = 0
        self.always = always
        self.stable = stable
        self._reg = reg

    def _record(self, v) -> None:
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += v
        self.count += 1

    def observe(self, v) -> None:
        if self.always:
            self._record(v)
            return
        reg = self._reg
        if reg is not None and reg.enabled:
            self._record(v)
            reg.data_writes += 1

    def snapshot_value(self):
        # integers only (total may be float when observing floats; round
        # to a fixed precision so the snapshot stays byte-stable)
        return {"count": self.count,
                "sum": round(self.total, 9),
                "buckets": {repr(b): c for b, c in
                            zip(self.buckets, self.counts[:-1])},
                "overflow": self.counts[-1]}


class MetricsRegistry:
    """Name -> instrument map with idempotent creation and deterministic
    snapshots.  One process-wide instance lives at `observe.metrics
    .REGISTRY`; tests build private ones."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.data_writes = 0          # gated writes that landed (probe)
        self._instruments: Dict[str, object] = {}

    # -- creation (idempotent by name) ----------------------------------
    def _make(self, cls, name: str, **kw):
        inst = self._instruments.get(name)
        if inst is not None:
            if not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}")
            return inst
        inst = cls(name, reg=self, **kw)
        self._instruments[name] = inst
        return inst

    def counter(self, name: str, always: bool = False,
                stable: bool = True) -> Counter:
        return self._make(Counter, name, always=always, stable=stable)

    def gauge(self, name: str, always: bool = False,
              stable: bool = True) -> Gauge:
        return self._make(Gauge, name, always=always, stable=stable)

    def histogram(self, name: str, buckets: Iterable[float] = DEFAULT_BUCKETS,
                  always: bool = False, stable: bool = True) -> Histogram:
        return self._make(Histogram, name, buckets=buckets, always=always,
                          stable=stable)

    def get(self, name: str):
        return self._instruments.get(name)

    # -- enable/disable --------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    # -- snapshots --------------------------------------------------------
    def instruments(self) -> List[object]:
        return [self._instruments[k] for k in sorted(self._instruments)]

    def snapshot(self, include_unstable: bool = False) -> dict:
        """{name: value} in sorted name order.  Only `stable` instruments
        by default — the deterministic, diffable subset (bench emits this
        verbatim into its JSON).  Histograms render as nested dicts with
        repr'd bucket edges."""
        out = {}
        for name in sorted(self._instruments):
            inst = self._instruments[name]
            if inst.stable or include_unstable:
                out[name] = inst.snapshot_value()
        return out

    def snapshot_json(self, include_unstable: bool = False) -> str:
        """Canonical byte form of snapshot() (sorted keys, no spaces) —
        the thing two same-seed runs must agree on byte for byte."""
        return json.dumps(self.snapshot(include_unstable),
                          sort_keys=True, separators=(",", ":"))

    def reset(self) -> None:
        """Zero every instrument (tests); registration survives."""
        for inst in self._instruments.values():
            if isinstance(inst, Histogram):
                inst.counts = [0] * (len(inst.buckets) + 1)
                inst.total = 0.0
                inst.count = 0
            else:
                inst.value = 0
        self.data_writes = 0


# the process-wide registry: crypto caches, the autotuner, network
# counters and the span layer all bind into this one
REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return REGISTRY


def counter(name: str, always: bool = False, stable: bool = True) -> Counter:
    return REGISTRY.counter(name, always=always, stable=stable)


def gauge(name: str, always: bool = False, stable: bool = True) -> Gauge:
    return REGISTRY.gauge(name, always=always, stable=stable)


def histogram(name: str, buckets: Iterable[float] = DEFAULT_BUCKETS,
              always: bool = False, stable: bool = True) -> Histogram:
    return REGISTRY.histogram(name, buckets=buckets, always=always,
                              stable=stable)


def enabled() -> bool:
    return REGISTRY.enabled
