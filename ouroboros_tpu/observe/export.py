"""Render observability state for external consumers.

Three formats, one per audience:

- `prometheus_text(registry)` — the text exposition format a scrape
  endpoint serves (counters/gauges/histograms, `ouro_` namespace, names
  dot->underscore mangled, sorted — deterministic for a fixed registry
  state).
- `chrome_trace(spans)` — span trees as chrome://tracing / Perfetto
  `trace_event` JSON ("X" complete events, microsecond timestamps).
  Load via chrome://tracing "Load" or ui.perfetto.dev.
- `events_jsonl(events)` — typed utils/tracer.py events as JSON lines:
  one object per event carrying the dataclass type name and its fields
  (bytes hex-encoded), so a log pipeline gets the TYPED schema instead
  of parsing strings.  `jsonl_tracer(fh)` is the live bridge: a Tracer
  writing each traced event straight to a file handle.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List

from ..utils.tracer import Tracer
from .metrics import Histogram, MetricsRegistry, quantile_from_buckets
from .spans import Span

PROM_PREFIX = "ouro_"


def _split_labels(name: str) -> tuple:
    """(base, inner-label-text) for names carrying a `{k="v",...}` label
    block (observe/netmetrics.py labeled instruments); ("name", "") for
    plain names."""
    if name.endswith("}") and "{" in name:
        base, labels = name.split("{", 1)
        return base, labels[:-1]
    return name, ""


def _mangle(base: str) -> str:
    out = []
    for ch in base:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    return PROM_PREFIX + "".join(out)




def _prom_num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def prometheus_text(reg: MetricsRegistry,
                    include_unstable: bool = True) -> str:
    """Text exposition of every instrument (unstable ones included by
    default — a scrape endpoint wants live values; pass False for the
    deterministic subset)."""
    lines: List[str] = []
    typed: set = set()
    for inst in reg.instruments():
        if not (inst.stable or include_unstable):
            continue
        base, labels = _split_labels(inst.name)
        name = _mangle(base)
        # ONE TYPE line per base name: labeled series of one base are
        # samples of one metric, and a real Prometheus parser rejects a
        # duplicate TYPE line (instruments iterate in sorted-name order,
        # so same-base series are contiguous)
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {inst.kind}")
        if isinstance(inst, Histogram):
            pre = labels + "," if labels else ""
            suf = f"{{{labels}}}" if labels else ""
            cum = 0
            for edge, c in zip(inst.buckets, inst.counts[:-1]):
                cum += c
                lines.append(f'{name}_bucket{{{pre}le='
                             f'"{_prom_num(edge)}"}} {cum}')
            cum += inst.counts[-1]
            lines.append(f'{name}_bucket{{{pre}le="+Inf"}} {cum}')
            lines.append(f"{name}_sum{suf} {_prom_num(inst.total)}")
            lines.append(f"{name}_count{suf} {inst.count}")
        else:
            suf = f"{{{labels}}}" if labels else ""
            lines.append(f"{name}{suf} {_prom_num(inst.value)}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> dict:
    """Minimal exposition parser: {metric_name: float} for plain sample
    lines (bucketed samples keep their label suffix as part of the key).
    Used by the bench smoke gate to assert the exporter round-trips."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(None, 1)
            out[key] = float(val)
        except ValueError as e:
            raise ValueError(f"unparseable exposition line: {line!r}") \
                from e
    return out


def prom_histograms(parsed: dict) -> dict:
    """Histogram base names present in a parsed exposition: every metric
    with a `<name>_count` sample and at least one `<name>_bucket{le=..}`
    sample."""
    out = []
    for key in parsed:
        if key.endswith("_count"):
            base = key[:-len("_count")]
            if any(k.startswith(base + '_bucket{le="') for k in parsed):
                out.append(base)
    return {b: parsed[b + "_count"] for b in sorted(out)}


def prom_histogram_quantiles(parsed: dict, base: str,
                             qs=(0.50, 0.95, 0.99)) -> dict:
    """Deterministic quantiles recomputed from a SCRAPED exposition —
    the consumer-side mirror of Histogram.quantiles(), so a remote
    scraper (tools/obsreport.py --live, the acceptance test) extracts
    the same p50/p95/p99 the process would report locally.  `base` is
    the mangled metric name (e.g. "ouro_pipeline_submit_drain_secs")."""
    pre = base + '_bucket{le="'
    pts = []
    for key, v in parsed.items():
        if key.startswith(pre):
            le = key[len(pre):-2]
            if le != "+Inf":
                pts.append((float(le), v))
    pts.sort()
    edges = tuple(p[0] for p in pts)
    counts, prev = [], 0.0
    for _, cum in pts:                     # cumulative -> per-bucket
        counts.append(cum - prev)
        prev = cum
    counts.append(parsed.get(base + "_count", prev) - prev)  # overflow
    return {f"p{round(q * 100)}": quantile_from_buckets(edges, counts, q)
            for q in qs}


# --- chrome://tracing -------------------------------------------------------

def chrome_trace(spans: Iterable[Span], pid: int = 1) -> dict:
    """`trace_event` JSON for a forest of span trees.  Each category gets
    its own tid row so the five replay phases render as parallel tracks;
    timestamps are the spans' monotonic clock readings in microseconds
    (chrome only cares about relative position)."""
    events: List[dict] = []
    tids: dict = {}

    def emit(sp: Span):
        tid = tids.setdefault(sp.cat, len(tids) + 1)
        ev = {"name": sp.name, "cat": sp.cat, "ph": "X",
              "ts": round(sp.t0 * 1e6, 3),
              "dur": round(sp.duration * 1e6, 3),
              "pid": pid, "tid": tid}
        if sp.meta:
            ev["args"] = sp.meta
        events.append(ev)
        for c in sp.children:
            emit(c)

    for sp in spans:
        emit(sp)
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": cat}} for cat, tid in sorted(
                 tids.items(), key=lambda kv: kv[1])]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: Iterable[Span]) -> None:
    with open(path, "w") as f:
        json.dump(chrome_trace(spans), f, sort_keys=True)
        f.write("\n")


# --- typed tracer events -> JSONL ------------------------------------------

def _json_safe(v):
    if isinstance(v, (bytes, bytearray)):
        return v.hex()
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {k: _json_safe(x)
                for k, x in dataclasses.asdict(v).items()}
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_json_safe(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def event_record(ev) -> dict:
    """One typed event as a JSON-safe dict: {"type": TypeName, ...fields}.
    Dataclass events contribute their fields (a field literally named
    "type" — none today — would land as "type_" rather than clobber the
    schema key); anything else lands under "payload" (still typed by its
    class name — no string matching)."""
    rec = {"type": type(ev).__name__}
    if dataclasses.is_dataclass(ev) and not isinstance(ev, type):
        for f in dataclasses.fields(ev):
            key = f.name if f.name != "type" else "type_"
            rec[key] = _json_safe(getattr(ev, f.name))
    else:
        rec["payload"] = _json_safe(ev)
    return rec


def events_jsonl(events: Iterable) -> str:
    """Render an event sequence as JSON lines (deterministic: insertion
    order of fields is the dataclass field order; keys not re-sorted so
    `type` leads every line)."""
    return "".join(json.dumps(event_record(ev), separators=(",", ":"))
                   + "\n" for ev in events)


def jsonl_tracer(fh) -> Tracer:
    """A live Tracer writing each event to `fh` as one JSON line — the
    utils/tracer.py -> log-pipeline bridge."""
    def emit(ev):
        fh.write(json.dumps(event_record(ev), separators=(",", ":")))
        fh.write("\n")
    return Tracer(emit)
