"""Hierarchical timing spans with explicit device fencing.

A span is one named, categorised interval on the host timeline; spans
nest, forming one tree per top-level region (a replay window, a bench
rep, a compile).  Categories are the replay phase vocabulary the bench
attributes time to:

    host-seq   the sequential host pass (nonce evolution, envelope
               checks, proof extraction)
    dispatch   host-side prep + async kernel dispatch (submit_window)
    device     blocking on device results (the finish_window drain, a
               precompute fill)
    compile    XLA trace+compile (first call of a fused composite, the
               sharded-mesh build)
    sync       explicit block_until_ready fences draining the async
               dispatch queue before a timed region
    disk       storage-layer reads + CBOR decode on the streaming
               replay's prefetch thread (storage/stream.py) — the
               seconds the read-ahead hides under device verify

Clock discipline: **monotonic only** — `time.perf_counter()` on the
host, the active runtime's virtual clock under simharness (Sim time in
tests, the IO runtime's monotonic offset in production).  No wall-clock
(`time.time()`-style) reads anywhere: span math must be immune to NTP
steps, and sim tests must see exact virtual durations.

Fencing: a span created with `fence=True` drains the async dispatch
queue (`jax.block_until_ready` on a dummy transfer — the same fence the
autotuner and bench use) at BOTH edges, so the measured interval covers
exactly the work dispatched inside it and inherits nothing in flight.
The fence is skipped when jax was never imported — host-only flows must
not pull in the device stack just by timing themselves.

Disabled recording is near-free: `span()` returns one shared null
context manager (no allocation, no clock read).

Thread discipline: the pipelined replay runs its host-sequential pass on
a background producer thread (consensus/pipeline.py), so the recorder
keeps one open-span stack PER THREAD (a producer's `window.host_seq`
must never adopt the consumer's `window.drain` as a child just because
they overlap in wall time).  Completed roots land in one shared,
lock-guarded list so a drain sees both threads' trees.
"""
from __future__ import annotations

import sys
import threading
import time
from typing import List, Optional

from ..simharness import runtime as _runtime
from . import metrics as _metrics

PHASES = ("host-seq", "dispatch", "device", "compile", "sync", "stall",
          "disk")


def monotonic_now() -> float:
    """Virtual monotonic time under an active sim/IO runtime, host
    perf_counter otherwise."""
    rt = _runtime.current_or_none()
    if rt is not None:
        return rt.now()
    return time.perf_counter()


def device_fence() -> None:
    """Drain the async dispatch queue.  No-op unless jax is already
    imported (a fenced span in a host-only process must not load it)."""
    if "jax" not in sys.modules:
        return
    from ..crypto.autotune import _fence
    _fence()


class Span:
    """One completed (or in-flight) interval.  `t0`/`t1` are clock
    readings from `monotonic_now`; `children` are spans closed while
    this one was the innermost open span."""

    __slots__ = ("name", "cat", "t0", "t1", "children", "meta")

    def __init__(self, name: str, cat: str, t0: float):
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1: Optional[float] = None
        self.children: List["Span"] = []
        self.meta: Optional[dict] = None

    @property
    def duration(self) -> float:
        return (self.t1 - self.t0) if self.t1 is not None else 0.0

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def __repr__(self):
        return (f"Span({self.name!r}, cat={self.cat!r}, "
                f"dur={self.duration:.6f}, "
                f"children={len(self.children)})")


class _NullSpan:
    """Shared no-op context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _LiveSpan:
    __slots__ = ("_rec", "_name", "_cat", "_fence", "_span")

    def __init__(self, rec: "SpanRecorder", name: str, cat: str,
                 fence: bool):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._fence = fence
        self._span: Optional[Span] = None

    def __enter__(self) -> Span:
        if self._fence:
            device_fence()
        self._span = self._rec._open(self._name, self._cat)
        return self._span

    def __exit__(self, *exc):
        if self._fence:
            device_fence()
        self._rec._close(self._span)
        return False


class SpanRecorder:
    """Process-wide span collector: an open-span stack plus the list of
    completed root trees.  Bounded — a forgotten enabled recorder in a
    long-lived node must not grow without limit; overflow drops new
    roots and counts them."""

    def __init__(self, enabled: bool = False, max_roots: int = 100_000):
        self.enabled = enabled
        self.max_roots = max_roots
        self.roots: List[Span] = []
        self._tls = threading.local()      # per-thread open-span stack
        self._lock = threading.Lock()      # guards roots/dropped
        self.dropped = 0
        self.flight = None                 # armed FlightRecorder
        self._drop_counter = _metrics.counter("observe.spans_dropped",
                                              always=True)
        # per-phase duration histograms, bound lazily ONCE per category
        # (a span close must not pay a registry lookup): every close
        # feeds `latency.phase.<cat>`, so phase p50/p95/p99 are live on
        # the scrape endpoint while a replay runs
        self._phase_hist: dict = {}

    def _hist_for(self, cat: str):
        h = self._phase_hist.get(cat)
        if h is None:
            h = _metrics.latency_histogram(f"latency.phase.{cat}")
            self._phase_hist[cat] = h
        return h

    @property
    def _stack(self) -> List[Span]:
        """Open-span stack of the CALLING thread: nesting is a per-thread
        notion — a producer-thread span overlapping a consumer-thread
        span in wall time is concurrency, not containment."""
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- the public surface ------------------------------------------------
    def span(self, name: str, cat: str = "host-seq", fence: bool = False):
        """Context manager timing one interval.  Near-free when the
        recorder is disabled (returns a shared null CM)."""
        if not self.enabled:
            return _NULL
        return _LiveSpan(self, name, cat, fence)

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def drain(self) -> List[Span]:
        """Completed root spans since the last drain (open spans stay on
        the stack and attach to a later drain's roots when closed)."""
        with self._lock:
            out, self.roots = self.roots, []
        return out

    def clear(self) -> None:
        with self._lock:
            self.roots = []
            self._tls = threading.local()
            self.dropped = 0

    # -- recording ---------------------------------------------------------
    def _open(self, name: str, cat: str) -> Span:
        sp = Span(name, cat, monotonic_now())
        self._stack.append(sp)
        return sp

    def _close(self, sp: Span) -> None:
        if sp.t1 is not None:
            # already stamped: this span was adopted as a child by an
            # earlier out-of-order close (or its CM exited twice);
            # recording it again would attach it under a second
            # parent/root and double-count it in phase_totals
            return
        sp.t1 = monotonic_now()
        fl = self.flight
        if fl is not None:
            fl.span(sp)
        # tolerate out-of-order closes (a generator-held span closed
        # late): pop up to and including sp, re-parenting survivors
        stack = self._stack
        if sp in stack:
            while stack:
                top = stack.pop()
                if top is sp:
                    break
                if top.t1 is None:
                    top.t1 = sp.t1
                sp.children.append(top)
        parent = stack[-1] if stack else None
        # phase-latency feed: one sample per contiguous same-category
        # episode — a span nested under a SAME-cat parent (JaxBackend's
        # "window.drain" inside the pipeline's "pipeline.drain", both
        # device) is the same wait seen twice, and observing both would
        # double the histogram count and skew the quantiles
        if parent is None or parent.cat != sp.cat:
            self._hist_for(sp.cat).observe(sp.t1 - sp.t0)
        if parent is not None:
            parent.children.append(sp)
        else:
            with self._lock:
                if len(self.roots) < self.max_roots:
                    self.roots.append(sp)
                else:
                    self.dropped += 1
                    self._drop_counter.inc()


RECORDER = SpanRecorder()


def recorder() -> SpanRecorder:
    return RECORDER


def span(name: str, cat: str = "host-seq", fence: bool = False):
    """observe.spans.span("window.drain", cat="device") — module-level
    convenience over the process-wide recorder."""
    rec = RECORDER
    if not rec.enabled:
        return _NULL
    return _LiveSpan(rec, name, cat, fence)


def enabled() -> bool:
    return RECORDER.enabled


def intervals_of(spans_: List[Span], cat: Optional[str] = None,
                 name: Optional[str] = None) -> list:
    """(t0, t1) intervals of every completed span in the forest matching
    `cat` and/or `name` (None = match all).  Inputs for overlap math —
    the bench's host-under-device attribution."""
    out = []
    for root in spans_:
        for sp in root.walk():
            if sp.t1 is None:
                continue
            if cat is not None and sp.cat != cat:
                continue
            if name is not None and sp.name != name:
                continue
            out.append((sp.t0, sp.t1))
    return out


def merge_intervals(intervals: list) -> list:
    """Union of intervals as a sorted, disjoint list."""
    merged: list = []
    for t0, t1 in sorted(intervals):
        if merged and t0 <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], t1))
        else:
            merged.append((t0, t1))
    return merged


def overlap_seconds(a: list, b: list) -> float:
    """Total seconds where the union of `a` intersects the union of `b`
    — e.g. host-seq time HIDDEN under in-flight device time.  The two
    forests' clocks must be comparable (same monotonic_now source)."""
    a, b = merge_intervals(a), merge_intervals(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def phase_totals(spans_: List[Span]) -> dict:
    """Seconds per category over a forest of span trees.

    Each span contributes its SELF time (duration minus its children's
    durations) to its own category, so a dispatch span containing a
    compile span attributes the compile seconds to `compile`, never
    twice.  Categories outside PHASES aggregate under their own name."""
    totals: dict = {}

    def add(sp: Span):
        inner = sum(c.duration for c in sp.children)
        totals[sp.cat] = totals.get(sp.cat, 0.0) + max(
            0.0, sp.duration - inner)
        for c in sp.children:
            add(c)

    for sp in spans_:
        add(sp)
    return totals
