"""Era instantiations (the L6 layer of SURVEY.md §1).

- shelley.py — TPraos protocol + stake-pool UTxO ledger
  (ouroboros-consensus-shelley analog)
- byron.py   — PBFT era with EBBs + delegation (ouroboros-consensus-byron
  analog)
- cardano.py — the mainnet-shaped hard-fork composition
  (ouroboros-consensus-cardano analog)
"""
from .byron import (                                       # noqa: F401
    ByronLedger, ByronLedgerState, ByronLedgerView, ByronPBft, ByronTx,
    byron_genesis_setup, byron_sign_header, make_byron_tx, make_ebb,
)
from .shelley import (                                     # noqa: F401
    OCert, PoolInfo, ShelleyLedger, ShelleyLedgerState, ShelleyTx,
    TPraos, TPraosCanBeLeader, TPraosConfig, TPraosIsLeader,
    TPraosLedgerView, TPraosState, forge_tpraos_fields, make_ocert,
    make_shelley_tx, pool_id_of, shelley_genesis_setup,
)
