"""Real Cardano Byron header/block CBOR — parse the reference's actual
Byron-era bytes.

Golden bytes: `ouroboros-consensus-byron-test/test/golden/
{ByronNodeToNodeVersion1,disk}/*` and the HFC-wrapped forms under
`ouroboros-consensus-cardano-test/test/golden/CardanoNodeToNodeVersion*/
{Header,Block}_Byron_{regular,EBB}`.

Encodings (cardano-ledger Byron dialect):

    block  = tag24( bytes( [0, ebb] / [1, main] ) )
    main   = [ header, body, extra ]
    header = [ protocol_magic, prev_hash(32), body_proof,
               [ [epoch, slot], issuer_xpub(64), [difficulty],
                 block_signature ],
               extra ]
    ebb hdr= [ protocol_magic, prev_hash(32), body_proof_hash(32),
               [ epoch, [difficulty] ], extra ]

and the node-to-node header wrapper is `[[tag, size_hint], tag24(bytes
header)]` (further wrapped in `[era_ix, ...]` by the HFC).

The header HASH is blake2b-256 of `CBOR([tag, header])` — the re-tagged
wrapper, NOT the bare header — verified bit-exactly against the
reference's golden `disk/HeaderHash` in tests/test_real_header.py.

Byron's signature scheme is Ed25519-BIP32 over extended keys
(cardano-crypto, outside this repo's scope); this module provides parse +
byte-identical re-encode + hash conformance, the interop surface the
storage layer needs (ImmutableDB Parser.hs reads exactly these bytes).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Optional

from ..utils import cbor


def _blake2b(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


@dataclass(frozen=True)
class RealByronHeader:
    is_ebb: bool
    magic: int
    prev_hash: bytes
    epoch: int
    slot: Optional[int]          # None for EBBs (epoch boundary)
    issuer_xpub: Optional[bytes]  # 64B extended public key; None for EBBs
    difficulty: int
    raw: bytes                   # exact header byte slice
    has_extra: bool = True       # 5-element form (disk/Cardano dialects)

    @property
    def header_hash(self) -> bytes:
        """blake2b-256 of the re-tagged wrapper [0|1, header]; defined
        for the full 5-element header form only (the node-to-node V1
        4-element codec is not the hashed representation)."""
        if not self.has_extra:
            raise ValueError("header hash needs the full (extra-bearing) "
                             "header form")
        tag = 0 if self.is_ebb else 1
        return _blake2b(bytes([0x82, tag]) + self.raw)

    def to_cbor(self) -> bytes:
        return self.raw


def _parse_header_obj(obj: Any, raw: bytes) -> RealByronHeader:
    """Field extraction: 5-element headers carry the extra-data section
    (disk / Cardano-wrapper dialect); the ByronNodeToNodeVersion1 header
    codec sends 4 elements (no extra).  The header HASH is only defined
    for the full 5-element form."""
    if not isinstance(obj, list):
        raise ValueError("Byron header must be an array")
    if len(obj) in (4, 5) and isinstance(obj[3], list) \
            and len(obj[3]) == 4 and isinstance(obj[3][1], bytes):
        # regular main-block header
        consensus = obj[3]
        epoch, slot = int(consensus[0][0]), int(consensus[0][1])
        return RealByronHeader(False, int(obj[0]), bytes(obj[1]),
                               epoch, slot, bytes(consensus[1]),
                               int(consensus[2][0]), raw,
                               has_extra=len(obj) == 5)
    if len(obj) in (4, 5) and isinstance(obj[3], list) \
            and len(obj[3]) == 2 and isinstance(obj[3][1], list):
        # epoch-boundary header
        return RealByronHeader(True, int(obj[0]), bytes(obj[1]),
                               int(obj[3][0]), None, None,
                               int(obj[3][1][0]), raw,
                               has_extra=len(obj) == 5)
    raise ValueError("unrecognised Byron header shape")


def parse_header(raw: bytes) -> RealByronHeader:
    """Parse from any encoding: bare header, tag-24 wrapped, the
    node-to-node [[tag, size], tag24(..)] wrapper, or the HFC
    [era_ix, ...] wrapper — tag 0 = EBB, 1 = regular."""
    obj = cbor.loads(raw)
    ebb_hint: Optional[bool] = None
    if isinstance(obj, list) and len(obj) == 2 and isinstance(obj[0], int) \
            and isinstance(obj[1], list) and obj[1] \
            and isinstance(obj[1][0], list):
        # HFC era wrapper [era_ix, [[tag, size], tag24(...)]] — the inner
        # pair's FIRST element is a list, distinguishing it from a bare
        # pre-tagged [0|1, header] whose first header field is the
        # protocol-magic int
        obj = obj[1]
    if isinstance(obj, list) and len(obj) == 2 \
            and isinstance(obj[0], list) and isinstance(obj[1], cbor.Tag):
        ebb_hint = int(obj[0][0]) == 0    # [[tag, size_hint], tag24(...)]
        obj = obj[1]
    if isinstance(obj, cbor.Tag):
        if obj.tag != 24 or not isinstance(obj.value, bytes):
            raise ValueError(f"expected tag 24 bytes, got tag {obj.tag}")
        raw = obj.value
        obj = cbor.loads(raw)
    if isinstance(obj, list) and len(obj) == 2 \
            and isinstance(obj[0], int) and obj[0] in (0, 1) \
            and isinstance(obj[1], list):
        # pre-tagged [0|1, header] (ByronNodeToNodeVersion1 codec)
        if ebb_hint is None:
            ebb_hint = obj[0] == 0
        _, used = cbor.loads_prefix(raw[2:])
        raw = raw[2:2 + used]
        obj = obj[1]
    hdr = _parse_header_obj(obj, raw)
    if ebb_hint is not None and hdr.is_ebb != ebb_hint:
        raise ValueError("EBB wrapper tag contradicts header shape")
    return hdr


@dataclass(frozen=True)
class RealByronBlock:
    header: RealByronHeader
    body: Any                    # decoded payload (txs / ssc / dlg / upd)
    raw: bytes                   # the [0|1, [hdr, body, extra]] bytes

    @property
    def n_txs(self) -> int:
        if self.header.is_ebb:
            return 0
        return len(self.body[0])

    def to_cbor(self) -> bytes:
        return self.raw

    def to_wrapped_cbor(self) -> bytes:
        return cbor.dumps(cbor.Tag(24, self.raw))


def parse_block(raw: bytes) -> RealByronBlock:
    """Parse a Byron block: tag24(bytes([0|1, [header, body, extra]]))
    or the bare tagged pair."""
    obj = cbor.loads(raw)
    if isinstance(obj, cbor.Tag):
        if obj.tag != 24 or not isinstance(obj.value, bytes):
            raise ValueError(f"expected tag 24 bytes, got tag {obj.tag}")
        raw = obj.value
        obj = cbor.loads(raw)
    if not (isinstance(obj, list) and len(obj) == 2
            and isinstance(obj[0], int)):
        raise ValueError("Byron block must be [0|1, [...]]")
    tag, payload = int(obj[0]), obj[1]
    if tag not in (0, 1) or not isinstance(payload, list) \
            or len(payload) != 3:
        raise ValueError("unrecognised Byron block shape")
    # slice the header bytes out of the raw pair:
    # 0x82, tag byte, payload array head, header
    info = raw[2] & 0x1F
    hdr_start = 3 + {24: 1, 25: 2, 26: 4, 27: 8}.get(info, 0)
    _, used = cbor.loads_prefix(raw[hdr_start:])
    hdr_raw = raw[hdr_start:hdr_start + used]
    hdr = _parse_header_obj(payload[0], hdr_raw)
    if hdr.is_ebb != (tag == 0):
        raise ValueError("block tag contradicts header shape")
    return RealByronBlock(hdr, payload[1], raw)
