"""Byron-analog era: PBFT over a delegation-bearing UTxO ledger, with EBBs.

Reference: ouroboros-consensus-byron/src/Ouroboros/Consensus/Byron/
- Protocol.hs + Ledger/PBFT.hs — the PBFT protocol instance whose delegate
  set comes from the LEDGER (genesis keys delegate block issuance via
  heavyweight delegation certificates), not from static config.
- Ledger/Block.hs + ouroboros-consensus Block/EBB.hs — epoch boundary
  blocks: unsigned, bodyless blocks at the first slot of each epoch that
  share their predecessor's block NUMBER (the envelope quirk handled in
  consensus/header_validation.py).
- Ledger/Ledger.hs — UTxO rules + delegation state transitions.

The windowed signature-threshold arithmetic is the cheap sequential check;
the per-header Ed25519 delegate signature and the per-body tx witnesses are
the batchable proofs (PBFT.hs:226-302; SURVEY.md §2 batching gap).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

from ..chain.block import Point, point_of
from ..consensus.headers import body_hash_of, make_header
from ..consensus.ledger import LedgerError, LedgerRules
from ..consensus.protocol import ConsensusProtocol, ProtocolError
from ..crypto import ed25519_ref
from ..crypto.backend import Ed25519Req
from ..utils import cbor

SIG_FIELD = "byron_sig"
DELEGATE_FIELD = "byron_delegate_vk"
EBB_FIELD = "ebb"


def _b2b(data: bytes, n: int = 32) -> bytes:
    return hashlib.blake2b(data, digest_size=n).digest()


# ---------------------------------------------------------------------------
# Ledger view: the delegation map
# ---------------------------------------------------------------------------

@dataclass
class ByronLedgerView:
    """genesis-key index -> current delegate verification key (the PBFT
    ledger view, Byron/Ledger/PBFT.hs)."""
    delegates: tuple                   # (delegate_vk, ...) by genesis index

    def delegate_of(self, genesis_ix: int) -> Optional[bytes]:
        if 0 <= genesis_ix < len(self.delegates):
            return self.delegates[genesis_ix]
        return None


# ---------------------------------------------------------------------------
# The protocol: PBFT with ledger-supplied delegates
# ---------------------------------------------------------------------------

class ByronPBft(ConsensusProtocol):
    """PBFT (PBFT.hs:226-302) where `header.issuer` is a *genesis key
    index* and the signing key is the delegate the ledger view maps it to.

    ChainDepState = tuple of recent genesis-key indices (newest last),
    bounded by `window` — PBFT/State.hs.
    """

    accepts_ebb = True                 # Byron is the EBB era (Block/EBB.hs)

    def __init__(self, n_genesis_keys: int, threshold: float = 0.22,
                 window: int = 100, k: int = 5, epoch_length: int = 100):
        self.n = n_genesis_keys
        self.threshold = threshold
        self.window = window
        self.security_param = k
        self.epoch_length = epoch_length

    def slot_leader(self, slot: int) -> int:
        return slot % self.n

    def _limit(self) -> int:
        # strictly-greater-than comparison in the reference (PBFT.hs:279)
        return int(self.threshold * self.window)

    # -- state ---------------------------------------------------------------
    def initial_chain_dep_state(self):
        return ()

    def reupdate_chain_dep_state(self, ticked, header, ledger_view):
        if header.get(EBB_FIELD):
            return ticked                  # EBBs are outside the protocol
        signers = ticked + (header.issuer,)
        return signers[-self.window:]

    # -- checks --------------------------------------------------------------
    def sequential_checks(self, ticked, header,
                          ledger_view: ByronLedgerView):
        if header.get(EBB_FIELD):
            if header.get(SIG_FIELD) is not None or header.body_hash != \
                    _EBB_BODY_HASH:
                raise ProtocolError("Byron: malformed EBB")
            # canBeEBB: EBBs only occupy the first slot of an epoch
            if header.slot % self.epoch_length != 0:
                raise ProtocolError(
                    f"Byron: EBB at slot {header.slot}, not an epoch "
                    f"boundary (epoch_length={self.epoch_length})")
            return
        if not (0 <= header.issuer < self.n):
            raise ProtocolError(
                f"Byron/PBFT: issuer {header.issuer} is not a genesis key")
        if ledger_view.delegate_of(header.issuer) is None:
            raise ProtocolError(
                f"Byron/PBFT: genesis key {header.issuer} has no delegate")
        claimed = header.get(DELEGATE_FIELD)
        if claimed != ledger_view.delegate_of(header.issuer):
            raise ProtocolError(
                "Byron/PBFT: header's delegate key does not match the "
                "ledger's delegation map")
        if header.get(SIG_FIELD) is None:
            raise ProtocolError("Byron/PBFT: header missing signature")
        signers = (ticked + (header.issuer,))[-self.window:]
        count = sum(1 for s in signers if s == header.issuer)
        if count > max(1, self._limit()):
            raise ProtocolError(
                f"Byron/PBFT: signer {header.issuer} signed {count} of "
                f"last {len(signers)} blocks, exceeds threshold "
                f"{self.threshold}x{self.window}")

    def extract_proofs(self, ticked, header, ledger_view: ByronLedgerView):
        if header.get(EBB_FIELD):
            return []
        sig = header.get(SIG_FIELD)
        vk = ledger_view.delegate_of(header.issuer)
        if sig is None or vk is None:
            return []
        return [Ed25519Req(vk=vk, msg=header.bytes_dropping(SIG_FIELD),
                           sig=sig)]

    # -- leadership ----------------------------------------------------------
    def check_is_leader(self, can_be_leader, slot, ticked, ledger_view):
        """can_be_leader = genesis key index."""
        return True if self.slot_leader(slot) == can_be_leader else None


def byron_sign_header(delegate_sk: bytes, header):
    """Sign a Byron header with the delegate key (the key the ledger's
    delegation map currently points at)."""
    h = header.with_fields(**{
        DELEGATE_FIELD: ed25519_ref.public_key(delegate_sk)})
    sig = ed25519_ref.sign(delegate_sk, h.bytes_dropping(SIG_FIELD))
    return h.with_fields(**{SIG_FIELD: sig})


# EBBs have an empty body by construction
_EBB_BODY_HASH = body_hash_of(())


def make_ebb(prev, epoch: int, epoch_length: int):
    """Epoch boundary block header: first slot of `epoch`, no body, no
    signature, block number NOT incremented (Block/EBB.hs)."""
    slot = epoch * epoch_length
    if prev is None:
        h = make_header(None, slot, (), issuer=0)
    else:
        h = make_header(prev, slot, (), issuer=0)
        h = replace(h, block_no=prev.block_no, _cache={})
    return h.with_fields(**{EBB_FIELD: 1})


# ---------------------------------------------------------------------------
# The ledger: UTxO + heavyweight delegation
# ---------------------------------------------------------------------------

# certificates in tx bodies:
#   ("dlg", genesis_ix_bytes(8, big-endian), new_delegate_vk)
#     — witnessed by the GENESIS key of that index
#   ("upd", epoch_bytes(8, big-endian), b"")
#     — update proposal: adopt the next protocol version (i.e. hard-fork to
#       the next era) at the given epoch; witnessed by a genesis key.
#       This is the ledger-decided hard-fork trigger the HFC's
#       transition_epoch callback reads (TriggerHardForkAtVersion analog).
CERT_DLG = "dlg"
CERT_UPDATE = "upd"


@dataclass(frozen=True)
class ByronTx:
    """UTxO tx + optional delegation certs, Ed25519-witnessed over txid."""
    inputs: tuple                      # (txid, ix)
    outputs: tuple                     # (addr, amount)
    certs: tuple = ()
    witnesses: tuple = ()              # (vk, sig)

    _cache: dict = field(default_factory=dict, repr=False, hash=False,
                         compare=False)

    def body_encode(self):
        return [[list(i) for i in self.inputs],
                [list(o) for o in self.outputs],
                [list(c) for c in self.certs]]

    @property
    def txid(self) -> bytes:
        c = self._cache
        if "id" not in c:
            c["id"] = _b2b(cbor.dumps(self.body_encode()))
        return c["id"]

    def encode(self):
        return self.body_encode() + [[[vk, sig] for vk, sig in self.witnesses]]

    @classmethod
    def decode(cls, obj) -> "ByronTx":
        outputs = tuple((bytes(a), int(m)) for a, m in obj[1])
        if any(m < 0 for _a, m in outputs):
            raise ValueError("negative output amount")
        return cls(
            tuple((bytes(t), int(i)) for t, i in obj[0]),
            outputs,
            tuple((str(c[0]), bytes(c[1]), bytes(c[2])) for c in obj[2]),
            tuple((bytes(vk), bytes(sig)) for vk, sig in obj[3]))


def make_byron_tx(inputs: Sequence, outputs: Sequence, certs: Sequence,
                  signing_keys: Sequence[bytes]) -> ByronTx:
    tx = ByronTx(tuple(tuple(i) for i in inputs),
                 tuple(tuple(o) for o in outputs),
                 tuple(tuple(c) for c in certs))
    wits = tuple((ed25519_ref.public_key(sk), ed25519_ref.sign(sk, tx.txid))
                 for sk in signing_keys)
    return replace(tx, witnesses=wits)


@dataclass(frozen=True)
class ByronLedgerState:
    utxo: tuple                        # sorted ((txid, ix, addr, amount), ...)
    delegates: tuple                   # delegate_vk per genesis index
    slot: int
    tip: Point
    update_epoch: int = -1             # adopted hard-fork epoch, -1 = none

    def utxo_dict(self) -> dict:
        return {(t, i): (a, m) for t, i, a, m in self.utxo}

    def state_hash(self) -> bytes:
        enc = cbor.dumps([
            [[t, i, a, m] for t, i, a, m in self.utxo],
            list(self.delegates), self.slot, self.tip.encode(),
            self.update_epoch])
        return _b2b(enc)


def byron_transition_epoch(state: ByronLedgerState):
    """transition_epoch callback for the HFC Era record: the epoch the
    ledger's adopted update proposal names, if any."""
    return state.update_epoch if state.update_epoch >= 0 else None


def _freeze_utxo(utxo: dict) -> tuple:
    return tuple(sorted((t, i, a, m) for (t, i), (a, m) in utxo.items()))


class ByronLedger(LedgerRules):
    """UTxO + delegation rules (Byron/Ledger/Ledger.hs analog).

    genesis_vks: the fixed genesis keys; each starts self-delegated unless
    `initial_delegates` overrides.  A ("dlg", ix, vk) certificate witnessed
    by genesis key ix re-points its delegate (heavyweight delegation).
    """

    GENESIS_TXID = b"\x00" * 32

    def __init__(self, genesis: dict, genesis_vks: Sequence[bytes],
                 initial_delegates: Optional[Sequence[bytes]] = None):
        self.genesis = dict(genesis)
        self.genesis_vks = tuple(genesis_vks)
        self.initial_delegates = tuple(
            initial_delegates if initial_delegates is not None
            else genesis_vks)

    def initial_state(self) -> ByronLedgerState:
        utxo = {(self.GENESIS_TXID, ix): (addr, amount)
                for ix, (addr, amount) in enumerate(
                    sorted(self.genesis.items()))}
        return ByronLedgerState(_freeze_utxo(utxo), self.initial_delegates,
                                -1, Point.genesis())

    def tip(self, state: ByronLedgerState) -> Point:
        return state.tip

    def tick(self, state: ByronLedgerState, slot: int) -> ByronLedgerState:
        return replace(state, slot=slot)

    def ledger_view(self, state: ByronLedgerState) -> ByronLedgerView:
        return ByronLedgerView(state.delegates)

    # -- block application ---------------------------------------------------
    def _apply_txs(self, state: ByronLedgerState, block) -> ByronLedgerState:
        utxo = state.utxo_dict()
        delegates = list(state.delegates)
        update_epoch = state.update_epoch
        for tx in block.body:
            if len(set(tx.inputs)) != len(tx.inputs):
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} has duplicate inputs")
            spent = 0
            for txid, ix in tx.inputs:
                if (txid, ix) not in utxo:
                    raise LedgerError(f"missing input {txid.hex()[:12]}#{ix}")
                spent += utxo[(txid, ix)][1]
            if any(m < 0 for _a, m in tx.outputs):
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} has a negative output")
            if sum(m for _a, m in tx.outputs) > spent:
                raise LedgerError(f"tx {tx.txid.hex()[:12]} overspends")
            for kind, arg, vk in tx.certs:
                if kind == CERT_DLG:
                    gix = int.from_bytes(arg, "big")
                    if not 0 <= gix < len(delegates):
                        raise LedgerError(f"delegation for unknown genesis "
                                          f"key {gix}")
                    delegates[gix] = vk
                elif kind == CERT_UPDATE:
                    update_epoch = int.from_bytes(arg, "big")
                else:
                    raise LedgerError(f"unknown certificate kind {kind!r}")
            for txid, ix in tx.inputs:
                del utxo[(txid, ix)]
            for ix, (addr, amount) in enumerate(tx.outputs):
                utxo[(tx.txid, ix)] = (addr, amount)
        return replace(state, utxo=_freeze_utxo(utxo),
                       delegates=tuple(delegates), tip=point_of(block),
                       update_epoch=update_epoch)

    def check_tx_witnesses(self, state: ByronLedgerState,
                           tx: ByronTx) -> None:
        utxo = state.utxo_dict()
        wit_vks = {vk for vk, _ in tx.witnesses}
        for txid, ix in tx.inputs:
            if (txid, ix) in utxo and utxo[(txid, ix)][0] not in wit_vks:
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} spends without a witness")
        for kind, gix_raw, _vk in tx.certs:
            if kind == CERT_DLG:
                gix = int.from_bytes(gix_raw, "big")
                if not 0 <= gix < len(self.genesis_vks) \
                        or self.genesis_vks[gix] not in wit_vks:
                    raise LedgerError(
                        "delegation certificate without the genesis-key "
                        "witness")
            elif kind == CERT_UPDATE:
                if not any(vk in wit_vks for vk in self.genesis_vks):
                    raise LedgerError(
                        "update proposal without a genesis-key witness")

    def sequential_checks(self, ticked: ByronLedgerState, block) -> None:
        for tx in block.body:
            self.check_tx_witnesses(ticked, tx)

    def extract_proofs(self, ticked: ByronLedgerState, block) -> list:
        return [Ed25519Req(vk=vk, msg=tx.txid, sig=sig)
                for tx in block.body for vk, sig in tx.witnesses]

    def apply_block(self, ticked: ByronLedgerState, block,
                    backend=None) -> ByronLedgerState:
        from ..crypto.backend import default_backend
        backend = backend or default_backend()
        self.sequential_checks(ticked, block)
        reqs = self.extract_proofs(ticked, block)
        if reqs:
            if not all(backend.verify_ed25519_batch(reqs)):
                raise LedgerError(
                    f"invalid tx witness in block at slot {block.slot}")
        return self._apply_txs(ticked, block)

    def reapply_block(self, ticked: ByronLedgerState,
                      block) -> ByronLedgerState:
        return self._apply_txs(ticked, block)

    # -- mempool support -----------------------------------------------------
    def apply_tx(self, state: ByronLedgerState, tx: ByronTx,
                 backend=None) -> ByronLedgerState:
        blk = _OneTxBlock(tx, state.tip)
        self.check_tx_witnesses(state, tx)
        from ..crypto.backend import default_backend
        ok = (backend or default_backend()).verify_ed25519_batch(
            self.extract_proofs(state, blk))
        if not all(ok):
            raise LedgerError(f"tx {tx.txid.hex()[:12]}: bad witness")
        return replace(self._apply_txs(state, blk), tip=state.tip)


class _OneTxBlock:
    def __init__(self, tx: ByronTx, tip: Point):
        self.body = (tx,)
        self.slot = tip.slot
        self.hash = tip.hash
        self.header = self


# ---------------------------------------------------------------------------
# network setup helper
# ---------------------------------------------------------------------------

def byron_genesis_setup(n_keys: int, epoch_length: int = 100,
                        threshold: float = 0.5, window: int = 10,
                        k: int = 5, funds_per_key: int = 1000,
                        seed: bytes = b"byron-net"):
    """Protocol + ledger + per-genesis-key dicts (genesis_sk, delegate_sk,
    addr keys) for an n-key PBFT network, all keys self-delegated."""
    nodes, genesis, genesis_vks = [], {}, []
    for i in range(n_keys):
        tag = seed + b":%d" % i
        genesis_sk = _b2b(b"gen:" + tag)
        delegate_sk = _b2b(b"dlg:" + tag)
        addr_sk = _b2b(b"addr:" + tag)
        addr = ed25519_ref.public_key(addr_sk)
        genesis_vks.append(ed25519_ref.public_key(genesis_sk))
        genesis[addr] = funds_per_key
        nodes.append({"genesis_sk": genesis_sk, "delegate_sk": delegate_sk,
                      "addr_sk": addr_sk, "addr": addr, "index": i})
    protocol = ByronPBft(n_keys, threshold=threshold, window=window, k=k,
                         epoch_length=epoch_length)
    # every key initially delegates to its own delegate key
    ledger = ByronLedger(genesis, genesis_vks,
                         [ed25519_ref.public_key(n["delegate_sk"])
                          for n in nodes])
    return protocol, ledger, nodes
