"""The Cardano-analog composition: Byron(PBFT) -> Shelley-family(TPraos)
through the hard-fork combinator.

Reference: ouroboros-consensus-cardano/src/Ouroboros/Consensus/Cardano/
- Block.hs:161-186  — `CardanoEras c = [Byron, Shelley, ...]` and the HFC
  block over them; here `cardano_eras` builds the Era list.
- CanHardFork.hs:365-422 — the Byron->Shelley translations:
  `translateLedgerStateByronToShelley` (UTxO carried over, Shelley state
  initialised from the Shelley genesis staking) and
  `translateChainDepStateByronToShelley` (fresh TPraos state seeded from
  the Shelley genesis nonce).
- Cardano/Node.hs `protocolInfoCardano` — the per-era configs assembled in
  one place; here `cardano_setup`.

The hard-fork trigger is ledger-decided, as in the reference
(TriggerHardForkAtVersion): a Byron update-proposal certificate sets
`update_epoch`, which `byron_transition_epoch` exposes to the combinator's
Summary (eras/byron.py CERT_UPDATE).
"""
from __future__ import annotations

from typing import Optional

from ..consensus.hardfork import Era, EraParams, hard_fork_rules
from ..consensus.hardfork.combinator import ERA_FIELD
from ..consensus.headers import ProtocolBlock, ProtocolHeader
from ..crypto import ed25519_ref
from .byron import (
    ByronLedger, ByronLedgerState, ByronPBft, ByronTx,
    byron_genesis_setup, byron_transition_epoch,
)
from .shelley import (
    ShelleyLedger, ShelleyLedgerState, ShelleyTx, TPraos, TPraosConfig,
    TPraosState, shelley_genesis_setup,
)

BYRON, SHELLEY, ALLEGRA, MARY = 0, 1, 2, 3


def trigger_at_epoch(epoch: int):
    """TriggerHardForkAtEpoch analog (the reference's protocolInfoCardano
    per-era trigger, Cardano/Node.hs): the era's exit epoch is fixed by
    configuration rather than read from on-chain votes — the mechanism
    testnets (and our synthetic chains) use for the intra-Shelley forks."""
    return lambda _ledger_state: epoch


def translate_ledger_byron_to_shelley(shelley_ledger: ShelleyLedger):
    """CanHardFork.hs:365-422 ledger translation, closed over the Shelley
    genesis config (protocolInfoCardano's ShelleyGenesis): the Byron UTxO
    crosses unchanged (multi-asset column empty), pools/delegations start
    from the genesis staking so leader election works from the boundary."""
    cfg = shelley_ledger.config

    def translate(b: ByronLedgerState) -> ShelleyLedgerState:
        from .shelley import UtxoMap
        utxo = UtxoMap.from_items((t, i, a, m, ()) for t, i, a, m in b.utxo)
        delegs = tuple(sorted(shelley_ledger.initial_delegs.items()))
        pools = tuple(sorted(shelley_ledger.initial_pools.items()))
        snap = ShelleyLedger._stake_distr(utxo, delegs, pools)
        # the combinator ticked the Byron ledger to the boundary slot (the
        # first slot of the Shelley era)
        return ShelleyLedgerState(
            utxo=utxo, delegs=delegs, pools=pools,
            epoch=max(b.slot, 0) // cfg.epoch_length,
            snap_mark=snap, snap_set=snap,
            slot=b.slot, tip=b.tip)
    return translate


def translate_chain_dep_byron_to_shelley(genesis_seed: bytes):
    """Fresh TPraos state at the boundary, nonces seeded from the Shelley
    genesis (translateChainDepStateByronToShelley; the reference derives
    the initial nonce from the Shelley genesis hash)."""
    def translate(_pbft_state) -> TPraosState:
        return TPraosState.genesis(genesis_seed)
    return translate


def cardano_eras(byron_protocol: ByronPBft, byron_ledger: ByronLedger,
                 shelley_protocol: TPraos, shelley_ledger: ShelleyLedger,
                 byron_slot_length: float = 1.0,
                 shelley_slot_length: float = 0.5,
                 allegra_epoch: Optional[int] = None,
                 mary_epoch: Optional[int] = None) -> list:
    """The era list (CardanoEras analog, Cardano/Block.hs:161-186:
    Byron, Shelley, Allegra, Mary).  Epoch lengths come from the era
    configs; slot lengths may differ across the Byron fork (the mainnet
    20s -> 1s change, scaled).

    The intra-Shelley hops (CanHardFork.hs:365-422) keep the TPraos
    protocol and carry ledger + chain-dep state across unchanged (our
    ShelleyLedgerState is one type for the whole family; the rules object
    gates the per-era tx features: validity intervals from Allegra,
    multi-asset from Mary).  They fire at configured epochs
    (trigger_at_epoch); pass None to stop the ladder earlier."""
    if mary_epoch is not None and allegra_epoch is None:
        raise ValueError("mary_epoch requires allegra_epoch: the era "
                         "ladder cannot skip Allegra")
    s_params = EraParams(shelley_protocol.config.epoch_length,
                         shelley_slot_length)
    eras = [
        Era("byron", byron_protocol, byron_ledger,
            EraParams(byron_protocol.epoch_length, byron_slot_length),
            transition_epoch=byron_transition_epoch,
            translate_ledger=translate_ledger_byron_to_shelley(
                shelley_ledger),
            translate_chain_dep=translate_chain_dep_byron_to_shelley(
                shelley_protocol.genesis_seed)),
        Era("shelley", shelley_protocol, shelley_ledger, s_params,
            transition_epoch=(trigger_at_epoch(allegra_epoch)
                              if allegra_epoch is not None else None)),
    ]
    if allegra_epoch is not None:
        eras.append(Era(
            "allegra", shelley_protocol, shelley_ledger.with_era("allegra"),
            s_params,
            transition_epoch=(trigger_at_epoch(mary_epoch)
                              if mary_epoch is not None else None)))
        if mary_epoch is not None:
            eras.append(Era(
                "mary", shelley_protocol, shelley_ledger.with_era("mary"),
                s_params))
    return eras


def cardano_setup(n_nodes: int, epoch_length: int = 20,
                  shelley_config: Optional[TPraosConfig] = None,
                  seed: bytes = b"cardano-net",
                  funds_per_key: int = 1000,
                  allegra_epoch: Optional[int] = None,
                  mary_epoch: Optional[int] = None):
    """Keys + eras for an n-node network that can cross the fork.

    Every node holds both a Byron genesis/delegate key pair and a Shelley
    pool (cold/VRF/KES) whose staking address is the SAME address funded in
    the Byron genesis — so the Byron UTxO that crosses the boundary backs
    the Shelley stake distribution (the genesis-staking bootstrap).

    Returns (eras, rules, nodes) where nodes[i] carries byron/shelley
    credentials for forging."""
    if shelley_config is None:
        shelley_config = TPraosConfig(
            k=8, epoch_length=epoch_length, slots_per_kes_period=50,
            kes_depth=5, max_kes_evolutions=30)
    b_protocol, _b_ledger, b_nodes = byron_genesis_setup(
        n_nodes, epoch_length=epoch_length, threshold=0.9, window=10,
        k=shelley_config.k, funds_per_key=funds_per_key, seed=seed)
    s_protocol, s_ledger_tmp, s_pools = shelley_genesis_setup(
        n_nodes, shelley_config, stake_per_pool=funds_per_key,
        seed=seed + b":shelley")
    # fund the Shelley pool-owner addresses in the BYRON genesis, so the
    # crossing UTxO backs the Shelley stake snapshots
    genesis = {p["addr"]: funds_per_key for p in s_pools}
    genesis_vks = [ed25519_ref.public_key(n["genesis_sk"]) for n in b_nodes]
    b_ledger = ByronLedger(
        genesis, genesis_vks,
        [ed25519_ref.public_key(n["delegate_sk"]) for n in b_nodes])
    s_ledger = ShelleyLedger(
        genesis, shelley_config,
        initial_pools=dict(s_ledger_tmp.initial_pools),
        initial_delegs=dict(s_ledger_tmp.initial_delegs))
    eras = cardano_eras(b_protocol, b_ledger, s_protocol, s_ledger,
                        allegra_epoch=allegra_epoch, mary_epoch=mary_epoch)
    nodes = []
    for i in range(n_nodes):
        nodes.append({**b_nodes[i], **s_pools[i], "index": i})
    return eras, hard_fork_rules(eras), nodes


def cardano_block_decode(obj) -> ProtocolBlock:
    """Decode a block with the era-appropriate tx decoder, dispatching on
    the header's era tag (the nested-content role of the reference's
    era-tagged decoders, Block/NestedContent.hs)."""
    header = ProtocolHeader.decode(obj[0])
    era = header.get(ERA_FIELD, BYRON)
    tx_decode = ByronTx.decode if era == BYRON else ShelleyTx.decode
    body = tuple(tx_decode(t) for t in obj[1])
    return ProtocolBlock(header, body)
