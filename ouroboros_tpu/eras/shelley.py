"""Shelley-analog era: TPraos protocol + stake-pool UTxO ledger.

Reference: ouroboros-consensus-shelley/src/Ouroboros/Consensus/Shelley/
- Protocol.hs:355-453  — TPraos instance: `checkIsLeader` runs TWO VRF
  evaluations per slot (nonce eta and leader), `updateChainDepState` runs
  the PRTCL rule: KES header signature verify, both VRF verifies, and the
  operational-certificate Ed25519 verify, plus nonce evolution and ocert
  counter bookkeeping.
- Protocol.hs:472-491  — `checkLeaderValue` fixed-point threshold check
  (here eras/nonintegral.py).
- Protocol.hs:281-310  — `TPraosChainSelectView` tie-breaking: chain
  length, then ocert issue number (same issuer), then lower leader-VRF.
- Protocol/Crypto.hs:15-23 — StandardCrypto = Ed25519 + Sum6KES + PraosVRF;
  the crypto routes through the CryptoBackend batch seam instead.
- Protocol/HotKey.hs:48-149 — evolving KES hot key (crypto/kes.py +
  consensus/protocols/praos.py HotKey, reused here).
- Ledger/Ledger.hs:238-284 — applyLedgerBlock = BBODY incl. the Ed25519
  tx-witness multi-verify; here the witness proofs are extracted for one
  device batch per block window (the BASELINE config #4 primitive).

TPU-first shape: all state-DEPENDENT work (nonce evolution, thresholds,
counters, stake snapshots) is cheap host arithmetic in `sequential_checks` /
`reupdate_chain_dep_state`; every expensive proof (2 VRF + KES + OCert-sig
per header, N witness sigs per body) is emitted via `extract_proofs` so a
window of headers/blocks becomes ONE batched device call
(consensus/batch.py).

Ledger depth (the former round-2 simplifications, since implemented):
mark->set->go 3-deep stake snapshots (SNAP); reserves/treasury monetary
expansion with per-pool rewards by go-snapshot stake share x apparent
performance, claimed through exact-balance withdrawals (RUPD/WDRL); a
pool-retirement queue processed at epoch boundaries (POOLREAP); and the
full TICKN nonce rule mixing the previous epoch's last header hash into
the active nonce.  The independent spec oracle in testing/dual.py
recomputes the three ledger rules; the nonce rule is covered by direct
unit tests (tests/test_shelley_depth.py TestFullNonceRule).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace
from fractions import Fraction
from functools import lru_cache
from typing import Any, Optional, Sequence

from ..chain.block import Point, point_of
from ..consensus.ledger import LedgerError, LedgerRules, OutsideForecastRange
from ..consensus.protocol import ConsensusProtocol, ProtocolError
from ..consensus.protocols.praos import HotKey
from ..crypto import ed25519_ref, kes as kes_mod, vrf_ref
from ..crypto.backend import (
    Ed25519Req, GLOBAL_BETA_CACHE, KesReq, VrfReq,
)
from ..utils import cbor

# header protocol-evidence fields (sign-the-header-minus-KES-sig convention)
ETA_VRF_FIELD = "tp_eta_vrf"
LEADER_VRF_FIELD = "tp_leader_vrf"
KES_FIELD = "tp_kes_sig"
OCERT_FIELD = "tp_ocert"
ISSUER_FIELD = "tp_issuer_vk"

POOL_ID_BYTES = 28                     # Blake2b-224 of the cold vk


def _b2b(data: bytes, n: int = 32) -> bytes:
    return hashlib.blake2b(data, digest_size=n).digest()


@lru_cache(maxsize=4096)
def pool_id_of(cold_vk: bytes) -> bytes:
    """KeyHash of a pool's cold key (Blake2b-224, as in Shelley).
    Memoized: the replay hot path derives it three times per header from
    a handful of distinct keys."""
    return _b2b(cold_vk, POOL_ID_BYTES)


# ---------------------------------------------------------------------------
# Operational certificates
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class OCert:
    """Operational certificate: the cold key delegates block issuance to a
    KES hot key (OCert in the PRTCL rule; verified per header)."""
    kes_vk: bytes                      # hot-key root verification key
    counter: int                       # issue number (monotone per pool)
    kes_period_start: int              # first KES period the hot key covers
    sigma: bytes                       # cold-key Ed25519 sig over the body

    def body_bytes(self) -> bytes:
        return cbor.dumps([self.kes_vk, self.counter, self.kes_period_start])

    def to_bytes(self) -> bytes:
        return cbor.dumps([self.kes_vk, self.counter, self.kes_period_start,
                           self.sigma])

    @classmethod
    def from_bytes(cls, raw: bytes) -> "OCert":
        obj = cbor.loads(raw)
        return cls(bytes(obj[0]), int(obj[1]), int(obj[2]), bytes(obj[3]))


def make_ocert(cold_sk: bytes, kes_vk: bytes, counter: int,
               kes_period_start: int) -> OCert:
    body = cbor.dumps([kes_vk, counter, kes_period_start])
    return OCert(kes_vk, counter, kes_period_start,
                 ed25519_ref.sign(cold_sk, body))


# ---------------------------------------------------------------------------
# Protocol configuration / ledger view
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TPraosConfig:
    k: int = 5                         # security parameter
    f: Fraction = Fraction(1, 2)       # active slot coefficient
    epoch_length: int = 100
    slots_per_kes_period: int = 10
    kes_depth: int = 6                 # Sum6KES -> 64 periods
    max_kes_evolutions: int = 62
    # monetary expansion / treasury cut (the rho and tau protocol
    # parameters of the reward calculation)
    rho: Fraction = Fraction(1, 10)
    tau: Fraction = Fraction(1, 5)

    @property
    def stability_window(self) -> int:
        """3k/f slots — the randomness-stabilisation window after which the
        candidate nonce freezes (and the ledger-view forecast horizon)."""
        f = self.f
        return (3 * self.k * f.denominator + f.numerator - 1) // f.numerator


@dataclass(frozen=True)
class PoolInfo:
    stake_num: int
    stake_den: int
    vrf_vk: bytes

    @property
    def sigma(self) -> Fraction:
        return Fraction(self.stake_num, self.stake_den) \
            if self.stake_den else Fraction(0)


@dataclass
class TPraosLedgerView:
    """What TPraos needs from the ledger: the pool stake distribution of
    the snapshot used for leader election (PoolDistr in the reference)."""
    pools: dict                        # pool_id -> PoolInfo

    def get(self, pool_id: bytes) -> Optional[PoolInfo]:
        return self.pools.get(pool_id)


# ---------------------------------------------------------------------------
# Chain-dependent state
# ---------------------------------------------------------------------------


def _fast_replace(obj, **kw):
    """dataclasses.replace for the hot sequential pass: ~25us -> ~2us per
    call by skipping the kwargs->__init__ round-trip (and __post_init__'s
    UtxoMap coercion, which hot callers already guarantee).  Only used
    where every field value is already in its canonical type."""
    new = object.__new__(type(obj))
    d = dict(obj.__dict__)
    d.update(kw)
    new.__dict__.update(d)
    return new


@dataclass(frozen=True)
class TPraosState:
    """PrtclState + TICKN analog: epoch nonces and per-pool ocert counters.

    eta0   — active nonce: seeds both VRF inputs all epoch
    eta_v  — evolving nonce: folds in every block nonce
    eta_c  — candidate: trails eta_v until the stability window, then frozen
    eta_ph — previous-header nonce: hash of the last applied header (the
             PRTCL "lab"); at the epoch boundary it is the hash of the
             previous epoch's final header, mixed into eta0 (the full
             TICKN rule the reference applies)
    counters — ((pool_id, issue_no), ...) sorted
    """
    epoch: int
    eta0: bytes
    eta_v: bytes
    eta_c: bytes
    counters: tuple = ()
    eta_ph: bytes = b"\x00" * 32

    @classmethod
    def genesis(cls, seed: bytes = b"shelley-genesis") -> "TPraosState":
        eta = _b2b(b"eta0:" + seed)
        return cls(0, eta, eta, eta, ())

    def counter_of(self, pool_id: bytes) -> int:
        for p, c in self.counters:
            if p == pool_id:
                return c
        return -1

    def with_counter(self, pool_id: bytes, counter: int) -> "TPraosState":
        d = dict(self.counters)
        d[pool_id] = counter
        return _fast_replace(self, counters=tuple(sorted(d.items())))


@dataclass(frozen=True)
class TPraosIsLeader:
    """IsLeader evidence: both VRF proofs for the slot."""
    eta_proof: bytes
    leader_proof: bytes


@dataclass(frozen=True)
class TPraosCanBeLeader:
    """Forging credentials (TPraosCanBeLeader analog)."""
    cold_sk: bytes
    vrf_sk: bytes
    ocert: OCert

    @property
    def cold_vk(self) -> bytes:
        return ed25519_ref.public_key(self.cold_sk)

    @property
    def pool_id(self) -> bytes:
        return pool_id_of(self.cold_vk)


@dataclass(frozen=True)
class TPraosSelectView:
    """Chain comparison projection (TPraosChainSelectView,
    Protocol.hs:281-310)."""
    block_no: int
    slot: int
    issuer_vk: bytes
    issue_no: int
    leader_vrf: int                    # lower wins ties


def _vrf_alpha(domain: bytes, slot: int, eta0: bytes) -> bytes:
    """mkSeed analog: VRF input = H(domain || slot || eta0)."""
    return _b2b(domain + slot.to_bytes(8, "big") + eta0)


def _leader_value(beta: bytes) -> int:
    return int.from_bytes(beta, "big")


class TPraos(ConsensusProtocol):
    """The TPraos consensus protocol over a TPraosLedgerView."""

    def __init__(self, config: TPraosConfig,
                 genesis_seed: bytes = b"shelley-genesis"):
        self.config = config
        self.genesis_seed = genesis_seed
        self.security_param = config.k
        self._betas = GLOBAL_BETA_CACHE

    # -- epochs / periods ----------------------------------------------------
    def epoch_of(self, slot: int) -> int:
        return slot // self.config.epoch_length

    def first_slot_of(self, epoch: int) -> int:
        return epoch * self.config.epoch_length

    def kes_period_of(self, slot: int) -> int:
        return slot // self.config.slots_per_kes_period

    def _freeze_slot(self, epoch: int) -> int:
        """Slot at which this epoch's candidate nonce freezes."""
        return self.first_slot_of(epoch + 1) - self.config.stability_window

    # -- state ---------------------------------------------------------------
    def initial_chain_dep_state(self) -> TPraosState:
        return TPraosState.genesis(self.genesis_seed)

    def tick_chain_dep_state(self, state: TPraosState, ledger_view,
                             slot: int) -> TPraosState:
        """Cross epoch boundaries (TICKN): the candidate nonce combines
        with the previous epoch's last header hash (eta_ph) to become the
        active nonce — the full rule (candidate ⭒ prev-hash nonce)."""
        target = self.epoch_of(slot)
        while state.epoch < target:
            nxt = state.epoch + 1
            eta0 = _b2b(b"tickn:" + state.eta_c + state.eta_ph
                        + nxt.to_bytes(8, "big"))
            state = replace(state, epoch=nxt, eta0=eta0)
        return state

    # -- header decoding -----------------------------------------------------
    def _decode_header(self, header):
        """Parse the protocol fields; memoized on the header's own cache —
        the hot path (sequential_checks + extract_proofs +
        reupdate_chain_dep_state) decodes each header three times."""
        got = header._cache.get("tp_dec")
        if got is not None:
            return got
        issuer_vk = header.get(ISSUER_FIELD)
        ocert_raw = header.get(OCERT_FIELD)
        pi_eta = header.get(ETA_VRF_FIELD)
        pi_leader = header.get(LEADER_VRF_FIELD)
        kes_sig = header.get(KES_FIELD)
        if None in (issuer_vk, ocert_raw, pi_eta, pi_leader, kes_sig):
            raise ProtocolError("TPraos: header missing protocol fields")
        try:
            ocert = OCert.from_bytes(ocert_raw)
        except Exception as e:
            raise ProtocolError(f"TPraos: malformed OCert: {e}") from e
        got = (issuer_vk, ocert, pi_eta, pi_leader, kes_sig)
        header._cache["tp_dec"] = got
        return got

    # -- validation ----------------------------------------------------------
    def sequential_checks(self, ticked: TPraosState, header,
                          ledger_view: TPraosLedgerView) -> None:
        cfg = self.config
        # defense-in-depth: validate_envelope / the HFC era gate reject this
        # first on every production path; kept so TPraos is safe standalone
        if header.get("ebb"):
            raise ProtocolError("TPraos: Shelley admits no EBBs")
        issuer_vk, ocert, pi_eta, pi_leader, _ = self._decode_header(header)
        pid = pool_id_of(issuer_vk)
        pool = ledger_view.get(pid)
        if pool is None:
            raise ProtocolError(
                f"TPraos: issuer pool {pid.hex()[:12]} not in the stake "
                f"distribution")
        try:
            beta_leader = self._betas.get(pi_leader)
        except ValueError as e:
            raise ProtocolError(f"TPraos: malformed leader VRF: {e}") from e
        from .nonintegral import check_leader_value
        if not check_leader_value(_leader_value(beta_leader),
                                  8 * vrf_ref.OUTPUT_LEN,
                                  pool.sigma, cfg.f):
            raise ProtocolError(
                f"TPraos: leader VRF value above stake threshold at slot "
                f"{header.slot} (sigma={pool.sigma})")
        period = self.kes_period_of(header.slot)
        evolutions = period - ocert.kes_period_start
        if not 0 <= evolutions < min(cfg.max_kes_evolutions,
                                     kes_mod.total_periods(cfg.kes_depth)):
            raise ProtocolError(
                f"TPraos: KES period {period} outside OCert window "
                f"[{ocert.kes_period_start}, +{cfg.max_kes_evolutions})")
        if ocert.counter < ticked.counter_of(pid):
            raise ProtocolError(
                f"TPraos: OCert issue number {ocert.counter} regressed "
                f"below {ticked.counter_of(pid)}")
        # OCERT rule bounds the new issue number: m <= n <= m+1, where a
        # pool with no recorded counter defaults to m=0 (so n in {0, 1})
        current = max(ticked.counter_of(pid), 0)
        if ocert.counter > current + 1:
            raise ProtocolError(
                f"TPraos: OCert issue number {ocert.counter} jumps past "
                f"{current} + 1")

    def extract_proofs(self, ticked: TPraosState, header,
                       ledger_view: TPraosLedgerView) -> list:
        cfg = self.config
        try:
            issuer_vk, ocert, pi_eta, pi_leader, kes_sig = \
                self._decode_header(header)
        except ProtocolError:
            return []
        pool = ledger_view.get(pool_id_of(issuer_vk))
        if pool is None:
            return []
        period = self.kes_period_of(header.slot)
        c = header._cache
        kes_msg = c.get("tp_kes_msg")
        if kes_msg is None:
            kes_msg = c["tp_kes_msg"] = header.bytes_dropping(KES_FIELD)
        ocert_body = c.get("tp_ocert_body")
        if ocert_body is None:
            ocert_body = c["tp_ocert_body"] = ocert.body_bytes()
        return [
            VrfReq(vk=pool.vrf_vk,
                   alpha=_vrf_alpha(b"eta", header.slot, ticked.eta0),
                   proof=pi_eta),
            VrfReq(vk=pool.vrf_vk,
                   alpha=_vrf_alpha(b"leader", header.slot, ticked.eta0),
                   proof=pi_leader),
            Ed25519Req(vk=issuer_vk, msg=ocert_body, sig=ocert.sigma),
            KesReq(depth=cfg.kes_depth, vk=ocert.kes_vk,
                   period=period - ocert.kes_period_start,
                   msg=kes_msg, sig_bytes=kes_sig),
        ]

    def vrf_proofs_of(self, headers) -> list:
        proofs = []
        for h in headers:
            for field_name in (ETA_VRF_FIELD, LEADER_VRF_FIELD):
                pi = h.get(field_name)
                if pi is not None:
                    proofs.append(pi)
        return proofs

    def reupdate_chain_dep_state(self, ticked: TPraosState, header,
                                 ledger_view) -> TPraosState:
        """Nonce evolution (UPDN) + lab tracking + ocert counter
        bookkeeping — the cheap sequential pass."""
        issuer_vk, ocert, pi_eta, _, _ = self._decode_header(header)
        block_nonce = _b2b(self._betas.get(pi_eta))
        eta_v = _b2b(ticked.eta_v + block_nonce)
        eta_c = eta_v if header.slot < self._freeze_slot(ticked.epoch) \
            else ticked.eta_c
        return _fast_replace(ticked, eta_v=eta_v, eta_c=eta_c,
                             eta_ph=_b2b(b"lab:" + header.hash)).with_counter(
            pool_id_of(issuer_vk), ocert.counter)

    # -- leadership ----------------------------------------------------------
    def check_is_leader(self, can_be_leader: TPraosCanBeLeader, slot: int,
                        ticked: TPraosState,
                        ledger_view: TPraosLedgerView
                        ) -> Optional[TPraosIsLeader]:
        """checkIsLeader (Protocol.hs:366-415): evaluate both VRFs, compare
        the leader output to the stake threshold."""
        pool = ledger_view.get(can_be_leader.pool_id)
        if pool is None:
            return None
        pi_leader = vrf_ref.prove(
            can_be_leader.vrf_sk, _vrf_alpha(b"leader", slot, ticked.eta0))
        beta = vrf_ref.proof_to_hash(pi_leader)
        from .nonintegral import check_leader_value
        if not check_leader_value(_leader_value(beta),
                                  8 * vrf_ref.OUTPUT_LEN,
                                  pool.sigma, self.config.f):
            return None
        pi_eta = vrf_ref.prove(
            can_be_leader.vrf_sk, _vrf_alpha(b"eta", slot, ticked.eta0))
        return TPraosIsLeader(eta_proof=pi_eta, leader_proof=pi_leader)

    # -- chain ordering ------------------------------------------------------
    def select_view(self, header) -> TPraosSelectView:
        issuer_vk, ocert, _, pi_leader, _ = self._decode_header(header)
        return TPraosSelectView(
            block_no=header.block_no, slot=header.slot, issuer_vk=issuer_vk,
            issue_no=ocert.counter,
            leader_vrf=_leader_value(self._betas.get(pi_leader)))

    def prefer_candidate(self, ours: TPraosSelectView,
                         candidate: TPraosSelectView) -> bool:
        """Protocol.hs:281-310: longer chain; tie on length -> same issuer
        decides by issue number (doppelganger defence), different issuers
        by lower leader-VRF value."""
        if candidate.block_no != ours.block_no:
            return candidate.block_no > ours.block_no
        if candidate.issuer_vk == ours.issuer_vk \
                and candidate.issue_no != ours.issue_no:
            return candidate.issue_no > ours.issue_no
        return candidate.leader_vrf < ours.leader_vrf


def forge_tpraos_fields(protocol: TPraos, hot_key: HotKey,
                        can_be_leader: TPraosCanBeLeader,
                        is_leader: TPraosIsLeader, header):
    """Attach the TPraos evidence and KES-sign the header (the forging half
    of Protocol.hs:355-453 + HotKey.hs signing)."""
    h = header.with_fields(**{
        ISSUER_FIELD: can_be_leader.cold_vk,
        OCERT_FIELD: can_be_leader.ocert.to_bytes(),
        ETA_VRF_FIELD: is_leader.eta_proof,
        LEADER_VRF_FIELD: is_leader.leader_proof,
    })
    period = protocol.kes_period_of(header.slot) \
        - can_be_leader.ocert.kes_period_start
    sig = hot_key.sign_at(period, h.bytes_dropping(KES_FIELD))
    return h.with_fields(**{KES_FIELD: sig})


# ---------------------------------------------------------------------------
# The Shelley ledger: UTxO + stake pools + delegation
# ---------------------------------------------------------------------------

# certificates carried in tx bodies (CBOR-friendly tuples):
#   ("pool",  cold_vk, vrf_vk)  — register/update a stake pool
#   ("deleg", addr, pool_id)    — delegate addr's stake to a pool
#   ("retire", cold_vk, epoch8) — schedule the pool's retirement at the
#                                 named epoch (POOLREAP; epoch as 8 bytes BE)
CERT_POOL = "pool"
CERT_DELEG = "deleg"
CERT_RETIRE = "retire"


@dataclass(frozen=True)
class ShelleyTx:
    """Tx = inputs + outputs + certificates, Ed25519-witnessed over txid.

    One tx type serves the whole Shelley family, feature-gated per era
    (the reference's era-indexed tx types over shared machinery):
    - validity: () or (invalid_before, invalid_after) slots, -1 = unbounded
      — Allegra+ (timelock validity intervals)
    - mint: ((asset_id, qty), ...), qty<0 burns — Mary+ (multi-asset);
      outputs are (addr, amount[, assets]) with assets ((asset_id, qty),...)
    - withdrawals: ((pool_id, amount), ...) — claim a reward balance into
      the tx's spendable value (must match the balance exactly, as in the
      reference's WDRL rule; witnessed by the pool's cold key)
    """
    inputs: tuple                      # TxIn-like (txid, ix) pairs
    outputs: tuple                     # (addr, amount, assets) triples
    certs: tuple = ()
    witnesses: tuple = ()              # (vk, sig) pairs
    validity: tuple = ()
    mint: tuple = ()
    withdrawals: tuple = ()            # ((pool_id, amount), ...)

    _cache: dict = field(default_factory=dict, repr=False, hash=False,
                         compare=False)

    def body_encode(self):
        return [[list(i) for i in self.inputs],
                [[a, m, [list(av) for av in assets]]
                 for a, m, assets in self.outputs],
                [list(c) for c in self.certs],
                list(self.validity),
                [list(mv) for mv in self.mint],
                [list(w) for w in self.withdrawals]]

    @property
    def txid(self) -> bytes:
        c = self._cache
        if "id" not in c:
            # span-assembled body bytes from ProtocolBlock.from_bytes,
            # when present — skips re-encoding the body
            bb = c.pop("body_bytes", None)
            c["id"] = _b2b(bb if bb is not None
                           else cbor.dumps(self.body_encode()))
        return c["id"]

    def encode(self):
        return self.body_encode() + [[[vk, sig] for vk, sig in self.witnesses]]

    @classmethod
    def decode(cls, obj) -> "ShelleyTx":
        outputs = tuple((bytes(o[0]), int(o[1]),
                         tuple((bytes(a), int(q)) for a, q in o[2]))
                        for o in obj[1])
        if any(m < 0 for _a, m, _assets in outputs):
            raise ValueError("negative output amount")
        return cls(
            tuple((bytes(t), int(i)) for t, i in obj[0]),
            outputs,
            tuple((str(c[0]), bytes(c[1]), bytes(c[2])) for c in obj[2]),
            tuple((bytes(vk), bytes(sig)) for vk, sig in obj[6]),
            tuple(int(v) for v in obj[3]),
            tuple((bytes(a), int(q)) for a, q in obj[4]),
            tuple((bytes(p), int(q)) for p, q in obj[5]))


def _norm_output(o) -> tuple:
    """(addr, amount) or (addr, amount, assets) -> canonical triple."""
    if len(o) == 2:
        return (o[0], o[1], ())
    return (o[0], o[1], tuple(sorted(tuple(av) for av in o[2])))


def make_shelley_tx(inputs: Sequence, outputs: Sequence, certs: Sequence,
                    signing_keys: Sequence[bytes], validity: tuple = (),
                    mint: Sequence = (),
                    withdrawals: Sequence = ()) -> ShelleyTx:
    tx = ShelleyTx(tuple(tuple(i) for i in inputs),
                   tuple(_norm_output(o) for o in outputs),
                   tuple(tuple(c) for c in certs),
                   validity=tuple(validity),
                   mint=tuple(sorted(tuple(mv) for mv in mint)),
                   withdrawals=tuple(sorted(tuple(w) for w in withdrawals)))
    wits = tuple((ed25519_ref.public_key(sk), ed25519_ref.sign(sk, tx.txid))
                 for sk in signing_keys)
    return replace(tx, witnesses=wits)


@dataclass(frozen=True)
class ShelleyLedgerState:
    """UTxO + delegation map + registered pools + mark/set/go stake
    snapshots + the accounting pots (reserves/treasury/rewards) + the
    pool-retirement queue — the NEWEPOCH state surface of
    Shelley/Ledger/Ledger.hs:238-284's `applyBlock` rules."""
    utxo: Any                # UtxoMap: (txid, ix) -> (addr, amount, assets)
    delegs: tuple                      # sorted ((addr, pool_id), ...)
    pools: tuple                       # sorted ((pool_id, vrf_vk), ...)
    epoch: int
    snap_mark: tuple                   # ((pool_id, stake, vrf_vk), ...)
    snap_set: tuple                    # snapshot used for leader election
    slot: int
    tip: Point
    snap_go: tuple = ()                # snapshot rewards are computed from
    reserves: int = 0                  # undistributed coin (shrinks by rho)
    treasury: int = 0
    rewards: tuple = ()                # sorted ((pool_id, claimable), ...)
    retiring: tuple = ()               # sorted ((pool_id, epoch), ...)
    blocks_made: tuple = ()            # sorted ((pool_id, n)) this epoch

    def __post_init__(self):
        if not isinstance(self.utxo, UtxoMap):
            # decoders/tests build states from plain 5-tuple sequences
            object.__setattr__(self, "utxo",
                               UtxoMap.from_items(self.utxo))

    def utxo_dict(self) -> dict:
        return self.utxo.to_dict()

    def reward_of(self, pid: bytes) -> int:
        for p, amt in self.rewards:
            if p == pid:
                return amt
        return 0

    def state_hash(self) -> bytes:
        enc = cbor.dumps([
            [[t, i, a, m, [list(av) for av in assets]]
             for t, i, a, m, assets in self.utxo],
            [[a, p] for a, p in self.delegs],
            [[p, v] for p, v in self.pools],
            self.epoch,
            [[p, s, v] for p, s, v in self.snap_mark],
            [[p, s, v] for p, s, v in self.snap_set],
            self.slot, self.tip.encode(),
            [[p, s, v] for p, s, v in self.snap_go],
            self.reserves, self.treasury,
            [[p, a] for p, a in self.rewards],
            [[p, e] for p, e in self.retiring],
            [[p, n] for p, n in self.blocks_made]])
        return _b2b(enc)


class UtxoMap:
    """Persistent UTxO set: immutable view over a shared base dict plus an
    overlay (adds + deletes), so extending the chain by one block is
    O(inputs + outputs) instead of O(|UTxO|) — the tuple-freeze
    representation made a mainnet-scale replay quadratic.  The overlay is
    flattened into a fresh base every ~|base|/4 mutations, keeping lookup
    chains one level deep while old states (LedgerDB's k snapshots) stay
    valid because bases are never mutated in place.

    Iteration yields sorted (txid, ix, addr, amount, assets) 5-tuples —
    the exact order of the old sorted-tuple representation, so
    state_hash()es are unchanged."""

    __slots__ = ("_base", "_adds", "_dels")

    def __init__(self, base: dict, adds: dict, dels: frozenset):
        self._base = base
        self._adds = adds
        self._dels = dels

    @classmethod
    def from_dict(cls, d: dict) -> "UtxoMap":
        return cls(dict(d), {}, frozenset())

    @classmethod
    def from_items(cls, items) -> "UtxoMap":
        return cls({(t, i): (a, m, assets)
                    for t, i, a, m, assets in items}, {}, frozenset())

    def get(self, key, default=None):
        v = self._adds.get(key)
        if v is not None:
            return v
        if key in self._dels:
            return default
        return self._base.get(key, default)

    def __contains__(self, key) -> bool:
        if key in self._adds:
            return True
        return key not in self._dels and key in self._base

    def to_dict(self) -> dict:
        d = {k: v for k, v in self._base.items() if k not in self._dels}
        d.update(self._adds)
        return d

    def __iter__(self):
        return iter(sorted((t, i, a, m, assets)
                           for (t, i), (a, m, assets)
                           in self.to_dict().items()))

    def __len__(self) -> int:
        # adds that shadow a live base entry are overwrites, not new keys
        extra = sum(1 for k in self._adds
                    if k not in self._base or k in self._dels)
        return (len(self._base) + extra
                - sum(1 for k in self._dels if k in self._base))

    def __eq__(self, other) -> bool:
        if isinstance(other, UtxoMap):
            return self.to_dict() == other.to_dict()
        return NotImplemented

    __hash__ = None

    def apply(self, spent, added) -> "UtxoMap":
        """New map with `spent` keys removed and `added` (key, value)
        pairs inserted — O(delta) amortized."""
        adds = dict(self._adds)
        dels = set(self._dels)
        for k in spent:
            # ALWAYS record the delete: popping only the overlay entry
            # would resurrect a stale base entry if the same outpoint was
            # deleted, re-created, and spent again
            adds.pop(k, None)
            dels.add(k)
        for k, v in added:
            adds[k] = v
            dels.discard(k)
        if len(adds) + len(dels) > max(64, len(self._base) // 4):
            base = {k: v for k, v in self._base.items() if k not in dels}
            base.update(adds)
            return UtxoMap(base, {}, frozenset())
        return UtxoMap(self._base, adds, frozenset(dels))


def _freeze_utxo(utxo: dict) -> UtxoMap:
    return UtxoMap.from_dict(utxo)


# Shelley-family eras in order; later eras accept earlier features
SHELLEY_FAMILY = ("shelley", "allegra", "mary")


class ShelleyLedger(LedgerRules):
    """LedgerRules over ShelleyLedgerState, parameterized by era.

    era="shelley" | "allegra" | "mary" gates tx features (the reference's
    ShelleyBasedEra reuse across Allegra/Mary): validity intervals from
    Allegra, multi-asset values + minting from Mary.

    Stake distribution: at every epoch boundary the snapshots rotate
    go <- set <- mark <- live (SNAP); leader election (ledger_view) reads
    `set`, so a delegation change needs two boundaries to affect
    leadership, and rewards are computed from `go` — the full
    mark/set/go pipeline of the reference.
    """

    GENESIS_TXID = b"\x00" * 32

    def __init__(self, genesis: dict, config: TPraosConfig,
                 initial_pools: Optional[dict] = None,
                 initial_delegs: Optional[dict] = None,
                 era: str = "shelley",
                 initial_reserves: int = 1_000_000):
        """genesis: {addr: amount}; initial_pools: {pool_id: vrf_vk};
        initial_delegs: {addr: pool_id}; initial_reserves seeds the
        monetary-expansion pot the reward calculation draws from."""
        if era not in SHELLEY_FAMILY:
            raise ValueError(f"unknown Shelley-family era {era!r}")
        self.genesis = dict(genesis)
        self.config = config
        self.initial_pools = dict(initial_pools or {})
        self.initial_delegs = dict(initial_delegs or {})
        self.era = era
        self._era_ix = SHELLEY_FAMILY.index(era)
        self.initial_reserves = initial_reserves

    def with_era(self, era: str) -> "ShelleyLedger":
        """Same genesis/config under a later era's feature gates — how the
        HFC composes Allegra/Mary over the shared Shelley machinery (the
        reference's ShelleyBasedEra reuse, CanHardFork.hs:365-422)."""
        return ShelleyLedger(self.genesis, self.config, self.initial_pools,
                             self.initial_delegs, era=era,
                             initial_reserves=self.initial_reserves)

    @property
    def supports_validity(self) -> bool:
        return self._era_ix >= SHELLEY_FAMILY.index("allegra")

    @property
    def supports_multiasset(self) -> bool:
        return self._era_ix >= SHELLEY_FAMILY.index("mary")

    # -- state construction --------------------------------------------------
    def initial_state(self) -> ShelleyLedgerState:
        utxo = {(self.GENESIS_TXID, ix): (addr, amount, ())
                for ix, (addr, amount) in enumerate(
                    sorted(self.genesis.items()))}
        utxo_f = _freeze_utxo(utxo)
        delegs = tuple(sorted(self.initial_delegs.items()))
        pools = tuple(sorted(self.initial_pools.items()))
        snap = self._stake_distr(utxo_f, delegs, pools)
        return ShelleyLedgerState(utxo_f, delegs, pools, 0, snap, snap,
                                  -1, Point.genesis(), snap_go=snap,
                                  reserves=self.initial_reserves)

    @staticmethod
    def _stake_distr(utxo: "UtxoMap", delegs: tuple, pools: tuple) -> tuple:
        """Aggregate UTxO lovelace per pool through the delegation map
        (native assets carry no stake)."""
        by_addr: dict = {}
        for addr, amount, _assets in utxo.to_dict().values():
            by_addr[addr] = by_addr.get(addr, 0) + amount
        registered = dict(pools)
        by_pool: dict = {}
        for addr, pid in delegs:
            if pid in registered:
                by_pool[pid] = by_pool.get(pid, 0) + by_addr.get(addr, 0)
        return tuple(sorted((pid, stake, registered[pid])
                            for pid, stake in by_pool.items() if stake > 0))

    def tip(self, state: ShelleyLedgerState) -> Point:
        return state.tip

    # -- ticking (epoch boundary: rewards, rotation, retirement) -------------
    def _epoch_rewards(self, state: ShelleyLedgerState
                       ) -> tuple[int, int, tuple]:
        """One epoch's reward calculation (the RUPD/NEWEPOCH pulse):
        rho of the reserves becomes the pot, tau of the pot goes to the
        treasury, the rest is split over the GO snapshot's pools by stake
        share scaled by apparent performance (blocks made / expected);
        the undistributed remainder returns to the reserves.  All integer
        arithmetic — every node computes the identical result."""
        cfg = self.config
        pot = state.reserves * cfg.rho.numerator // cfg.rho.denominator
        if pot == 0:
            return state.reserves, state.treasury, state.rewards
        to_treasury = pot * cfg.tau.numerator // cfg.tau.denominator
        distributable = pot - to_treasury
        total_go = sum(s for _p, s, _v in state.snap_go)
        made = dict(state.blocks_made)
        total_blocks = sum(made.values())
        rewards = dict(state.rewards)
        paid = 0
        for pid, stake, _vrf in state.snap_go:
            if total_go == 0 or total_blocks == 0:
                break
            base = distributable * stake // total_go
            expected = max(1, total_blocks * stake // total_go)
            r = base * min(made.get(pid, 0), expected) // expected
            if r:
                rewards[pid] = rewards.get(pid, 0) + r
                paid += r
        reserves = state.reserves - to_treasury - paid
        return reserves, state.treasury + to_treasury, \
            tuple(sorted(rewards.items()))

    def tick(self, state: ShelleyLedgerState, slot: int) -> ShelleyLedgerState:
        target = slot // self.config.epoch_length
        while state.epoch < target:
            nxt = state.epoch + 1
            # 1. rewards from the (pre-rotation) GO snapshot and the
            #    blocks made in the ending epoch
            reserves, treasury, rewards = self._epoch_rewards(state)
            # 2. snapshot rotation go <- set <- mark <- live (SNAP)
            live = self._stake_distr(state.utxo, state.delegs, state.pools)
            # 3. pool retirement (POOLREAP): pools due at the new epoch
            #    leave the registry; their delegations lapse; accrued
            #    rewards stay claimable
            due = {p for p, e in state.retiring if e <= nxt}
            pools = tuple((p, v) for p, v in state.pools if p not in due)
            delegs = (tuple((a, p) for a, p in state.delegs
                            if p not in due) if due else state.delegs)
            state = replace(
                state, epoch=nxt, snap_go=state.snap_set,
                snap_set=state.snap_mark, snap_mark=live,
                pools=pools, delegs=delegs,
                retiring=tuple((p, e) for p, e in state.retiring
                               if p not in due),
                reserves=reserves, treasury=treasury, rewards=rewards,
                blocks_made=())
        return _fast_replace(state, slot=slot)

    # -- protocol support ----------------------------------------------------
    def ledger_view(self, state: ShelleyLedgerState) -> TPraosLedgerView:
        # identity-cached on the snap_set tuple: within an epoch every
        # state shares the same snapshot object, so the per-header replay
        # path reuses one view instead of rebuilding dict + totals
        cached = getattr(self, "_view_cache", None)
        if cached is not None and cached[0] is state.snap_set:
            return cached[1]
        total = sum(s for _p, s, _v in state.snap_set)
        view = TPraosLedgerView({
            pid: PoolInfo(stake, total, vrf_vk)
            for pid, stake, vrf_vk in state.snap_set})
        self._view_cache = (state.snap_set, view)
        return view

    def forecast_view(self, state: ShelleyLedgerState,
                      slot: int) -> TPraosLedgerView:
        """Ledger view at a future slot; the horizon is the stability
        window past the tip (ledgerViewForecastAt for Shelley)."""
        if slot > state.slot + self.config.stability_window:
            raise OutsideForecastRange(
                f"slot {slot} beyond horizon "
                f"{state.slot + self.config.stability_window}")
        if slot // self.config.epoch_length == state.epoch:
            # same epoch: no snapshot rotation, the view is the state's own
            return self.ledger_view(state)
        return self.ledger_view(self.tick(state, max(slot, state.slot)))

    # -- block application ---------------------------------------------------
    def _check_features(self, tx: ShelleyTx, slot: int) -> None:
        """Era gating + validity-interval check (cheap, sequential)."""
        if tx.validity:
            if not self.supports_validity:
                raise LedgerError(
                    f"validity intervals need allegra+, era is {self.era}")
            before, after = tx.validity
            if (before >= 0 and slot < before) or \
                    (after >= 0 and slot > after):
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} outside validity interval "
                    f"[{before}, {after}] at slot {slot}")
        if (tx.mint or any(assets for _a, _m, assets in tx.outputs)) \
                and not self.supports_multiasset:
            raise LedgerError(
                f"multi-asset values need mary, era is {self.era}")

    def _apply_txs(self, state: ShelleyLedgerState,
                   block) -> ShelleyLedgerState:
        utxo = state.utxo
        delegs = pools = None          # copied lazily: certs are rare
        rewards = retiring = None      # likewise
        for tx in block.body:
            self._check_features(tx, block.slot)
            if len(set(tx.inputs)) != len(tx.inputs):
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} has duplicate inputs")
            spent = 0
            consumed_assets: dict = {}
            for txid, ix in tx.inputs:
                entry = utxo.get((txid, ix))
                if entry is None:
                    raise LedgerError(
                        f"missing input {txid.hex()[:12]}#{ix}")
                _addr, amount, assets = entry
                spent += amount
                for aid, qty in assets:
                    consumed_assets[aid] = consumed_assets.get(aid, 0) + qty
            for pid, amount in tx.withdrawals:
                if rewards is None:
                    rewards = dict(state.rewards)
                bal = rewards.get(pid, 0)
                # WDRL: the claim must match the reward balance exactly
                if amount <= 0 or amount != bal:
                    raise LedgerError(
                        f"tx {tx.txid.hex()[:12]}: withdrawal {amount} != "
                        f"reward balance {bal} of {pid.hex()[:12]}")
                del rewards[pid]
                spent += amount
            for aid, qty in tx.mint:
                consumed_assets[aid] = consumed_assets.get(aid, 0) + qty
            produced = 0
            produced_assets: dict = {}
            for _addr, amount, assets in tx.outputs:
                # Coin is non-negative by construction in the reference
                if amount < 0:
                    raise LedgerError(
                        f"tx {tx.txid.hex()[:12]} has a negative output")
                produced += amount
                for aid, qty in assets:
                    if qty <= 0:
                        raise LedgerError("output asset quantity must be "
                                          "positive")
                    produced_assets[aid] = produced_assets.get(aid, 0) + qty
            if produced > spent:
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} produces {produced} > "
                    f"spends {spent}")
            consumed_assets = {a: q for a, q in consumed_assets.items()
                               if q != 0}
            if produced_assets != consumed_assets:
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]}: asset balance mismatch "
                    f"(consumed+minted != produced)")
            for kind, a, b in tx.certs:
                if pools is None:
                    delegs = dict(state.delegs)
                    pools = dict(state.pools)
                if kind == CERT_POOL:
                    pid = pool_id_of(a)
                    pools[pid] = b
                    if retiring is None:
                        retiring = dict(state.retiring)
                    # re-registration cancels a pending retirement
                    retiring.pop(pid, None)
                elif kind == CERT_DELEG:
                    if b not in pools:
                        raise LedgerError(
                            f"delegation to unregistered pool "
                            f"{b.hex()[:12]}")
                    delegs[a] = b
                elif kind == CERT_RETIRE:
                    pid = pool_id_of(a)
                    if pid not in pools:
                        raise LedgerError(
                            f"retirement of unregistered pool "
                            f"{pid.hex()[:12]}")
                    epoch = int.from_bytes(b, "big")
                    if epoch <= state.epoch:
                        raise LedgerError(
                            f"retirement epoch {epoch} not after the "
                            f"current epoch {state.epoch}")
                    if retiring is None:
                        retiring = dict(state.retiring)
                    retiring[pid] = epoch
                else:
                    raise LedgerError(f"unknown certificate kind {kind!r}")
            utxo = utxo.apply(
                tx.inputs,
                [((tx.txid, ix), (addr, amount, assets))
                 for ix, (addr, amount, assets) in enumerate(tx.outputs)])
        # block production accounting for the reward calculation (the
        # BlocksMade map); the mempool's header-less pseudo-blocks skip it
        blocks_made = state.blocks_made
        header = getattr(block, "header", None)
        issuer_vk = header.get(ISSUER_FIELD) if header is not None \
            and hasattr(header, "get") else None
        if issuer_vk is not None:
            made = dict(blocks_made)
            pid = pool_id_of(issuer_vk)
            made[pid] = made.get(pid, 0) + 1
            blocks_made = tuple(sorted(made.items()))
        return _fast_replace(
            state, utxo=utxo,
            delegs=state.delegs if delegs is None
            else tuple(sorted(delegs.items())),
            pools=state.pools if pools is None
            else tuple(sorted(pools.items())),
            rewards=state.rewards if rewards is None
            else tuple(sorted(rewards.items())),
            retiring=state.retiring if retiring is None
            else tuple(sorted(retiring.items())),
            blocks_made=blocks_made,
            tip=point_of(block))

    def check_tx_witnesses(self, state: ShelleyLedgerState,
                           tx: ShelleyTx) -> None:
        """Structural check: every spender, certificate authoriser, and
        minting policy has a witness (validity of the signatures is the
        batchable proof)."""
        utxo = state.utxo
        wit_vks = {vk for vk, _ in tx.witnesses}
        for txid, ix in tx.inputs:
            entry = utxo.get((txid, ix))
            if entry is not None and entry[0] not in wit_vks:
                raise LedgerError(
                    f"tx {tx.txid.hex()[:12]} spends from "
                    f"{entry[0].hex()[:12]} without a witness")
        for kind, a, _b in tx.certs:
            if kind == CERT_POOL and a not in wit_vks:
                raise LedgerError(
                    "pool registration without the cold-key witness")
            if kind == CERT_DELEG and a not in wit_vks:
                raise LedgerError(
                    "delegation without the staking-key witness")
            if kind == CERT_RETIRE and a not in wit_vks:
                raise LedgerError(
                    "pool retirement without the cold-key witness")
        # withdrawals: the pool's cold key must witness the claim
        wit_pids = {pool_id_of(vk) for vk in wit_vks}
        for pid, _amt in tx.withdrawals:
            if pid not in wit_pids:
                raise LedgerError(
                    f"withdrawal from {pid.hex()[:12]} without the pool "
                    f"cold-key witness")
        # minting: asset_id is the key-hash of the policy key, which must
        # witness the tx (the Mary "policy script = key" base case)
        policy_hashes = {pool_id_of(vk) for vk in wit_vks}
        for aid, _qty in tx.mint:
            if aid not in policy_hashes:
                raise LedgerError(
                    f"minting asset {aid.hex()[:12]} without its policy-key "
                    f"witness")

    def sequential_checks(self, ticked: ShelleyLedgerState, block) -> None:
        for tx in block.body:
            self._check_features(tx, block.slot)
            self.check_tx_witnesses(ticked, tx)

    def extract_proofs(self, ticked: ShelleyLedgerState, block) -> list:
        """The BBODY Ed25519 witness multi-verify, batched
        (Shelley/Ledger/Ledger.hs:279-284)."""
        return [Ed25519Req(vk=vk, msg=tx.txid, sig=sig)
                for tx in block.body for vk, sig in tx.witnesses]

    def apply_block(self, ticked: ShelleyLedgerState, block,
                    backend=None) -> ShelleyLedgerState:
        from ..crypto.backend import default_backend
        backend = backend or default_backend()
        self.sequential_checks(ticked, block)
        reqs = self.extract_proofs(ticked, block)
        if reqs:
            ok = backend.verify_ed25519_batch(reqs)
            if not all(ok):
                raise LedgerError(
                    f"invalid tx witness in block at slot {block.slot}")
        return self._apply_txs(ticked, block)

    def reapply_block(self, ticked: ShelleyLedgerState,
                      block) -> ShelleyLedgerState:
        return self._apply_txs(ticked, block)

    # -- mempool support -----------------------------------------------------
    def apply_tx(self, state: ShelleyLedgerState, tx: ShelleyTx,
                 backend=None) -> ShelleyLedgerState:
        """Validate one tx against `state` without moving the chain tip
        (mempool revalidation semantics)."""
        blk = _OneTxBlock(tx, state.tip)
        self.check_tx_witnesses(state, tx)
        from ..crypto.backend import default_backend
        ok = (backend or default_backend()).verify_ed25519_batch(
            self.extract_proofs(state, blk))
        if not all(ok):
            raise LedgerError(f"tx {tx.txid.hex()[:12]}: bad witness")
        return replace(self._apply_txs(state, blk), tip=state.tip)

    def tx_proofs(self, state: ShelleyLedgerState, tx: ShelleyTx) -> list:
        """One tx's witness obligations (the batching-service admission
        seam): same requests apply_tx verifies inline."""
        return self.extract_proofs(state, _OneTxBlock(tx, state.tip))


class _OneTxBlock:
    """Body-only pseudo-block anchored at an existing tip point so
    _apply_txs can run without a real header (mempool path)."""

    def __init__(self, tx: ShelleyTx, tip: Point):
        self.body = (tx,)
        self.slot = tip.slot
        self.hash = tip.hash
        self.header = self


# ---------------------------------------------------------------------------
# Network setup helper (genesis with working leader election from slot 0)
# ---------------------------------------------------------------------------

@dataclass
class ShelleyPoolKeys:
    cold_sk: bytes
    vrf_sk: bytes
    kes_seed: bytes
    addr_sk: bytes                     # the pool owner's staking/payment key

    @property
    def cold_vk(self) -> bytes:
        return ed25519_ref.public_key(self.cold_sk)

    @property
    def pool_id(self) -> bytes:
        return pool_id_of(self.cold_vk)

    @property
    def vrf_vk(self) -> bytes:
        return vrf_ref.public_key(self.vrf_sk)


def shelley_genesis_setup(n_pools: int, config: TPraosConfig,
                          stake_per_pool: int = 1000,
                          seed: bytes = b"shelley-net"):
    """Keys + protocol + ledger for an n-pool network where every pool has
    equal stake and leader election works from slot 0.  Returns
    (protocol, ledger, [per-pool dict with keys/ocert/hot_key])."""
    pools = []
    genesis, initial_pools, initial_delegs = {}, {}, {}
    for i in range(n_pools):
        tag = seed + b":%d" % i
        keys = ShelleyPoolKeys(
            cold_sk=_b2b(b"cold:" + tag),
            vrf_sk=_b2b(b"vrf:" + tag),
            kes_seed=_b2b(b"kes:" + tag),
            addr_sk=_b2b(b"addr:" + tag))
        kes_key = kes_mod.KesSignKey(config.kes_depth, keys.kes_seed)
        ocert = make_ocert(keys.cold_sk, kes_key.verification_key,
                           counter=0, kes_period_start=0)
        addr = ed25519_ref.public_key(keys.addr_sk)
        genesis[addr] = stake_per_pool
        initial_pools[keys.pool_id] = keys.vrf_vk
        initial_delegs[addr] = keys.pool_id
        pools.append({
            "keys": keys,
            "hot_key": HotKey(kes_key),
            "ocert": ocert,
            "can_be_leader": TPraosCanBeLeader(
                cold_sk=keys.cold_sk, vrf_sk=keys.vrf_sk, ocert=ocert),
            "addr": addr,
        })
    protocol = TPraos(config)
    ledger = ShelleyLedger(genesis, config, initial_pools, initial_delegs)
    return protocol, ledger, pools
