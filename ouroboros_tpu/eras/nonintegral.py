"""Deterministic fixed-point exp/ln — the leader-threshold arithmetic.

Reference seam: `checkLeaderValue` (ouroboros-consensus-shelley/src/
Ouroboros/Consensus/Shelley/Protocol.hs:472-491) delegates to the ledger's
`NonIntegral` fixed-point exp/ln so that the Praos leader check

    certNat/2^512  <  1 - (1-f)^sigma

is evaluated *identically on every node* — floating point would be a
consensus hazard.  Same design here: 34-decimal-digit fixed point over
Python ints (the reference's FixedPoint precision), ln via the artanh
series, exp via range-reduced Taylor, all loops terminating on exact
fixed-point zero so results are platform-independent.
"""
from __future__ import annotations

from fractions import Fraction
from functools import lru_cache

DIGITS = 34
SCALE = 10 ** DIGITS
ONE = SCALE


def _tdiv(a: int, b: int) -> int:
    """Divide truncating toward zero — mandatory for series convergence:
    floor division leaves negative terms stuck at -1 forever."""
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def from_fraction(x: Fraction) -> int:
    """Fraction -> fixed point (truncated)."""
    return _tdiv(x.numerator * SCALE, x.denominator)


def fp_mul(a: int, b: int) -> int:
    return _tdiv(a * b, SCALE)


def fp_div(a: int, b: int) -> int:
    return _tdiv(a * SCALE, b)


def fp_ln(x: int) -> int:
    """ln(x) for x > 0 in fixed point.

    ln(x) = 2·artanh(z), z = (x-1)/(x+1); the series z + z^3/3 + z^5/5 + ...
    converges for every positive x and terminates when a term underflows
    the fixed-point grid.
    """
    if x <= 0:
        raise ValueError("fp_ln: non-positive argument")
    z = fp_div(x - ONE, x + ONE)
    z2 = fp_mul(z, z)
    term = z
    total = 0
    k = 1
    while term != 0:
        total += _tdiv(term, k)
        term = fp_mul(term, z2)
        k += 2
    return 2 * total


def fp_exp(x: int) -> int:
    """e^x in fixed point via Taylor with range reduction.

    |x| is halved until < 1 so the series converges in few exactly-computed
    terms, then the result is squared back up.
    """
    halvings = 0
    while abs(x) > ONE:
        x = _tdiv(x, 2)
        halvings += 1
    total, term, k = ONE, ONE, 1
    while term != 0:
        term = _tdiv(fp_mul(term, x), k)
        total += term
        k += 1
    for _ in range(halvings):
        total = fp_mul(total, total)
    return total


@lru_cache(maxsize=1024)
def _leader_threshold(sigma: Fraction, f: Fraction) -> int:
    """exp(-sigma·ln(1-f)) in fixed point — depends only on the pool's
    relative stake and the active-slot coefficient, which are constant for
    a whole epoch, so the expensive series arithmetic runs once per
    (pool, epoch) instead of once per header (it was ~half the replay's
    host pass)."""
    return fp_exp(-fp_mul(from_fraction(sigma), fp_ln(from_fraction(1 - f))))


def check_leader_value(cert_nat: int, cert_bits: int,
                       sigma: Fraction, f: Fraction) -> bool:
    """Praos leader check: cert_nat/2^cert_bits < 1 - (1-f)^sigma.

    Evaluated as  1/q < exp(-sigma·ln(1-f))  with q = 1 - p, exactly the
    form of the reference's `checkLeaderValue` (Protocol.hs:472-491).
    sigma is the pool's relative stake; f the active-slot coefficient.
    """
    if sigma == 0:
        return False
    # q = 1 - cert_nat/2^bits in fixed point, truncated — identical to
    # from_fraction(1 - Fraction(cert_nat, 2^bits)) without Fraction gcds
    q_fp = _tdiv(((1 << cert_bits) - cert_nat) * SCALE, 1 << cert_bits)
    if q_fp <= 0:        # q underflows the fixed-point grid: never a leader
        return False
    lhs = fp_div(ONE, q_fp)                  # 1/q
    return lhs < _leader_threshold(sigma, f)
