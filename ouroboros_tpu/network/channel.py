"""Channels — in-memory duplex links used by drivers, tests, and ThreadNet.

Reference: ouroboros-network-framework/src/Ouroboros/Network/Channel.hs
(createConnectedChannels + delay/loss variants used by ThreadNet,
SURVEY.md §4.3).  Built on simharness STM queues, so whole networks run
deterministically in simulation.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

from .. import simharness as sim
from ..simharness import TBQueue


class Channel:
    """One direction-pair endpoint: send/recv of opaque items (bytes for
    wire-level channels, message objects for Direct-style tests)."""

    def __init__(self, outq: TBQueue, inq: TBQueue, delay: float = 0.0,
                 label: str = ""):
        self._out = outq
        self._in = inq
        self._delay = delay
        self.label = label

    async def send(self, item: Any) -> None:
        if self._delay:
            await sim.sleep(self._delay)
        await sim.atomically(lambda tx: self._out.put(tx, item))

    async def recv(self) -> Any:
        return await sim.atomically(self._in.get)

    async def wait_ready(self, timeout: float) -> bool:
        """Block until recv() would not block (True) or `timeout` elapses
        (False) — WITHOUT consuming anything.  The cancellation-free way to
        poll a possibly-quiescent peer (vs wrapping recv in sim.timeout,
        which can lose state in the cancelled continuation)."""
        return await sim.wait_pred(lambda tx: self._in.size(tx) > 0, timeout)



def channel_pair(capacity: int = 64, delay: float = 0.0,
                 label: str = "chan") -> Tuple[Channel, Channel]:
    """Two connected endpoints; what A sends, B receives (and vice versa)."""
    ab = TBQueue(capacity, label=f"{label}.ab")
    ba = TBQueue(capacity, label=f"{label}.ba")
    return (Channel(ab, ba, delay, label + ".A"),
            Channel(ba, ab, delay, label + ".B"))
