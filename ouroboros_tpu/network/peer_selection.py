"""Peer-selection governor — declarative connectivity targets.

Reference: ouroboros-network/src/Ouroboros/Network/PeerSelection/
Governor.hs:427-469 (main loop re-running a guarded STM decision set),
Governor/Types.hs:89-94 (`PeerSelectionTargets` {root/known/established/
active}), KnownPeers.hs (known-peer set with reconnect times),
LedgerPeers.hs:96 (`accPoolStake` stake-weighted sampling), and the churn
stub `peerChurnGovernor` (Governor.hs:557).

As in the reference snapshot, the governor is a standalone, heavily
property-tested component (diffusion wires the subscription machinery;
governor-driven P2P was future work there — SURVEY.md §2).  Decisions are
pure (`governor_decisions`) over an immutable view so properties mirror
the reference's: targets are reached, no oscillation, suspensions respected.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, Optional, Sequence

from .. import simharness as sim
from ..observe import metrics as _metrics
from ..simharness import Retry, TVar

# governor churn counters (ISSUE 14): successful ladder transitions plus
# failure-driven suspensions, pre-bound (OBS002).  Gated int bumps —
# invisible to sim determinism.
_PROMOTED_COLD = _metrics.counter("net.governor.promote_cold")
_PROMOTED_WARM = _metrics.counter("net.governor.promote_warm")
_DEMOTED_HOT = _metrics.counter("net.governor.demote_hot")
_DEMOTED_WARM = _metrics.counter("net.governor.demote_warm")
_CHURN_ROUNDS = _metrics.counter("net.governor.churn_rounds")
_GOV_SUSPENSIONS = _metrics.counter("net.governor.suspensions")


@dataclass(frozen=True)
class PeerSelectionTargets:
    """Governor/Types.hs:89-94."""
    target_known: int = 20
    target_established: int = 10
    target_active: int = 5

    def sane(self) -> bool:
        return (0 <= self.target_active <= self.target_established
                <= self.target_known)


@dataclass
class KnownPeerInfo:
    """KnownPeers.hs per-peer bookkeeping."""
    source: str = "gossip"           # "root" | "ledger" | "gossip"
    fail_count: int = 0
    reconnect_at: float = 0.0        # suspended until (virtual time)


class KnownPeers:
    """The known-peer set (PeerSelection/KnownPeers.hs)."""

    def __init__(self):
        self.peers: Dict[object, KnownPeerInfo] = {}

    def add(self, addr, source: str = "gossip") -> None:
        self.peers.setdefault(addr, KnownPeerInfo(source=source))

    def remove(self, addr) -> None:
        self.peers.pop(addr, None)

    def suspend(self, addr, until: float) -> None:
        info = self.peers.get(addr)
        if info is not None:
            info.fail_count += 1
            info.reconnect_at = max(info.reconnect_at, until)

    def cooldown(self, addr, until: float) -> None:
        """Churn cool-down: delay re-selection WITHOUT counting a failure."""
        info = self.peers.get(addr)
        if info is not None:
            info.reconnect_at = max(info.reconnect_at, until)

    def available(self, now: float, exclude=()) -> list:
        ex = set(exclude)
        return sorted((a for a, i in self.peers.items()
                       if a not in ex and i.reconnect_at <= now),
                      key=str)

    def __len__(self) -> int:
        return len(self.peers)

    def __contains__(self, addr) -> bool:
        return addr in self.peers


@dataclass(frozen=True)
class GovernorView:
    """Immutable snapshot the pure decision step runs over."""
    now: float
    targets: PeerSelectionTargets
    known: tuple                     # available (non-suspended) known addrs
    known_total: int
    established: tuple               # warm + hot
    active: tuple                    # hot subset


@dataclass(frozen=True)
class Decision:
    kind: str                        # below
    addr: object = None

# decision kinds (each maps to one guarded job in Governor.hs:427-469)
REQUEST_MORE_PEERS = "request-more-peers"
PROMOTE_COLD = "promote-cold-to-warm"    # connect
PROMOTE_WARM = "promote-warm-to-hot"     # activate protocols
DEMOTE_HOT = "demote-hot-to-warm"
DEMOTE_WARM = "demote-warm-to-cold"


def governor_decisions(view: GovernorView,
                       rng: Optional[random.Random] = None) -> list[Decision]:
    """One pure decision round: everything the guarded set would fire now.

    Mirrors Governor.hs's decision order: grow known peers, then promote
    toward the established/active targets, then demote overshoot."""
    out: list[Decision] = []
    t = view.targets
    est, act = set(view.established), set(view.active)

    if view.known_total < t.target_known:
        out.append(Decision(REQUEST_MORE_PEERS))

    cold = [a for a in view.known if a not in est]
    want_est = t.target_established - len(est)
    pick = rng.sample if rng else (lambda xs, n: xs[:n])
    for a in pick(cold, min(want_est, len(cold))) if want_est > 0 else []:
        out.append(Decision(PROMOTE_COLD, a))

    warm = [a for a in view.established if a not in act]
    want_act = t.target_active - len(act)
    for a in pick(warm, min(want_act, len(warm))) if want_act > 0 else []:
        out.append(Decision(PROMOTE_WARM, a))

    over_act = len(act) - t.target_active
    if over_act > 0:
        for a in sorted(act, key=str)[:over_act]:
            out.append(Decision(DEMOTE_HOT, a))

    over_est = len(est) - t.target_established
    if over_est > 0:
        demotable = sorted((a for a in est if a not in act), key=str)
        for a in demotable[:over_est]:
            out.append(Decision(DEMOTE_WARM, a))
    return out


def ledger_peer_sample(stake_map: Dict[object, int], n: int,
                       rng: random.Random) -> list:
    """Stake-weighted sampling without replacement (accPoolStake,
    LedgerPeers.hs:96): repeatedly draw from the cumulative stake line."""
    pool = dict(stake_map)
    out = []
    while pool and len(out) < n:
        total = sum(pool.values())
        x = rng.uniform(0, total)
        acc = 0.0
        chosen = None
        for addr in sorted(pool, key=str):
            acc += pool[addr]
            if x <= acc:
                chosen = addr
                break
        if chosen is None:
            chosen = sorted(pool, key=str)[-1]
        out.append(chosen)
        del pool[chosen]
    return out


class PeerSelectionActions:
    """Side-effect interface the governor loop drives (the reference's
    PeerSelectionActions record): override in the integration layer."""

    async def request_peers(self) -> Sequence:
        """Root/ledger peer discovery: return new addrs (RootPeersDNS /
        LedgerPeers role)."""
        return []

    async def gossip(self, addr) -> Sequence:
        """Ask one established peer for ITS known peers (the gossip /
        peer-sharing requests of Governor.hs's known-peers-below-target
        job).  Default: nothing."""
        return []

    async def connect(self, addr) -> bool:
        """Cold→warm (establish).  True on success."""
        return True

    async def activate(self, addr) -> bool:
        """Warm→hot (start the mini-protocol set)."""
        return True

    async def deactivate(self, addr) -> None:
        """Hot→warm."""

    async def disconnect(self, addr) -> None:
        """Warm→cold."""


class PeerSelectionGovernor:
    """The main loop (Governor.hs:427): re-run decisions when state
    changes or a retry timer expires."""

    def __init__(self, targets: PeerSelectionTargets,
                 actions: PeerSelectionActions,
                 seed: int = 0, retry_interval: float = 5.0,
                 suspend_base: float = 10.0,
                 gossip_interval: float = 30.0,
                 self_addr=None):
        assert targets.sane()
        self.targets = targets
        self.actions = actions
        self.rng = random.Random(seed)
        self.retry_interval = retry_interval
        self.suspend_base = suspend_base
        self.gossip_interval = gossip_interval
        self.self_addr = self_addr
        self.known = KnownPeers()
        self.established: set = set()
        self.active: set = set()
        self.wakeup = TVar(0, label="governor-wakeup")
        self._v = 0
        self._last_gossip: Dict[object, float] = {}
        self.trace: list = []

    def poke(self) -> None:
        self._v += 1
        try:
            self.wakeup.set_notify(self._v)
        except Exception:
            self.wakeup._value = self._v

    def view(self) -> GovernorView:
        return GovernorView(
            now=sim.now(), targets=self.targets,
            known=tuple(self.known.available(sim.now())),
            known_total=len(self.known),
            established=tuple(sorted(self.established, key=str)),
            active=tuple(sorted(self.active, key=str)))

    def report_failure(self, addr) -> None:
        """Connection/protocol failure feedback (ErrorPolicy verdicts land
        here): exponential-backoff suspension (KnownPeers reconnect)."""
        info = self.known.peers.get(addr)
        backoff = self.suspend_base * (2 ** min(info.fail_count if info
                                                else 0, 6))
        self.known.suspend(addr, sim.now() + backoff)
        self.established.discard(addr)
        self.active.discard(addr)
        _GOV_SUSPENSIONS.inc()
        self.poke()

    async def _apply(self, d: Decision) -> None:
        self.trace.append((sim.now(), d.kind, d.addr))
        if d.kind == REQUEST_MORE_PEERS:
            for a in await self.actions.request_peers():
                self.known.add(a, source="root")
            # gossip round: ask established peers (not recently asked) for
            # their peers — the transitive discovery that fills KnownPeers
            # past the root set (Governor.hs known-peers job)
            now = sim.now()
            for peer in sorted(self.established, key=str):
                if now - self._last_gossip.get(peer, -1e9) \
                        < self.gossip_interval:
                    continue
                self._last_gossip[peer] = now
                for a in await self.actions.gossip(peer):
                    if a != self.self_addr:
                        self.known.add(a, source="gossip")
        elif d.kind == PROMOTE_COLD:
            ok = await self.actions.connect(d.addr)
            if ok:
                self.established.add(d.addr)
                _PROMOTED_COLD.inc()
                info = self.known.peers.get(d.addr)
                if info is not None:
                    info.fail_count = 0
            else:
                self.report_failure(d.addr)
        elif d.kind == PROMOTE_WARM:
            if await self.actions.activate(d.addr):
                self.active.add(d.addr)
                _PROMOTED_WARM.inc()
            else:
                self.report_failure(d.addr)
        elif d.kind == DEMOTE_HOT:
            await self.actions.deactivate(d.addr)
            self.active.discard(d.addr)
            _DEMOTED_HOT.inc()
        elif d.kind == DEMOTE_WARM:
            await self.actions.disconnect(d.addr)
            self.established.discard(d.addr)
            _DEMOTED_WARM.inc()

    async def churn_round(self) -> Optional[object]:
        """One churn step (peerChurnGovernor, Governor.hs:557): demote a
        random hot peer to cold with a cool-down so the replacement is a
        DIFFERENT peer — continuous rotation keeps the peer graph fresh
        and defeats eclipse-by-staleness.  Returns the rotated peer."""
        if not self.active:
            return None
        victim = self.rng.choice(sorted(self.active, key=str))
        _CHURN_ROUNDS.inc()
        self.trace.append((sim.now(), "churn", victim))
        await self.actions.deactivate(victim)
        self.active.discard(victim)
        await self.actions.disconnect(victim)
        self.established.discard(victim)
        self.known.cooldown(victim, sim.now() + self.retry_interval)
        self.poke()
        return victim

    async def run_churn(self, interval: float = 60.0) -> None:
        """The churn loop; fork alongside run()."""
        while True:
            await sim.sleep(interval)
            await self.churn_round()

    async def run(self) -> None:
        while True:
            decisions = governor_decisions(self.view(), self.rng)
            progressed = False
            for d in decisions:
                before = (len(self.known), len(self.established),
                          len(self.active))
                await self._apply(d)
                after = (len(self.known), len(self.established),
                         len(self.active))
                progressed = progressed or after != before
            if progressed:
                await sim.yield_()
                continue
            # idle: wait for a poke or the retry timer (suspended peers
            # coming back / discovery returning nothing yet)
            seen = self.wakeup.value

            def wait(tx, seen=seen):
                if tx.read(self.wakeup) == seen:
                    raise Retry()
            done, _ = await sim.timeout(self.retry_interval,
                                        sim.atomically(wait))
