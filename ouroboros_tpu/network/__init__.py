"""network — typed protocols, channels, mux, mini-protocols, diffusion.

Reference layers L1-L4 (SURVEY.md §1): typed-protocols, network-mux,
ouroboros-network-framework, ouroboros-network.
"""
