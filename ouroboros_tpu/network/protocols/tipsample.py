"""TipSample — experimental tip sampling from established peers.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/TipSample/
Type.hs (states StIdle / StFollowTip n / StDone; messages MsgFollowTip,
MsgNextTip, MsgNextTipDone, MsgDone) and Codec.hs (tags 0-3).

The client asks for the next `n` tip changes at-or-after a slot; the server
streams n-1 MsgNextTip then a final MsgNextTipDone that returns agency.  The
reference carries the outstanding count in the type (StFollowTip (S n));
here the runtime spec loops in one "FollowTip" state and the *count* contract
(exactly n tips, last one Done) is enforced by the client loop below —
the same dynamic check surface as the rest of this package's session types.
"""
from __future__ import annotations

from dataclasses import dataclass

from ...chain import Tip
from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgFollowTip:
    TAG = 0
    n: int           # how many tip changes to stream (>= 1)
    slot: int        # start at this slot or after

    def encode_args(self):
        return [self.n, self.slot]

    @classmethod
    def decode_args(cls, a):
        return cls(int(a[0]), int(a[1]))


@dataclass(frozen=True)
class MsgNextTip:
    TAG = 1
    tip: Tip

    def encode_args(self):
        return [self.tip.encode()]

    @classmethod
    def decode_args(cls, a):
        return cls(Tip.decode(a[0]))


@dataclass(frozen=True)
class MsgNextTipDone:
    TAG = 2
    tip: Tip

    def encode_args(self):
        return [self.tip.encode()]

    @classmethod
    def decode_args(cls, a):
        return cls(Tip.decode(a[0]))


@dataclass(frozen=True)
class MsgDone:
    TAG = 3

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


SPEC = ProtocolSpec(
    name="tip-sample",
    init_state="TSIdle",
    agency={"TSIdle": CLIENT, "TSFollowTip": SERVER, "TSDone": NOBODY},
    transitions={
        ("TSIdle", "MsgFollowTip"): "TSFollowTip",
        ("TSFollowTip", "MsgNextTip"): "TSFollowTip",
        ("TSFollowTip", "MsgNextTipDone"): "TSIdle",
        ("TSIdle", "MsgDone"): "TSDone",
    })

CODEC = Codec([MsgFollowTip, MsgNextTip, MsgNextTipDone, MsgDone])


async def client_sample(session, requests):
    """For each (n, slot) request, collect exactly n tips; returns the list
    of per-request tip lists.  Raises if the server miscounts (the dynamic
    rendering of StFollowTip (S n))."""
    rounds = []
    for n, slot in requests:
        if n < 1:
            raise ValueError("tip-sample: n must be >= 1")
        await session.send(MsgFollowTip(n, slot))
        tips = []
        while True:
            msg = await session.recv()
            tips.append(msg.tip)
            if isinstance(msg, MsgNextTipDone):
                break
            if len(tips) >= n:
                raise RuntimeError(
                    f"tip-sample: server sent more than {n} tips "
                    f"without MsgNextTipDone")
        if len(tips) != n:
            raise RuntimeError(
                f"tip-sample: server ended after {len(tips)} tips, "
                f"expected {n}")
        rounds.append(tips)
    await session.send(MsgDone())
    return rounds


async def server_from_tip_source(session, tip_source):
    """Serve tip changes from `tip_source`, an async callable
    (slot, after_tip) -> Tip yielding each next tip at-or-after `slot`
    (the follower-driven shape of TipSample/Server.hs)."""
    last = None
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgDone):
            return
        slot = msg.slot
        for i in range(msg.n):
            last = await tip_source(slot, last)
            if i == msg.n - 1:
                await session.send(MsgNextTipDone(last))
            else:
                await session.send(MsgNextTip(last))
