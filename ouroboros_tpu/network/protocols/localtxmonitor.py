"""LocalTxMonitor — mempool observation for local clients.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/LocalTxMonitor/
Type.hs (states StIdle / StBusy / StDone; messages MsgRequestTx /
MsgReplyTx / MsgDone).  At the reference snapshot only the type exists (no
codec/client/server shipped); the rebuild provides the full set so wallets
and explorers can stream mempool contents.

Semantics (Type.hs docstring): the server returns each transaction that is
in the mempool and has not yet been sent to this client; slow clients may
miss txs evicted in the meantime — observationally equivalent to missing
them on the network.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgRequestTx:
    TAG = 0

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgReplyTx:
    TAG = 1
    tx: bytes

    def encode_args(self):
        return [self.tx]

    @classmethod
    def decode_args(cls, a):
        return cls(bytes(a[0]))


@dataclass(frozen=True)
class MsgDone:
    TAG = 2

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


SPEC = ProtocolSpec(
    name="local-tx-monitor",
    init_state="TMIdle",
    agency={"TMIdle": CLIENT, "TMBusy": SERVER, "TMDone": NOBODY},
    transitions={
        ("TMIdle", "MsgRequestTx"): "TMBusy",
        ("TMBusy", "MsgReplyTx"): "TMIdle",
        ("TMIdle", "MsgDone"): "TMDone",
    })

CODEC = Codec([MsgRequestTx, MsgReplyTx, MsgDone])


async def server_from_mempool(session, mempool):
    """Stream each mempool tx once per client; blocks (virtually) until a
    new tx arrives.  `mempool` needs snapshot_txs() -> [tx bytes] and an
    awaitable wait_for_new(seen_count) used when drained."""
    sent = 0
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgDone):
            return
        while True:
            txs = mempool.snapshot_txs()
            if sent < len(txs):
                break
            await mempool.wait_for_new(sent)
        await session.send(MsgReplyTx(txs[sent]))
        sent += 1


async def client_collect(session, n: int):
    """Request n transactions, then terminate; returns them."""
    out = []
    for _ in range(n):
        await session.send(MsgRequestTx())
        out.append((await session.recv()).tx)
    await session.send(MsgDone())
    return out
