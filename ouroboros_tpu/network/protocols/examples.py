"""PingPong + ReqResp — the teaching/test fixture protocols.

Reference: typed-protocols-examples/src/Network/TypedProtocol/
{PingPong,ReqResp}/Type.hs.  PingPong is the smallest protocol with client
agency (MsgPing/MsgPong/MsgDone); ReqResp is the generic request-response
shape (MsgReq/MsgResp/MsgDone) used throughout the reference's driver and
channel tests.  Both serve the same role here: minimal fixtures for the
session-type machinery, pipelining, and codec plumbing.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec

# --- PingPong ---------------------------------------------------------------


@dataclass(frozen=True)
class MsgPing:
    TAG = 0

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgPong:
    TAG = 1

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgPingDone:
    TAG = 2

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


PING_PONG_SPEC = ProtocolSpec(
    name="ping-pong",
    init_state="PPIdle",
    agency={"PPIdle": CLIENT, "PPBusy": SERVER, "PPDone": NOBODY},
    transitions={
        ("PPIdle", "MsgPing"): "PPBusy",
        ("PPBusy", "MsgPong"): "PPIdle",
        ("PPIdle", "MsgPingDone"): "PPDone",
    })

PING_PONG_CODEC = Codec([MsgPing, MsgPong, MsgPingDone])


async def ping_pong_client(session, rounds: int) -> int:
    """Send `rounds` pings, count pongs (PingPong/Client.hs shape)."""
    pongs = 0
    for _ in range(rounds):
        await session.send(MsgPing())
        reply = await session.recv()
        assert isinstance(reply, MsgPong)
        pongs += 1
    await session.send(MsgPingDone())
    return pongs


async def ping_pong_server(session) -> int:
    """Answer every ping; returns how many were served."""
    served = 0
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgPingDone):
            return served
        await session.send(MsgPong())
        served += 1


# --- ReqResp ----------------------------------------------------------------


@dataclass(frozen=True)
class MsgReq:
    TAG = 0
    payload: Any

    def encode_args(self):
        return [self.payload]

    @classmethod
    def decode_args(cls, a):
        return cls(a[0])


@dataclass(frozen=True)
class MsgResp:
    TAG = 1
    payload: Any

    def encode_args(self):
        return [self.payload]

    @classmethod
    def decode_args(cls, a):
        return cls(a[0])


@dataclass(frozen=True)
class MsgReqDone:
    TAG = 2

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


REQ_RESP_SPEC = ProtocolSpec(
    name="req-resp",
    init_state="RRIdle",
    agency={"RRIdle": CLIENT, "RRBusy": SERVER, "RRDone": NOBODY},
    transitions={
        ("RRIdle", "MsgReq"): "RRBusy",
        ("RRBusy", "MsgResp"): "RRIdle",
        ("RRIdle", "MsgReqDone"): "RRDone",
    })

REQ_RESP_CODEC = Codec([MsgReq, MsgResp, MsgReqDone])


async def req_resp_client(session, requests) -> list:
    """Issue each request in turn, collect responses
    (ReqResp/Client.hs reqRespClientMap shape)."""
    out = []
    for r in requests:
        await session.send(MsgReq(r))
        out.append((await session.recv()).payload)
    await session.send(MsgReqDone())
    return out


async def req_resp_client_pipelined(session, requests) -> list:
    """Pipelined variant: all requests in flight before collecting —
    the reqRespClientMapPipelined fixture (ReqResp/Client.hs) used to
    check pipelined == unpipelined results."""
    for r in requests:
        await session.send_pipelined(MsgReq(r), reply_state="RRIdle")
    out = [(await session.collect()).payload for _ in requests]
    await session.send(MsgReqDone())
    return out


async def req_resp_server(session, serve: Callable[[Any], Any]):
    """Answer requests with serve(payload) until MsgReqDone."""
    served = 0
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgReqDone):
            return served
        await session.send(MsgResp(serve(msg.payload)))
        served += 1
