"""Mini-protocols: ChainSync, BlockFetch, TxSubmission(+2 via Hello),
KeepAlive, Handshake, LocalStateQuery, LocalTxSubmission, LocalTxMonitor,
TipSample, plus the PingPong/ReqResp teaching protocols.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/*/Type.hs state
machines, rebuilt as ProtocolSpecs + message dataclasses + async peers.
"""
