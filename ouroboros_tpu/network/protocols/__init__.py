"""Mini-protocols: ChainSync, BlockFetch, TxSubmission, KeepAlive,
Handshake, LocalStateQuery, LocalTxSubmission.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/*/Type.hs state
machines, rebuilt as ProtocolSpecs + message dataclasses + async peers.
"""
