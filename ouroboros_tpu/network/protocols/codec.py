"""Generic CBOR message codec: msg <-> bytes as [tag, *args].

Reference pattern: Protocol/*/Codec.hs (CBOR per message, tag-discriminated).
Each message class declares `TAG` and implements encode_args()/decode_args().
"""
from __future__ import annotations

from typing import Any, Sequence, Type

from ...utils import cbor


class CodecError(Exception):
    pass


class Codec:
    def __init__(self, messages: Sequence[Type]):
        self.by_tag = {}
        for cls in messages:
            tag = cls.TAG
            if tag in self.by_tag:
                raise ValueError(f"duplicate tag {tag}")
            self.by_tag[tag] = cls

    def encode(self, msg) -> bytes:
        return cbor.dumps([msg.TAG] + list(msg.encode_args()))

    def decode(self, raw: bytes):
        try:
            obj = cbor.loads(raw)
        except cbor.CBORError as e:
            raise CodecError(str(e)) from e
        if not isinstance(obj, list) or not obj:
            raise CodecError("message must be a CBOR list [tag, ...]")
        cls = self.by_tag.get(obj[0])
        if cls is None:
            raise CodecError(f"unknown message tag {obj[0]}")
        try:
            return cls.decode_args(obj[1:])
        except (IndexError, TypeError, ValueError) as e:
            raise CodecError(f"bad args for {cls.__name__}: {e}") from e


def roundtrip_property(codec: Codec, msgs) -> bool:
    """Codec round-trip check used by per-protocol tests (SURVEY.md §4.4)."""
    for m in msgs:
        if codec.decode(codec.encode(m)) != m:
            return False
    return True
