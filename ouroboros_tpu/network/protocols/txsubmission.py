"""TxSubmission2 — pull-based transaction relay (the server asks).

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/TxSubmission/
Type.hs:43-215.  Agency is inverted vs the other protocols: the inbound side
(SERVER role here) requests tx ids/txs; the outbound side (CLIENT role, the
node with the mempool) replies.  Windowed acks bound memory (SURVEY.md §5
"long-context": windowed TxSubmission acks).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec, branch
from .codec import Codec


@dataclass(frozen=True)
class MsgRequestTxIds:
    TAG = 0
    blocking: bool
    ack: int      # how many previously-sent ids the server has processed
    req: int      # how many new ids may be sent

    def encode_args(self):
        return [self.blocking, self.ack, self.req]

    @classmethod
    def decode_args(cls, a):
        return cls(bool(a[0]), int(a[1]), int(a[2]))


@dataclass(frozen=True)
class MsgReplyTxIds:
    TAG = 1
    ids_and_sizes: tuple   # ((txid: bytes, size: int), ...)

    def encode_args(self):
        return [[[i, s] for i, s in self.ids_and_sizes]]

    @classmethod
    def decode_args(cls, a):
        return cls(tuple((bytes(i), int(s)) for i, s in a[0]))


@dataclass(frozen=True)
class MsgRequestTxs:
    TAG = 2
    ids: tuple

    def encode_args(self):
        # tsIdList must use indefinite-length framing — the reference
        # codec accepts nothing else (messages.cddl:78 note)
        from ...utils.cbor import IndefList
        return [IndefList(self.ids)]

    @classmethod
    def decode_args(cls, a):
        return cls(tuple(bytes(i) for i in a[0]))


@dataclass(frozen=True)
class MsgReplyTxs:
    TAG = 3
    txs: tuple             # opaque tx bytes

    def encode_args(self):
        from ...utils.cbor import IndefList
        return [IndefList(self.txs)]

    @classmethod
    def decode_args(cls, a):
        return cls(tuple(bytes(t) for t in a[0]))


@dataclass(frozen=True)
class MsgDone:
    TAG = 4

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


SPEC = ProtocolSpec(
    name="tx-submission",
    init_state="TxIdle",
    agency={"TxIdle": SERVER, "TxIdsBlocking": CLIENT,
            "TxIdsNonBlocking": CLIENT, "TxTxs": CLIENT, "TxDone": NOBODY},
    transitions={
        ("TxIdle", "MsgRequestTxIds"): branch(
            lambda m: "TxIdsBlocking" if m.blocking else "TxIdsNonBlocking",
            "TxIdsBlocking", "TxIdsNonBlocking"),
        ("TxIdsBlocking", "MsgReplyTxIds"): "TxIdle",
        ("TxIdsBlocking", "MsgDone"): "TxDone",
        ("TxIdsNonBlocking", "MsgReplyTxIds"): "TxIdle",
        ("TxIdle", "MsgRequestTxs"): "TxTxs",
        ("TxTxs", "MsgReplyTxs"): "TxIdle",
    })

CODEC = Codec([MsgRequestTxIds, MsgReplyTxIds, MsgRequestTxs, MsgReplyTxs,
               MsgDone])


async def outbound_from_mempool(session, mempool_reader, done_when_drained=True):
    """Outbound side (CLIENT role): serves tx ids/txs from a mempool reader.

    mempool_reader: object with next_ids(n) -> [(txid, size)] (advancing an
    internal cursor) and lookup(txid) -> tx bytes | None.
    Reference: TxSubmission/Outbound.hs + Mempool/Reader.hs.
    """
    unacked: list = []
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgRequestTxIds):
            del unacked[:msg.ack]
            new = mempool_reader.next_ids(msg.req)
            unacked.extend(i for i, _ in new)
            if not new and msg.blocking and done_when_drained:
                await session.send(MsgDone())
                return
            await session.send(MsgReplyTxIds(tuple(new)))
        elif isinstance(msg, MsgRequestTxs):
            txs = tuple(t for t in (mempool_reader.lookup(i)
                                    for i in msg.ids) if t is not None)
            await session.send(MsgReplyTxs(txs))


async def inbound_collect(session, sink, window: int = 10,
                          max_rounds: int = 1000):
    """Inbound side (SERVER role): window-request ids, fetch txs, feed sink.

    sink(tx) -> None.  The peer may legitimately reply with *fewer* txs than
    requested (mempool eviction between id advertisement and the fetch —
    Outbound.hs filters missing ids), so txs are NOT paired with requested
    ids here; the mempool derives the id by hashing the tx, as the reference
    inbound does (TxSubmission/Inbound.hs:52-172, windowed acks + dedup).
    """
    ack = 0
    for _ in range(max_rounds):
        await session.send(MsgRequestTxIds(True, ack, window))
        reply = await session.recv()
        if isinstance(reply, MsgDone):
            return
        ids = [i for i, _ in reply.ids_and_sizes]
        if ids:
            await session.send(MsgRequestTxs(tuple(ids)))
            for tx in (await session.recv()).txs:
                sink(tx)
        ack = len(ids)
