"""TxSubmission2 — Hello-wrapped TxSubmission.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/TxSubmission2/
Type.hs (TxSubmission2 = Hello TxSubmission StIdle) and Codec.hs:62-63
(codecHello with helloTag 6).

The outbound side (CLIENT role, the node offering its mempool) sends
MsgHello first, then the plain TxSubmission exchange runs: the inbound
side requests tx ids / txs, the outbound side replies.
"""
from __future__ import annotations

from . import txsubmission as tx1
from .hello import wrap

SPEC, CODEC, MsgHello = wrap(tx1.SPEC, tx1.CODEC, hello_tag=6,
                             name="tx-submission-2")

# Re-exports so users of TxSubmission2 see the full message vocabulary.
MsgRequestTxIds = tx1.MsgRequestTxIds
MsgReplyTxIds = tx1.MsgReplyTxIds
MsgRequestTxs = tx1.MsgRequestTxs
MsgReplyTxs = tx1.MsgReplyTxs
MsgDone = tx1.MsgDone


async def outbound_from_mempool(session, mempool_reader,
                                done_when_drained: bool = True):
    """Outbound side: announce with MsgHello, then serve ids/txs
    (TxSubmission2's initiator, Protocol/TxSubmission2/Client.hs shape)."""
    await session.send(MsgHello())
    return await tx1.outbound_from_mempool(
        session, mempool_reader, done_when_drained=done_when_drained)


async def inbound_collect(session, sink, window: int = 10,
                          max_rounds: int = 1000):
    """Inbound side: wait for the peer's MsgHello, then run the windowed
    id/tx collection loop (Protocol/TxSubmission2/Server.hs shape)."""
    hello = await session.recv()
    if not isinstance(hello, MsgHello):
        raise RuntimeError(f"tx-submission-2: expected MsgHello, "
                           f"got {type(hello).__name__}")
    return await tx1.inbound_collect(session, sink, window=window,
                                     max_rounds=max_rounds)
