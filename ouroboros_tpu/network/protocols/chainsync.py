"""ChainSync — header-chain following.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/ChainSync/
Type.hs:26-128 (states StIdle/StNext/StIntersect; messages below),
Examples.hs (follower-driven server), PipelineDecision.hs (pipelining
policy, reimplemented in consensus/chain_sync_client.py).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ...chain import Block, BlockHeader, Point, Tip, point_of
from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgRequestNext:
    TAG = 0

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgAwaitReply:
    TAG = 1

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgRollForward:
    TAG = 2
    header: BlockHeader
    tip: Tip

    def encode_args(self):
        # wrappedHeader = #6.24(bytes .cbor blockHeader): the header rides
        # inside a tag-24 CBOR-in-CBOR envelope (messages.cddl:34)
        from ...utils import cbor
        return [cbor.Tag(24, cbor.dumps(self.header.encode())),
                self.tip.encode()]

    @classmethod
    def decode_args(cls, a):
        from ...utils import cbor
        return cls(BlockHeader.decode(cbor.unwrap_tag24(a[0])),
                   Tip.decode(a[1]))


@dataclass(frozen=True)
class MsgRollBackward:
    TAG = 3
    point: Point
    tip: Tip

    def encode_args(self):
        return [self.point.encode(), self.tip.encode()]

    @classmethod
    def decode_args(cls, a):
        return cls(Point.decode(a[0]), Tip.decode(a[1]))


@dataclass(frozen=True)
class MsgFindIntersect:
    TAG = 4
    points: tuple

    def encode_args(self):
        return [[p.encode() for p in self.points]]

    @classmethod
    def decode_args(cls, a):
        return cls(tuple(Point.decode(p) for p in a[0]))


@dataclass(frozen=True)
class MsgIntersectFound:
    TAG = 5
    point: Point
    tip: Tip

    def encode_args(self):
        return [self.point.encode(), self.tip.encode()]

    @classmethod
    def decode_args(cls, a):
        return cls(Point.decode(a[0]), Tip.decode(a[1]))


@dataclass(frozen=True)
class MsgIntersectNotFound:
    TAG = 6
    tip: Tip

    def encode_args(self):
        return [self.tip.encode()]

    @classmethod
    def decode_args(cls, a):
        return cls(Tip.decode(a[0]))


@dataclass(frozen=True)
class MsgDone:
    TAG = 7

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


SPEC = ProtocolSpec(
    name="chain-sync",
    init_state="StIdle",
    agency={"StIdle": CLIENT, "StNext": SERVER, "StMustReply": SERVER,
            "StIntersect": SERVER, "StDone": NOBODY},
    transitions={
        ("StIdle", "MsgRequestNext"): "StNext",
        ("StNext", "MsgAwaitReply"): "StMustReply",
        ("StNext", "MsgRollForward"): "StIdle",
        ("StNext", "MsgRollBackward"): "StIdle",
        ("StMustReply", "MsgRollForward"): "StIdle",
        ("StMustReply", "MsgRollBackward"): "StIdle",
        ("StIdle", "MsgFindIntersect"): "StIntersect",
        ("StIntersect", "MsgIntersectFound"): "StIdle",
        ("StIntersect", "MsgIntersectNotFound"): "StIdle",
        ("StIdle", "MsgDone"): "StDone",
    })

CODEC = Codec([MsgRequestNext, MsgAwaitReply, MsgRollForward,
               MsgRollBackward, MsgFindIntersect, MsgIntersectFound,
               MsgIntersectNotFound, MsgDone])


def make_codec(header_decode) -> Codec:
    """Codec with a custom header decoder (per-block-type codecs, the
    reference's `codecChainSync` parameterised over the header —
    Protocol/ChainSync/Codec.hs).  header_decode: CBOR object -> header."""
    class _RollForward(MsgRollForward):
        @classmethod
        def decode_args(cls, a):
            from ...utils import cbor
            return cls(header_decode(cbor.unwrap_tag24(a[0])),
                       Tip.decode(a[1]))
    _RollForward.__name__ = "MsgRollForward"
    return Codec([MsgRequestNext, MsgAwaitReply, _RollForward,
                  MsgRollBackward, MsgFindIntersect, MsgIntersectFound,
                  MsgIntersectNotFound, MsgDone])


async def server_from_producer(session, producer_state, fid: int,
                               header_of=None):
    """ChainSync server driven by a ChainProducerState follower
    (Examples.hs's chainSyncServerExample).

    header_of: block -> header to advertise (default: .header attribute).
    When the follower is caught up the server sends MsgAwaitReply and then
    blocks on the producer's version TVar until the chain changes (the
    followerInstructionBlocking semantics) — no polling.
    """
    from ... import simharness as sim
    from ...simharness import Retry

    hdr = header_of or (lambda b: b.header)

    def tip() -> Tip:
        ch = producer_state.chain
        return Tip(ch.head_point, ch.head_block_no)

    while True:
        msg = await session.recv()
        if isinstance(msg, MsgDone):
            return
        if isinstance(msg, MsgFindIntersect):
            found = None
            for p in msg.points:
                if producer_state.chain.contains_point(p):
                    found = p
                    break
            if found is None:
                await session.send(MsgIntersectNotFound(tip()))
            else:
                producer_state.set_follower_point(fid, found)
                await session.send(MsgIntersectFound(found, tip()))
            continue
        # MsgRequestNext
        ins = producer_state.follower_instruction(fid)
        if ins is None:
            await session.send(MsgAwaitReply())
            while ins is None:
                # read the version and re-check the instruction with no
                # yield point in between: a block added during the
                # MsgAwaitReply send (or any earlier await) is seen here
                # instead of being lost to the wait below
                seen = producer_state.version.value
                ins = producer_state.follower_instruction(fid)
                if ins is not None:
                    break

                def wait_change(tx, seen=seen):
                    if tx.read(producer_state.version) == seen:
                        raise Retry()
                await sim.atomically(wait_change)
                ins = producer_state.follower_instruction(fid)
        kind, payload = ins
        if kind == "forward":
            await session.send(MsgRollForward(hdr(payload), tip()))
        else:
            await session.send(MsgRollBackward(payload, tip()))


async def client_sync_to_tip(session, points: Sequence[Point],
                             fragment, header_store: Optional[dict] = None):
    """Simple (unpipelined) client: find intersection, follow until caught
    up to the server tip, then MsgDone.  Updates `fragment`
    (AnchoredFragment of headers) in place; used by tests and as the shape
    model for the consensus ChainSync client."""
    await session.send(MsgFindIntersect(tuple(points)))
    reply = await session.recv()
    if isinstance(reply, MsgIntersectNotFound):
        await session.send(MsgDone())
        return None
    while True:
        await session.send(MsgRequestNext())
        msg = await session.recv()
        if isinstance(msg, MsgAwaitReply):
            # caught up: stop following (test client semantics)
            msg = await session.recv()
            await _apply(msg, fragment, header_store)
            await session.send(MsgDone())
            return fragment
        await _apply(msg, fragment, header_store)
        if fragment.head_point == msg.tip.point:
            await session.send(MsgDone())
            return fragment


async def _apply(msg, fragment, header_store):
    if isinstance(msg, MsgRollForward):
        fragment.add_block(msg.header)
        if header_store is not None:
            header_store[msg.header.hash] = msg.header
    elif isinstance(msg, MsgRollBackward):
        if not fragment.truncate_to(msg.point):
            raise RuntimeError("server rolled back beyond our fragment")
    else:
        raise RuntimeError(f"unexpected {msg}")
