"""KeepAlive — RTT probe + liveness.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/KeepAlive/
Type.hs:42-74 and KeepAlive.hs:41-55 (client loop feeding per-peer GSV
DeltaQ state).
"""
from __future__ import annotations

from dataclasses import dataclass

from ... import simharness as sim
from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgKeepAlive:
    TAG = 0
    cookie: int

    def encode_args(self):
        return [self.cookie]

    @classmethod
    def decode_args(cls, a):
        return cls(int(a[0]))


@dataclass(frozen=True)
class MsgKeepAliveResponse:
    TAG = 1
    cookie: int

    def encode_args(self):
        return [self.cookie]

    @classmethod
    def decode_args(cls, a):
        return cls(int(a[0]))


@dataclass(frozen=True)
class MsgDone:
    TAG = 2

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


SPEC = ProtocolSpec(
    name="keep-alive",
    init_state="KAClient",
    agency={"KAClient": CLIENT, "KAServer": SERVER, "KADone": NOBODY},
    transitions={
        ("KAClient", "MsgKeepAlive"): "KAServer",
        ("KAServer", "MsgKeepAliveResponse"): "KAClient",
        ("KAClient", "MsgDone"): "KADone",
    })

CODEC = Codec([MsgKeepAlive, MsgKeepAliveResponse, MsgDone])


async def server(session):
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgDone):
            return
        await session.send(MsgKeepAliveResponse(msg.cookie))


async def client_probe(session, rounds, interval: float,
                       on_rtt=None, response_timeout=None):
    """Probe loop: send cookie, measure virtual RTT, report to on_rtt
    (the DeltaQ feed, KeepAlive.hs:41-55).  rounds=None probes forever
    (the node's long-lived keep-alive).

    response_timeout: the per-reply watchdog (timeLimitsKeepAlive, 60 s in
    the reference) — a responder silent past it raises KeepAliveTimeout,
    the whole-connection liveness verdict the kernel converts into a mux
    teardown.  The wait is a non-destructive wait_ready poll, so the
    timeout path consumes nothing."""
    rtts = []
    cookie = 0
    while rounds is None or cookie < rounds:
        t0 = sim.now()
        await session.send(MsgKeepAlive(cookie & 0xFFFF))
        if response_timeout is not None:
            ready = await session.channel.wait_ready(response_timeout)
            if not ready:
                from ...node.watchdog import KeepAliveTimeout
                sim.trace_event(("timeout", "keep-alive", "KAServer",
                                 cookie), label="watchdog")
                raise KeepAliveTimeout("keep-alive", "KAServer",
                                       response_timeout)
        reply = await session.recv()
        if reply.cookie != cookie & 0xFFFF:
            raise RuntimeError("keep-alive cookie mismatch")
        rtt = sim.now() - t0
        rtts.append(rtt)
        if on_rtt:
            on_rtt(rtt)
        cookie += 1
        if rounds is not None and cookie == rounds:
            break
        await sim.sleep(interval)
    await session.send(MsgDone())
    return rtts
