"""LocalStateQuery — node-to-client ledger queries with acquire/release.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/LocalStateQuery/
Type.hs:33-124 (acquire a point, query against that ledger state, release)
and consensus's server vs LedgerDB past states
(MiniProtocol/LocalStateQuery/Server.hs).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ...chain import Point
from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgAcquire:
    TAG = 0
    point: Optional[Point]   # None = current tip

    def encode_args(self):
        return [self.point.encode() if self.point else None]

    @classmethod
    def decode_args(cls, a):
        return cls(Point.decode(a[0]) if a[0] is not None else None)


@dataclass(frozen=True)
class MsgAcquired:
    TAG = 1

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgFailure:
    TAG = 2
    reason: str

    def encode_args(self):
        return [self.reason]

    @classmethod
    def decode_args(cls, a):
        return cls(str(a[0]))


@dataclass(frozen=True)
class MsgQuery:
    TAG = 3
    query: Any               # CBOR-encodable query value

    def encode_args(self):
        return [self.query]

    @classmethod
    def decode_args(cls, a):
        return cls(a[0])


@dataclass(frozen=True)
class MsgResult:
    TAG = 4
    result: Any

    def encode_args(self):
        return [self.result]

    @classmethod
    def decode_args(cls, a):
        return cls(a[0])


@dataclass(frozen=True)
class MsgReAcquire:
    TAG = 5
    point: Optional[Point]

    def encode_args(self):
        return [self.point.encode() if self.point else None]

    @classmethod
    def decode_args(cls, a):
        return cls(Point.decode(a[0]) if a[0] is not None else None)


@dataclass(frozen=True)
class MsgRelease:
    TAG = 6

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgDone:
    TAG = 7

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


SPEC = ProtocolSpec(
    name="local-state-query",
    init_state="LSQIdle",
    agency={"LSQIdle": CLIENT, "LSQAcquiring": SERVER,
            "LSQAcquired": CLIENT, "LSQQuerying": SERVER, "LSQDone": NOBODY},
    transitions={
        ("LSQIdle", "MsgAcquire"): "LSQAcquiring",
        ("LSQIdle", "MsgDone"): "LSQDone",
        ("LSQAcquiring", "MsgAcquired"): "LSQAcquired",
        ("LSQAcquiring", "MsgFailure"): "LSQIdle",
        ("LSQAcquired", "MsgQuery"): "LSQQuerying",
        ("LSQAcquired", "MsgReAcquire"): "LSQAcquiring",
        ("LSQAcquired", "MsgRelease"): "LSQIdle",
        ("LSQQuerying", "MsgResult"): "LSQAcquired",
    })

CODEC = Codec([MsgAcquire, MsgAcquired, MsgFailure, MsgQuery, MsgResult,
               MsgReAcquire, MsgRelease, MsgDone])


async def server(session, acquire_state, answer):
    """acquire_state(point|None) -> state handle | None;
    answer(state, query) -> result."""
    state = None
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgDone):
            return
        if isinstance(msg, (MsgAcquire, MsgReAcquire)):
            state = acquire_state(msg.point)
            if state is None:
                await session.send(MsgFailure("point not available"))
            else:
                await session.send(MsgAcquired())
        elif isinstance(msg, MsgQuery):
            await session.send(MsgResult(answer(state, msg.query)))
        elif isinstance(msg, MsgRelease):
            state = None


async def query_once(session, query, point: Optional[Point] = None):
    """Client helper: acquire, query, release, done."""
    await session.send(MsgAcquire(point))
    reply = await session.recv()
    if isinstance(reply, MsgFailure):
        await session.send(MsgDone())
        return None
    await session.send(MsgQuery(query))
    result = (await session.recv()).result
    await session.send(MsgRelease())
    await session.send(MsgDone())
    return result
