"""Hello — protocol transformer reversing initial agency.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/Trans/Hello/
Type.hs (StHello / StTalk embedding) and Codec.hs:75-134 (flat encoding:
MsgHello gets its own tag, MsgTalk is invisible on the wire).

The wrapped protocol gains one extra initial state in which the CLIENT must
send MsgHello; afterwards the inner protocol runs unchanged.  This is how
TxSubmission2 fixes TxSubmission's inverted initial agency: the outbound
side announces itself before the inbound side starts asking for tx ids.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..typed import CLIENT, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgHello:
    TAG = None  # assigned per instantiation via make_hello_msg

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


def make_hello_msg(tag: int):
    """A MsgHello class carrying the instantiation-specific wire tag
    (codecHello's helloTag argument, Trans/Hello/Codec.hs:88)."""
    return type("MsgHello", (MsgHello,), {"TAG": tag})


def wrap(spec: ProtocolSpec, codec: Codec, hello_tag: int,
         name: str | None = None):
    """Hello-transform a protocol: returns (spec', codec', MsgHello class).

    spec': initial state "Hello" with client agency; MsgHello moves to the
    inner protocol's initial state; all inner states/transitions unchanged
    (the StTalk embedding is the identity on state names).
    codec': flat — inner messages keep their tags, MsgHello adds hello_tag.
    """
    hello_cls = make_hello_msg(hello_tag)
    spec2 = ProtocolSpec(
        name=name or f"hello-{spec.name}",
        init_state="Hello",
        agency={"Hello": CLIENT, **spec.agency},
        transitions={("Hello", "MsgHello"): spec.init_state,
                     **spec.transitions})
    codec2 = Codec(list(codec.by_tag.values()) + [hello_cls])
    return spec2, codec2, hello_cls
