"""LocalTxSubmission — wallet-to-node transaction submission.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/
LocalTxSubmission/Type.hs (submit / accept / reject-with-reason).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgSubmitTx:
    TAG = 0
    tx: bytes

    def encode_args(self):
        return [self.tx]

    @classmethod
    def decode_args(cls, a):
        return cls(bytes(a[0]))


@dataclass(frozen=True)
class MsgAcceptTx:
    TAG = 1

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgRejectTx:
    TAG = 2
    reason: str

    def encode_args(self):
        return [self.reason]

    @classmethod
    def decode_args(cls, a):
        return cls(str(a[0]))


@dataclass(frozen=True)
class MsgDone:
    TAG = 3

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


SPEC = ProtocolSpec(
    name="local-tx-submission",
    init_state="LTSIdle",
    agency={"LTSIdle": CLIENT, "LTSBusy": SERVER, "LTSDone": NOBODY},
    transitions={
        ("LTSIdle", "MsgSubmitTx"): "LTSBusy",
        ("LTSIdle", "MsgDone"): "LTSDone",
        ("LTSBusy", "MsgAcceptTx"): "LTSIdle",
        ("LTSBusy", "MsgRejectTx"): "LTSIdle",
    })

CODEC = Codec([MsgSubmitTx, MsgAcceptTx, MsgRejectTx, MsgDone])


async def server(session, try_add):
    """try_add(tx_bytes) -> None (accepted) | str (rejection reason)."""
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgDone):
            return
        err = try_add(msg.tx)
        if err is None:
            await session.send(MsgAcceptTx())
        else:
            await session.send(MsgRejectTx(err))


async def submit(session, txs):
    """Client: submit txs in order; returns list of None|reason."""
    results = []
    for tx in txs:
        await session.send(MsgSubmitTx(tx))
        reply = await session.recv()
        results.append(None if isinstance(reply, MsgAcceptTx)
                       else reply.reason)
    await session.send(MsgDone())
    return results
