"""Handshake — version negotiation, the first protocol on every connection.

Reference: ouroboros-network-framework/src/Ouroboros/Network/Protocol/
Handshake/Type.hs:43-126 (StPropose/StConfirm; propose map -> accept or
refuse) and Version.hs:19-86 (Versions map, acceptableVersion policy).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgProposeVersions:
    TAG = 0
    versions: tuple   # ((version_number, params_cbor), ...) ascending

    def encode_args(self):
        # versionTable is a CBOR MAP with unique ascending keys
        # (messages.cddl:108-115; Handshake/Codec.hs)
        nums = [v for v, _p in self.versions]
        if len(set(nums)) != len(nums):
            raise ValueError("duplicate version numbers in proposal")
        return [{v: p for v, p in sorted(self.versions)}]

    @classmethod
    def decode_args(cls, a):
        # the CBOR layer already rejects duplicate keys; enforce the
        # CDDL's ascending-order requirement here (the reference codec
        # rejects misordered version tables too)
        keys = [int(v) for v in a[0].keys()]
        if keys != sorted(keys):
            raise ValueError("version table keys not ascending")
        return cls(tuple((int(v), p) for v, p in a[0].items()))


@dataclass(frozen=True)
class MsgAcceptVersion:
    TAG = 1
    version: int
    params: Any

    def encode_args(self):
        return [self.version, self.params]

    @classmethod
    def decode_args(cls, a):
        return cls(int(a[0]), a[1])


# refuseReason variants (messages.cddl:117-123)

@dataclass(frozen=True)
class RefuseVersionMismatch:
    """[0, [*versionNumber]] — no common version; carries ours."""
    TAG = 0
    versions: tuple = ()

    def encode(self):
        return [0, list(self.versions)]


@dataclass(frozen=True)
class RefuseHandshakeDecodeError:
    """[1, versionNumber, tstr]."""
    TAG = 1
    version: int = 0
    message: str = ""

    def encode(self):
        return [1, self.version, self.message]


@dataclass(frozen=True)
class RefuseRefused:
    """[2, versionNumber, tstr] — version acceptable but params refused."""
    TAG = 2
    version: int = 0
    message: str = ""

    def encode(self):
        return [2, self.version, self.message]


def _decode_reason(obj):
    tag = int(obj[0])
    if tag == 0:
        return RefuseVersionMismatch(tuple(int(v) for v in obj[1]))
    if tag == 1:
        return RefuseHandshakeDecodeError(int(obj[1]), str(obj[2]))
    if tag == 2:
        return RefuseRefused(int(obj[1]), str(obj[2]))
    raise ValueError(f"unknown refuse reason tag {tag}")


@dataclass(frozen=True)
class MsgRefuse:
    TAG = 2
    reason: Any       # one of the Refuse* dataclasses

    def encode_args(self):
        return [self.reason.encode()]

    @classmethod
    def decode_args(cls, a):
        return cls(_decode_reason(a[0]))


SPEC = ProtocolSpec(
    name="handshake",
    init_state="StPropose",
    agency={"StPropose": CLIENT, "StConfirm": SERVER, "StDone": NOBODY},
    transitions={
        ("StPropose", "MsgProposeVersions"): "StConfirm",
        ("StConfirm", "MsgAcceptVersion"): "StDone",
        ("StConfirm", "MsgRefuse"): "StDone",
    })

CODEC = Codec([MsgProposeVersions, MsgAcceptVersion, MsgRefuse])


class Versions:
    """Map of version number -> (params, application); mirrors Version.hs."""

    def __init__(self):
        self._vs: dict[int, tuple] = {}

    def add(self, number: int, params, application=None) -> "Versions":
        self._vs[number] = (params, application)
        return self

    def numbers(self):
        return sorted(self._vs)

    def get(self, number: int):
        return self._vs.get(number)


def accept_highest_common(local: Versions, proposed) -> Optional[int]:
    """Default acceptableVersion policy: highest common version number."""
    proposed_numbers = {v for v, _ in proposed}
    common = [v for v in local.numbers() if v in proposed_numbers]
    return common[-1] if common else None


async def client_propose(session, versions: Versions):
    """Returns ("accepted", version, params) or ("refused", reason)."""
    await session.send(MsgProposeVersions(
        tuple((v, versions.get(v)[0]) for v in versions.numbers())))
    reply = await session.recv()
    if isinstance(reply, MsgRefuse):
        return ("refused", reply.reason)
    return ("accepted", reply.version, reply.params)


async def server_accept(session, versions: Versions,
                        policy: Callable = accept_highest_common):
    msg = await session.recv()
    chosen = policy(versions, msg.versions)
    if chosen is None:
        reason = RefuseVersionMismatch(tuple(versions.numbers()))
        await session.send(MsgRefuse(reason))
        return ("refused", reason)
    params, _app = versions.get(chosen)
    await session.send(MsgAcceptVersion(chosen, params))
    return ("accepted", chosen, dict(msg.versions).get(chosen))
