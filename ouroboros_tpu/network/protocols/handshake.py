"""Handshake — version negotiation, the first protocol on every connection.

Reference: ouroboros-network-framework/src/Ouroboros/Network/Protocol/
Handshake/Type.hs:43-126 (StPropose/StConfirm; propose map -> accept or
refuse) and Version.hs:19-86 (Versions map, acceptableVersion policy).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgProposeVersions:
    TAG = 0
    versions: tuple   # ((version_number, params_cbor), ...) ascending

    def encode_args(self):
        return [[[v, p] for v, p in self.versions]]

    @classmethod
    def decode_args(cls, a):
        return cls(tuple((int(v), p) for v, p in a[0]))


@dataclass(frozen=True)
class MsgAcceptVersion:
    TAG = 1
    version: int
    params: Any

    def encode_args(self):
        return [self.version, self.params]

    @classmethod
    def decode_args(cls, a):
        return cls(int(a[0]), a[1])


@dataclass(frozen=True)
class MsgRefuse:
    TAG = 2
    reason: str

    def encode_args(self):
        return [self.reason]

    @classmethod
    def decode_args(cls, a):
        return cls(str(a[0]))


SPEC = ProtocolSpec(
    name="handshake",
    init_state="StPropose",
    agency={"StPropose": CLIENT, "StConfirm": SERVER, "StDone": NOBODY},
    transitions={
        ("StPropose", "MsgProposeVersions"): "StConfirm",
        ("StConfirm", "MsgAcceptVersion"): "StDone",
        ("StConfirm", "MsgRefuse"): "StDone",
    })

CODEC = Codec([MsgProposeVersions, MsgAcceptVersion, MsgRefuse])


class Versions:
    """Map of version number -> (params, application); mirrors Version.hs."""

    def __init__(self):
        self._vs: dict[int, tuple] = {}

    def add(self, number: int, params, application=None) -> "Versions":
        self._vs[number] = (params, application)
        return self

    def numbers(self):
        return sorted(self._vs)

    def get(self, number: int):
        return self._vs.get(number)


def accept_highest_common(local: Versions, proposed) -> Optional[int]:
    """Default acceptableVersion policy: highest common version number."""
    proposed_numbers = {v for v, _ in proposed}
    common = [v for v in local.numbers() if v in proposed_numbers]
    return common[-1] if common else None


async def client_propose(session, versions: Versions):
    """Returns ("accepted", version, params) or ("refused", reason)."""
    await session.send(MsgProposeVersions(
        tuple((v, versions.get(v)[0]) for v in versions.numbers())))
    reply = await session.recv()
    if isinstance(reply, MsgRefuse):
        return ("refused", reply.reason)
    return ("accepted", reply.version, reply.params)


async def server_accept(session, versions: Versions,
                        policy: Callable = accept_highest_common):
    msg = await session.recv()
    chosen = policy(versions, msg.versions)
    if chosen is None:
        await session.send(MsgRefuse("no common version"))
        return ("refused", "no common version")
    params, _app = versions.get(chosen)
    await session.send(MsgAcceptVersion(chosen, params))
    return ("accepted", chosen, dict(msg.versions).get(chosen))
