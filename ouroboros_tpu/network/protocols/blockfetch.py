"""BlockFetch — range-batched block download.

Reference: ouroboros-network/src/Ouroboros/Network/Protocol/BlockFetch/
Type.hs:27-54 (MsgRequestRange/MsgStartBatch/MsgBlock/MsgBatchDone/
MsgNoBlocks) + Server/Client wrappers.
"""
from __future__ import annotations

from dataclasses import dataclass

from ...chain import Block, Point
from ..typed import CLIENT, NOBODY, SERVER, ProtocolSpec
from .codec import Codec


@dataclass(frozen=True)
class MsgRequestRange:
    TAG = 0
    start: Point       # inclusive
    end: Point         # inclusive

    def encode_args(self):
        return [self.start.encode(), self.end.encode()]

    @classmethod
    def decode_args(cls, a):
        return cls(Point.decode(a[0]), Point.decode(a[1]))


@dataclass(frozen=True)
class MsgClientDone:
    TAG = 1

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgStartBatch:
    TAG = 2

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgNoBlocks:
    TAG = 3

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


@dataclass(frozen=True)
class MsgBlock:
    TAG = 4
    block: Block

    def encode_args(self):
        # msgBlock = [4, #6.24(bytes .cbor block)] (messages.cddl:55):
        # blocks travel tag-24 CBOR-in-CBOR wrapped
        from ...utils import cbor
        return [cbor.Tag(24, cbor.dumps(self.block.encode()))]

    @classmethod
    def decode_args(cls, a):
        from ...utils import cbor
        return cls(Block.decode(cbor.unwrap_tag24(a[0])))


@dataclass(frozen=True)
class MsgBatchDone:
    TAG = 5

    def encode_args(self):
        return []

    @classmethod
    def decode_args(cls, a):
        return cls()


SPEC = ProtocolSpec(
    name="block-fetch",
    init_state="BFIdle",
    agency={"BFIdle": CLIENT, "BFBusy": SERVER, "BFStreaming": SERVER,
            "BFDone": NOBODY},
    transitions={
        ("BFIdle", "MsgRequestRange"): "BFBusy",
        ("BFIdle", "MsgClientDone"): "BFDone",
        ("BFBusy", "MsgStartBatch"): "BFStreaming",
        ("BFBusy", "MsgNoBlocks"): "BFIdle",
        ("BFStreaming", "MsgBlock"): "BFStreaming",
        ("BFStreaming", "MsgBatchDone"): "BFIdle",
    })

CODEC = Codec([MsgRequestRange, MsgClientDone, MsgStartBatch, MsgNoBlocks,
               MsgBlock, MsgBatchDone])


def make_codec(block_decode) -> Codec:
    """Codec with a custom block decoder (codecBlockFetch parameterised
    over the block type — Protocol/BlockFetch/Codec.hs)."""
    class _Block(MsgBlock):
        @classmethod
        def decode_args(cls, a):
            from ...utils import cbor
            return cls(block_decode(cbor.unwrap_tag24(a[0])))
    _Block.__name__ = "MsgBlock"
    return Codec([MsgRequestRange, MsgClientDone, MsgStartBatch,
                  MsgNoBlocks, _Block, MsgBatchDone])


async def server_from_blocks(session, lookup_range):
    """Server: lookup_range(start, end) -> list[Block] | None.

    Reference: BlockFetch/Server.hs serving from a ChainDB iterator."""
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgClientDone):
            return
        blocks = lookup_range(msg.start, msg.end)
        if not blocks:
            await session.send(MsgNoBlocks())
            continue
        await session.send(MsgStartBatch())
        for b in blocks:
            await session.send(MsgBlock(b))
        await session.send(MsgBatchDone())


async def fetch_range(session, start: Point, end: Point):
    """Client one-shot: request a range, collect the batch (or None)."""
    await session.send(MsgRequestRange(start, end))
    msg = await session.recv()
    if isinstance(msg, MsgNoBlocks):
        return None
    blocks = []
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgBatchDone):
            return blocks
        blocks.append(msg.block)
