"""DeltaQ/GSV — per-peer latency model driving BlockFetch peer ordering.

Reference: ouroboros-network/src/Ouroboros/Network/DeltaQ.hs:175-328
(`GSV` = G geographic/propagation delay + S size-scaled serialisation time
+ V variance; `PeerGSV` {outbound, inbound}; `gsvRequestResponseDuration`
estimating a request/response exchange), fed online by KeepAlive RTT
probes (KeepAlive.hs:41-55) and mux SDU timestamps
(network-mux/src/Network/Mux/DeltaQ/TraceStats.hs one-way-delay mins).
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

from ..observe import metrics as _metrics
from ..observe import netmetrics as _net

# per-protocol round-trip latency (ISSUE 14): the KeepAlive probe is the
# protocol that measures a true RTT; BlockFetch/handshake request
# latencies live beside it under the same net.rtt.* namespace (bound in
# node/block_fetch.py and node/kernel.py).  Handles pre-bound (OBS002).
_RTT_KEEPALIVE = _metrics.latency_histogram("net.rtt.keepalive_secs")
_OWD_SECS = _metrics.latency_histogram("net.deltaq.owd_secs")


@dataclass(frozen=True)
class GSV:
    """One direction's latency model.

    g -- propagation delay (seconds), the minimum observed
    s -- serialisation time per byte (seconds/byte)
    v -- variance proxy: mean positive deviation from g (seconds)
    """
    g: float = 0.0
    s: float = 2e-6          # ~4 Mb/s default until measured (DeltaQ.hs
                             # defaultGSV ballpark)
    v: float = 0.0

    def duration(self, nbytes: int) -> float:
        return self.g + self.s * nbytes + self.v


@dataclass(frozen=True)
class PeerGSV:
    """Both directions (DeltaQ.hs:187 `PeerGSV`)."""
    outbound: GSV = GSV()
    inbound: GSV = GSV()

    def request_response_duration(self, req_bytes: int,
                                  resp_bytes: int) -> float:
        """gsvRequestResponseDuration: one exchange's expected time."""
        return (self.outbound.duration(req_bytes)
                + self.inbound.duration(resp_bytes))


class PeerGSVTracker:
    """Online estimator: min-tracking for G, EWMA for V, differential
    size fit for S (TraceStats.hs accumulates per-SDU samples the same
    way: min one-way-delay as the G estimate, deviations as V)."""

    def __init__(self, alpha: float = 0.2,
                 label: Optional[str] = None):
        self.alpha = alpha
        self.gsv = PeerGSV()
        self._rtt_count = 0
        self._owd_count = 0
        # when labelled, every accepted sample publishes the inbound GSV
        # estimate as per-peer gauges (net.deltaq.{g,s,v}) through the
        # bounded-label helper — live DeltaQ state on the scrape endpoint
        self._label = label
        self._gauges = None

    def _publish(self) -> None:
        if self._label is None or not _metrics.REGISTRY.enabled:
            return
        g = self._gauges
        if g is None:
            peer = _net.peer_label(self._label)
            g = self._gauges = (
                _net.labeled_gauge("net.deltaq.g_secs", peer=peer),
                _net.labeled_gauge("net.deltaq.s_secs_per_byte",
                                   peer=peer),
                _net.labeled_gauge("net.deltaq.v_secs", peer=peer))
        inn = self.gsv.inbound
        g[0].set(inn.g)
        g[1].set(inn.s)
        g[2].set(inn.v)

    def observe_rtt(self, rtt: float) -> None:
        """A KeepAlive round-trip for a tiny payload: attribute half to
        each direction's G (the probe body is ~bytes, S negligible)."""
        _RTT_KEEPALIVE.observe(rtt)
        half = rtt / 2.0
        self._rtt_count += 1
        out, inn = self.gsv.outbound, self.gsv.inbound
        if self._rtt_count == 1:
            # keep a better inbound G already learned from SDU timestamps
            in_g = min(inn.g, half) if self._owd_count else half
            self.gsv = PeerGSV(replace(out, g=half), replace(inn, g=in_g))
            self._publish()
            return
        new_out = self._update_dir(out, half)
        new_in = self._update_dir(inn, half)
        self.gsv = PeerGSV(new_out, new_in)
        self._publish()

    def _update_dir(self, d: GSV, sample_g: float) -> GSV:
        g = min(d.g, sample_g)
        dev = max(0.0, sample_g - g)
        v = (1 - self.alpha) * d.v + self.alpha * dev
        return replace(d, g=g, v=v)

    def observe_owd(self, owd: float, nbytes: int) -> None:
        """A per-SDU one-way-delay sample from the mux demuxer's
        timestamp difference (DeltaQ/TraceStats.hs): min-tracked G,
        deviations into V, and for sized SDUs a per-byte S refinement —
        passive estimation with no KeepAlive traffic needed."""
        inn = self.gsv.inbound
        # first inbound sample initialises G (0.0 default = "unmeasured");
        # a separate counter so RTT/transfer initialisation stays intact
        first = self._owd_count == 0 and self._rtt_count == 0
        g = owd if first else min(inn.g, owd)
        dev = max(0.0, owd - g)
        v = (1 - self.alpha) * inn.v + self.alpha * dev
        s = inn.s
        if nbytes >= 4096 and owd > g:
            s_sample = (owd - g) / nbytes
            s = min(s, s_sample)
        self.gsv = PeerGSV(self.gsv.outbound,
                           replace(inn, g=g, v=v, s=s))
        self._owd_count += 1
        _OWD_SECS.observe(owd)
        self._publish()

    def observe_transfer(self, nbytes: int, duration: float) -> None:
        """A sized inbound transfer (a BlockFetch batch): refine S as the
        best (minimum) observed per-byte rate beyond G."""
        if nbytes <= 0:
            return
        inn = self.gsv.inbound
        s_sample = max(0.0, (duration - inn.g) / nbytes)
        s = min(inn.s, s_sample) if self._rtt_count else s_sample
        self.gsv = PeerGSV(self.gsv.outbound, replace(inn, s=s))
        self._publish()

    @property
    def measured(self) -> bool:
        """True once ANY real sample (RTT probe or SDU one-way delay)
        landed — before that the GSV is the optimistic default and must
        not be used to set deadlines (an unmeasured peer would get an
        impossibly tight watchdog)."""
        return self._rtt_count > 0 or self._owd_count > 0

    def expected_fetch_time(self, nbytes: int,
                            req_bytes: int = 100) -> float:
        return self.gsv.request_response_duration(req_bytes, nbytes)
