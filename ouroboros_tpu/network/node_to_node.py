"""NodeToNode — version bundle + protocol numbering for node links.

Reference: ouroboros-network/src/Ouroboros/Network/NodeToNode.hs:211-212,
382-391 (protocol numbers: handshake=0, chainsync=2, blockfetch=3,
txsubmission=4, keepalive=8), NodeToNode/Version.hs:27-48 (version enum +
`NodeToNodeVersionData` = network magic), and the acceptableVersion policy
of Protocol/Handshake/Version.hs:86 (same magic required).
"""
from __future__ import annotations

from typing import Optional

from .protocols.handshake import Versions

HANDSHAKE_NUM = 0
CHAINSYNC_NUM = 2
BLOCKFETCH_NUM = 3
TXSUBMISSION_NUM = 4
KEEPALIVE_NUM = 8

# node-to-client protocol numbers (NodeToNode.hs:382-391)
LOCAL_CHAINSYNC_NUM = 5
LOCAL_TXSUBMISSION_NUM = 6
LOCAL_STATEQUERY_NUM = 7

NODE_TO_NODE_V1 = 1
NODE_TO_NODE_V2 = 2          # adds tx-submission (mirrors the enum growth)

# per-protocol ingress byte limits (the mux parameter sets of
# NodeToNode.hs:157+ — bounded per-protocol flow control, §5)
INGRESS_LIMITS = {
    CHAINSYNC_NUM: 0x9_0000,
    BLOCKFETCH_NUM: 0x10_0000,
    TXSUBMISSION_NUM: 0x2_0000,
    KEEPALIVE_NUM: 0x1000,
}


def node_to_node_versions(network_magic: int = 0) -> Versions:
    """The default version offer: all known versions, same magic."""
    vs = Versions()
    for v in (NODE_TO_NODE_V1, NODE_TO_NODE_V2):
        vs.add(v, {"magic": network_magic})
    return vs


def accept_same_magic(local: Versions, proposed) -> Optional[int]:
    """acceptableVersion: highest common number whose network magic equals
    ours (Version.hs:86 — a magic mismatch is a refusal)."""
    prop = dict(proposed)
    best = None
    for v in local.numbers():
        if v in prop:
            local_params = local.get(v)[0]
            offered = prop[v] or {}
            if dict(offered).get("magic") == local_params.get("magic"):
                best = v
    return best
