"""Subscription workers — valency-tracked outbound connection maintenance.

Reference: ouroboros-network-framework/src/Ouroboros/Network/Subscription/
Worker.hs:207-233 (`worker`/`subscriptionLoop`: keep `valency` live
connections from a target list, redialling as they fail), Ip.hs:66-89 (IP
targets), Dns.hs:239-292 (name resolution + the A/AAAA race: both address
families resolve concurrently and the first usable answer wins, the loser
is kept as fallback), PeerState.hs (per-peer suspension state consulted
before dialling), with ErrorPolicy verdicts driving the suspensions.

The dial function abstracts the transport (in-sim kernel dialling here;
a socket Snocket plugs into the same seam).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence

from .. import simharness as sim
from .error_policy import ErrorPolicy, eval_error_policies


class Resolver:
    """Name resolution seam (Dns.hs `Resolver`): resolve_a/resolve_aaaa
    return address lists for one family.  Implementations: a dict-backed
    sim resolver below; a getaddrinfo-backed one for the IO runtime."""

    async def resolve_a(self, name: str) -> list:
        return []

    async def resolve_aaaa(self, name: str) -> list:
        return []


class DictResolver(Resolver):
    """Deterministic resolver for sim tests: {name: (a_list, aaaa_list)}
    with optional per-family artificial latency."""

    def __init__(self, table: Dict[str, tuple], a_delay: float = 0.0,
                 aaaa_delay: float = 0.0):
        self.table = dict(table)
        self.a_delay = a_delay
        self.aaaa_delay = aaaa_delay

    async def resolve_a(self, name: str) -> list:
        if self.a_delay:
            await sim.sleep(self.a_delay)
        return list(self.table.get(name, ((), ()))[0])

    async def resolve_aaaa(self, name: str) -> list:
        if self.aaaa_delay:
            await sim.sleep(self.aaaa_delay)
        return list(self.table.get(name, ((), ()))[1])


class GetAddrInfoResolver(Resolver):
    """IO-runtime resolver over the system's getaddrinfo."""

    def __init__(self, port: int):
        self.port = port

    async def _resolve(self, name: str, family) -> list:
        import asyncio
        import socket
        loop = asyncio.get_event_loop()
        try:
            infos = await loop.getaddrinfo(name, self.port, family=family,
                                           type=socket.SOCK_STREAM)
        except OSError:
            return []
        # normalise sockaddrs to the (host, port) shape every dial path
        # consumes (AF_INET6 sockaddrs carry flowinfo/scopeid extras)
        return [(info[4][0], info[4][1]) for info in infos]

    async def resolve_a(self, name: str) -> list:
        import socket
        return await self._resolve(name, socket.AF_INET)

    async def resolve_aaaa(self, name: str) -> list:
        import socket
        return await self._resolve(name, socket.AF_INET6)


async def resolve_racing(resolver: Resolver, name: str,
                         prefer_delay: float = 0.05) -> list:
    """The Dns.hs A/AAAA race: both lookups run concurrently and the
    FIRST usable answer wins — a hung family cannot stall dialling.  After
    the winner arrives, the other family gets `prefer_delay` more to land
    (AAAA answering within the window still leads, as in the reference);
    a straggler past the window is dropped, not awaited."""
    from ..simharness import TQueue
    answers: TQueue = TQueue(label=f"dns-{name}")

    async def run(tag, coro):
        addrs = await coro

        def push(tx):
            answers.put(tx, (tag, addrs))
        await sim.atomically(push)

    sim.spawn(run("aaaa", resolver.resolve_aaaa(name)),
              label=f"dns-aaaa-{name}")
    sim.spawn(run("a", resolver.resolve_a(name)),
              label=f"dns-a-{name}")
    got: dict = {}
    # wait for the first USABLE (non-empty) answer, or both to finish
    while len(got) < 2:
        tag, addrs = await sim.atomically(answers.get)
        got[tag] = addrs
        if addrs:
            break
    if len(got) < 2:
        done, item = await sim.timeout(prefer_delay,
                                       sim.atomically(answers.get))
        if done and item is not None:
            got[item[0]] = item[1]
    a6 = got.get("aaaa", [])
    a4 = got.get("a", [])
    if a6:
        return list(a6) + [a for a in a4 if a not in a6]
    return list(a4)


async def dns_subscription_targets(resolver: Resolver, names: Sequence[str],
                                   prefer_delay: float = 0.05) -> list:
    """Resolve a root-peer name list into a concrete dial-target list
    (RootPeersDNS's role for the governor/subscription layer).  Names
    resolve CONCURRENTLY — wall clock is bounded by the slowest single
    lookup, not the sum."""
    results: dict = {}

    async def one(name):
        results[name] = await resolve_racing(resolver, name, prefer_delay)

    handles = [sim.spawn(one(n), label=f"dns-targets-{n}") for n in names]
    for h in handles:
        await h.wait()
    out: list = []
    for name in names:
        for addr in results.get(name, []):
            if addr not in out:
                out.append(addr)
    return out


@dataclass
class PeerState:
    """Subscription/PeerState.hs: per-address dial bookkeeping."""
    fail_count: int = 0
    suspended_until: float = 0.0
    connected: bool = False


class SubscriptionWorker:
    """Maintain `valency` live connections from `targets`.

    dial(addr) -> Async handle whose completion (normal or exceptional)
    means the connection ended.  Failures are classified by the error
    policies into suspension windows before the address is redialled.
    """

    def __init__(self, targets: Sequence, valency: int,
                 dial: Callable, error_policies: Sequence[ErrorPolicy] = (),
                 base_backoff: float = 5.0, label: str = "subscription"):
        self.targets = list(targets)
        self.valency = min(valency, len(self.targets))
        self.dial = dial
        self.error_policies = list(error_policies)
        self.base_backoff = base_backoff
        self.label = label
        self.states: Dict[object, PeerState] = {
            a: PeerState() for a in self.targets}
        self.trace: list = []
        self._conns: Dict[object, object] = {}     # addr -> Async

    def _candidates(self) -> list:
        now = sim.now()
        return [a for a in self.targets
                if not self.states[a].connected
                and self.states[a].suspended_until <= now]

    def _on_conn_end(self, addr, exc: Optional[BaseException]) -> None:
        st = self.states[addr]
        st.connected = False
        if exc is not None:
            verdict = eval_error_policies(self.error_policies, exc)
            dur = verdict.duration if verdict is not None \
                else self.base_backoff
        else:
            dur = self.base_backoff
        st.fail_count += 1
        st.suspended_until = sim.now() + dur * (2 ** min(st.fail_count, 5))
        self.trace.append((sim.now(), "conn-end", addr, repr(exc)))

    async def run(self) -> None:
        """subscriptionLoop: top up to valency, then block until a
        connection ends (watcher threads feed an STM queue) or a
        suspension window expires."""
        from ..simharness import TQueue
        endings: TQueue = TQueue(label=f"{self.label}-endings")

        async def watch(addr, handle):
            exc = None
            try:
                await handle.wait()
            except BaseException as e:
                exc = e

            def push(tx):
                endings.put(tx, (addr, exc))
            await sim.atomically(push)

        while True:
            for addr in self._candidates():
                if len(self._conns) >= self.valency:
                    break
                st = self.states[addr]
                st.connected = True
                self.trace.append((sim.now(), "dial", addr))
                handle = self.dial(addr)
                self._conns[addr] = handle
                sim.spawn(watch(addr, handle),
                          label=f"{self.label}.watch-{addr}")

            # wait for an ending, or poll again when the earliest
            # suspension expires
            now = sim.now()
            pending = [s.suspended_until for s in self.states.values()
                       if not s.connected and s.suspended_until > now]
            wait_for = min(pending) - now if pending else self.base_backoff
            done, item = await sim.timeout(
                max(wait_for, 0.01),
                sim.atomically(lambda tx: endings.get(tx)))
            if done and item is not None:
                addr, exc = item
                self._conns.pop(addr, None)
                self._on_conn_end(addr, exc)
