"""Subscription workers — valency-tracked outbound connection maintenance.

Reference: ouroboros-network-framework/src/Ouroboros/Network/Subscription/
Worker.hs:207-233 (`worker`/`subscriptionLoop`: keep `valency` live
connections from a target list, redialling as they fail), Ip.hs:66-89 (IP
targets), PeerState.hs (per-peer suspension state consulted before
dialling), with ErrorPolicy verdicts driving the suspensions.

The dial function abstracts the transport (in-sim kernel dialling here;
a socket Snocket plugs into the same seam).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence

from .. import simharness as sim
from .error_policy import ErrorPolicy, eval_error_policies


@dataclass
class PeerState:
    """Subscription/PeerState.hs: per-address dial bookkeeping."""
    fail_count: int = 0
    suspended_until: float = 0.0
    connected: bool = False


class SubscriptionWorker:
    """Maintain `valency` live connections from `targets`.

    dial(addr) -> Async handle whose completion (normal or exceptional)
    means the connection ended.  Failures are classified by the error
    policies into suspension windows before the address is redialled.
    """

    def __init__(self, targets: Sequence, valency: int,
                 dial: Callable, error_policies: Sequence[ErrorPolicy] = (),
                 base_backoff: float = 5.0, label: str = "subscription"):
        self.targets = list(targets)
        self.valency = min(valency, len(self.targets))
        self.dial = dial
        self.error_policies = list(error_policies)
        self.base_backoff = base_backoff
        self.label = label
        self.states: Dict[object, PeerState] = {
            a: PeerState() for a in self.targets}
        self.trace: list = []
        self._conns: Dict[object, object] = {}     # addr -> Async

    def _candidates(self) -> list:
        now = sim.now()
        return [a for a in self.targets
                if not self.states[a].connected
                and self.states[a].suspended_until <= now]

    def _on_conn_end(self, addr, exc: Optional[BaseException]) -> None:
        st = self.states[addr]
        st.connected = False
        if exc is not None:
            verdict = eval_error_policies(self.error_policies, exc)
            dur = verdict.duration if verdict is not None \
                else self.base_backoff
        else:
            dur = self.base_backoff
        st.fail_count += 1
        st.suspended_until = sim.now() + dur * (2 ** min(st.fail_count, 5))
        self.trace.append((sim.now(), "conn-end", addr, repr(exc)))

    async def run(self) -> None:
        """subscriptionLoop: top up to valency, then block until a
        connection ends (watcher threads feed an STM queue) or a
        suspension window expires."""
        from ..simharness import TQueue
        endings: TQueue = TQueue(label=f"{self.label}-endings")

        async def watch(addr, handle):
            exc = None
            try:
                await handle.wait()
            except BaseException as e:
                exc = e

            def push(tx):
                endings.put(tx, (addr, exc))
            await sim.atomically(push)

        while True:
            for addr in self._candidates():
                if len(self._conns) >= self.valency:
                    break
                st = self.states[addr]
                st.connected = True
                self.trace.append((sim.now(), "dial", addr))
                handle = self.dial(addr)
                self._conns[addr] = handle
                sim.spawn(watch(addr, handle),
                          label=f"{self.label}.watch-{addr}")

            # wait for an ending, or poll again when the earliest
            # suspension expires
            now = sim.now()
            pending = [s.suspended_until for s in self.states.values()
                       if not s.connected and s.suspended_until > now]
            wait_for = min(pending) - now if pending else self.base_backoff
            done, item = await sim.timeout(
                max(wait_for, 0.01),
                sim.atomically(lambda tx: endings.get(tx)))
            if done and item is not None:
                addr, exc = item
                self._conns.pop(addr, None)
                self._on_conn_end(addr, exc)
