"""Subscription workers — valency-tracked outbound connection maintenance.

Reference: ouroboros-network-framework/src/Ouroboros/Network/Subscription/
Worker.hs:207-233 (`worker`/`subscriptionLoop`: keep `valency` live
connections from a target list, redialling as they fail), Ip.hs:66-89 (IP
targets), Dns.hs:239-292 (name resolution + the A/AAAA race: both address
families resolve concurrently and the first usable answer wins, the loser
is kept as fallback), PeerState.hs (per-peer suspension state consulted
before dialling), with ErrorPolicy verdicts driving the suspensions.

The dial function abstracts the transport (in-sim kernel dialling here;
a socket Snocket plugs into the same seam).
"""
from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence

from .. import simharness as sim
from ..observe import metrics as _metrics
from ..observe import netmetrics as _net
from .error_policy import ErrorPolicy, SuspendDecision, eval_error_policies

# process-wide reconnect/suspension counters (ISSUE 7): the registry
# replaces grepping sim traces for dial/suspend tuples.  Gated writes —
# int bumps, invisible to sim determinism (no clock, no RNG).
_DIALS = _metrics.counter("subscription.dials")
_RECONNECTS = _metrics.counter("subscription.reconnects")
_CLEAN_ENDS = _metrics.counter("subscription.clean_ends")
_SUSPENSIONS = _metrics.counter("subscription.suspensions")
_FATALS = _metrics.counter("subscription.fatals")


class Resolver:
    """Name resolution seam (Dns.hs `Resolver`): resolve_a/resolve_aaaa
    return address lists for one family.  Implementations: a dict-backed
    sim resolver below; a getaddrinfo-backed one for the IO runtime."""

    async def resolve_a(self, name: str) -> list:
        return []

    async def resolve_aaaa(self, name: str) -> list:
        return []


class DictResolver(Resolver):
    """Deterministic resolver for sim tests: {name: (a_list, aaaa_list)}
    with optional per-family artificial latency."""

    def __init__(self, table: Dict[str, tuple], a_delay: float = 0.0,
                 aaaa_delay: float = 0.0):
        self.table = dict(table)
        self.a_delay = a_delay
        self.aaaa_delay = aaaa_delay

    async def resolve_a(self, name: str) -> list:
        if self.a_delay:
            await sim.sleep(self.a_delay)
        return list(self.table.get(name, ((), ()))[0])

    async def resolve_aaaa(self, name: str) -> list:
        if self.aaaa_delay:
            await sim.sleep(self.aaaa_delay)
        return list(self.table.get(name, ((), ()))[1])


class GetAddrInfoResolver(Resolver):
    """IO-runtime resolver over the system's getaddrinfo."""

    def __init__(self, port: int):
        self.port = port

    async def _resolve(self, name: str, family) -> list:
        import asyncio
        import socket
        loop = asyncio.get_event_loop()
        try:
            infos = await loop.getaddrinfo(name, self.port, family=family,
                                           type=socket.SOCK_STREAM)
        except OSError:
            return []
        # normalise sockaddrs to the (host, port) shape every dial path
        # consumes (AF_INET6 sockaddrs carry flowinfo/scopeid extras)
        return [(info[4][0], info[4][1]) for info in infos]

    async def resolve_a(self, name: str) -> list:
        import socket
        return await self._resolve(name, socket.AF_INET)

    async def resolve_aaaa(self, name: str) -> list:
        import socket
        return await self._resolve(name, socket.AF_INET6)


async def resolve_racing(resolver: Resolver, name: str,
                         prefer_delay: float = 0.05) -> list:
    """The Dns.hs A/AAAA race: both lookups run concurrently and the
    FIRST usable answer wins — a hung family cannot stall dialling.  After
    the winner arrives, the other family gets `prefer_delay` more to land
    (AAAA answering within the window still leads, as in the reference);
    a straggler past the window is dropped, not awaited."""
    from ..simharness import TQueue
    answers: TQueue = TQueue(label=f"dns-{name}")

    async def run(tag, coro):
        addrs = await coro

        def push(tx):
            answers.put(tx, (tag, addrs))
        await sim.atomically(push)

    sim.spawn(run("aaaa", resolver.resolve_aaaa(name)),
              label=f"dns-aaaa-{name}")
    sim.spawn(run("a", resolver.resolve_a(name)),
              label=f"dns-a-{name}")
    got: dict = {}
    # wait for the first USABLE (non-empty) answer, or both to finish
    while len(got) < 2:
        tag, addrs = await sim.atomically(answers.get)
        got[tag] = addrs
        if addrs:
            break
    if len(got) < 2:
        done, item = await sim.timeout(prefer_delay,
                                       sim.atomically(answers.get))
        if done and item is not None:
            got[item[0]] = item[1]
    a6 = got.get("aaaa", [])
    a4 = got.get("a", [])
    if a6:
        return list(a6) + [a for a in a4 if a not in a6]
    return list(a4)


async def dns_subscription_targets(resolver: Resolver, names: Sequence[str],
                                   prefer_delay: float = 0.05) -> list:
    """Resolve a root-peer name list into a concrete dial-target list
    (RootPeersDNS's role for the governor/subscription layer).  Names
    resolve CONCURRENTLY — wall clock is bounded by the slowest single
    lookup, not the sum."""
    results: dict = {}

    async def one(name):
        results[name] = await resolve_racing(resolver, name, prefer_delay)

    handles = [sim.spawn(one(n), label=f"dns-targets-{n}") for n in names]
    for h in handles:
        await h.wait()
    out: list = []
    for name in names:
        for addr in results.get(name, []):
            if addr not in out:
                out.append(addr)
    return out


@dataclass
class PeerState:
    """Subscription/PeerState.hs: per-address dial bookkeeping.

    The two suspension clocks mirror the reference's SuspendDecision
    split: `consumer_until` blocks only OUR outbound dialling
    (suspend-consumer — the peer's inbound service to us may be fine),
    `peer_until` marks the peer bad in both directions (suspend-peer —
    protocol violation / invalid data; an accept path can consult
    `peer_suspended`)."""
    fail_count: int = 0
    consumer_until: float = 0.0
    peer_until: float = 0.0
    connected: bool = False
    dials: int = 0            # lifetime dial count (dials>1 = reconnect)

    @property
    def suspended_until(self) -> float:
        return max(self.consumer_until, self.peer_until)


class SubscriptionFatal(Exception):
    """A THROW verdict: the error policy classified the failure as fatal
    to the application, not to the one peer (ErrorPolicy.hs `Throw`).
    Carries the original exception as __cause__."""


class SubscriptionWorker:
    """Maintain `valency` live connections from `targets`.

    dial(addr) -> Async handle whose completion (normal or exceptional)
    means the connection ended.  Failures are classified by the error
    policies into SuspendDecision verdicts (Worker.hs + PeerState.hs):

    - throw             -> SubscriptionFatal out of run() (application dies)
    - suspend-peer      -> both-direction suspension, exponential backoff
    - suspend-consumer  -> dial-side suspension, exponential backoff
    - clean end         -> fail_count RESET, one base_backoff churn pause
                           (a successful session wipes the escalation —
                           the reference re-zeroes the peer state when a
                           connection completes without error)

    Backoff is `duration * 2^min(fail_count-1, 5)` plus seeded jitter so
    a fleet of workers never thundering-herds a recovering peer — and the
    jitter comes from a per-worker blake2b-seeded RNG, keeping whole-sim
    replays byte-identical.
    """

    def __init__(self, targets: Sequence, valency: int,
                 dial: Callable, error_policies: Sequence[ErrorPolicy] = (),
                 base_backoff: float = 5.0, label: str = "subscription",
                 jitter: float = 0.25, seed: int = 0):
        self.targets = list(targets)
        self.valency = min(valency, len(self.targets))
        self.dial = dial
        self.error_policies = list(error_policies)
        self.base_backoff = base_backoff
        self.label = label
        self.jitter = jitter
        h = hashlib.blake2b(f"{seed}:{label}".encode(), digest_size=8)
        self.rng = random.Random(int.from_bytes(h.digest(), "big"))
        self.states: Dict[object, PeerState] = {
            a: PeerState() for a in self.targets}
        self.trace: list = []
        self._conns: Dict[object, object] = {}     # addr -> Async

    def _candidates(self) -> list:
        now = sim.now()
        return [a for a in self.targets
                if not self.states[a].connected
                and self.states[a].suspended_until <= now]

    def peer_suspended(self, addr) -> bool:
        """True while `addr` sits in a suspend-peer window — the signal an
        accept/server path can consult to refuse the peer's inbound too."""
        st = self.states.get(addr)
        return st is not None and st.peer_until > sim.now()

    def _backoff(self, duration: float, fail_count: int) -> float:
        scaled = duration * (2 ** min(max(fail_count - 1, 0), 5))
        return scaled * (1.0 + self.rng.random() * self.jitter)

    def _on_conn_end(self, addr, exc: Optional[BaseException]) -> None:
        st = self.states[addr]
        st.connected = False
        now = sim.now()
        if exc is None:
            # clean session: reset the escalation entirely; pause one
            # base_backoff (no exponent) before re-dialling so a cleanly
            # churning peer is not hammered — but never escalates either
            st.fail_count = 0
            st.consumer_until = now + self._backoff(self.base_backoff, 0)
            self.trace.append((now, "conn-end", addr, None))
            _CLEAN_ENDS.inc()
            sim.trace_event((self.label, "conn-end-clean", addr),
                            label="subscription")
            return
        verdict = eval_error_policies(self.error_policies, exc)
        if verdict is None:
            verdict = SuspendDecision("suspend-consumer", self.base_backoff)
        if verdict.kind == "throw":
            # fatal: surface to the application instead of converting the
            # verdict into a quiet backoff window
            _FATALS.inc()
            sim.trace_event((self.label, "fatal", addr, repr(exc)),
                            label="subscription")
            raise SubscriptionFatal(
                f"{self.label}: THROW verdict for {addr}") from exc
        st.fail_count += 1
        until = now + self._backoff(verdict.duration, st.fail_count)
        st.consumer_until = max(st.consumer_until, until)
        if verdict.kind == "suspend-peer":
            st.peer_until = max(st.peer_until, until)
        _SUSPENSIONS.inc()
        if _metrics.REGISTRY.enabled:
            # per-peer suspension attribution through the bounded-label
            # helper; cold path (one write per connection death)
            _net.labeled_counter("net.peer.suspensions",
                                 peer=_net.peer_label(addr)).inc()
        self.trace.append((now, "conn-end", addr, repr(exc)))
        sim.trace_event((self.label, "suspend", addr, verdict.kind,
                         round(until - now, 6), st.fail_count),
                        label="subscription")

    async def run(self) -> None:
        """subscriptionLoop: top up to valency, then block until a
        connection ends (watcher threads feed an STM queue) or a
        suspension window expires."""
        from ..simharness import TQueue
        endings: TQueue = TQueue(label=f"{self.label}-endings")

        async def watch(addr, handle):
            exc = None
            try:
                await handle.wait()
            except BaseException as e:
                exc = e

            def push(tx):
                endings.put(tx, (addr, exc))
            await sim.atomically(push)

        while True:
            for addr in self._candidates():
                if len(self._conns) >= self.valency:
                    break
                st = self.states[addr]
                st.connected = True
                if st.dials:
                    _RECONNECTS.inc()
                st.dials += 1
                _DIALS.inc()
                self.trace.append((sim.now(), "dial", addr))
                sim.trace_event((self.label, "dial", addr, st.fail_count),
                                label="subscription")
                handle = self.dial(addr)
                self._conns[addr] = handle
                sim.spawn(watch(addr, handle),
                          label=f"{self.label}.watch-{addr}")

            # wait for an ending, or poll again when the earliest
            # suspension expires
            now = sim.now()
            pending = [s.suspended_until for s in self.states.values()
                       if not s.connected and s.suspended_until > now]
            wait_for = min(pending) - now if pending else self.base_backoff
            done, item = await sim.timeout(
                max(wait_for, 0.01),
                sim.atomically(lambda tx: endings.get(tx)))
            if done and item is not None:
                addr, exc = item
                self._conns.pop(addr, None)
                self._on_conn_end(addr, exc)
