"""The reference's wire grammar (messages.cddl) as executable schema rules.

The reference pins its codecs against a published CDDL spec
(`ouroboros-network/test-cddl/Main.hs` generating messages and checking
them with the `cddl` tool against `ouroboros-network/test/messages.cddl`,
itself checked against docs/network-spec/miniprotocols.tex).  This module
is a rule-for-rule port of that grammar into small validator combinators,
so our golden corpus can be checked against the REFERENCE grammar rather
than a self-hash (VERDICT r3 next-step 4).

Every rule name below mirrors the CDDL rule it ports, with the
messages.cddl line cited.  The grammar's polymorphic leaves (headerHash,
block, transaction, rejectReason — messages.cddl:137-158 "the codecs are
polymorphic in the underlying data types"; the CDDL pins the *test*
instantiation, e.g. `transaction = int`) are parameterised here so our
instantiation (32-byte hashes, CBOR tx bodies) validates through the same
structural skeleton.  Structural rules — message tags, arities, tag-24
wrapping, map-vs-array — are checked exactly.
"""
from __future__ import annotations

from ..utils import cbor


class Mismatch(Exception):
    """Value does not match the grammar rule."""


# -- combinators -------------------------------------------------------------

class Rule:
    name = "?"

    def check(self, obj) -> None:
        raise NotImplementedError

    def matches(self, obj) -> bool:
        try:
            self.check(obj)
            return True
        except Mismatch:
            return False

    def __truediv__(self, other) -> "Alt":
        return Alt(self, other)


class _Pred(Rule):
    def __init__(self, name, fn):
        self.name, self._fn = name, fn

    def check(self, obj):
        if not self._fn(obj):
            raise Mismatch(f"{obj!r} is not {self.name}")


uint = _Pred("uint", lambda o: isinstance(o, int) and not isinstance(o, bool)
             and o >= 0)
int_ = _Pred("int", lambda o: isinstance(o, int) and not isinstance(o, bool))
tstr = _Pred("tstr", lambda o: isinstance(o, str))
bstr = _Pred("bstr", lambda o: isinstance(o, bytes))
bool_ = _Pred("bool", lambda o: isinstance(o, bool))
any_ = _Pred("any", lambda o: True)
word16 = uint    # messages.cddl:159-161: word16/32/64 = uint
word32 = uint
word64 = uint


class Lit(Rule):
    def __init__(self, value):
        self.value = value
        self.name = repr(value)

    def check(self, obj):
        if obj != self.value or isinstance(obj, bool) != isinstance(
                self.value, bool):
            raise Mismatch(f"expected literal {self.value!r}, got {obj!r}")


class Arr(Rule):
    """Fixed-shape array; a trailing Star rule matches zero-or-more."""

    def __init__(self, *rules, name="array"):
        self.rules = rules
        self.name = name

    def check(self, obj):
        if not isinstance(obj, list):
            raise Mismatch(f"{self.name}: expected array, got "
                           f"{type(obj).__name__}")
        rules = list(self.rules)
        star = rules.pop() if rules and isinstance(rules[-1], Star) else None
        if star is None and len(obj) != len(rules):
            raise Mismatch(f"{self.name}: expected {len(rules)} elements, "
                           f"got {len(obj)}")
        if star is not None and len(obj) < len(rules):
            raise Mismatch(f"{self.name}: expected >= {len(rules)} "
                           f"elements, got {len(obj)}")
        for r, item in zip(rules, obj):
            r.check(item)
        if star is not None:
            for item in obj[len(rules):]:
                star.rule.check(item)


class Star(Rule):
    """`*rule` inside an Arr."""

    def __init__(self, rule):
        self.rule = rule
        self.name = f"*{rule.name}"

    def check(self, obj):     # only meaningful inside Arr
        self.rule.check(obj)


class Alt(Rule):
    def __init__(self, *rules, name=None):
        flat = []
        for r in rules:
            flat.extend(r.rules if isinstance(r, Alt) else [r])
        self.rules = flat
        self.name = name or " / ".join(r.name for r in flat)

    def check(self, obj):
        errs = []
        for r in self.rules:
            try:
                return r.check(obj)
            except Mismatch as e:
                errs.append(str(e))
        raise Mismatch(f"no alternative of ({self.name}) matched "
                       f"{obj!r}: {errs}")


class Tag24Cbor(Rule):
    """#6.24(bytes .cbor inner) — CBOR-in-CBOR (messages.cddl:34,55)."""

    def __init__(self, inner: Rule):
        self.inner = inner
        self.name = f"#6.24(bytes .cbor {inner.name})"

    def check(self, obj):
        if not isinstance(obj, cbor.Tag) or obj.tag != 24:
            raise Mismatch(f"expected tag 24, got {obj!r}")
        if not isinstance(obj.value, bytes):
            raise Mismatch("tag 24 payload must be bytes")
        self.inner.check(cbor.loads(obj.value))


class VersionTable(Rule):
    """versionTable: CBOR map, unique keys in ascending order
    (messages.cddl:104-115)."""

    def __init__(self, key_rule: Rule, value_rule: Rule):
        self.key_rule, self.value_rule = key_rule, value_rule
        self.name = "versionTable"

    def check(self, obj):
        if not isinstance(obj, dict):
            raise Mismatch(f"versionTable must be a map, got "
                           f"{type(obj).__name__}")
        keys = list(obj)
        if keys != sorted(keys):
            raise Mismatch("versionTable keys must be ascending")
        for k, v in obj.items():
            self.key_rule.check(k)
            self.value_rule.check(v)


def named(name: str, rule: Rule) -> Rule:
    rule.name = name
    return rule


# -- the grammar, rule for rule (messages.cddl) ------------------------------

def grammar(header_hash: Rule = int_, block_body: Rule = any_,
            tx_id: Rule = int_, transaction: Rule = int_,
            reject_reason: Rule = int_, version_number: Rule = uint,
            params: Rule = any_):
    """Build the messages.cddl rule set.  Defaults are the CDDL's own test
    instantiation; pass our leaves to validate this repo's dialect through
    the same structure."""
    g = {}
    # messages.cddl:152-155
    origin = named("origin", Arr(name="origin"))
    block_header_hash = named("blockHeaderHash",
                              Arr(word64, header_hash,
                                  name="blockHeaderHash"))
    point = named("point", origin / block_header_hash)
    g["point"] = point
    g["points"] = named("points", Arr(Star(point), name="points"))
    tip = named("tip", Arr(point, uint, name="tip"))
    g["tip"] = tip
    # blockHeader (messages.cddl:142) — test instantiation; ours differs,
    # callers pass their own rule through wrapped_header
    g["blockHeader"] = named(
        "blockHeader", Arr(int_, Arr(Star(int_)), word64, word64, int_,
                           name="blockHeader"))
    g["block"] = named("block", Arr(g["blockHeader"], block_body,
                                    name="block"))

    # ChainSync (messages.cddl:16-33)
    def chainsync(wrapped_header: Rule):
        return named("chainSyncMessage", Alt(
            Arr(Lit(0), name="msgRequestNext"),
            Arr(Lit(1), name="msgAwaitReply"),
            Arr(Lit(2), Tag24Cbor(wrapped_header), tip,
                name="msgRollForward"),
            Arr(Lit(3), point, tip, name="msgRollBackward"),
            Arr(Lit(4), g["points"], name="msgFindIntersect"),
            Arr(Lit(5), point, tip, name="msgIntersectFound"),
            Arr(Lit(6), tip, name="msgIntersectNotFound"),
            Arr(Lit(7), name="chainSyncMsgDone")))
    g["chainsync"] = chainsync

    # BlockFetch (messages.cddl:42-56)
    def blockfetch(block_rule: Rule):
        return named("blockFetchMessage", Alt(
            Arr(Lit(0), point, point, name="msgRequestRange"),
            Arr(Lit(1), name="msgClientDone"),
            Arr(Lit(2), name="msgStartBatch"),
            Arr(Lit(3), name="msgNoBlocks"),
            Arr(Lit(4), Tag24Cbor(block_rule), name="msgBlock"),
            Arr(Lit(5), name="msgBatchDone")))
    g["blockfetch"] = blockfetch

    # TxSubmission (messages.cddl:62-82)
    tx_id_and_size = named("txIdAndSize", Arr(tx_id, word32,
                                              name="txIdAndSize"))
    ts_id_list = named("tsIdList", Arr(Star(tx_id), name="tsIdList"))
    ts_tx_list = named("tsTxList", Arr(Star(transaction), name="tsTxList"))
    g["txsubmission"] = named("txSubmissionMessage", Alt(
        Arr(Lit(0), bool_, word16, word16, name="msgRequestTxIds"),
        Arr(Lit(1), Arr(Star(tx_id_and_size)), name="msgReplyTxIds"),
        Arr(Lit(2), ts_id_list, name="msgRequestTxs"),
        Arr(Lit(3), ts_tx_list, name="msgReplyTxs"),
        Arr(Lit(4), name="tsMsgDone"),
        Arr(Lit(5), name="msgReplyKTnxBye")))

    # Handshake (messages.cddl:88-123)
    refuse_reason = named("refuseReason", Alt(
        Arr(Lit(0), Arr(Star(version_number)),
            name="refuseReasonVersionMismatch"),
        Arr(Lit(1), version_number, tstr,
            name="refuseReasonHandshakeDecodeError"),
        Arr(Lit(2), version_number, tstr, name="refuseReasonRefused")))
    g["handshake"] = named("handshakeMessage", Alt(
        Arr(Lit(0), VersionTable(version_number, params),
            name="msgProposeVersions"),
        Arr(Lit(1), version_number, any_, name="msgAcceptVersion"),
        Arr(Lit(2), refuse_reason, name="msgRefuse")))

    # LocalTxSubmission (messages.cddl:126-135)
    g["localtxsubmission"] = named("localTxSubmissionMessage", Alt(
        Arr(Lit(0), transaction, name="msgSubmitTx"),
        Arr(Lit(1), name="msgAcceptTx"),
        Arr(Lit(2), reject_reason, name="msgRejectTx"),
        Arr(Lit(3), name="ltMsgDone")))
    return g
