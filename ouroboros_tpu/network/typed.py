"""Typed protocols — session-typed state machines, runtime-enforced.

Reference: typed-protocols/src/Network/TypedProtocol/Core.hs:264-403 (the
Protocol class + Message GADT + Peer) and Pipelined.hs (type-level pipelining).
Haskell enforces protocol conformance statically; the Python rebuild enforces
it dynamically: a ProtocolSpec declares per-state agency and the transition
relation, and every send/recv is checked against it, so a misbehaving peer
fails deterministically at the exact violating step (same error surface the
reference gets at compile time, moved to simulation time).

A peer is an async function `peer(session)`; `run_peer` drives it over a
Channel with a Codec.  Pipelining follows Driver.hs:150-186: a receiver task
drains replies into a collect queue while the sender keeps issuing requests,
bounded by `max_outstanding`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from .. import simharness as sim
from ..simharness import TBQueue
from .channel import Channel

CLIENT, SERVER, NOBODY = "client", "server", "nobody"


class ProtocolError(Exception):
    """Agency/transition violation or codec failure."""


def branch(fn: Callable, *targets: str) -> Callable:
    """Tag a message-value-dependent transition callable with its
    statically-known target states.

    The Haskell reference encodes value-dependent branches in the Message
    GADT's result index, so the compiler still sees every target state; a
    bare Python callable hides them.  `branch` restores the static view:
    ouro-lint's protocol pass (tools/analysis/protocol_pass.py) reads
    `.targets` for reachability/totality and rejects opaque callables.
    The returned dispatcher also enforces the declaration at run time, so
    the analyzer's graph can't silently diverge from actual behaviour."""
    if not targets:
        raise ValueError("branch() needs at least one target state")
    declared = frozenset(targets)

    def dispatch(msg):
        nxt = fn(msg)
        if nxt not in declared:
            raise ProtocolError(
                f"branch callable returned undeclared state {nxt!r}; "
                f"declared targets are {sorted(declared)}")
        return nxt

    dispatch.targets = tuple(targets)
    return dispatch


@dataclass(frozen=True)
class ProtocolSpec:
    """States + agency + transitions for one mini-protocol.

    transitions: (state, message type name) -> next state, or a callable
    (msg -> next state) for message-value-dependent transitions (e.g.
    TxSubmission's blocking flag).
    agency: state -> CLIENT | SERVER | NOBODY (who may send in that state).
    """
    name: str
    init_state: str
    agency: dict
    transitions: dict

    def _next(self, state: str, msg) -> Optional[str]:
        nxt = self.transitions.get((state, type(msg).__name__))
        if callable(nxt):
            return nxt(msg)
        return nxt

    def check_send(self, state: str, role: str, msg) -> str:
        who = self.agency.get(state, NOBODY)
        if who != role:
            raise ProtocolError(
                f"{self.name}: {role} tried to send {type(msg).__name__} "
                f"in state {state} where agency is {who}")
        nxt = self._next(state, msg)
        if nxt is None:
            raise ProtocolError(
                f"{self.name}: message {type(msg).__name__} not allowed "
                f"in state {state}")
        return nxt

    def is_done(self, state: str) -> bool:
        return self.agency.get(state, NOBODY) == NOBODY


class Session:
    """The per-peer protocol handle: send/recv with conformance checking."""

    def __init__(self, spec: ProtocolSpec, role: str, channel: Channel):
        self.spec = spec
        self.role = role
        self.channel = channel
        self.state = spec.init_state

    @property
    def done(self) -> bool:
        return self.spec.is_done(self.state)

    async def send(self, msg) -> None:
        self.state = self.spec.check_send(self.state, self.role, msg)
        await self.channel.send(msg)

    async def recv(self):
        other = SERVER if self.role == CLIENT else CLIENT
        who = self.spec.agency.get(self.state, NOBODY)
        if who != other:
            raise ProtocolError(
                f"{self.spec.name}: {self.role} tried to recv in state "
                f"{self.state} where agency is {who}")
        msg = await self.channel.recv()
        nxt = self.spec._next(self.state, msg)
        if nxt is None:
            raise ProtocolError(
                f"{self.spec.name}: peer sent {type(msg).__name__} "
                f"invalid in state {self.state}")
        self.state = nxt
        return msg


class PipelinedSession(Session):
    """Client-side pipelining: fire requests ahead of replies.

    Reference: Pipelined.hs:63 (type-level outstanding bound) and the
    two-thread driver (Driver.hs:150-186).  send_pipelined() advances the
    state machine through the *expected* reply state immediately; replies
    are collected in order via collect().
    """

    def __init__(self, spec: ProtocolSpec, role: str, channel: Channel,
                 max_outstanding: int = 16):
        super().__init__(spec, role, channel)
        self.max_outstanding = max_outstanding
        self._outstanding: list[str] = []   # states awaiting replies

    async def send_pipelined(self, msg, reply_state: str) -> None:
        """Send msg; the reply (to be collected later) is expected in the
        state the msg moves us to; after the reply we'll be in reply_state."""
        if len(self._outstanding) >= self.max_outstanding:
            raise ProtocolError(f"{self.spec.name}: pipeline depth exceeded")
        st = self.spec.check_send(self.state, self.role, msg)
        self._outstanding.append(st)
        self.state = reply_state
        await self.channel.send(msg)

    @property
    def outstanding(self) -> int:
        return len(self._outstanding)

    async def collect(self):
        """Await the oldest outstanding reply.

        A reply may span several messages (e.g. ChainSync's MsgAwaitReply
        followed by the eventual MsgRollForward): when the state after this
        message still has peer agency, the continuation state goes back to
        the front of the queue so the next collect() consumes the rest.

        Cancellation-safe: the outstanding entry is only consumed AFTER the
        recv completes, so wrapping collect() in a timeout and cancelling it
        (e.g. the ChainSync client's horizon-stall poll) leaves the pipeline
        bookkeeping intact — the reply the server still owes will be matched
        against the right expected state by the next collect()."""
        if not self._outstanding:
            raise ProtocolError(f"{self.spec.name}: nothing to collect")
        reply_in_state = self._outstanding[0]
        msg = await self.channel.recv()
        # no await between here and the pop: atomic under the cooperative
        # scheduler, so a single consumer can never double-collect the entry
        popped = self._outstanding.pop(0)
        assert popped is reply_in_state
        nxt = self.spec._next(reply_in_state, msg)
        if nxt is None:
            raise ProtocolError(
                f"{self.spec.name}: pipelined peer sent "
                f"{type(msg).__name__} invalid in state {reply_in_state}")
        other = SERVER if self.role == CLIENT else CLIENT
        if self.spec.agency.get(nxt, NOBODY) == other:
            self._outstanding.insert(0, nxt)
        return msg


async def run_peer(spec: ProtocolSpec, role: str, channel: Channel,
                   peer: Callable, pipelined: bool = False,
                   max_outstanding: int = 16):
    """Run an async peer function against a channel; returns its result.

    The message-object analog of runPeerWithDriver (Driver.hs:17-25); byte
    framing happens one layer down (mux channels / codecs).
    """
    if pipelined:
        session = PipelinedSession(spec, role, channel, max_outstanding)
    else:
        session = Session(spec, role, channel)
    return await peer(session)


async def connect(spec: ProtocolSpec, client, server,
                  capacity: int = 64, delay: float = 0.0):
    """Direct client<->server execution over an in-memory channel pair —
    the Proofs.hs `connect` analog used throughout protocol tests."""
    from .channel import channel_pair
    ca, cb = channel_pair(capacity=capacity, delay=delay,
                          label=spec.name)
    ch = sim.spawn(run_peer(spec, CLIENT, ca, client),
                   label=f"{spec.name}.client")
    sh = sim.spawn(run_peer(spec, SERVER, cb, server),
                   label=f"{spec.name}.server")
    return await ch.wait(), await sh.wait()
