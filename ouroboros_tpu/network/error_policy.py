"""ErrorPolicy — classify connection failures into suspend/shutdown verdicts.

Reference: ouroboros-network-framework/src/Ouroboros/Network/ErrorPolicy.hs
:52-89 (`ErrorPolicy` GADT matching exception types, `evalErrorPolicy`,
`SuspendDecision` semigroup: SuspendPeer/SuspendConsumer/Throw with
duration-max combining), and the consensus instantiation
(ouroboros-consensus/src/Ouroboros/Consensus/Node/ErrorPolicy.hs).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Type


@dataclass(frozen=True)
class SuspendDecision:
    """What to do about a peer after an exception.

    kind: "suspend-peer" (both directions) | "suspend-consumer" (our
    outbound only) | "throw" (fatal: shut the application down).
    """
    kind: str
    duration: float = 0.0

    def __or__(self, other: "SuspendDecision") -> "SuspendDecision":
        """The semigroup (ErrorPolicy.hs `SuspendDecision` Semigroup):
        throw dominates; suspend-peer dominates suspend-consumer;
        durations combine by max."""
        if "throw" in (self.kind, other.kind):
            return SuspendDecision("throw")
        kind = "suspend-peer" if "suspend-peer" in (self.kind, other.kind) \
            else "suspend-consumer"
        return SuspendDecision(kind, max(self.duration, other.duration))


def suspend_peer(duration: float) -> SuspendDecision:
    return SuspendDecision("suspend-peer", duration)


def suspend_consumer(duration: float) -> SuspendDecision:
    return SuspendDecision("suspend-consumer", duration)


THROW = SuspendDecision("throw")


@dataclass(frozen=True)
class ErrorPolicy:
    """One rule: exception class -> decision (ErrorPolicy.hs:52)."""
    exc_type: Type[BaseException]
    decide: Callable[[BaseException], Optional[SuspendDecision]]


def eval_error_policies(policies: Sequence[ErrorPolicy],
                        exc: BaseException) -> Optional[SuspendDecision]:
    """First match (in list order) wins, so specific rules listed before a
    catch-all take precedence; a single rule may still return None to
    decline (evalErrorPolicy/evalErrorPolicies — the reference combines
    only the verdicts of *independent* policy sets with the semigroup,
    which callers can do with `|`)."""
    for p in policies:
        if isinstance(exc, p.exc_type):
            d = p.decide(exc)
            if d is not None:
                return d
    return None


def default_node_policies(violation: float = 200.0,
                          transport: float = 20.0,
                          unknown: float = 60.0) -> list[ErrorPolicy]:
    """The consensus-flavoured defaults (Node/ErrorPolicy.hs): protocol
    violations and validation failures suspend the peer for a long time;
    transport hiccups suspend briefly; everything unknown suspends
    conservatively.  The three duration knobs exist so sim/chaos harnesses
    can scale the windows to sim time while exercising the SAME policy
    set (testing a hand-copied list would let the two drift)."""
    from ..node.chain_sync import ChainSyncClientError
    from ..node.watchdog import WatchdogTimeout
    from .mux import MuxError
    from .typed import ProtocolError
    from ..network.protocols.codec import CodecError
    return [
        ErrorPolicy(ChainSyncClientError,
                    lambda e: suspend_peer(violation)),
        ErrorPolicy(ProtocolError, lambda e: suspend_peer(violation)),
        ErrorPolicy(CodecError, lambda e: suspend_peer(violation)),
        # a peer silent past its per-state time limit is likely overloaded
        # or partitioned, not hostile: brief consumer-side suspension, then
        # redial (the reference's shortDelay for timeout errors)
        ErrorPolicy(WatchdogTimeout, lambda e: suspend_consumer(transport)),
        # the mux died under the protocol (bearer EOF / poisoned teardown
        # after a watchdog kill): transport-level hiccup, brief suspension
        ErrorPolicy(MuxError, lambda e: suspend_consumer(transport)),
        ErrorPolicy(ConnectionError, lambda e: suspend_consumer(transport)),
        ErrorPolicy(Exception, lambda e: suspend_consumer(unknown)),
    ]
