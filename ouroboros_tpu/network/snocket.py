"""Snocket — the transport abstraction: one dial/serve surface over TCP,
Unix sockets, and in-sim bearers.

Reference: ouroboros-network-framework/src/Ouroboros/Network/Snocket.hs:
163-214 (the record of getLocalAddr/getRemoteAddr/openToConnect/connect/
bind/listen/accept/close; socketSnocket :216, localSnocket :20, the accept
loop berkeleyAccept :110), Server/ConnectionTable.hs (live-connection
tracking + duplicate refusal), Server/RateLimiting.hs (accept rate limits:
soft limit delays accepts, hard limit blocks until a connection closes).

The same node code (handshake -> mux -> mini-protocols) runs over every
implementation; deterministic tests use SimSnocket, real deployments pick
TCP or Unix by address — exactly the property the reference's record
encodes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from .. import simharness as sim
from ..simharness import TBQueue
from .mux import QueueBearer


class SnocketError(Exception):
    pass


class Snocket:
    """The transport record.  Bearers returned by connect/accept speak the
    mux SDU interface (write(SDU)/read() + sdu_size)."""

    async def connect(self, addr) -> Any:
        """openToConnect + connect: dial, return a bearer."""
        raise NotImplementedError

    async def listen(self, addr) -> "Listener":
        """bind + listen: return a Listener whose accept() yields
        (bearer, remote_addr)."""
        raise NotImplementedError


class Listener:
    addr: Any

    async def accept(self) -> tuple:
        raise NotImplementedError

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# In-sim transport (the Bearer/Queues.hs analog behind the same record)
# ---------------------------------------------------------------------------

class SimSnocket(Snocket):
    """Address registry of in-memory bearer pairs; fully deterministic
    under the simulator."""

    def __init__(self, delay: float = 0.0, sdu_size: int = 12288):
        self.delay = delay
        self.sdu_size = sdu_size
        self._listeners: Dict[Any, "_SimListener"] = {}
        self._next_ephemeral = 1

    async def connect(self, addr):
        lst = self._listeners.get(addr)
        if lst is None or lst.closed:
            raise SnocketError(f"connection refused: {addr!r}")
        a2b = TBQueue(256, label=f"snocket.{addr}.c2s")
        b2a = TBQueue(256, label=f"snocket.{addr}.s2c")
        local = ("ephemeral", self._next_ephemeral)
        self._next_ephemeral += 1
        server_bearer = QueueBearer(b2a, a2b, self.sdu_size, self.delay)
        client_bearer = QueueBearer(a2b, b2a, self.sdu_size, self.delay)
        await sim.atomically(
            lambda tx: lst.pending.put(tx, (server_bearer, local)))
        return client_bearer

    async def listen(self, addr):
        if addr in self._listeners and not self._listeners[addr].closed:
            raise SnocketError(f"address in use: {addr!r}")
        lst = _SimListener(addr)
        self._listeners[addr] = lst
        return lst


class _SimListener(Listener):
    def __init__(self, addr):
        self.addr = addr
        self.pending = TBQueue(64, label=f"snocket.{addr}.accept")
        self.closed = False

    async def accept(self):
        return await sim.atomically(self.pending.get)

    def close(self):
        self.closed = True


# ---------------------------------------------------------------------------
# Real-socket transports (IO runtime only)
# ---------------------------------------------------------------------------

class TcpSnocket(Snocket):
    """socketSnocket: addr = (host, port)."""

    async def connect(self, addr):
        import asyncio

        from .socket_bearer import SocketBearer
        host, port = addr
        reader, writer = await asyncio.open_connection(host, port)
        return SocketBearer(reader, writer)

    async def listen(self, addr):
        import asyncio
        host, port = addr
        lst = _AsyncioListener()
        server = await asyncio.start_server(lst._on_conn, host, port)
        lst.server = server
        lst.addr = (host, server.sockets[0].getsockname()[1])
        return lst


class UnixSnocket(Snocket):
    """localSnocket: addr = filesystem path (the node-to-client IPC
    transport; named pipes on Windows are out of scope)."""

    async def connect(self, addr):
        import asyncio

        from .socket_bearer import SocketBearer
        reader, writer = await asyncio.open_unix_connection(addr)
        return SocketBearer(reader, writer)

    async def listen(self, addr):
        import asyncio
        lst = _AsyncioListener()
        server = await asyncio.start_unix_server(lst._on_conn, addr)
        lst.server = server
        lst.addr = addr
        return lst


class _AsyncioListener(Listener):
    def __init__(self):
        import asyncio
        self.server = None
        self.addr = None
        self._pending: "asyncio.Queue" = asyncio.Queue()
        self._conn_seq = 0

    async def _on_conn(self, reader, writer):
        from .socket_bearer import SocketBearer
        remote = writer.get_extra_info("peername")
        if not remote:
            # AF_UNIX clients are unbound (peername is "" for every one);
            # a sequence number keeps ConnectionTable keys unique
            self._conn_seq += 1
            remote = ("unix-peer", self._conn_seq)
        await self._pending.put((SocketBearer(reader, writer), remote))

    async def accept(self):
        return await self._pending.get()

    def close(self):
        if self.server is not None:
            self.server.close()


def snocket_for(addr, sim_registry: Optional[SimSnocket] = None) -> Snocket:
    """Address-family dispatch (Snocket.hs AddressFamily): tuples are TCP,
    strings are Unix paths, anything else resolves against the sim
    registry."""
    if isinstance(addr, tuple) and len(addr) == 2 \
            and isinstance(addr[1], int):
        return TcpSnocket()
    if isinstance(addr, str) and addr.startswith("/"):
        return UnixSnocket()
    if sim_registry is not None:
        return sim_registry
    raise SnocketError(f"no transport for address {addr!r}")


# ---------------------------------------------------------------------------
# ConnectionTable + accept rate limiting (the server side of Socket.hs)
# ---------------------------------------------------------------------------

class ConnectionTable:
    """Live-connection bookkeeping (Server/ConnectionTable.hs): refuse a
    second connection to the same remote, expose counts for limits."""

    def __init__(self):
        self._conns: Dict[Any, Any] = {}

    def __len__(self) -> int:
        return len(self._conns)

    def include(self, remote, handle=None) -> bool:
        """Register; False if the remote is already connected."""
        if remote in self._conns:
            return False
        self._conns[remote] = handle
        return True

    def remove(self, remote) -> None:
        self._conns.pop(remote, None)

    def __contains__(self, remote) -> bool:
        return remote in self._conns


@dataclass(frozen=True)
class AcceptLimits:
    """Server/RateLimiting.hs AcceptedConnectionsLimit."""
    hard_limit: int = 512              # block accepts at this many live
    soft_limit: int = 384              # above this, delay each accept
    delay: float = 5.0                 # the soft-limit pacing delay


async def run_server(listener: Listener, handler: Callable,
                     table: Optional[ConnectionTable] = None,
                     limits: AcceptLimits = AcceptLimits()) -> None:
    """The accept loop (berkeleyAccept + rate limiting): accept, apply
    limits, register in the table, fork the handler.  `handler(bearer,
    remote)` runs as its own thread; the table slot frees when it ends."""
    table = table if table is not None else ConnectionTable()
    while True:
        while len(table) >= limits.hard_limit:
            await sim.sleep(limits.delay)      # hard limit: stop accepting
        bearer, remote = await listener.accept()
        if len(table) >= limits.soft_limit:
            await sim.sleep(limits.delay)      # soft limit: pace accepts
        if not table.include(remote):
            close = getattr(bearer, "close", None)
            if close:
                close()
            sim.trace_event(("server-duplicate-conn", remote))
            continue

        async def run(bearer=bearer, remote=remote):
            try:
                await handler(bearer, remote)
            finally:
                table.remove(remote)
                close = getattr(bearer, "close", None)
                if close:
                    close()

        sim.spawn(run(), label=f"server-conn-{remote}")
