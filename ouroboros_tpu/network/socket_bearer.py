"""Socket mux bearer — SDU framing over a real TCP/Unix stream.

Reference: network-mux/src/Network/Mux/Bearer/Socket.hs (socket bearer,
12288-byte SDUs, recv timeouts) with the wire format of Codec.hs:16-40
(8-byte header: 32-bit timestamp, mode bit + 15-bit protocol number,
16-bit length, big-endian) — byte-compatible with the in-sim QueueBearer's
SDU encoding.

IO-runtime only: reading awaits asyncio streams, which the deterministic
simulator rejects by design (tests use QueueBearer there).
"""
from __future__ import annotations

import asyncio

from .. import simharness as sim
from .mux import SDU, MuxError


class SocketBearer:
    """MuxBearer over an asyncio (reader, writer) stream pair."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, sdu_size: int = 12288,
                 read_timeout: float = 300.0):
        self.reader = reader
        self.writer = writer
        self.sdu_size = sdu_size
        self.read_timeout = read_timeout

    def _timestamp(self) -> int:
        return int(sim.now() * 1e6) & 0xFFFFFFFF

    async def write(self, sdu: SDU) -> None:
        raw = SDU(self._timestamp(), sdu.mode, sdu.num,
                  sdu.payload).encode()
        self.writer.write(raw)
        await self.writer.drain()

    async def read(self) -> SDU:
        try:
            header = await asyncio.wait_for(self.reader.readexactly(8),
                                            self.read_timeout)
            ts, mode, num, length = SDU.decode_header(header)
            payload = await asyncio.wait_for(
                self.reader.readexactly(length), self.read_timeout)
        except asyncio.IncompleteReadError as e:
            raise MuxError("bearer closed") from e
        except asyncio.TimeoutError as e:
            raise MuxError("bearer read timeout") from e
        return SDU(ts, mode, num, payload)

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass
