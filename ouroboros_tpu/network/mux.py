"""Multiplexer — one bearer, N mini-protocol byte streams.

Reference: network-mux/src/Network/Mux.hs (newMux/runMux/miniProtocolJob),
Egress.hs:77-105 (single writer, fair SDU interleaving), Ingress.hs:100-122
(per-protocol ingress queues with byte limits), Codec.hs:16-40 (8-byte SDU
header: 32-bit timestamp | 1-bit mode + 15-bit protocol num | 16-bit length,
big-endian), Bearer/Queues.hs:25 (pure queue bearer for tests).

Wire-compatible SDU framing; the runtime is simharness threads + STM, so mux
behaviour (fairness, backpressure, overflow kills) is deterministic in tests.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional

from .. import simharness as sim
from ..observe import metrics as _metrics
from ..observe import netmetrics as _net
from ..simharness import TBQueue, TVar, retry

_TEARDOWNS = _metrics.counter("mux.teardowns")

INITIATOR, RESPONDER = 0, 1
HEADER = struct.Struct(">IHH")   # timestamp, mode|num, length


class MuxError(Exception):
    pass


@dataclass(frozen=True)
class SDU:
    timestamp: int      # lower 32 bits of sender's µs clock (RemoteClockModel)
    mode: int           # INITIATOR | RESPONDER (direction bit)
    num: int            # protocol number (15 bits)
    payload: bytes

    def encode(self) -> bytes:
        if self.num >= 1 << 15:
            raise MuxError("protocol number out of range")
        if len(self.payload) >= 1 << 16:
            raise MuxError("SDU payload too large")
        return HEADER.pack(self.timestamp & 0xFFFFFFFF,
                           (self.mode << 15) | self.num,
                           len(self.payload)) + self.payload

    @classmethod
    def decode_header(cls, raw: bytes) -> tuple[int, int, int, int]:
        ts, mn, ln = HEADER.unpack(raw[:8])
        return ts, mn >> 15, mn & 0x7FFF, ln


class QueueBearer:
    """In-memory bearer: SDU-preserving queue pair (Bearer/Queues.hs:25)."""

    def __init__(self, outq: TBQueue, inq: TBQueue, sdu_size: int = 12288,
                 delay: float = 0.0):
        self.sdu_size = sdu_size
        self._out = outq
        self._in = inq
        self._delay = delay

    async def write(self, sdu: SDU) -> None:
        raw = sdu.encode()
        if self._delay:
            await sim.sleep(self._delay)
        await sim.atomically(lambda tx: self._out.put(tx, raw))

    async def read(self) -> SDU:
        raw = await sim.atomically(self._in.get)
        ts, mode, num, ln = SDU.decode_header(raw)
        payload = raw[8:]
        if len(payload) != ln:
            raise MuxError("SDU length mismatch")
        return SDU(ts, mode, num, payload)


def bearer_pair(sdu_size: int = 12288, delay: float = 0.0, capacity: int = 256):
    a2b = TBQueue(capacity, label="bearer.a2b")
    b2a = TBQueue(capacity, label="bearer.b2a")
    return (QueueBearer(a2b, b2a, sdu_size, delay),
            QueueBearer(b2a, a2b, sdu_size, delay))


class MuxChannel:
    """Byte-stream channel for one (protocol num, direction)."""

    def __init__(self, mux: "Mux", num: int, mode: int):
        self._mux = mux
        self._num = num
        self._mode = mode
        # egress staging (drained by the muxer thread, Egress.hs Wanton)
        self.egress = TVar(b"", label=f"mux.egress.{num}.{mode}")
        # ingress chunks + byte accounting (Ingress.hs)
        self.ingress = TVar(b"", label=f"mux.ingress.{num}.{mode}")
        self.ingress_limit = 0x3FFFF

    EGRESS_CAP = 0xFFFF * 4

    async def send(self, data: bytes) -> None:
        """Queue bytes for egress; blocks while previous data undrained
        (the Wanton backpressure of Egress.hs:77).  Payloads larger than
        the egress cap are enqueued in chunks as the muxer drains.
        Raises MuxError once the mux is closed (teardown poisons the
        channels — a blocked protocol must die, not hang)."""
        off = 0
        while off < len(data):
            def tx_fn(tx, off=off):
                if tx.read(self._mux._closed):
                    return None
                cur = tx.read(self.egress)
                room = self.EGRESS_CAP - len(cur)
                if room <= 0:
                    retry()
                chunk = data[off:off + room]
                tx.write(self.egress, cur + chunk)
                return len(chunk)
            sent = await sim.atomically(tx_fn)
            if sent is None:
                raise MuxError(f"{self._mux.label}: mux closed")
            off += sent

    async def recv(self) -> bytes:
        """Receive whatever bytes have arrived (at least one); raises
        MuxError when the mux closed with nothing pending."""
        def tx_fn(tx):
            buf = tx.read(self.ingress)
            if buf:
                tx.write(self.ingress, b"")
                return buf
            if tx.read(self._mux._closed):
                return None
            retry()
        out = await sim.atomically(tx_fn)
        if out is None:
            raise MuxError(f"{self._mux.label}: mux closed")
        return out

    async def wait_ready(self, timeout: float) -> bool:
        """True when ingress bytes are pending OR the mux died, False
        after `timeout` — non-destructive (see Channel.wait_ready).
        Reporting a dead mux as ready matters for the watchdog path: the
        caller's follow-up recv() raises MuxError NOW, instead of a
        transport death masquerading as peer silence for the remainder of
        the state's time limit."""
        return await sim.wait_pred(
            lambda tx: bool(tx.read(self.ingress))
            or tx.read(self._mux._closed), timeout)

    async def try_recv(self) -> bytes:
        """Drain pending ingress bytes without blocking (b"" when none)."""
        def tx_fn(tx):
            buf = tx.read(self.ingress)
            if buf:
                tx.write(self.ingress, b"")
            return buf
        return await sim.atomically(tx_fn)


class Mux:
    """The mux proper: fair egress servicing + demux (Mux.hs:176-282)."""

    def __init__(self, bearer, label: str = "mux", owd_observer=None):
        self.bearer = bearer
        self.label = label
        # owd_observer(owd_seconds, sdu_bytes): fed one sample per received
        # SDU from the header timestamp (DeltaQ/TraceStats.hs) — passive
        # latency estimation riding the normal traffic
        self.owd_observer = owd_observer
        self._channels: dict[tuple[int, int], MuxChannel] = {}
        self._jobs: list = []
        self._demux_job = None
        # set by stop() (and on demux/egress death): poisons every
        # channel so blocked mini-protocols raise MuxError instead of
        # hanging — the reference's mux teardown kills its protocol
        # threads (Mux.hs JobPool cancellation)
        self._closed = TVar(False, label=f"{label}.closed")
        # bumped on channel registration so the egress loop's STM retry
        # re-reads the channel set (a snapshot would miss late channels)
        self._chan_version = TVar(0, label=f"{label}.chanver")
        # per-peer traffic accounting (ISSUE 14), built lazily on the
        # first ENABLED write: with observation off the per-SDU cost is
        # exactly one flag read — no label formatting, no instrument
        # writes (the bench --smoke disabled-observation probe)
        self._io: Optional[_net.MuxIO] = None

    def _io_acct(self) -> _net.MuxIO:
        io = self._io
        if io is None:
            io = self._io = _net.MuxIO(self.label)
        return io

    def channel(self, num: int, mode: int) -> MuxChannel:
        key = (num, mode)
        if key not in self._channels:
            self._channels[key] = MuxChannel(self, num, mode)
            if self._jobs:   # mux running: wake the egress loop
                self._chan_version.set_notify(self._chan_version.value + 1)
            else:
                self._chan_version._value += 1
        return self._channels[key]

    def start(self) -> None:
        self._jobs.append(sim.spawn(self._egress_loop(),
                                    label=f"{self.label}.muxer"))
        # named, not positional: wait_closed() must track THIS job even if
        # start() ever grows or reorders spawns (ADVICE r4)
        self._demux_job = sim.spawn(self._demux_loop(),
                                    label=f"{self.label}.demuxer")
        self._jobs.append(self._demux_job)

    def stop(self) -> None:
        self._mark_closed()
        for j in self._jobs:
            j.cancel()

    def _mark_closed(self) -> None:
        if not self._closed.value:     # count each mux teardown once
            _TEARDOWNS.inc()
        try:
            self._closed.set_notify(True)
        except Exception:
            self._closed._value = True

    async def wait_closed(self) -> None:
        """Block until the demuxer job ends — i.e. the bearer EOFed or
        errored (the connection-down signal servers hold on).  Returns
        immediately if the mux was never started."""
        if self._demux_job is None:
            return
        try:
            await self._demux_job.wait()
        except BaseException:
            pass

    async def _egress_loop(self):
        """Round-robin over channels; one SDU per channel per cycle
        (Egress.hs:77-105 fairness).  A bearer-write death (EOF or an
        injected LinkDown) poisons the channels exactly like a demux-side
        death — otherwise senders block on full egress TVars and a
        transport death masquerades as peer silence until a watchdog
        notices."""
        try:
            await self._egress_body()
        except sim.AsyncCancelled:
            self._mark_closed()
            raise
        except BaseException as exc:
            sim.trace_event((self.label, "bearer-died", repr(exc)),
                            label="mux")
            self._mark_closed()
            raise

    async def _egress_body(self):
        while True:
            # wait until any channel has egress data; reading _chan_version
            # inside the transaction adds it to the retry read set, so late
            # channel registrations wake this loop
            def wait_any(tx):
                tx.read(self._chan_version)
                for ch in self._channels.values():
                    if tx.read(ch.egress):
                        return True
                retry()
            await sim.atomically(wait_any)
            for ch in list(self._channels.values()):
                def take(tx, ch=ch):
                    buf = tx.read(ch.egress)
                    if not buf:
                        return None
                    cut = self.bearer.sdu_size
                    tx.write(ch.egress, buf[cut:])
                    return buf[:cut]
                chunk = await sim.atomically(take)
                if chunk:
                    ts = int(sim.now() * 1e6) & 0xFFFFFFFF
                    await self.bearer.write(
                        SDU(ts, ch._mode, ch._num, chunk))
                    if _metrics.REGISTRY.enabled:
                        self._io_acct().egress(ch._num, len(chunk))

    async def _demux_loop(self):
        """Read SDUs, route to ingress queues; overflow kills the mux
        (Ingress.hs:100-122 MuxIngressQueueOverRun semantics).  Any exit
        (bearer EOF/error/overflow) poisons the channels so protocol
        threads blocked in recv/send fail rather than hang."""
        try:
            await self._demux_body()
        except sim.AsyncCancelled:
            self._mark_closed()
            raise
        except BaseException as exc:
            # bearer death (incl. injected LinkDown) is a recovery-relevant
            # event: make the teardown reason visible in the sim trace so a
            # chaos run is debuggable from the trace alone
            sim.trace_event((self.label, "bearer-died", repr(exc)),
                            label="mux")
            self._mark_closed()
            raise

    async def _demux_body(self):
        while True:
            sdu = await self.bearer.read()
            if _metrics.REGISTRY.enabled:
                self._io_acct().ingress(sdu.num, len(sdu.payload))
            if self.owd_observer is not None:
                # 32-bit µs wraparound-safe one-way delay from the sender's
                # RemoteClockModel timestamp (TraceStats.hs)
                now_us = int(sim.now() * 1e6) & 0xFFFFFFFF
                delta = (now_us - sdu.timestamp) & 0xFFFFFFFF
                if delta < 1 << 31:          # sane (not clock-behind)
                    self.owd_observer(delta / 1e6, len(sdu.payload) + 8)
            # the sender's direction bit is flipped on receive: the remote
            # initiator's data feeds our responder-side channel (Ingress.hs)
            key = (sdu.num, 1 - sdu.mode)
            ch = self._channels.get(key)
            if ch is None:
                # the reference's newMux registers every ingress queue of
                # the MiniProtocolBundle before data can flow (responders
                # start on demand — Mux.hs:264 StartOnDemand); our lazy
                # registration gets the same effect by creating the queue
                # here, buffering until the protocol attaches
                ch = self.channel(sdu.num, 1 - sdu.mode)

            def put(tx, ch=ch, data=sdu.payload):
                buf = tx.read(ch.ingress)
                if len(buf) + len(data) > ch.ingress_limit:
                    raise MuxError(
                        f"{self.label}: ingress overflow on {ch._num}")
                tx.write(ch.ingress, buf + data)
            await sim.atomically(put)


class CodecChannel:
    """Message-level channel over a byte stream + Codec: CBOR-prefix framing.

    The Driver/Simple.hs byte-level driver analog: accumulates chunks and
    decodes one CBOR item per message (mux SDU boundaries are invisible to
    the protocol layer, as in the reference).
    """

    def __init__(self, byte_channel, codec):
        self._ch = byte_channel
        self._codec = codec
        self._buf = b""

    async def send(self, msg) -> None:
        await self._ch.send(self._codec.encode(msg))

    async def recv(self):
        from ..utils import cbor
        while True:
            if self._buf:
                try:
                    _, used = cbor.loads_prefix(self._buf)
                except cbor.CBORTruncated:
                    used = 0   # partial message: wait for more bytes
                if used:
                    raw, self._buf = self._buf[:used], self._buf[used:]
                    return self._codec.decode(raw)
            self._buf += await self._ch.recv()

    async def wait_ready(self, timeout: float) -> bool:
        """True when a COMPLETE message is decodable within `timeout`,
        False otherwise — message-aware, so a peer dribbling a partial
        frame cannot make the caller's follow-up recv() block unboundedly.
        Partial bytes are pulled into the channel's own buffer (safe: the
        buffer survives and the message layer never sees a torn frame)."""
        from ..utils import cbor
        deadline = sim.now() + timeout
        while True:
            if self._buf:
                try:
                    _, used = cbor.loads_prefix(self._buf)
                    if used:
                        return True
                except cbor.CBORTruncated:
                    pass
            remaining = deadline - sim.now()
            if remaining <= 0 or not await self._ch.wait_ready(remaining):
                return False
            got = await self._ch.try_recv()
            if not got:
                # ready with nothing pending = the byte channel closed
                # underneath: report ready so the caller's recv() raises
                # the MuxError now (also avoids a livelock re-polling a
                # permanently-ready dead channel)
                return True
            self._buf += got
