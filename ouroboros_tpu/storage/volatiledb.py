"""VolatileDB — unordered block store for the tip region.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Storage/VolatileDB/
(SURVEY.md §2): append to the current file, rotating after
max_blocks_per_file (Impl.hs); in-memory reverse index hash→location and
successor map prev_hash→{hash} for `filterByPredecessor` (Impl/Index.hs,
Impl/State.hs); GC whole files by slot (`garbageCollect`);
corruption-tolerant parse that truncates a torn tail (Impl/Parser.hs).

Record format per block: CBOR [hash, prev_hash, slot, block_no, crc]
followed by the raw block bytes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional

from ..utils import cbor
from .fs import FsApi, FsError, crc32

DIR = ("volatile",)


@dataclass(frozen=True)
class BlockInfo:
    hash: bytes
    prev_hash: bytes
    slot: int
    block_no: int
    file_no: int
    offset: int          # offset of the block bytes (after the header)
    size: int


def _file(n: int) -> tuple:
    return DIR + (f"vol-{n:05d}.dat",)


class VolatileDB:
    def __init__(self, fs: FsApi, max_blocks_per_file: int = 50):
        self.fs = fs
        self.max_blocks_per_file = max_blocks_per_file
        self._index: dict[bytes, BlockInfo] = {}
        self._successors: dict[bytes, set] = {}
        self._file_blocks: dict[int, list[bytes]] = {}   # file -> hashes
        self._current_file = 0
        self._current_count = 0

    # -- open + reindex -------------------------------------------------------
    @classmethod
    def open(cls, fs: FsApi, max_blocks_per_file: int = 50) -> "VolatileDB":
        db = cls(fs, max_blocks_per_file)
        fs.mkdirs(DIR)
        file_nos = sorted(int(name.split("-")[1].split(".")[0])
                          for name in fs.list_dir(DIR)
                          if name.startswith("vol-"))
        for n in file_nos:
            db._load_file(n)
        if file_nos:
            db._current_file = file_nos[-1]
            db._current_count = len(db._file_blocks.get(file_nos[-1], []))
            if db._current_count >= max_blocks_per_file:
                db._current_file += 1
                db._current_count = 0
        return db

    def _load_file(self, n: int) -> None:
        """Parse one file, truncating at the first corrupt record."""
        fs = self.fs
        raw = fs.read_file(_file(n))
        pos = 0
        while pos < len(raw):
            try:
                hdr, used = cbor.loads_prefix(raw[pos:])
                h, prev, slot, block_no, crc = (bytes(hdr[0]), bytes(hdr[1]),
                                                int(hdr[2]), int(hdr[3]),
                                                int(hdr[4]))
                size = int(hdr[5])
                start = pos + used
                data = raw[start:start + size]
                if len(data) < size or crc32(data) != crc:
                    raise ValueError("corrupt record")
            except (cbor.CBORError, ValueError, IndexError, TypeError):
                fs.truncate_file(_file(n), pos)
                break
            self._add_index(BlockInfo(h, prev, slot, block_no, n, start,
                                      size))
            pos = start + size

    def _add_index(self, info: BlockInfo) -> None:
        self._index[info.hash] = info
        self._successors.setdefault(info.prev_hash, set()).add(info.hash)
        self._file_blocks.setdefault(info.file_no, []).append(info.hash)

    # -- writes ---------------------------------------------------------------
    def put_block(self, h: bytes, prev_hash: bytes, slot: int, block_no: int,
                  data: bytes) -> None:
        """Idempotent (duplicate puts ignored, as in the reference)."""
        if h in self._index:
            return
        n = self._current_file
        header = cbor.dumps([h, prev_hash, slot, block_no, crc32(data),
                             len(data)])
        try:
            base = self.fs.file_size(_file(n))
        except FsError:
            base = 0
        self.fs.append_file(_file(n), header + data)
        self._add_index(BlockInfo(h, prev_hash, slot, block_no, n,
                                  base + len(header), len(data)))
        self._current_count += 1
        if self._current_count >= self.max_blocks_per_file:
            self._current_file += 1
            self._current_count = 0

    # -- queries --------------------------------------------------------------
    def __contains__(self, h: bytes) -> bool:
        return h in self._index

    def __len__(self) -> int:
        return len(self._index)

    def get_block(self, h: bytes) -> Optional[bytes]:
        info = self._index.get(h)
        if info is None:
            return None
        return self.fs.read_range(_file(info.file_no), info.offset, info.size)

    def block_info(self, h: bytes) -> Optional[BlockInfo]:
        return self._index.get(h)

    def filter_by_predecessor(self, prev_hash: bytes) -> frozenset:
        """Successor hashes of `prev_hash` (candidate-construction seed,
        Impl/Index.hs successor map)."""
        return frozenset(self._successors.get(prev_hash, ()))

    # -- GC -------------------------------------------------------------------
    def garbage_collect(self, slot: int) -> None:
        """Drop whole files whose blocks are all older than `slot`
        (file-granular GC, as in the reference)."""
        for n in list(self._file_blocks):
            if n == self._current_file:
                continue
            hashes = self._file_blocks[n]
            if all(self._index[h].slot < slot for h in hashes):
                for h in hashes:
                    info = self._index.pop(h)
                    succ = self._successors.get(info.prev_hash)
                    if succ:
                        succ.discard(h)
                        if not succ:
                            del self._successors[info.prev_hash]
                del self._file_blocks[n]
                self.fs.remove(_file(n))
