"""ChainDB — the chain database: selection, followers, iterators, GC.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Storage/ChainDB/
(SURVEY.md §2): facade API (API.hs:117-317 addBlockAsync/getCurrentChain/
followers/iterators/invalid set), chain selection triage add-to-current /
switch-to-fork / store-only (Impl/ChainSel.hs:410-476), candidate
construction via the VolatileDB successor map (Paths.maximalCandidates,
ChainSel.hs:516), candidate validation through the LedgerDB
(Impl/LgrDB.hs:350-400), background copy-to-immutable + snapshot + GC
(Impl/Background.hs:84-102), open-time replay from the newest snapshot
(LedgerDB/OnDisk.hs:277).

TPU-first difference: candidate validation uses
consensus/batch.validate_blocks_batched — one device batch per candidate
window instead of the reference's strictly sequential fold.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from ..chain.block import GENESIS_HASH, Point, point_of
from ..chain.fragment import AnchoredFragment
from ..consensus.batch import validate_blocks_batched
from ..consensus.ledger import (
    ExtLedgerRules, ExtLedgerState, OutsideForecastRange,
)
from .fs import FsApi
from .immutabledb import ImmutableDB
from .ledgerdb import DiskPolicy, LedgerDB
from .volatiledb import VolatileDB


@dataclass(frozen=True)
class AddBlockResult:
    """What chain selection did with the block (TraceAddBlockEvent analog)."""
    kind: str          # "extended" | "switched" | "stored" | "invalid" | \
                       # "duplicate" | "too_old"
    new_tip: Point


class Follower:
    """ChainDB follower: a read pointer on the current chain
    (Impl/Follower.hs).  instruction() is pull-based; blocking waits are
    layered on top via the version counter."""

    def __init__(self, db: "ChainDB", fid: int):
        self.db = db
        self.fid = fid
        self.point = db.immutable_tip_point()
        self.needs_rollback = False

    def instruction(self) -> Optional[tuple]:
        """("rollback", Point) | ("forward", block) | None when caught up."""
        db = self.db
        chain = db.current_chain
        if self.needs_rollback:
            self.needs_rollback = False
            return ("rollback", self.point)
        on_volatile = (chain.contains_point(self.point)
                       or self.point == chain.anchor)
        if not on_volatile:
            # behind the immutable anchor (copy_to_immutable advanced it)?
            # stream the immutable chain — those blocks ARE on the chain
            imm_slot = db.immutable.slot_of_hash(self.point.hash)
            if (self.point.is_genesis and db.immutable.tip is not None) \
                    or (imm_slot is not None and imm_slot == self.point.slot):
                nxt = db.immutable.next_after_hash(
                    None if self.point.is_genesis else self.point.hash)
                if nxt is not None:
                    entry, raw = nxt
                    blk = db.block_decode(raw)
                    self.point = point_of(blk)
                    return ("forward", blk)
                return None   # immutable tip == chain anchor: fall through
            # genuinely off-chain (fork switch): roll back to the deepest
            # point still on the chain
            self.point = db._deepest_common(self.point)
            return ("rollback", self.point)
        nxt = db._block_after(self.point)
        if nxt is None:
            return None
        self.point = point_of(nxt)
        return ("forward", nxt)


class ChainDB:
    def __init__(self, ext_rules: ExtLedgerRules, immutable: ImmutableDB,
                 volatile: VolatileDB, ledger_db: LedgerDB,
                 block_decode: Callable[[bytes], Any],
                 backend=None, disk_policy: DiskPolicy = DiskPolicy(),
                 fs: Optional[FsApi] = None,
                 encode_state: Optional[Callable] = None, tracer=None):
        from ..utils.tracer import NOP
        self.tracer = tracer if tracer is not None else NOP
        self.ext_rules = ext_rules
        self.immutable = immutable
        self.volatile = volatile
        self.ledger_db = ledger_db
        self.block_decode = block_decode
        self.backend = backend
        self.disk_policy = disk_policy
        self.fs = fs                          # for ledger snapshots
        self.encode_state = encode_state
        self.k = ext_rules.protocol.security_param
        # current chain: fragment of BLOCKS anchored at the immutable tip
        self.current_chain: AnchoredFragment = AnchoredFragment(
            ledger_db.anchor_point, (),
            anchor_block_no=self._anchor_block_no())
        self.invalid: dict[bytes, str] = {}       # hash -> reason
        self.version = 0                          # bumped on chain change
        self._on_change: list[Callable[[], None]] = []
        self._followers: dict[int, Follower] = {}
        self._next_fid = 0
        self._last_snapshot_slot = -1
        # in-future block buffering (cdbFutureBlocks + Fragment/InFuture.hs):
        # blocks whose slot is past the wall clock (allowing max_clock_skew
        # slots) wait here and re-triage when their slot arrives.  Enabled
        # by giving the DB a clock (current_slot_fn); tools/replay leave it
        # None (no wall clock — nothing is "future").
        self.current_slot_fn: Optional[Callable[[], int]] = None
        self.max_clock_skew_slots: int = 1
        self.future_blocks: dict[bytes, Any] = {}
        # async add-block queue (Background.hs addBlockRunner: ALL chain
        # selection runs on one writer thread)
        self._add_queue: list = []
        self._add_wakeup = None                   # lazily created TVar

    def _anchor_block_no(self) -> int:
        t = self.immutable.tip
        return t.block_no if t else -1

    # -- open: snapshot + replay + initial chain selection --------------------
    @classmethod
    def open(cls, fs: FsApi, ext_rules: ExtLedgerRules,
             encode_state: Callable, decode_state: Callable,
             block_decode: Callable[[bytes], Any],
             chunk_size: int = 100, max_blocks_per_file: int = 50,
             backend=None, disk_policy: DiskPolicy = DiskPolicy(),
             validate_chunks: bool = True, tracer=None) -> "ChainDB":
        immutable = ImmutableDB.open(fs, chunk_size,
                                     validate_all=validate_chunks)
        volatile = VolatileDB.open(fs, max_blocks_per_file)
        k = ext_rules.protocol.security_param

        # resume ledger: newest readable snapshot, else genesis (OnDisk.hs)
        snap = LedgerDB.read_latest_snapshot(fs, decode_state)
        if snap is not None:
            snap_slot, snap_point, ext_state = snap
        else:
            snap_point, ext_state = Point.genesis(), ext_rules.initial_state()

        # replay immutable blocks newer than the snapshot (no crypto)
        start = snap_point.slot + 1
        for entry, raw in immutable.stream(from_slot=max(start, 0)):
            block = block_decode(raw)
            ext_state = ext_rules.tick_then_reapply(ext_state, block)

        imm_tip = immutable.tip
        anchor = Point(imm_tip.slot, imm_tip.hash) if imm_tip \
            else Point.genesis()
        if ext_rules.tip(ext_state) != anchor:
            # snapshot newer than the immutable chain (shouldn't happen
            # with atomic snapshots) — fall back to genesis replay
            ext_state = ext_rules.initial_state()
            for entry, raw in immutable.stream():
                ext_state = ext_rules.tick_then_reapply(
                    ext_state, block_decode(raw))

        ledger_db = LedgerDB(k, anchor, ext_state)
        db = cls(ext_rules, immutable, volatile, ledger_db, block_decode,
                 backend=backend, disk_policy=disk_policy, fs=fs,
                 encode_state=encode_state, tracer=tracer)
        db._initial_chain_selection()
        return db

    def _initial_chain_selection(self) -> None:
        """Best volatile candidate from the immutable tip, re-run to a
        fixpoint as invalid blocks surface (ChainSel.hs:88-99; the invalid
        set is in-memory only, so reopen rediscovers them)."""
        best = self._best_candidate_from(self.current_chain.anchor)
        if best:
            self._try_adopt(self.current_chain.anchor, best)
        self._reselect_fixpoint()

    # -- queries --------------------------------------------------------------
    def tip_point(self) -> Point:
        return self.current_chain.head_point

    def tip_header(self):
        b = self.current_chain.head
        return b.header if b is not None else None

    def immutable_tip_point(self) -> Point:
        return self.current_chain.anchor

    @property
    def current_ledger(self) -> ExtLedgerState:
        return self.ledger_db.current

    def get_block(self, h: bytes) -> Optional[Any]:
        raw = self.volatile.get_block(h)
        if raw is None:
            raw = self.immutable.get_by_hash(h)
        return self.block_decode(raw) if raw is not None else None

    def get_is_invalid(self, h: bytes) -> bool:
        return h in self.invalid

    def contains_point(self, p: Point) -> bool:
        if p.is_genesis:
            return True
        if self.current_chain.contains_point(p) \
                or p == self.current_chain.anchor:
            return True
        slot = self.immutable.slot_of_hash(p.hash)
        return slot is not None and slot == p.slot

    # -- iterators (across Imm + current chain) -------------------------------
    def stream_blocks(self, from_point: Point, to_point: Point) -> list:
        """Blocks on the current chain in (from_point, to_point], resolved
        across ImmutableDB + VolatileDB (Impl/Iterator.hs semantics; used
        by the BlockFetch server)."""
        out = []
        # walk back from to_point to from_point collecting hashes
        cursor = to_point
        rev: list[Point] = []
        while cursor != from_point and not cursor.is_genesis:
            rev.append(cursor)
            blk = self.get_block(cursor.hash)
            if blk is None:
                return []
            prev = blk.prev_hash
            if prev == GENESIS_HASH:
                cursor = Point.genesis()
            else:
                pb = self.get_block(prev)
                if pb is None:
                    # predecessor is in the immutable index only by hash
                    slot = self.immutable.slot_of_hash(prev)
                    if slot is None:
                        return []
                    cursor = Point(slot, prev)
                else:
                    cursor = point_of(pb)
        if cursor != from_point:
            return []
        for p in reversed(rev):
            out.append(self.get_block(p.hash))
        return out

    # -- followers ------------------------------------------------------------
    def new_follower(self) -> Follower:
        f = Follower(self, self._next_fid)
        self._next_fid += 1
        self._followers[f.fid] = f
        return f

    def remove_follower(self, f: Follower) -> None:
        self._followers.pop(f.fid, None)

    def on_change(self, cb: Callable[[], None]) -> None:
        self._on_change.append(cb)

    def _bump(self) -> None:
        self.version += 1
        for cb in self._on_change:
            cb()

    def _deepest_common(self, point: Point) -> Point:
        """Deepest ancestor of `point` still on the current chain (follower
        repositioning after a fork switch)."""
        cursor = point
        while not cursor.is_genesis:
            if self.current_chain.contains_point(cursor) \
                    or cursor == self.current_chain.anchor \
                    or self.immutable.slot_of_hash(cursor.hash) == cursor.slot:
                return cursor
            blk = self.get_block(cursor.hash)
            if blk is None:
                return self.current_chain.anchor
            prev = blk.prev_hash
            if prev == GENESIS_HASH:
                return Point.genesis()
            pb = self.get_block(prev)
            if pb is None:
                return self.current_chain.anchor
            cursor = point_of(pb)
        return self.current_chain.anchor

    def _block_after(self, point: Point) -> Optional[Any]:
        """Next block on the current chain after `point`."""
        chain = self.current_chain
        if point == chain.anchor:
            return chain.blocks[0] if len(chain) else None
        idx = chain._index.get(point.hash)
        if idx is None or idx + 1 >= len(chain):
            return None
        return chain.blocks[idx + 1]

    # -- the add-block pipeline (ChainSel.hs:410-476) -------------------------
    def add_block(self, block: Any) -> AddBlockResult:
        h = block.hash
        if h in self.invalid:
            return AddBlockResult("invalid", self.tip_point())
        if self.volatile.block_info(h) is not None or h in self.immutable:
            return AddBlockResult("duplicate", self.tip_point())
        imm_tip_slot = self.current_chain.anchor.slot
        if block.slot <= imm_tip_slot:
            return AddBlockResult("too_old", self.tip_point())
        if self.current_slot_fn is not None:
            now_slot = self.current_slot_fn()
            if block.slot > now_slot + self.max_clock_skew_slots:
                # from the future (clock skew beyond tolerance): buffer,
                # re-triaged by on_slot_tick (cdbFutureBlocks)
                self.future_blocks[h] = block
                return AddBlockResult("from_future", self.tip_point())
        self.volatile.put_block(h, block.prev_hash, block.slot,
                                block.block_no, block.bytes)
        res = self._chain_selection_for(block)
        if self.tracer.active:
            from ..utils.tracer import TraceAddBlock
            self.tracer.trace(TraceAddBlock(
                kind=res.kind, slot=block.slot, block_no=block.block_no,
                hash=h))
        return res

    def on_slot_tick(self, slot: int) -> list[AddBlockResult]:
        """Re-triage buffered future blocks whose slot has arrived
        (Background.hs's per-slot chain-selection rerun for
        cdbFutureBlocks)."""
        due = [b for h, b in self.future_blocks.items()
               if b.slot <= slot + self.max_clock_skew_slots]
        out = []
        for b in sorted(due, key=lambda b: b.slot):
            self.future_blocks.pop(b.hash, None)
            out.append(self.add_block(b))
        return out

    # -- async add queue (Background.hs:84-102 addBlockRunner) ----------------
    def _queue_wakeup(self):
        if self._add_wakeup is None:
            from ..simharness import TVar
            self._add_wakeup = TVar(0, label="chaindb-add-queue")
        return self._add_wakeup

    def add_block_async(self, block: Any) -> None:
        """Enqueue for the single writer thread (ChainDB.addBlockAsync):
        callers never run chain selection themselves."""
        self._add_queue.append(block)
        wk = self._queue_wakeup()
        try:
            wk.set_notify(wk.value + 1)
        except Exception:
            wk._value = wk.value + 1

    async def add_block_runner(self) -> None:
        """The serialization point: drain the queue, one chain selection
        at a time (the reference's addBlockRunner background thread)."""
        from .. import simharness as sim
        from ..simharness import Retry
        wk = self._queue_wakeup()
        while True:
            while self._add_queue:
                block = self._add_queue.pop(0)
                res = self.add_block(block)
                sim.trace_event(("add-block-async", res.kind, block.slot))
            seen = wk.value

            def wait(tx, seen=seen):
                if tx.read(wk) == seen:
                    raise Retry()
            await sim.atomically(wait)

    def _beats_current(self, cand_view) -> bool:
        """Is `cand_view` strictly preferred over the current chain?  An
        EMPTY current chain loses to any valid candidate (the bare block-
        number sentinel of an empty fragment is not a protocol SelectView
        and must not reach prefer_candidate)."""
        if cand_view is None:
            return False
        head = self.current_chain.head
        if head is None:
            return True
        cur_view = self.ext_rules.protocol.select_view(
            getattr(head, "header", head))
        return self.ext_rules.protocol.prefer_candidate(cur_view, cand_view)

    def _reselect(self) -> bool:
        """One full re-selection pass: every candidate constructible from
        the anchor that beats the current chain, tried best-first from its
        ACTUAL fork point with the current chain.  Returns True if a
        candidate was adopted."""
        import functools
        cur = self.current_chain
        prefer = self.ext_rules.protocol.prefer_candidate
        cands = []
        for path in self._successors_closure(cur.anchor):
            v = self._candidate_select_view(cur.anchor, path)
            if self._beats_current(v):
                cands.append((path, v))
        cands.sort(key=functools.cmp_to_key(
            lambda a, b: -1 if prefer(b[1], a[1])
            else (1 if prefer(a[1], b[1]) else 0)))
        for path, _v in cands:
            fork = cur.anchor
            i = 0
            for b in path:
                if cur.contains_point(point_of(b)):
                    fork = point_of(b)
                    i += 1
                else:
                    break
            if i < len(path) and self._try_adopt(fork, path[i:]):
                return True
        return False

    def _reselect_fixpoint(self) -> bool:
        """Re-run selection until the invalid set stops growing: marking a
        block invalid during validation changes the ranking, so a losing
        candidate may now win (ChainSel.hs re-triage with the updated
        invalid set).  Returns True if any adoption happened."""
        adopted = False
        while True:                      # each retry marks >= 1 new invalid
            before = len(self.invalid)   # block, so bounded by volatile size
            adopted = self._reselect() or adopted
            if len(self.invalid) == before:
                return adopted

    def _chain_selection_for(self, block: Any) -> AddBlockResult:
        before_invalid = len(self.invalid)
        result = self._triage_once(block)
        # only a GROWN invalid set can change the candidate ranking; the
        # common extend/store path skips the full re-selection entirely
        if len(self.invalid) == before_invalid:
            return result
        if self._reselect_fixpoint() and result.kind in ("stored",
                                                         "invalid"):
            return AddBlockResult("switched", self.tip_point())
        if result.kind in ("extended", "switched"):
            return AddBlockResult(result.kind, self.tip_point())
        return result

    def _triage_once(self, block: Any) -> AddBlockResult:
        cur = self.current_chain
        tip = self.tip_point()
        if block.prev_hash == (tip.hash if not tip.is_genesis
                               else GENESIS_HASH):
            # triage 1: extends the current tip — adopt the best path
            # through it (picks up already-stored successors too)
            best = self._best_candidate_from(tip)
            ok = self._try_adopt(tip, best if best else [block])
            kind = "extended" if ok else "invalid"
            return AddBlockResult(kind, self.tip_point())
        # triage 2: reachable from some point on the current fragment?
        import functools
        prefer = self.ext_rules.protocol.prefer_candidate
        # the same candidate head is reachable from several fork points
        # (deeper forks re-walk the current chain) — keep, per head, the
        # SHALLOWEST rollback, then try candidates best-view-first
        by_head: dict[bytes, tuple] = {}
        cache: dict = {block.hash: block}
        for fork_point, blocks in self._candidates_through(block, cache):
            cand_view = self._candidate_select_view(fork_point, blocks)
            if not self._beats_current(cand_view):
                continue
            head = blocks[-1].hash
            depth = self._rollback_depth(fork_point)
            if depth is None:
                continue
            old = by_head.get(head)
            if old is None or depth < old[3]:
                by_head[head] = (fork_point, blocks, cand_view, depth)
        cands = sorted(
            by_head.values(),
            key=functools.cmp_to_key(
                lambda a, b: -1 if prefer(b[2], a[2])
                else (1 if prefer(a[2], b[2]) else a[3] - b[3])))
        for fork_point, blocks, _view, _depth in cands:
            if self._try_adopt(fork_point, blocks):
                return AddBlockResult("switched", self.tip_point())
        return AddBlockResult("stored", self.tip_point())


    def _candidate_select_view(self, fork_point: Point, blocks: Sequence):
        if not blocks:
            return None
        return self.ext_rules.protocol.select_view(
            getattr(blocks[-1], "header", blocks[-1]))

    # -- candidates (Paths.maximalCandidates over the successor map) ----------
    def _decode_cached(self, h: bytes, cache: dict) -> Optional[Any]:
        if h in cache:
            return cache[h]
        raw = self.volatile.get_block(h)
        blk = self.block_decode(raw) if raw is not None else None
        cache[h] = blk
        return blk

    def _successors_closure(self, point: Point,
                            cache: Optional[dict] = None) -> list[list]:
        """All maximal block-paths leaving `point`, via the VolatileDB
        successor map; invalid blocks prune the walk.  Decoded blocks are
        memoized in `cache` (shared across the fork points of one
        add_block call — the candidate hot path)."""
        if cache is None:
            cache = {}
        out: list[list] = []
        acc: list = []

        def walk(h: bytes):
            succs = [s for s in self.volatile.filter_by_predecessor(h)
                     if s not in self.invalid]
            extended = False
            for s in succs:
                blk = self._decode_cached(s, cache)
                if blk is None:
                    continue
                extended = True
                acc.append(blk)
                walk(s)
                acc.pop()
            if not extended and acc:
                out.append(list(acc))

        start = point.hash if not point.is_genesis else GENESIS_HASH
        walk(start)
        return out

    def _candidates_through(self, block: Any,
                            cache: Optional[dict] = None
                            ) -> list[tuple[Point, list]]:
        """(fork_point, blocks) candidates containing `block`, forking from
        any point on the current fragment (incl. anchor)."""
        if cache is None:
            cache = {}
        points = [self.current_chain.anchor] + [
            point_of(b) for b in self.current_chain.blocks]
        cands = []
        want = block.hash
        for p in points:
            for path in self._successors_closure(p, cache):
                if any(b.hash == want for b in path):
                    cands.append((p, path))
        return cands

    def _best_candidate_from(self, point: Point) -> Optional[list]:
        best, best_view = None, None
        for path in self._successors_closure(point):
            v = self._candidate_select_view(point, path)
            if v is None:
                continue
            if best is None:
                if self._beats_current(v):
                    best, best_view = path, v
            elif self.ext_rules.protocol.prefer_candidate(best_view, v):
                best, best_view = path, v
        return best

    # -- adoption: batched validation + switch --------------------------------
    def _try_adopt(self, fork_point: Point, blocks: Sequence) -> bool:
        """Validate `blocks` from `fork_point` (ONE batched device call via
        validate_blocks_batched) and switch/extend if a valid prefix still
        improves on the current chain (LgrDB.validate + switchTo)."""
        n_rollback = self._rollback_depth(fork_point)
        if n_rollback is None or n_rollback > self.k:
            return False
        base_state = self.ledger_db.current if n_rollback == 0 else None
        # state at the fork point
        if n_rollback > 0:
            st = self.ledger_db.state_at(fork_point)
            if st is None:
                return False
            base_state = st
        res = validate_blocks_batched(self.ext_rules, list(blocks),
                                      base_state, backend=self.backend)
        valid_blocks = list(blocks)[:res.n_valid]
        if res.error is not None and not isinstance(res.error,
                                                    OutsideForecastRange):
            # OutsideForecastRange is retry-later, never invalid: the
            # reference defers such blocks until the chain advances
            # (ADVICE r2; cf. ChainSync forecast-horizon waiting)
            for b in list(blocks)[res.n_valid:]:
                self.invalid[b.hash] = str(res.error)
                if self.tracer.active:
                    from ..utils.tracer import TraceInvalidBlock
                    self.tracer.trace(TraceInvalidBlock(
                        hash=b.hash, reason=str(res.error)))
        if not valid_blocks and n_rollback > 0:
            return False
        # does the valid prefix still beat the current chain?
        if n_rollback > 0 or res.n_valid < len(blocks):
            cand_view = self._candidate_select_view(fork_point, valid_blocks)
            if not self._beats_current(cand_view):
                return False
        elif not valid_blocks:
            return False
        # switch: truncate to fork point, extend with valid blocks
        new_chain = self.current_chain.copy()
        if not new_chain.truncate_to(fork_point):
            return False
        for b in valid_blocks:
            new_chain.add_block(b)
        ok = self.ledger_db.switch(
            n_rollback,
            lambda st: [(point_of(b), s)
                        for b, s in zip(valid_blocks, res.states)])
        if not ok:
            return False
        old_point = self.tip_point()
        if n_rollback > 0 and self.tracer.active:
            from ..utils.tracer import TraceSwitchedToFork
            self.tracer.trace(TraceSwitchedToFork(
                old_tip_slot=old_point.slot,
                new_tip_slot=new_chain.head_point.slot,
                rollback_depth=n_rollback))
        self.current_chain = new_chain
        self._bump()
        for f in self._followers.values():
            if not (new_chain.contains_point(f.point)
                    or f.point == new_chain.anchor):
                f.point = self._deepest_common(f.point)
                f.needs_rollback = True
        return True

    def _rollback_depth(self, fork_point: Point) -> Optional[int]:
        chain = self.current_chain
        if fork_point == chain.anchor:
            return len(chain)
        idx = chain._index.get(fork_point.hash)
        if idx is None:
            return None
        return len(chain) - (idx + 1)

    # -- background duties (Impl/Background.hs:84-102) ------------------------
    def copy_to_immutable(self) -> int:
        """Move blocks > k deep to the ImmutableDB, advance anchors, GC the
        VolatileDB, and (if due, and the DB was opened with a snapshot
        codec) snapshot the ledger.  Returns #copied."""
        chain = self.current_chain
        excess = len(chain) - self.k
        if excess <= 0:
            return 0
        to_copy = list(chain.blocks[:excess])
        for b in to_copy:
            hdr = getattr(b, "header", b)
            is_ebb = bool(hdr.get("ebb", 0)) if hasattr(hdr, "get") else False
            self.immutable.append_block(b.slot, b.block_no, b.hash,
                                        b.prev_hash, b.bytes, is_ebb=is_ebb)
        new_anchor_blk = to_copy[-1]
        self.current_chain = chain._rebuild(
            point_of(new_anchor_blk), chain.blocks[excess:],
            new_anchor_blk.block_no)
        self.ledger_db.prune_to_slot(new_anchor_blk.slot)
        self.volatile.garbage_collect(new_anchor_blk.slot + 1)
        if self.fs is not None and self.encode_state is not None:
            slot = new_anchor_blk.slot
            if slot - self._last_snapshot_slot >= \
                    self.disk_policy.snapshot_interval_slots:
                LedgerDB.take_snapshot(
                    self.fs, slot, self.ledger_db.anchor_point,
                    self.ledger_db.anchor_state,
                    self.encode_state, self.disk_policy)
                self._last_snapshot_slot = slot
        self._bump()
        return len(to_copy)
