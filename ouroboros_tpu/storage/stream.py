"""Streaming replay engine — disk → decode → verify, restartable.

Reference: the db-analyser replay path (SURVEY.md §3.5): the node opens
LedgerDB from the newest on-disk snapshot (LedgerDB/OnDisk.hs:277) and
streams ImmutableDB chunks through iterators (Impl/Iterator.hs) instead
of materialising the chain; DiskPolicy decides when replay checkpoints
(DiskPolicy.hs).  Our replay so far loaded every block into memory and
started from genesis — fine for a bench chain, not for a million-block
mainnet DB.

This module closes that gap with a third pipeline stage in front of the
producer/consumer replay (consensus/pipeline.py):

    prefetcher (thread)          producer (thread)      consumer (caller)
    --------------------------   --------------------   -----------------
    chunk n+k: ONE whole-file    window w+1: seq pass   window w: drain
      read through the FsApi       packing, prefetch,     install betas
      seam, CBOR decode into       async submit           on_window hook:
      window-sized batches                                  DiskPolicy
      (bounded read-ahead;                                  take_snapshot
       blocks when `depth`
       batches are waiting)

Disk + decode seconds hide behind device verify exactly the way the
host sequential pass does: the prefetcher feeds a third on/off signal
into the shared ProgressTracker ({prefetch busy} ∩ {≥1 window in
flight} accumulates O(1) into ``disk_hidden_secs``), and its work is
span-recorded under the ``disk`` phase so bench/obsreport attribute it
beside host-seq/device.

Era discipline: the engine is protocol-agnostic — a Cardano-composed
DB (eras/cardano.py) replays Byron EBBs through the Shelley translation
in ONE stream because era crossing lives in the hard-fork rules the
sequential pass already drives; the engine merely counts the crossings
it decodes (``replay.stream.era_crossings``).

Restartability: `on_window` fires on the consumer thread only after a
window's proofs all held, so the state it hands over is fully verified
— the engine snapshots it crash-consistently (storage/ledgerdb.py:
temp file + checksum + atomic rename; a corrupt/partial newest snapshot
falls back to the previous one) every `snapshot_interval_slots`.  At
open, `resume=True` restores the newest snapshot whose point is still
on the immutable chain and streams strictly AFTER it: a killed replay
resumes in seconds and reaches a byte-identical final state hash.

The snapshot codec defaults to Python-native serialisation behind the
same ``encode_state``/``decode_state`` seam LedgerDB always had (the
reference CBOR-encodes its ledger state; our era states are plain
frozen dataclasses, so the native codec round-trips them exactly — a
custom CBOR codec plugs into the same two arguments).
"""
from __future__ import annotations

import pickle
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

from ..consensus.pipeline import ProgressTracker
from ..observe import flight as _flight
from ..observe import metrics as _metrics
from ..observe import spans as _spans
from .ledgerdb import DiskPolicy, LedgerDB

#: header field carrying the hard-fork era tag (combinator.ERA_FIELD —
#: re-declared here so the storage layer stays import-light; the
#: combinator's tests pin the two equal)
ERA_FIELD = "hfc_era"

# observational stream instruments (live scrape/obsreport); the engine's
# own stats come from per-instance fields so they stay exact even with
# observation disabled.  Counts of chunks/blocks/bytes/eras are pure
# functions of the workload (stable); stall/depth/seconds are
# scheduling- and wall-clock-dependent (unstable).
_CHUNKS = _metrics.counter("replay.stream.chunks_read")
_BLOCKS = _metrics.counter("replay.stream.blocks_decoded")
_BYTES = _metrics.counter("replay.stream.bytes_read")
_ERAS = _metrics.counter("replay.stream.era_crossings")
_SNAPS = _metrics.counter("replay.stream.snapshots_written")
_STALLS = _metrics.counter("replay.stream.prefetch_stalls", stable=False)
_DEPTH = _metrics.gauge("replay.stream.prefetch_depth", stable=False)
_DISK_SECS = _metrics.gauge("replay.stream.disk_secs", stable=False)
_DISK_HIDDEN = _metrics.gauge("replay.stream.disk_hidden_secs",
                              stable=False)
_SNAP_SECS = _metrics.gauge("replay.stream.snapshot_write_secs",
                            stable=False)
_RESTORE_SECS = _metrics.gauge("replay.stream.restore_secs", stable=False)
_RESUME_SLOT = _metrics.gauge("replay.stream.resumed_from_slot")

# load-bearing thread accounting, like the pipeline's producer pair: a
# replay that returns with started != finished leaked its prefetcher
_P_STARTED = _metrics.counter("stream.prefetchers_started", always=True)
_P_FINISHED = _metrics.counter("stream.prefetchers_finished", always=True)

THREAD_NAME = "ouro-stream-prefetch"


def pickle_encode(state: Any) -> bytes:
    return pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)


def pickle_decode(raw: Any) -> Any:
    return pickle.loads(bytes(raw))


@dataclass(frozen=True)
class StreamResumed:
    """Typed flight-recorder event: a replay restored from a snapshot
    (arm FLIGHT around a replay to make resume part of any post-mortem,
    e.g. a kill/resume parity mismatch)."""
    slot: int
    point_slot: int
    snapshots_seen: int


class BlockPrefetcher:
    """Bounded read-ahead: a background thread streams (and decodes)
    ImmutableDB chunks into window-sized batches; iterating the
    prefetcher yields decoded blocks, blocking only when the reader is
    genuinely behind the replay.

    Reads are chunk-granular through the FsApi seam (`db.chunk_blocks`:
    one whole-file read per chunk) so a spinning disk sees sequential
    I/O; DBs without the chunk API (the reference-format read view)
    fall back to the per-block iterator, same thread, same bounds.

    Coordination: one Condition guards {batches, stop, eof, error}.
    The thread blocks while `depth` batches are queued (back-pressure),
    the consumer blocks while none are; `close()` wakes and joins the
    thread — the engine calls it in a finally, so an aborted replay
    (first-error-wins, a snapshot-hook kill) never leaks it.  A read or
    decode failure parks on `error` and re-raises on the consumer after
    the already-queued batches drain."""

    def __init__(self, db, decode: Callable[[bytes], Any],
                 window: int = 512, depth: int = 4,
                 tracker: Optional[ProgressTracker] = None,
                 after_hash: Optional[bytes] = None):
        self.db = db
        self.decode = decode
        self.window = max(1, window)
        self.depth = max(1, depth)
        self.tracker = tracker
        self.after_hash = after_hash
        # exact per-instance accounting (engine stats read these; the
        # registry instruments mirror them for live observers)
        self.chunks_read = 0
        self.blocks_decoded = 0
        self.bytes_read = 0
        self.era_crossings = 0
        self.stalls = 0
        self._last_era: Optional[int] = None
        self._cond = threading.Condition()
        self._batches: deque = deque()
        self._stop = False
        self._eof = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run,
                                        name=THREAD_NAME, daemon=True)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "BlockPrefetcher":
        _P_STARTED.inc()
        self._thread.start()
        return self

    def close(self) -> None:
        """Stop and join the prefetch thread (idempotent)."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread.ident is not None:
            self._thread.join()

    # -- the reading thread --------------------------------------------------
    def _decode_batch(self, pairs) -> list:
        out = []
        for _entry, raw in pairs:
            b = self.decode(raw)
            hdr = getattr(b, "header", b)
            era = hdr.get(ERA_FIELD) if hasattr(hdr, "get") else None
            if era is not None:
                if self._last_era is not None and era != self._last_era:
                    self.era_crossings += 1
                    _ERAS.inc()
                self._last_era = era
            out.append(b)
        self.blocks_decoded += len(out)
        _BLOCKS.inc(len(out))
        return out

    def _read_decoded(self) -> Iterator[list]:
        """Decoded blocks in chain order, one chunk's worth per step —
        the disk signal (tracker + `disk`-phase spans) brackets exactly
        the read+decode work, never the queue wait."""
        tracker = self.tracker
        chunk_api = hasattr(self.db, "chunk_blocks")
        if chunk_api:
            cursor = self.db.start_after(self.after_hash)
            if cursor is None:
                return
            n0, i0 = cursor
            for n in self.db.chunk_numbers():
                if n < n0:
                    continue
                if tracker is not None:
                    tracker.disk_begin()
                try:
                    with _spans.span("stream.read", cat="disk"):
                        pairs = self.db.chunk_blocks(
                            n, from_index=i0 if n == n0 else 0)
                    self.chunks_read += 1
                    self.bytes_read += sum(len(raw) for _e, raw in pairs)
                    _CHUNKS.inc()
                    _BYTES.inc(sum(len(raw) for _e, raw in pairs))
                    with _spans.span("stream.decode", cat="disk"):
                        blocks = self._decode_batch(pairs)
                finally:
                    if tracker is not None:
                        tracker.disk_end()
                yield blocks
            return
        # generic fallback: per-block iterator (reference-format views);
        # `after_hash` skips the already-replayed prefix
        skipping = self.after_hash is not None
        buf_pairs: list = []
        for entry, raw in self.db.stream():
            if skipping:
                if getattr(entry, "hash", None) == self.after_hash \
                        or getattr(entry, "header_hash",
                                   None) == self.after_hash:
                    skipping = False
                continue
            buf_pairs.append((entry, raw))
            if len(buf_pairs) >= self.window:
                yield self._fallback_decode(buf_pairs)
                buf_pairs = []
        if skipping:
            # the resume point never appeared: yielding nothing would
            # silently report the stale snapshot as the final state
            raise ValueError(
                "resume point is not on the streamed chain (snapshot "
                "outlived the DB?)")
        if buf_pairs:
            yield self._fallback_decode(buf_pairs)

    def _fallback_decode(self, pairs) -> list:
        tracker = self.tracker
        if tracker is not None:
            tracker.disk_begin()
        try:
            self.chunks_read += 1          # one read burst ≈ one chunk
            self.bytes_read += sum(len(raw) for _e, raw in pairs)
            _CHUNKS.inc()
            _BYTES.inc(sum(len(raw) for _e, raw in pairs))
            with _spans.span("stream.decode", cat="disk"):
                return self._decode_batch(pairs)
        finally:
            if tracker is not None:
                tracker.disk_end()

    def _run(self) -> None:
        try:
            buf: list = []
            for blocks in self._read_decoded():
                buf.extend(blocks)
                while len(buf) >= self.window:
                    if not self._put(buf[:self.window]):
                        return
                    buf = buf[self.window:]
            if buf:
                self._put(buf)
        except BaseException as e:   # surfaced on the consumer
            with self._cond:
                self._error = e
                self._cond.notify_all()
        finally:
            _P_FINISHED.inc()
            with self._cond:
                self._eof = True
                self._cond.notify_all()

    def _put(self, batch: list) -> bool:
        """Queue one batch, blocking at the read-ahead bound; False when
        the consumer asked us to stop."""
        with self._cond:
            if len(self._batches) >= self.depth and not self._stop:
                self.stalls += 1
                _STALLS.inc()
                self._cond.wait_for(
                    lambda: self._stop
                    or len(self._batches) < self.depth)
            if self._stop:
                return False
            self._batches.append(batch)
            _DEPTH.set(len(self._batches))
            self._cond.notify_all()
            return True

    # -- the consuming side --------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: self._batches or self._eof
                    or self._error is not None or self._stop)
                if self._batches:
                    batch = self._batches.popleft()
                    _DEPTH.set(len(self._batches))
                    self._cond.notify_all()
                elif self._error is not None:
                    err, self._error = self._error, None
                    raise err
                else:
                    return                 # eof (or stopped)
            yield from batch               # lock NOT held


@dataclass(frozen=True)
class StreamConfig:
    """Engine knobs.  `read_ahead` is the prefetch bound in windows —
    together with the pipeline's DEPTH it fixes the peak number of
    decoded blocks alive at once to (read_ahead + ~3) * window,
    independent of chain length.  `policy` drives both the snapshot
    cadence during replay and the trim count
    (storage/ledgerdb.DiskPolicy); `take_snapshots=False` makes the
    run read-only on the DB directory (plain validation)."""
    window: int = 512
    read_ahead: int = 4
    policy: DiskPolicy = DiskPolicy()
    resume: bool = True
    take_snapshots: bool = True


@dataclass
class StreamReplayResult:
    """ReplayResult + the stream's own accounting."""
    final_state: Any
    n_valid: int
    error: Optional[Exception]
    stats: dict = field(default_factory=dict)

    @property
    def all_valid(self) -> bool:
        return self.error is None


class StreamingReplayEngine:
    """One replay of one on-disk chain DB: restore, stream, verify,
    checkpoint.  Construct per run (`db_analyser --resume`, bench's
    stream leg, the kill/resume tests); the heavyweight state — key
    caches, compiled programs — lives in the backend and survives
    across engines."""

    def __init__(self, fs, db, rules, decode: Callable[[bytes], Any],
                 backend=None, config: Optional[StreamConfig] = None,
                 encode_state: Callable[[Any], Any] = pickle_encode,
                 decode_state: Callable[[Any], Any] = pickle_decode):
        self.fs = fs
        self.db = db
        self.rules = rules
        self.decode = decode
        self.backend = backend
        self.cfg = config if config is not None else StreamConfig()
        self._enc = encode_state
        self._dec = decode_state
        self.snapshots_written = 0
        self.snapshot_write_secs = 0.0
        self.restore_secs = 0.0

    # -- restore -------------------------------------------------------------
    def restore(self) -> Optional[tuple]:
        """(slot, point, state) of the newest USABLE snapshot: readable
        (checksum holds — ledgerdb skips torn/corrupt ones) AND whose
        point is still on the immutable chain (a snapshot can outlive
        its blocks when startup validation truncated a corrupt tail —
        resuming from it would strand the replay off-chain)."""
        t0 = _spans.monotonic_now()
        seen = 0
        try:
            for slot, point, state in LedgerDB.iter_snapshots(self.fs,
                                                              self._dec):
                seen += 1
                if point.is_genesis or point.hash in self.db:
                    _RESUME_SLOT.set(slot)
                    _flight.FLIGHT.note(
                        StreamResumed(slot, point.slot, seen))
                    return slot, point, state
            return None
        finally:
            self.restore_secs = _spans.monotonic_now() - t0
            _RESTORE_SECS.set(round(self.restore_secs, 6))

    # -- snapshotting ---------------------------------------------------------
    def _take_snapshot(self, point, state) -> None:
        t0 = _spans.monotonic_now()
        with _spans.span("stream.snapshot", cat="disk"):
            LedgerDB.take_snapshot(self.fs, point.slot, point, state,
                                   self._enc, self.cfg.policy)
        self.snapshots_written += 1
        self.snapshot_write_secs += _spans.monotonic_now() - t0
        _SNAPS.inc()
        _SNAP_SECS.set(round(self.snapshot_write_secs, 6))

    # -- the replay ------------------------------------------------------------
    def replay(self) -> StreamReplayResult:
        from ..consensus.batch import replay_blocks_pipelined

        cfg = self.cfg
        restored = self.restore() if cfg.resume else None
        after_hash: Optional[bytes] = None
        state = self.rules.initial_state()
        resumed_from: Optional[int] = None
        if restored is not None:
            resumed_from, point, state = restored
            if not point.is_genesis:
                after_hash = point.hash
        # ETA denominator: O(1) on the native chunk-indexed DB; a
        # reference-format view would pay a full extra read pass for
        # __len__, so it streams without a total
        total = len(self.db) if hasattr(self.db, "chunk_numbers") \
            and after_hash is None else None
        tracker = ProgressTracker(total)
        interval = cfg.policy.snapshot_interval_slots
        # the interval counts from the stream's START (the resume slot,
        # or the initial state's tip for a fresh run) — the first window
        # must not trigger an unconditional full-state serialisation the
        # policy never asked for
        last_snap = {"slot": resumed_from if resumed_from is not None
                     else self.rules.tip(state).slot}

        def on_window(st, _n_done, point):
            if point.slot - last_snap["slot"] >= interval:
                self._take_snapshot(point, st)
                last_snap["slot"] = point.slot

        if not cfg.take_snapshots:
            on_window = None
        pre = BlockPrefetcher(self.db, self.decode, window=cfg.window,
                              depth=cfg.read_ahead, tracker=tracker,
                              after_hash=after_hash).start()
        t0 = _spans.monotonic_now()
        try:
            res = replay_blocks_pipelined(
                self.rules, pre, state, backend=self.backend,
                window=cfg.window, total_blocks=total, tracker=tracker,
                on_window=on_window)
        finally:
            pre.close()
        replay_secs = _spans.monotonic_now() - t0
        if cfg.take_snapshots and res.error is None \
                and res.final_state is not None:
            # tip checkpoint: the next open restores in O(snapshot), no
            # replay at all (skipped when the tip snapshot already
            # exists — a fully-resumed rerun writes nothing)
            tip = self.rules.tip(res.final_state)
            if not tip.is_genesis and last_snap["slot"] != tip.slot:
                self._take_snapshot(tip, res.final_state)
                last_snap["slot"] = tip.slot
        _DISK_SECS.set(round(tracker.disk_secs, 6))
        _DISK_HIDDEN.set(round(tracker.disk_hidden_secs, 6))
        stats = {
            "blocks": res.n_valid,
            "replay_secs": round(replay_secs, 4),
            "chunks_read": pre.chunks_read,
            "blocks_decoded": pre.blocks_decoded,
            "bytes_read": pre.bytes_read,
            "era_crossings": pre.era_crossings,
            "prefetch_stalls": pre.stalls,
            "read_ahead": cfg.read_ahead,
            "disk_secs": round(tracker.disk_secs, 4),
            "disk_hidden_secs": round(tracker.disk_hidden_secs, 4),
            "disk_hidden_frac": round(
                tracker.disk_hidden_secs / tracker.disk_secs, 3)
            if tracker.disk_secs > 0 else 0.0,
            "host_seq_secs": round(tracker.host_secs, 4),
            "host_hidden_secs": round(tracker.hidden_secs, 4),
            "snapshots_written": self.snapshots_written,
            "snapshot_write_secs": round(self.snapshot_write_secs, 4),
            "restore_secs": round(self.restore_secs, 4),
            "resumed_from_slot": resumed_from,
        }
        return StreamReplayResult(res.final_state, res.n_valid,
                                  res.error, stats)


def prefetcher_threads_alive() -> int:
    """Live prefetch threads (leak gates share this with the
    started/finished counter pair, like the pipeline's producer)."""
    return sum(t.name == THREAD_NAME and t.is_alive()
               for t in threading.enumerate())
