"""ImmutableDB — append-only chunked block store with recovery.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Storage/ImmutableDB/
(SURVEY.md §2): 3 files per chunk — `.chunk` concatenated blobs,
`.primary`/`.secondary` indices (Impl/Index/{Primary,Secondary}.hs) with
per-block CRC; chunk layout maps slots to files (Chunks/Layout.hs); startup
validation CRCs every block and truncates the corrupt tail
(Impl/Validation.hs); streaming iterators (Impl/Iterator.hs).

TPU-first simplification that keeps the semantics: one `.secondary` CBOR
index per chunk (offset/size/crc/hash/slot/block_no per entry); the primary
(slot→entry) mapping is rebuilt in memory at open — the LRU index cache of
the reference collapses into the in-memory dict.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

from ..utils import cbor
from .fs import FsApi, FsError, crc32

DIR = ("immutable",)


@dataclass(frozen=True)
class SecondaryEntry:
    """One block's index record (Impl/Index/Secondary.hs entry).

    is_ebb mirrors the reference's per-entry EBB marker: an epoch-boundary
    block may SHARE its slot with the following real block (the two
    relative slots of Chunks/Layout.hs)."""
    offset: int
    size: int
    crc: int
    hash: bytes
    prev_hash: bytes
    slot: int
    block_no: int
    is_ebb: int = 0

    def encode(self):
        return [self.offset, self.size, self.crc, self.hash, self.prev_hash,
                self.slot, self.block_no, self.is_ebb]

    @classmethod
    def decode(cls, obj):
        return cls(int(obj[0]), int(obj[1]), int(obj[2]), bytes(obj[3]),
                   bytes(obj[4]), int(obj[5]), int(obj[6]),
                   int(obj[7]) if len(obj) > 7 else 0)


def _slot_ok(tip: SecondaryEntry, slot: int, is_ebb: bool) -> bool:
    """Strictly increasing slots, except the real block following an EBB
    may share its slot (Chunks/Layout.hs relative-slot pair)."""
    if slot > tip.slot:
        return True
    return slot == tip.slot and bool(tip.is_ebb) and not is_ebb


def _chunk_file(n: int) -> tuple:
    return DIR + (f"{n:05d}.chunk",)


def _secondary_file(n: int) -> tuple:
    return DIR + (f"{n:05d}.secondary",)


class ImmutableDB:
    """Append-only store; blocks enter in strictly increasing slot order
    (they are ≥k deep, so reorgs never touch them)."""

    def __init__(self, fs: FsApi, chunk_size: int = 100):
        self.fs = fs
        self.chunk_size = chunk_size
        # chunk -> [SecondaryEntry]; slot -> (chunk, idx); hash -> slot
        self._chunks: dict[int, list[SecondaryEntry]] = {}
        self._by_slot: dict[int, tuple] = {}
        self._by_hash: dict[bytes, int] = {}
        self._tip: Optional[SecondaryEntry] = None

    # -- open + validation ----------------------------------------------------
    @classmethod
    def open(cls, fs: FsApi, chunk_size: int = 100,
             validate_all: bool = True) -> "ImmutableDB":
        """Open, validating chunks in order; the first corrupt entry
        truncates the DB there (Impl/Validation.hs tail truncation).

        Chunk numbers come from BOTH file kinds: an orphan `.secondary`
        whose `.chunk` is gone (a crash between the two deletes, or a
        lost data file) is corruption at that chunk — its stale index
        must not survive to mis-describe a future append, and every
        later chunk is past the corruption point."""
        db = cls(fs, chunk_size)
        fs.mkdirs(DIR)
        chunk_nos = sorted(
            {int(name.split(".")[0]) for name in fs.list_dir(DIR)
             if name.endswith((".chunk", ".secondary"))})
        good = True
        for n in chunk_nos:
            if not good:
                fs.remove(_chunk_file(n))          # past corruption: drop
                fs.remove(_secondary_file(n))
                continue
            good = db._load_chunk(n, validate_all)
        return db

    def _load_chunk(self, n: int, validate: bool) -> bool:
        """Load chunk n; returns False if a corrupt tail was truncated."""
        fs = self.fs
        try:
            raw_idx = fs.read_file(_secondary_file(n))
        except FsError:
            raw_idx = b""
        entries: list[SecondaryEntry] = []
        pos = 0
        while pos < len(raw_idx):
            try:
                obj, used = cbor.loads_prefix(raw_idx[pos:])
                entries.append(SecondaryEntry.decode(obj))
                pos += used
            except (cbor.CBORError, ValueError, IndexError):
                break
        try:
            chunk_len = fs.file_size(_chunk_file(n))
        except FsError:
            chunk_len = 0
        keep: list[SecondaryEntry] = []
        for e in entries:
            if e.offset + e.size > chunk_len:
                break
            if validate:
                data = fs.read_range(_chunk_file(n), e.offset, e.size)
                if crc32(data) != e.crc:
                    break
            if self._tip is not None and not _slot_ok(self._tip, e.slot,
                                                      bool(e.is_ebb)):
                break                               # non-monotone: corrupt
            keep.append(e)
            self._index(n, e)
        end_of_entries = keep[-1].offset + keep[-1].size if keep else 0
        clean = (len(keep) == len(entries) and pos >= len(raw_idx)
                 and chunk_len == end_of_entries)   # orphan chunk bytes
                                                    # (lost index) = corrupt
        if not clean:
            end = keep[-1].offset + keep[-1].size if keep else 0
            if chunk_len > end:
                fs.truncate_file(_chunk_file(n), end)
            if keep or fs.exists(_chunk_file(n)):
                fs.write_file(_secondary_file(n),
                              b"".join(cbor.dumps(e.encode())
                                       for e in keep))
            else:
                # orphan index: no data file at all — drop it rather
                # than leave an empty stub behind
                fs.remove(_secondary_file(n))
        return clean

    def _index(self, n: int, e: SecondaryEntry) -> None:
        self._chunks.setdefault(n, []).append(e)
        loc = (n, len(self._chunks[n]) - 1)
        # an EBB and its successor share a slot; the real block wins the
        # slot index (appended second), hashes stay unique
        self._by_slot[e.slot] = loc
        self._by_hash[e.hash] = loc
        self._tip = e

    # -- queries --------------------------------------------------------------
    @property
    def tip(self) -> Optional[SecondaryEntry]:
        return self._tip

    def __contains__(self, h: bytes) -> bool:
        return h in self._by_hash

    def chunk_of(self, slot: int) -> int:
        return slot // self.chunk_size

    def get_by_slot(self, slot: int) -> Optional[bytes]:
        """Block bytes at `slot`.  When an EBB shares the slot with its
        successor, this resolves to the non-EBB block (the real block wins
        the slot index); use get_by_hash/stream to reach the EBB itself."""
        loc = self._by_slot.get(slot)
        if loc is None:
            return None
        n, i = loc
        e = self._chunks[n][i]
        return self.fs.read_range(_chunk_file(n), e.offset, e.size)

    def get_by_hash(self, h: bytes) -> Optional[bytes]:
        loc = self._by_hash.get(h)
        if loc is None:
            return None
        n, i = loc
        e = self._chunks[n][i]
        return self.fs.read_range(_chunk_file(n), e.offset, e.size)

    def slot_of_hash(self, h: bytes) -> Optional[int]:
        loc = self._by_hash.get(h)
        if loc is None:
            return None
        n, i = loc
        return self._chunks[n][i].slot

    def _entry_at(self, n: int, j: int
                  ) -> Optional[tuple[SecondaryEntry, bytes]]:
        while n <= (max(self._chunks) if self._chunks else -1):
            chunk = self._chunks.get(n, [])
            if j < len(chunk):
                e = chunk[j]
                return e, self.fs.read_range(_chunk_file(n), e.offset,
                                             e.size)
            n, j = n + 1, 0
        return None

    def next_after_hash(self, h: Optional[bytes]
                        ) -> Optional[tuple[SecondaryEntry, bytes]]:
        """Chain successor of the block with hash `h` (None/unknown hash =
        start of the chain) — EBB-safe: walks chunk order, not slots."""
        if h is None:
            return self._entry_at(min(self._chunks), 0) if self._chunks \
                else None
        loc = self._by_hash.get(h)
        if loc is None:
            return None
        return self._entry_at(loc[0], loc[1] + 1)

    def entry_by_hash(self, h: bytes) -> Optional[SecondaryEntry]:
        loc = self._by_hash.get(h)
        if loc is None:
            return None
        n, i = loc
        return self._chunks[n][i]

    def stream(self, from_slot: int = 0,
               to_slot: Optional[int] = None
               ) -> Iterator[tuple[SecondaryEntry, bytes]]:
        """Iterate (entry, block bytes) in slot order (Impl/Iterator.hs)."""
        for n in sorted(self._chunks):
            for e in self._chunks[n]:
                if e.slot < from_slot:
                    continue
                if to_slot is not None and e.slot > to_slot:
                    return
                yield e, self.fs.read_range(_chunk_file(n), e.offset, e.size)

    # -- chunk-granular streaming (the storage/stream.py read path) ----------
    def chunk_numbers(self) -> list:
        return sorted(self._chunks)

    def chunk_blocks(self, n: int,
                     from_index: int = 0) -> list:
        """Chunk n's (entry, block bytes) pairs from ONE whole-file read
        — the streaming replay's disk unit (one fs op per chunk instead
        of one per block; the reference's iterator equally reads chunk
        files sequentially, Impl/Iterator.hs)."""
        entries = self._chunks.get(n, ())
        if from_index >= len(entries):
            return []
        raw = self.fs.read_file(_chunk_file(n))
        return [(e, bytes(raw[e.offset:e.offset + e.size]))
                for e in entries[from_index:]]

    def start_after(self, h: Optional[bytes]) -> Optional[tuple]:
        """(chunk, index) of the first block AFTER the one with hash `h`
        (None/genesis: the very first block) — the resume cursor for
        chunk-granular streaming.  None when `h` is unknown or nothing
        follows it."""
        if h is None:
            return (min(self._chunks), 0) if self._chunks else None
        loc = self._by_hash.get(h)
        if loc is None:
            return None
        n, j = loc[0], loc[1] + 1
        while n <= max(self._chunks):
            if j < len(self._chunks.get(n, ())):
                return (n, j)
            n, j = n + 1, 0
        return None

    def __len__(self) -> int:
        # count entries, not slots: an EBB and its successor share a slot
        # (ADVICE r2), so len(self._by_slot) would undercount by one per EBB
        return sum(len(c) for c in self._chunks.values())

    # -- append ---------------------------------------------------------------
    def append_block(self, slot: int, block_no: int, h: bytes,
                     prev_hash: bytes, data: bytes,
                     is_ebb: bool = False) -> None:
        if self._tip is not None and not _slot_ok(self._tip, slot, is_ebb):
            raise ValueError(
                f"append slot {slot} not after tip slot {self._tip.slot}")
        n = self.chunk_of(slot)
        try:
            offset = self.fs.file_size(_chunk_file(n))
        except FsError:
            offset = 0
        e = SecondaryEntry(offset, len(data), crc32(data), h, prev_hash,
                           slot, block_no, int(is_ebb))
        self.fs.append_file(_chunk_file(n), data)
        self.fs.append_file(_secondary_file(n), cbor.dumps(e.encode()))
        self._index(n, e)
