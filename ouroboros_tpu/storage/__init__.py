"""Storage layer: injectable FS, ImmutableDB, VolatileDB, LedgerDB, ChainDB.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Storage/ (SURVEY.md §2
L5 storage trio + ChainDB).  Every component takes an `FsApi` so tests run
on the in-memory MockFS with fault injection (the HasFS lesson,
Storage/FS/API.hs).
"""
from .fs import FsApi, IoFS, MockFS, FsError, crc32
from .immutabledb import ImmutableDB
from .volatiledb import VolatileDB
from .ledgerdb import LedgerDB, DiskPolicy
from .stream import (
    BlockPrefetcher, StreamConfig, StreamingReplayEngine,
    StreamReplayResult,
)

__all__ = [
    "FsApi", "IoFS", "MockFS", "FsError", "crc32",
    "ImmutableDB", "VolatileDB", "LedgerDB", "DiskPolicy",
    "BlockPrefetcher", "StreamConfig", "StreamingReplayEngine",
    "StreamReplayResult",
]
