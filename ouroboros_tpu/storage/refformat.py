"""Reference ImmutableDB on-disk format — reader + writer.

The reference stores the immutable chain as three files per chunk
(SURVEY.md §2 ImmutableDB; files named %05d.{chunk,primary,secondary},
Impl/Util.hs:60-73):

- NNNNN.chunk      the raw block bytes, concatenated
- NNNNN.primary    version byte 0x01, then (numSlots+1) Word32 BE offsets
                   into the secondary file, non-decreasing, starting at 0;
                   a repeated offset means the relative slot is empty
                   (Impl/Index/Primary.hs:82-136)
- NNNNN.secondary  fixed-size entries: Word64 BE block offset, Word16 BE
                   header offset, Word16 BE header size, Word32 BE CRC-32
                   of the block bytes, the 32-byte header hash, and
                   Word64 BE slotNo (or epochNo for an EBB)
                   (Impl/Index/Secondary.hs:59-135)

Chunk layout: `simpleChunkInfo` (uniform chunk size, EBBs allowed —
Chunks/Internal.hs:73-74): relative slot 0 of chunk N is reserved for the
EBB of epoch N, and a regular block in slot s lives in chunk s // size at
relative slot (s mod size) + 1 (Chunks/Layout.hs:185-203).  The primary
index of a chunk therefore has size+2 offsets (EBB slot + size regular
slots + the final end offset).

This module is the interop bridge of SURVEY.md §7 P2: db_synth can WRITE
this format and db_analyser can READ it (auto-detected), so our replay
tooling speaks the same on-disk dialect as the reference's db-analyser.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator, Optional, Sequence
from zlib import crc32

from .fs import FsApi, FsError

VERSION = 1
HASH_LEN = 32
ENTRY_SIZE = 8 + 2 + 2 + 4 + HASH_LEN + 8

DIR = ("immutable",)        # same directory our own ImmutableDB uses


def chunk_file(n: int) -> tuple:
    return DIR + ("%05d.chunk" % n,)


def primary_file(n: int) -> tuple:
    return DIR + ("%05d.primary" % n,)


def secondary_file(n: int) -> tuple:
    return DIR + ("%05d.secondary" % n,)


@dataclass(frozen=True)
class RefEntry:
    """One secondary-index entry (Secondary.hs Entry)."""
    block_offset: int                  # into the chunk file
    header_offset: int                 # header start within the block
    header_size: int
    checksum: int                      # CRC-32 of the block bytes
    header_hash: bytes
    slot_or_epoch: int                 # slotNo; epochNo when is_ebb
    is_ebb: bool

    def encode(self) -> bytes:
        return struct.pack(">QHHI", self.block_offset, self.header_offset,
                           self.header_size, self.checksum) \
            + self.header_hash + struct.pack(">Q", self.slot_or_epoch)

    @classmethod
    def decode(cls, raw: bytes, is_ebb: bool) -> "RefEntry":
        boff, hoff, hsize, crc = struct.unpack_from(">QHHI", raw, 0)
        h = raw[16:16 + HASH_LEN]
        (soe,) = struct.unpack_from(">Q", raw, 16 + HASH_LEN)
        return cls(boff, hoff, hsize, crc, h, soe, is_ebb)

    def slot(self, chunk_no: int, chunk_size: int) -> int:
        """Absolute slot number (an EBB shares the slot of the first slot
        of its epoch — slotNoOfEBB)."""
        if self.is_ebb:
            return self.slot_or_epoch * chunk_size
        return self.slot_or_epoch


class RefChunkWriter:
    """Accumulates one chunk's blocks, then emits the three files."""

    def __init__(self, chunk_no: int, chunk_size: int):
        self.chunk_no = chunk_no
        self.chunk_size = chunk_size
        self.blocks = bytearray()
        self.entries: list[RefEntry] = []
        self.rel_slots: list[int] = []

    def append(self, slot: int, header_hash: bytes, data: bytes,
               is_ebb: bool = False,
               header_offset: int = 0, header_size: int = 0) -> None:
        if is_ebb:
            # the simpleChunkInfo layout identifies chunks with epochs
            # (EBB of epoch N at relative slot 0 of chunk N); an EBB off a
            # chunk boundary would record the wrong epochNo on disk
            if slot % self.chunk_size != 0:
                raise ValueError(
                    f"EBB at slot {slot} is not on a chunk boundary: the "
                    f"reference format needs chunk_size == epoch_length "
                    f"for EBB-bearing chains (got chunk_size "
                    f"{self.chunk_size})")
            rel = 0
            soe = self.chunk_no                     # epoch number
        else:
            rel = slot % self.chunk_size + 1
            soe = slot
        self.entries.append(RefEntry(
            len(self.blocks), header_offset, header_size,
            crc32(data), header_hash, soe, is_ebb))
        self.rel_slots.append(rel)
        self.blocks += data

    def primary_bytes(self) -> bytes:
        """Version byte + the sparse offset vector (Primary.hs layout)."""
        n_slots = self.chunk_size + 1               # EBB slot + regular
        offsets = [0]
        j = 0
        cur = 0
        for rel in range(n_slots):
            if j < len(self.rel_slots) and self.rel_slots[j] == rel:
                cur += ENTRY_SIZE
                j += 1
            offsets.append(cur)
        return bytes([VERSION]) + b"".join(
            struct.pack(">I", o) for o in offsets)

    def write(self, fs: FsApi) -> None:
        fs.write_file(chunk_file(self.chunk_no), bytes(self.blocks))
        fs.write_file(secondary_file(self.chunk_no),
                      b"".join(e.encode() for e in self.entries))
        fs.write_file(primary_file(self.chunk_no), self.primary_bytes())


class RefDbWriter:
    """Streaming writer: append blocks in chain order, chunks are emitted
    as they fill (db_synth --format reference)."""

    def __init__(self, fs: FsApi, chunk_size: int,
                 epoch_length: Optional[int] = None):
        """epoch_length, when known, is validated on the first EBB: the
        reference's EBB layout identifies chunks with epochs (EBB of epoch
        N at relative slot 0 of chunk N), so EBB-bearing chains need
        chunk_size == epoch_length or the on-disk epochNo would be wrong.
        EBB-free chains (Shelley-only) may use any chunk size."""
        self.fs = fs
        self.chunk_size = chunk_size
        self.epoch_length = epoch_length
        self._cur: Optional[RefChunkWriter] = None
        fs.mkdirs(DIR)

    def _chunk_for(self, n: int) -> RefChunkWriter:
        if self._cur is not None and self._cur.chunk_no != n:
            self._cur.write(self.fs)
            self._cur = None
        if self._cur is None:
            self._cur = RefChunkWriter(n, self.chunk_size)
        return self._cur

    def append_block(self, slot: int, header_hash: bytes, data: bytes,
                     is_ebb: bool = False, header_offset: int = 0,
                     header_size: int = 0) -> None:
        if is_ebb and self.epoch_length is not None \
                and self.epoch_length != self.chunk_size:
            raise ValueError(
                f"reference format with EBBs requires chunk_size == "
                f"epoch_length (got {self.chunk_size} vs "
                f"{self.epoch_length}); pass --chunk-size equal to the "
                f"epoch length")
        n = (slot // self.chunk_size)
        self._chunk_for(n).append(slot, header_hash, data, is_ebb,
                                  header_offset, header_size)

    def close(self) -> None:
        if self._cur is not None:
            self._cur.write(self.fs)
            self._cur = None


def _chunk_numbers(fs: FsApi) -> list[int]:
    out = []
    for name in fs.list_dir(DIR):
        if name.endswith(".primary"):
            out.append(int(name[:-8]))
    return sorted(out)


def is_reference_db(fs: FsApi) -> bool:
    """True when the directory holds reference-format index files."""
    try:
        return bool(_chunk_numbers(fs))
    except FsError:
        return False


@dataclass
class RefBlock:
    entry: RefEntry
    chunk_no: int
    data: bytes


class RefDbReader:
    """Reads a reference-format ImmutableDB, CRC-validated.

    Corruption semantics mirror the reference's startup validation
    (Impl/Validation.hs): a CRC mismatch or torn index truncates the
    chain at the previous good block."""

    def __init__(self, fs: FsApi, chunk_size: int):
        self.fs = fs
        self.chunk_size = chunk_size

    def read_chunk(self, n: int) -> list[RefBlock]:
        primary = self.fs.read_file(primary_file(n))
        if not primary or primary[0] != VERSION:
            raise ValueError(f"chunk {n}: bad primary index version")
        offs = [struct.unpack_from(">I", primary, 1 + 4 * i)[0]
                for i in range((len(primary) - 1) // 4)]
        secondary = self.fs.read_file(secondary_file(n))
        blob = self.fs.read_file(chunk_file(n))
        blocks: list[RefBlock] = []
        for rel in range(len(offs) - 1):
            if offs[rel + 1] <= offs[rel]:
                continue                            # empty relative slot
            raw = secondary[offs[rel]:offs[rel] + ENTRY_SIZE]
            if len(raw) < ENTRY_SIZE:
                break                               # torn secondary tail
            blocks.append(RefBlock(
                RefEntry.decode(raw, is_ebb=(rel == 0)), n, b""))
        # second pass: slice block bytes using consecutive block offsets
        for i, rb in enumerate(blocks):
            start = rb.entry.block_offset
            end = (blocks[i + 1].entry.block_offset
                   if i + 1 < len(blocks) else len(blob))
            data = blob[start:end]
            if crc32(data) != rb.entry.checksum:
                return blocks[:i]                   # corrupt tail
            blocks[i] = RefBlock(rb.entry, n, data)
        return blocks

    def stream(self) -> Iterator[RefBlock]:
        for n in _chunk_numbers(self.fs):
            yield from self.read_chunk(n)

    def iter_entries(self) -> Iterator[RefEntry]:
        """Secondary-index entries only — no chunk blobs, no CRC: the
        cheap membership scan resume needs (is this snapshot point
        still on the chain?) without replaying the data files."""
        for n in _chunk_numbers(self.fs):
            primary = self.fs.read_file(primary_file(n))
            if not primary or primary[0] != VERSION:
                return
            offs = [struct.unpack_from(">I", primary, 1 + 4 * i)[0]
                    for i in range((len(primary) - 1) // 4)]
            secondary = self.fs.read_file(secondary_file(n))
            for rel in range(len(offs) - 1):
                if offs[rel + 1] <= offs[rel]:
                    continue
                raw = secondary[offs[rel]:offs[rel] + ENTRY_SIZE]
                if len(raw) < ENTRY_SIZE:
                    return
                yield RefEntry.decode(raw, is_ebb=(rel == 0))

    def __iter__(self) -> Iterator[RefBlock]:
        return self.stream()


class RefImmutableView:
    """Duck-typed read-only stand-in for ImmutableDB on the analyser
    path: stream() yields (entry, block bytes) like ImmutableDB.stream,
    so db_analyser replays reference-format DBs unchanged.  Membership
    (`hash in view` — the streaming engine's is-this-snapshot-point-
    still-on-chain check) scans the index files only, never the chunk
    blobs."""

    def __init__(self, reader: RefDbReader):
        self._r = reader

    def stream(self):
        for rb in self._r:
            yield rb.entry, rb.data

    def __contains__(self, h: bytes) -> bool:
        return any(e.header_hash == h for e in self._r.iter_entries())

    def __len__(self) -> int:
        return sum(1 for _ in self._r)
