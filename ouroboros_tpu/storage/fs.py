"""Injectable file-system API with a real impl and a fault-injecting mock.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Storage/FS/API.hs
(HasFS record-of-functions), FS/IO.hs (real impl), FS/CRC.hs, and the test
mock with error injection Test/Util/FS/Sim/{MockFS,Error}.hs — the seam
that lets every storage component run against simulated disks with
injected faults (SURVEY.md §4.3).

Paths are tuples of str components relative to the FS root.
"""
from __future__ import annotations

import os
import zlib
from typing import Iterable, Optional


class FsError(OSError):
    """Storage-layer file system error."""


def crc32(data: bytes, prev: int = 0) -> int:
    return zlib.crc32(data, prev) & 0xFFFFFFFF


class FsApi:
    """Abstract FS: whole-file and append-oriented ops (the subset the
    storage layer needs; handles are kept internal to discourage stateful
    handle leaks — the ResourceRegistry lesson)."""

    def read_file(self, path: tuple) -> bytes:
        raise NotImplementedError

    def write_file(self, path: tuple, data: bytes) -> None:
        """Atomic whole-file write (write temp + rename)."""
        raise NotImplementedError

    def append_file(self, path: tuple, data: bytes) -> None:
        raise NotImplementedError

    def truncate_file(self, path: tuple, size: int) -> None:
        raise NotImplementedError

    def read_range(self, path: tuple, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def file_size(self, path: tuple) -> int:
        raise NotImplementedError

    def exists(self, path: tuple) -> bool:
        raise NotImplementedError

    def list_dir(self, path: tuple) -> list[str]:
        raise NotImplementedError

    def mkdirs(self, path: tuple) -> None:
        raise NotImplementedError

    def remove(self, path: tuple) -> None:
        raise NotImplementedError

    def rename(self, src: tuple, dst: tuple) -> None:
        raise NotImplementedError


class IoFS(FsApi):
    """Real directory-rooted FS (FS/IO.hs analog)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _p(self, path: tuple) -> str:
        return os.path.join(self.root, *path)

    def read_file(self, path):
        try:
            with open(self._p(path), "rb") as f:
                return f.read()
        except OSError as e:
            raise FsError(str(e)) from e

    def write_file(self, path, data):
        p = self._p(path)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    def append_file(self, path, data):
        with open(self._p(path), "ab") as f:
            f.write(data)

    def truncate_file(self, path, size):
        with open(self._p(path), "r+b") as f:
            f.truncate(size)

    def read_range(self, path, offset, size):
        with open(self._p(path), "rb") as f:
            f.seek(offset)
            return f.read(size)

    def file_size(self, path):
        try:
            return os.path.getsize(self._p(path))
        except OSError as e:
            raise FsError(str(e)) from e

    def exists(self, path):
        return os.path.exists(self._p(path))

    def list_dir(self, path):
        try:
            return sorted(os.listdir(self._p(path)))
        except FileNotFoundError:
            return []

    def mkdirs(self, path):
        os.makedirs(self._p(path), exist_ok=True)

    def remove(self, path):
        try:
            os.remove(self._p(path))
        except FileNotFoundError:
            pass

    def rename(self, src, dst):
        os.replace(self._p(src), self._p(dst))


class MockFS(FsApi):
    """In-memory FS with injectable faults (Test/Util/FS/Sim analog).

    Fault hooks:
      fail_after_ops:   raise FsError once the op counter passes N
      partial_writes:   append/write only writes a prefix once armed
    Both model the crash/torn-write scenarios the reference's storage
    state-machine tests inject (SURVEY.md §4.2 corruption commands).
    """

    def __init__(self):
        self.files: dict[tuple, bytearray] = {}
        self.dirs: set[tuple] = {()}
        self.ops = 0
        self.fail_after_ops: Optional[int] = None
        self.partial_write_next: Optional[int] = None   # keep this many bytes

    # -- fault machinery ------------------------------------------------------
    def _tick(self):
        self.ops += 1
        if self.fail_after_ops is not None and self.ops > self.fail_after_ops:
            raise FsError(f"injected failure at op {self.ops}")

    def _maybe_truncate(self, data: bytes) -> bytes:
        if self.partial_write_next is not None:
            keep = self.partial_write_next
            self.partial_write_next = None
            return data[:keep]
        return data

    def snapshot(self) -> dict:
        """Copy of all file contents — crash-recovery tests restore this."""
        return {p: bytes(d) for p, d in self.files.items()}

    def restore(self, snap: dict) -> None:
        self.files = {p: bytearray(d) for p, d in snap.items()}

    # -- FsApi ----------------------------------------------------------------
    def read_file(self, path):
        self._tick()
        if path not in self.files:
            raise FsError(f"no such file {path}")
        return bytes(self.files[path])

    def write_file(self, path, data):
        self._tick()
        self.files[path] = bytearray(self._maybe_truncate(data))

    def append_file(self, path, data):
        self._tick()
        self.files.setdefault(path, bytearray()).extend(
            self._maybe_truncate(data))

    def truncate_file(self, path, size):
        self._tick()
        if path not in self.files:
            raise FsError(f"no such file {path}")
        del self.files[path][size:]

    def read_range(self, path, offset, size):
        self._tick()
        if path not in self.files:
            raise FsError(f"no such file {path}")
        return bytes(self.files[path][offset:offset + size])

    def file_size(self, path):
        if path not in self.files:
            raise FsError(f"no such file {path}")
        return len(self.files[path])

    def exists(self, path):
        return path in self.files or path in self.dirs

    def list_dir(self, path):
        n = len(path)
        names = {p[n] for p in list(self.files) + list(self.dirs)
                 if len(p) > n and p[:n] == path}
        return sorted(names)

    def mkdirs(self, path):
        for i in range(len(path) + 1):
            self.dirs.add(path[:i])

    def remove(self, path):
        self.files.pop(path, None)

    def rename(self, src, dst):
        if src in self.files:
            self.files[dst] = self.files.pop(src)
