"""LedgerDB — in-memory k-bounded ledger snapshots + on-disk checkpoints.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Storage/LedgerDB/
InMemory.hs:250-449 (anchored sequence of ledger states per block up to k,
`ledgerDbPush`/`ledgerDbSwitch`), OnDisk.hs:27-421 (CBOR snapshots
`takeSnapshot`/`readSnapshot`/`trimSnapshots` named by slot, replay from
newest snapshot at open), DiskPolicy.hs.

The in-memory sequence keeps a state per block so any rollback ≤ k is a
list truncation, not a replay.  The batched validation path
(consensus/batch.py validate_blocks_batched) plugs in via `switch`'s
`apply` callback returning the window's states at once.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from ..chain.block import Point
from ..utils import cbor
from .fs import FsApi, FsError

DIR = ("ledger",)


@dataclass(frozen=True)
class DiskPolicy:
    """How many snapshots to keep, and how often to take them
    (DiskPolicy.hs)."""
    num_snapshots: int = 2
    snapshot_interval_slots: int = 100


class LedgerDB:
    """Anchored sequence: anchor state (at the immutable tip) + one state
    per volatile block (≤ k of them, newest last)."""

    def __init__(self, k: int, anchor_point: Point, anchor_state: Any):
        self.k = k
        self.anchor_point = anchor_point
        self.anchor_state = anchor_state
        self._states: list[tuple[Point, Any]] = []

    # -- queries --------------------------------------------------------------
    @property
    def current(self) -> Any:
        return self._states[-1][1] if self._states else self.anchor_state

    @property
    def tip_point(self) -> Point:
        return self._states[-1][0] if self._states else self.anchor_point

    def __len__(self) -> int:
        return len(self._states)

    def state_at(self, point: Point) -> Optional[Any]:
        """State whose tip is `point` (LocalStateQuery acquire semantics)."""
        if point == self.anchor_point:
            return self.anchor_state
        for p, s in self._states:
            if p == point:
                return s
        return None

    def past_points(self) -> list[Point]:
        return [self.anchor_point] + [p for p, _ in self._states]

    # -- updates --------------------------------------------------------------
    def push(self, point: Point, state: Any) -> None:
        """ledgerDbPush + implicit prune to k."""
        self._states.append((point, state))
        if len(self._states) > self.k:
            # the oldest state becomes the new anchor (copy-to-immutable)
            self.anchor_point, self.anchor_state = self._states[0]
            del self._states[0]

    def prune_to_slot(self, slot: int) -> None:
        """Advance the anchor until it is at or past `slot` (called when the
        immutable tip advances — the copy-to-immutable path)."""
        while self.anchor_point.slot < slot and self._states:
            self.anchor_point, self.anchor_state = self._states[0]
            del self._states[0]

    def rollback(self, n: int) -> bool:
        """Drop the newest n states; False if n > len (deeper than k)."""
        if n > len(self._states):
            return False
        if n:
            del self._states[-n:]
        return True

    def switch(self, rollback_n: int,
               apply_window: Callable[[Any], Sequence[tuple[Point, Any]]]
               ) -> bool:
        """ledgerDbSwitch: rollback n then apply a window of new blocks.

        apply_window(state_at_fork) returns the new (point, state) pairs —
        typically produced by ONE batched validate_blocks_batched call.
        """
        if rollback_n > len(self._states):
            return False
        saved = self._states[len(self._states) - rollback_n:]
        if rollback_n:
            del self._states[-rollback_n:]
        try:
            new = apply_window(self.current)
        except Exception:
            self._states.extend(saved)
            raise
        for p, s in new:
            self.push(p, s)
        return True

    # -- on-disk snapshots ----------------------------------------------------
    @staticmethod
    def _snap_file(slot: int) -> tuple:
        return DIR + (f"snap-{slot:012d}",)

    @staticmethod
    def take_snapshot(fs: FsApi, slot: int, point: Point, state: Any,
                      encode_state: Callable[[Any], Any],
                      policy: DiskPolicy = DiskPolicy()) -> None:
        """Write a snapshot named by slot; trim old ones (OnDisk.hs:343,
        trimSnapshots)."""
        fs.mkdirs(DIR)
        payload = cbor.dumps([point.encode(), encode_state(state)])
        fs.write_file(LedgerDB._snap_file(slot), payload)
        snaps = sorted(n for n in fs.list_dir(DIR) if n.startswith("snap-"))
        for name in snaps[:-policy.num_snapshots]:
            fs.remove(DIR + (name,))

    @staticmethod
    def read_latest_snapshot(fs: FsApi,
                             decode_state: Callable[[Any], Any]
                             ) -> Optional[tuple[int, Point, Any]]:
        """Newest readable snapshot: (slot, point, state); corrupt snapshots
        are skipped, falling back to older ones (OnDisk.hs resume)."""
        snaps = sorted((n for n in fs.list_dir(DIR) if n.startswith("snap-")),
                       reverse=True)
        for name in snaps:
            try:
                obj = cbor.loads(fs.read_file(DIR + (name,)))
                point = Point.decode(obj[0])
                state = decode_state(obj[1])
                return int(name.split("-")[1]), point, state
            except (cbor.CBORError, FsError, ValueError, IndexError):
                continue
        return None
