"""LedgerDB — in-memory k-bounded ledger snapshots + on-disk checkpoints.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Storage/LedgerDB/
InMemory.hs:250-449 (anchored sequence of ledger states per block up to k,
`ledgerDbPush`/`ledgerDbSwitch`), OnDisk.hs:27-421 (CBOR snapshots
`takeSnapshot`/`readSnapshot`/`trimSnapshots` named by slot, replay from
newest snapshot at open), DiskPolicy.hs.

The in-memory sequence keeps a state per block so any rollback ≤ k is a
list truncation, not a replay.  The batched validation path
(consensus/batch.py validate_blocks_batched) plugs in via `switch`'s
`apply` callback returning the window's states at once.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from ..chain.block import Point
from ..utils import cbor
from .fs import FsApi, FsError, crc32

DIR = ("ledger",)


@dataclass(frozen=True)
class DiskPolicy:
    """How many snapshots to keep, and how often to take them
    (DiskPolicy.hs)."""
    num_snapshots: int = 2
    snapshot_interval_slots: int = 100


class LedgerDB:
    """Anchored sequence: anchor state (at the immutable tip) + one state
    per volatile block (≤ k of them, newest last)."""

    def __init__(self, k: int, anchor_point: Point, anchor_state: Any):
        self.k = k
        self.anchor_point = anchor_point
        self.anchor_state = anchor_state
        self._states: list[tuple[Point, Any]] = []

    # -- queries --------------------------------------------------------------
    @property
    def current(self) -> Any:
        return self._states[-1][1] if self._states else self.anchor_state

    @property
    def tip_point(self) -> Point:
        return self._states[-1][0] if self._states else self.anchor_point

    def __len__(self) -> int:
        return len(self._states)

    def state_at(self, point: Point) -> Optional[Any]:
        """State whose tip is `point` (LocalStateQuery acquire semantics)."""
        if point == self.anchor_point:
            return self.anchor_state
        for p, s in self._states:
            if p == point:
                return s
        return None

    def past_points(self) -> list[Point]:
        return [self.anchor_point] + [p for p, _ in self._states]

    # -- updates --------------------------------------------------------------
    def push(self, point: Point, state: Any) -> None:
        """ledgerDbPush + implicit prune to k."""
        self._states.append((point, state))
        if len(self._states) > self.k:
            # the oldest state becomes the new anchor (copy-to-immutable)
            self.anchor_point, self.anchor_state = self._states[0]
            del self._states[0]

    def prune_to_slot(self, slot: int) -> None:
        """Advance the anchor until it is at or past `slot` (called when the
        immutable tip advances — the copy-to-immutable path)."""
        while self.anchor_point.slot < slot and self._states:
            self.anchor_point, self.anchor_state = self._states[0]
            del self._states[0]

    def rollback(self, n: int) -> bool:
        """Drop the newest n states; False if n > len (deeper than k)."""
        if n > len(self._states):
            return False
        if n:
            del self._states[-n:]
        return True

    def switch(self, rollback_n: int,
               apply_window: Callable[[Any], Sequence[tuple[Point, Any]]]
               ) -> bool:
        """ledgerDbSwitch: rollback n then apply a window of new blocks.

        apply_window(state_at_fork) returns the new (point, state) pairs —
        typically produced by ONE batched validate_blocks_batched call.
        """
        if rollback_n > len(self._states):
            return False
        saved = self._states[len(self._states) - rollback_n:]
        if rollback_n:
            del self._states[-rollback_n:]
        try:
            new = apply_window(self.current)
        except Exception:
            self._states.extend(saved)
            raise
        for p, s in new:
            self.push(p, s)
        return True

    # -- on-disk snapshots ----------------------------------------------------
    # Checksummed snapshot framing (ISSUE 15): MAGIC + CRC-32(body) +
    # body, where body = CBOR [point, state].  The CRC is what makes a
    # torn write DETECTABLE on filesystems without atomic whole-file
    # writes; the tmp-file + rename below is what makes the common case
    # atomic.  Files without the magic are read as the legacy unframed
    # format, so pre-existing snapshots stay restorable.
    SNAP_MAGIC = b"OSNAP1"

    @staticmethod
    def _snap_file(slot: int) -> tuple:
        return DIR + (f"snap-{slot:012d}",)

    @staticmethod
    def take_snapshot(fs: FsApi, slot: int, point: Point, state: Any,
                      encode_state: Callable[[Any], Any],
                      policy: DiskPolicy = DiskPolicy()) -> None:
        """Write a snapshot named by slot, crash-consistently: the bytes
        land in a `.tmp` sibling first and only an atomic rename
        publishes the name readers look for — a kill mid-write leaves
        the previous snapshot set intact (OnDisk.hs takeSnapshot
        discipline).  Old snapshots are trimmed to the policy
        (OnDisk.hs:343 trimSnapshots)."""
        fs.mkdirs(DIR)
        body = cbor.dumps([point.encode(), encode_state(state)])
        payload = (LedgerDB.SNAP_MAGIC
                   + crc32(body).to_bytes(4, "big") + body)
        final = LedgerDB._snap_file(slot)
        tmp = DIR + (final[-1] + ".tmp",)
        fs.write_file(tmp, payload)
        fs.rename(tmp, final)
        snaps = LedgerDB.snapshot_names(fs)
        for name in snaps[:-policy.num_snapshots]:
            fs.remove(DIR + (name,))
        # sweep staging files orphaned by earlier crashes (kill between
        # write and rename) — readers already ignore them, but each one
        # holds a full ledger state of disk forever.  Single-writer
        # discipline: one engine owns a DB dir at a time, so no live
        # .tmp can be swept out from under a concurrent writer.
        for name in fs.list_dir(DIR):
            if name.endswith(".tmp"):
                fs.remove(DIR + (name,))

    @staticmethod
    def snapshot_names(fs: FsApi) -> list:
        """Published snapshot file names, oldest first (`.tmp` staging
        files are not snapshots — a crash may leave one behind)."""
        return sorted(n for n in fs.list_dir(DIR)
                      if n.startswith("snap-") and not n.endswith(".tmp"))

    @staticmethod
    def iter_snapshots(fs: FsApi, decode_state: Callable[[Any], Any]):
        """Yield (slot, point, state) for each READABLE snapshot, newest
        first.  A corrupt or partial snapshot — bad magic-framed CRC,
        torn CBOR, undecodable state — is skipped, falling back to the
        next older one (OnDisk.hs resume; the engine also needs the
        fallback when the newest snapshot points past a truncated
        ImmutableDB)."""
        for name in reversed(LedgerDB.snapshot_names(fs)):
            try:
                raw = fs.read_file(DIR + (name,))
                magic = LedgerDB.SNAP_MAGIC
                if raw[:len(magic)] == magic:
                    want = int.from_bytes(raw[len(magic):len(magic) + 4],
                                          "big")
                    body = raw[len(magic) + 4:]
                    if crc32(body) != want:
                        continue               # torn/corrupt: fall back
                else:
                    body = raw                 # legacy unframed snapshot
                obj = cbor.loads(body)
                point = Point.decode(obj[0])
                try:
                    state = decode_state(obj[1])
                except Exception:
                    # the promise is skip-and-fall-back, whatever the
                    # codec raises: pickle.UnpicklingError on garbage
                    # legacy bytes, AttributeError/ImportError when a
                    # state class moved, anything a custom codec throws
                    continue
                yield int(name.split("-")[1]), point, state
            except (cbor.CBORError, FsError, ValueError, IndexError,
                    EOFError):
                continue

    @staticmethod
    def read_latest_snapshot(fs: FsApi,
                             decode_state: Callable[[Any], Any]
                             ) -> Optional[tuple[int, Point, Any]]:
        """Newest readable snapshot: (slot, point, state); corrupt
        snapshots are skipped, falling back to older ones."""
        for found in LedgerDB.iter_snapshots(fs, decode_state):
            return found
        return None
