"""Minimal CBOR (RFC 8949) encoder/decoder.

The reference serialises every protocol message and ledger snapshot as CBOR
(codecs under Protocol/*/Codec.hs; snapshots in Storage/LedgerDB/OnDisk.hs).
This is a compact self-contained implementation covering the subset those
formats need: uints/nints, byte/text strings, arrays, maps, tags, simple
values, floats, and indefinite-length arrays.
"""
from __future__ import annotations

import struct
from typing import Any

__all__ = ["dumps", "loads", "CBORError", "CBORTruncated", "Tag"]


class CBORError(ValueError):
    pass


class CBORTruncated(CBORError):
    """Input ends mid-item — a partial message, not a corrupt stream.
    Framing layers catch this specifically and wait for more bytes."""


class Tag:
    __slots__ = ("tag", "value")

    def __init__(self, tag: int, value: Any):
        self.tag = tag
        self.value = value

    def __eq__(self, other):
        return (isinstance(other, Tag) and self.tag == other.tag
                and self.value == other.value)

    def __repr__(self):
        return f"Tag({self.tag}, {self.value!r})"


def _head(major: int, arg: int) -> bytes:
    if arg < 24:
        return bytes([(major << 5) | arg])
    if arg < 256:
        return bytes([(major << 5) | 24, arg])
    if arg < 65536:
        return bytes([(major << 5) | 25]) + arg.to_bytes(2, "big")
    if arg < 2**32:
        return bytes([(major << 5) | 26]) + arg.to_bytes(4, "big")
    if arg < 2**64:
        return bytes([(major << 5) | 27]) + arg.to_bytes(8, "big")
    raise CBORError("integer too large for CBOR head")


class IndefList(list):
    """A list encoded with indefinite length (0x9f ... 0xff) — some
    reference codecs REQUIRE this framing (e.g. TxSubmission's tsIdList,
    ouroboros-network/test/messages.cddl:78 note)."""


def _encode(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, int):
        if obj >= 0:
            out += _head(0, obj)
        else:
            out += _head(1, -1 - obj)
    elif isinstance(obj, bytes):
        out += _head(2, len(obj))
        out += obj
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out += _head(3, len(raw))
        out += raw
    elif isinstance(obj, IndefList):
        out.append(0x9F)
        for item in obj:
            _encode(item, out)
        out.append(0xFF)
    elif isinstance(obj, (list, tuple)):
        out += _head(4, len(obj))
        for item in obj:
            _encode(item, out)
    elif isinstance(obj, dict):
        out += _head(5, len(obj))
        for k, v in obj.items():
            _encode(k, out)
            _encode(v, out)
    elif isinstance(obj, Tag):
        out += _head(6, obj.tag)
        _encode(obj.value, out)
    elif isinstance(obj, float):
        out.append(0xFB)
        out += struct.pack(">d", obj)
    else:
        raise CBORError(f"cannot CBOR-encode {type(obj).__name__}")


def dumps(obj: Any) -> bytes:
    out = bytearray()
    _encode(obj, out)
    return bytes(out)


class _Decoder:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise CBORTruncated("truncated CBOR")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def _arg(self, info: int) -> int:
        if info < 24:
            return info
        if info == 24:
            return self._take(1)[0]
        if info == 25:
            return int.from_bytes(self._take(2), "big")
        if info == 26:
            return int.from_bytes(self._take(4), "big")
        if info == 27:
            return int.from_bytes(self._take(8), "big")
        raise CBORError(f"unsupported additional info {info}")

    def decode(self) -> Any:
        b = self._take(1)[0]
        major, info = b >> 5, b & 0x1F
        if major == 0:
            return self._arg(info)
        if major == 1:
            return -1 - self._arg(info)
        if major == 2:
            return bytes(self._take(self._arg(info)))
        if major == 3:
            return self._take(self._arg(info)).decode("utf-8")
        if major == 4:
            if info == 31:                     # indefinite-length array
                items = []
                while True:
                    if self.data[self.pos:self.pos + 1] == b"\xff":
                        self.pos += 1
                        return items
                    items.append(self.decode())
            return [self.decode() for _ in range(self._arg(info))]
        if major == 5:
            n = self._arg(info)
            out = {}
            for _ in range(n):
                k = self.decode()
                v = self.decode()
                if isinstance(k, list):
                    # array map keys (Shelley tx bodies use them) become
                    # tuples so the dict stays usable; _encode re-emits
                    # tuples as arrays, preserving round-trips
                    k = _freeze(k)
                if k in out:
                    # RFC 8949 §5.6: maps with duplicate keys are invalid;
                    # silently keeping the last key let a peer smuggle
                    # conflicting entries past CDDL-unique-key rules
                    # (ADVICE r4 on the handshake versionTable)
                    raise CBORError(f"duplicate map key {k!r}")
                out[k] = v
            return out
        if major == 6:
            return Tag(self._arg(info), self.decode())
        # major 7
        if info == 20:
            return False
        if info == 21:
            return True
        if info == 22 or info == 23:
            return None
        if info == 25:
            # half float
            h = int.from_bytes(self._take(2), "big")
            return _decode_half(h)
        if info == 26:
            return struct.unpack(">f", self._take(4))[0]
        if info == 27:
            return struct.unpack(">d", self._take(8))[0]
        raise CBORError(f"unsupported simple value {info}")


def _freeze(obj):
    """Recursively convert lists to tuples (for use as map keys)."""
    if isinstance(obj, list):
        return tuple(_freeze(x) for x in obj)
    return obj


def _decode_half(h: int) -> float:
    sign = -1.0 if h & 0x8000 else 1.0
    exp = (h >> 10) & 0x1F
    frac = h & 0x3FF
    if exp == 0:
        return sign * frac * 2.0 ** -24
    if exp == 31:
        return sign * (float("inf") if frac == 0 else float("nan"))
    return sign * (1 + frac / 1024.0) * 2.0 ** (exp - 15)


def loads(data: bytes, allow_trailing: bool = False):
    dec = _Decoder(data)
    obj = dec.decode()
    if not allow_trailing and dec.pos != len(data):
        raise CBORError(f"trailing bytes after CBOR value at {dec.pos}")
    return obj


def unwrap_tag24(obj):
    """CBOR-in-CBOR unwrap (#6.24(bytes .cbor x), messages.cddl:34,55):
    returns the decoded inner value for a tag-24-over-bytes envelope,
    or the object unchanged otherwise."""
    if isinstance(obj, Tag) and obj.tag == 24 and isinstance(obj.value,
                                                             bytes):
        return loads(obj.value)
    return obj


def loads_prefix(data: bytes) -> tuple[Any, int]:
    """Decode one CBOR item, returning (value, bytes_consumed)."""
    dec = _Decoder(data)
    obj = dec.decode()
    return obj, dec.pos


# ---------------------------------------------------------------------------
# Structural span scanning: walk items WITHOUT building objects, so decode
# paths can keep raw-byte slices of sub-items (header bytes, tx bodies) and
# the hot sequential pass never re-encodes what it just decoded (re-encoding
# was 40% of the replay's host pass in the r5 profile).
# ---------------------------------------------------------------------------

def skip_item(data: bytes, pos: int) -> int:
    """End offset of the CBOR item starting at `pos` (no object built)."""
    b = data[pos]
    major, info = b >> 5, b & 0x1F
    pos += 1
    if info < 24:
        arg = info
    elif info == 24:
        arg = data[pos]
        pos += 1
    elif info == 25:
        arg = int.from_bytes(data[pos:pos + 2], "big")
        pos += 2
    elif info == 26:
        arg = int.from_bytes(data[pos:pos + 4], "big")
        pos += 4
    elif info == 27:
        arg = int.from_bytes(data[pos:pos + 8], "big")
        pos += 8
    elif info == 31 and major in (2, 3, 4, 5):
        # indefinite length: scan children to the break byte
        while data[pos] != 0xFF:
            pos = skip_item(data, pos)
            if major == 5:
                pos = skip_item(data, pos)
        return pos + 1
    else:
        if major == 7 and info in (20, 21, 22, 23):
            return pos
        raise CBORError(f"unsupported additional info {info}")
    if major in (0, 1):
        return pos
    if major in (2, 3):
        return pos + arg
    if major == 4:
        for _ in range(arg):
            pos = skip_item(data, pos)
        return pos
    if major == 5:
        for _ in range(2 * arg):
            pos = skip_item(data, pos)
        return pos
    if major == 6:
        return skip_item(data, pos)
    # major 7 with numeric arg encodings (float16/32/64 handled via info)
    return pos


def list_spans(data: bytes, pos: int = 0) -> list:
    """(start, end) spans of each element of the LIST item at `pos`."""
    b = data[pos]
    major, info = b >> 5, b & 0x1F
    if major != 4:
        raise CBORError(f"list_spans: item at {pos} is major {major}")
    pos += 1
    if info < 24:
        n = info
    elif info == 24:
        n = data[pos]
        pos += 1
    elif info == 25:
        n = int.from_bytes(data[pos:pos + 2], "big")
        pos += 2
    elif info == 26:
        n = int.from_bytes(data[pos:pos + 4], "big")
        pos += 4
    elif info == 31:
        spans = []
        while data[pos] != 0xFF:
            end = skip_item(data, pos)
            spans.append((pos, end))
            pos = end
        return spans
    else:
        raise CBORError(f"unsupported list length info {info}")
    spans = []
    for _ in range(n):
        end = skip_item(data, pos)
        spans.append((pos, end))
        pos = end
    return spans
