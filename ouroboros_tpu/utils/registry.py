"""ResourceRegistry, RAWLock, FileLock — resource-ownership utilities.

Reference:
- ouroboros-consensus/src/Ouroboros/Consensus/Util/ResourceRegistry.hs:20-208
  — scoped ownership of resources and threads: everything allocated in a
  registry is released (in reverse allocation order) when the registry
  scope closes; leaks become errors instead of silent drips.
- ouroboros-consensus/src/Ouroboros/Consensus/Util/MonadSTM/RAWLock.hs —
  Read-Append-Write lock: many readers ∥ one appender; writer exclusive.
- ouroboros-consensus/src/Ouroboros/Consensus/Node/DbLock.hs — advisory
  on-disk lock guarding the ChainDB directory against double-open.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Optional

from .. import simharness as sim


class RegistryClosedError(Exception):
    """Allocation against a closed registry (ResourceRegistry.hs's
    RegistryClosedException)."""


class RegistryCloseError(Exception):
    """One or more releases failed while closing a registry (the
    ResourceRegistryThreadException aggregate)."""

    def __init__(self, errors):
        super().__init__(f"{len(errors)} release(s) failed: {errors!r}")
        self.errors = errors


class ResourceRegistry:
    """Scoped resource + thread ownership.

    Use as `async with ResourceRegistry() as reg:`; on exit every thread is
    cancelled and every resource released, newest first — the withRegistry
    bracket.  `allocate` returns a key usable for early `release`.
    """

    def __init__(self, label: str = ""):
        self.label = label
        self._next_key = 0
        self._resources: dict[int, tuple[str, Callable[[], Any]]] = {}
        self._threads: dict[int, Any] = {}
        self._closed = False

    # -- resources ------------------------------------------------------------
    def allocate(self, acquire: Callable[[], Any],
                 release: Callable[[Any], Any], label: str = "") -> tuple:
        """Acquire a resource under this registry; returns (key, resource).
        `release(resource)` runs at close (or at explicit release())."""
        self._check_open()
        resource = acquire()
        key = self._next_key
        self._next_key += 1
        self._resources[key] = (label, lambda: release(resource))
        return key, resource

    def release(self, key: int) -> None:
        """Release one resource early (ResourceRegistry.hs `release`)."""
        entry = self._resources.pop(key, None)
        if entry is not None:
            entry[1]()

    # -- threads --------------------------------------------------------------
    def fork_thread(self, coro, label: str = ""):
        """Spawn a thread owned by this registry (forkThread): it is
        cancelled when the registry closes; if it is still registered when
        it finishes, it unregisters itself."""
        self._check_open()
        key = self._next_key
        self._next_key += 1
        task = sim.spawn(self._reap(key, coro), label=label)
        self._threads[key] = task
        return task

    async def _reap(self, key: int, coro):
        try:
            return await coro
        finally:
            self._threads.pop(key, None)

    # -- lifecycle ------------------------------------------------------------
    def _check_open(self):
        if self._closed:
            raise RegistryClosedError(
                f"registry {self.label or id(self)} is closed")

    @property
    def n_live(self) -> int:
        """Live resources + threads — the leak-detection observable
        (ResourceRegistry.hs:156-208 turns nonzero-at-close into errors;
        tests assert on this)."""
        return len(self._resources) + len(self._threads)

    async def close(self) -> list:
        """Cancel owned threads, release resources newest-first; returns
        exceptions raised by releases (collected, not rethrown — the
        reference collects into a ResourceRegistryThreadException)."""
        if self._closed:
            return []
        self._closed = True
        errors = []
        for key in sorted(self._threads, reverse=True):
            # a thread may finish (and self-unregister) while we await
            # cancellation of a later-keyed one
            task = self._threads.pop(key, None)
            if task is None:
                continue
            try:
                await task.cancel_wait()
            except Exception as e:          # noqa: BLE001 — collect, report
                errors.append(e)
        for key in sorted(self._resources, reverse=True):
            _, rel = self._resources.pop(key)
            try:
                rel()
            except Exception as e:          # noqa: BLE001
                errors.append(e)
        return errors

    async def __aenter__(self):
        return self

    async def __aexit__(self, exc_type, exc, tb):
        errors = await self.close()
        if errors and exc_type is None:
            # the reference rethrows collected release failures wrapped in
            # ResourceRegistryThreadException; don't mask an in-flight one
            raise RegistryCloseError(errors)
        if errors:
            sim.trace_event(("registry.close_errors", errors), "registry")
        return False


class PoisonedError(Exception):
    """RAWLock was poisoned by an exception in a critical section."""


class RAWLock:
    """Read-Append-Write lock over a protected value.

    Concurrency matrix (RAWLock.hs header): readers run concurrently with
    each other and with the single appender; the writer is exclusive.  A
    writer *waiting* to take the lock already blocks new readers/appenders
    (the reference's WaitingToWrite state — writers cannot be starved).
    State is one TVar of (readers, appender, writer, waiting, poisoned)
    driven through STM retry, the same shape as the reference's
    unsafeAcquire*/unsafeRelease* internals.
    """

    def __init__(self, value: Any = None):
        self._state = sim.TVar((0, False, False, False, None),
                               label="rawlock")
        self._value = sim.TVar(value, label="rawlock.value")

    # -- acquire/release internals -------------------------------------------
    async def acquire_read(self) -> Any:
        def tx(t):
            readers, appender, writer, waiting, poison = t.read(self._state)
            if poison is not None:
                raise PoisonedError(str(poison))
            t.check(not writer and not waiting)
            t.write(self._state,
                    (readers + 1, appender, writer, waiting, poison))
            return t.read(self._value)
        return await sim.atomically(tx)

    async def release_read(self) -> None:
        def tx(t):
            readers, appender, writer, waiting, poison = t.read(self._state)
            t.write(self._state,
                    (readers - 1, appender, writer, waiting, poison))
        await sim.atomically(tx)

    async def acquire_append(self) -> Any:
        def tx(t):
            readers, appender, writer, waiting, poison = t.read(self._state)
            if poison is not None:
                raise PoisonedError(str(poison))
            t.check(not appender and not writer and not waiting)
            t.write(self._state, (readers, True, writer, waiting, poison))
            return t.read(self._value)
        return await sim.atomically(tx)

    async def release_append(self, new_value: Any) -> None:
        def tx(t):
            readers, appender, writer, waiting, poison = t.read(self._state)
            t.write(self._state, (readers, False, writer, waiting, poison))
            t.write(self._value, new_value)
        await sim.atomically(tx)

    async def acquire_write(self) -> Any:
        # phase 1: announce intent — blocks new readers/appenders
        def claim(t):
            readers, appender, writer, waiting, poison = t.read(self._state)
            if poison is not None:
                raise PoisonedError(str(poison))
            t.check(not writer and not waiting)
            t.write(self._state, (readers, appender, writer, True, poison))
        await sim.atomically(claim)

        # phase 2: wait for current readers/appender to drain, then write
        def take(t):
            readers, appender, writer, waiting, poison = t.read(self._state)
            if poison is not None:
                raise PoisonedError(str(poison))
            t.check(readers == 0 and not appender)
            t.write(self._state, (0, False, True, False, poison))
            return t.read(self._value)

        try:
            return await sim.atomically(take)
        except BaseException:
            # cancelled (or poisoned) while waiting: drop the waiting flag
            # so readers/appenders aren't blocked forever.  Done without
            # awaiting (a cancelled task cannot await again); the sync
            # read-modify-write is atomic under cooperative scheduling.
            readers, appender, writer, _, poison = self._state.value
            self._state.set_notify((readers, appender, writer, False,
                                    poison))
            raise

    async def release_write(self, new_value: Any) -> None:
        def tx(t):
            readers, appender, writer, waiting, poison = t.read(self._state)
            t.write(self._state, (readers, appender, False, waiting, poison))
            t.write(self._value, new_value)
        await sim.atomically(tx)

    # -- brackets -------------------------------------------------------------
    async def with_read_access(self, fn):
        v = await self.acquire_read()
        try:
            return await fn(v)
        finally:
            await self.release_read()

    async def with_append_access(self, fn):
        """fn(value) -> (result, new_value)."""
        v = await self.acquire_append()
        try:
            result, new_v = await fn(v)
        except BaseException as e:
            await self.poison(e)
            raise
        await self.release_append(new_v)
        return result

    async def with_write_access(self, fn):
        """fn(value) -> (result, new_value)."""
        v = await self.acquire_write()
        try:
            result, new_v = await fn(v)
        except BaseException as e:
            await self.poison(e)
            raise
        await self.release_write(new_v)
        return result

    async def read(self) -> Any:
        """Read the protected value without taking the lock (RAWLock.hs
        `read`): succeeds even while a writer is *waiting* (no IO follows),
        retries only while a write is in progress."""
        def tx(t):
            _, _, writer, _, poison = t.read(self._state)
            if poison is not None:
                raise PoisonedError(str(poison))
            t.check(not writer)
            return t.read(self._value)
        return await sim.atomically(tx)

    async def poison(self, exc: BaseException) -> None:
        """Mark the lock broken: all subsequent acquires raise
        (RAWLock.hs `poison` — turns deadlock-after-crash into an error)."""
        def tx(t):
            readers, appender, writer, waiting, _ = t.read(self._state)
            t.write(self._state,
                    (readers, appender, writer, waiting, repr(exc)))
        await sim.atomically(tx)


class FileLockError(Exception):
    pass


class FileLock:
    """Advisory exclusive file lock (Node/DbLock.hs over flock).

    Non-blocking acquire: a second holder raises FileLockError immediately,
    the double-open guard for on-disk DB directories."""

    def __init__(self, path: str):
        self.path = path
        self._fd: Optional[int] = None

    def acquire(self) -> None:
        import fcntl
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(fd)
            raise FileLockError(
                f"lock {self.path} is held by another process") from e
        self._fd = fd

    def release(self) -> None:
        import fcntl
        if self._fd is not None:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False
