"""utils — CBOR, resource registry, misc support."""
