"""Contravariant tracers + the per-subsystem tracer record.

Reference: the `Tracer m a` threaded through every constructor
(contra-tracer; consensus bundle at Node/Tracers.hs:51-62, ChainDB event
schema in Storage/ChainDB/Impl/Types.hs `TraceAddBlockEvent`).  The
events are TYPED dataclasses — the log schema — so tests assert on
decision events rather than string-matching a debug log.

The default tracers forward into the simulator's dynamic trace
(sim.trace_event), so every event is also visible in `run_trace` output;
`collecting()` returns a tracer+list pair for assertions.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional


class Tracer:
    """Contravariant event sink (Tracer m a).  nop tracers are free:
    trace() is a no-op when no emit function is attached."""

    __slots__ = ("_emit",)

    def __init__(self, emit: Optional[Callable[[Any], None]] = None):
        self._emit = emit

    def trace(self, ev: Any) -> None:
        if self._emit is not None:
            self._emit(ev)

    def contramap(self, f: Callable[[Any], Any]) -> "Tracer":
        if self._emit is None:
            return self
        return Tracer(lambda ev: self.trace(f(ev)))

    @property
    def active(self) -> bool:
        return self._emit is not None


NOP = Tracer()


def sim_tracer(label: str) -> Tracer:
    """Tracer into the simulator/runtime dynamic trace (traceM analog)."""
    from .. import simharness as sim
    return Tracer(lambda ev: sim.trace_event(ev, label))


def collecting() -> tuple[Tracer, list]:
    """(tracer, events) — events appended in trace order, for tests."""
    out: list = []
    return Tracer(out.append), out


# ---------------------------------------------------------------------------
# Event schemas (the typed log surface)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TraceAddBlock:
    """ChainDB.add_block outcome (TraceAddBlockEvent analog)."""
    kind: str                  # extended | switched | stored | ...
    slot: int
    block_no: int
    hash: bytes


@dataclass(frozen=True)
class TraceSwitchedToFork:
    """Chain selection adopted a fork (SwitchedToAFork)."""
    old_tip_slot: int
    new_tip_slot: int
    rollback_depth: int


@dataclass(frozen=True)
class TraceInvalidBlock:
    hash: bytes
    reason: str


@dataclass(frozen=True)
class TraceForgeEvent:
    """One slot's forging outcome (TraceForgeEvent analog)."""
    slot: int
    outcome: str               # forged | not-leader | error
    detail: str = ""


@dataclass(frozen=True)
class TraceFetchDecision:
    """One BlockFetch governor decision for one peer
    (TraceFetchDecision analog)."""
    peer_id: Any
    n_requested: int
    in_flight_bytes: int
    reason: str                # request | throttled | nothing-to-fetch


@dataclass(frozen=True)
class TraceChainSyncEvent:
    """ChainSync client progress (TraceChainSyncClientEvent analog)."""
    peer_id: Any
    event: str                 # roll-forward | roll-backward | validated
    slot: int
    n: int = 1


@dataclass
class NodeTracers:
    """The per-subsystem tracer bundle handed to the node constructors
    (Node/Tracers.hs:51-62)."""
    chain_db: Tracer = NOP
    forge: Tracer = NOP
    fetch: Tracer = NOP
    chain_sync: Tracer = NOP

    @classmethod
    def nop(cls) -> "NodeTracers":
        return cls()

    @classmethod
    def for_sim(cls, label: str) -> "NodeTracers":
        return cls(chain_db=sim_tracer(f"{label}.chaindb"),
                   forge=sim_tracer(f"{label}.forge"),
                   fetch=sim_tracer(f"{label}.fetch"),
                   chain_sync=sim_tracer(f"{label}.chainsync"))
