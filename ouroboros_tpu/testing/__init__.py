"""Test harness library — the ouroboros-consensus-test analog.

ThreadNet (multi-node network-in-the-simulator) lives here so test suites
and benchmarks share one harness (reference: ouroboros-consensus-test/src/
Test/ThreadNet/{General,Network}.hs).  The chaos layer runs the same
network under a seeded FaultPlan with subscription-based recovery.
"""
from .threadnet import (
    ChaosConfig, ChaosResult, PraosNetworkFactory, ThreadNetConfig,
    ThreadNetResult, chaos_error_policies, chaos_time_limits,
    praos_node_keys, run_chaos_threadnet, run_threadnet,
)

__all__ = ["ChaosConfig", "ChaosResult", "PraosNetworkFactory",
           "ThreadNetConfig", "ThreadNetResult", "chaos_error_policies",
           "chaos_time_limits", "praos_node_keys", "run_chaos_threadnet",
           "run_threadnet"]
