"""Test harness library — the ouroboros-consensus-test analog.

ThreadNet (multi-node network-in-the-simulator) lives here so test suites
and benchmarks share one harness (reference: ouroboros-consensus-test/src/
Test/ThreadNet/{General,Network}.hs).
"""
from .threadnet import (
    PraosNetworkFactory, ThreadNetConfig, ThreadNetResult, praos_node_keys,
    run_threadnet,
)

__all__ = ["PraosNetworkFactory", "ThreadNetConfig", "ThreadNetResult",
           "praos_node_keys", "run_threadnet"]
