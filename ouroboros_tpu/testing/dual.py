"""Dual ledger — impl vs executable-spec lockstep conformance oracle.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Ledger/Dual.hs (the
DualBlock machinery running the production ledger and the executable spec
side by side, failing on ANY observable divergence) and the byronspec
package it pairs with (SURVEY.md §2 ouroboros-consensus-byronspec).

The specs here are deliberately naive re-implementations of the era rules
over plain dicts — recomputed from scratch wherever the production ledger
keeps incremental state (stake snapshots, frozen tuples, sorted indexes) —
so lockstep runs catch exactly the bookkeeping bugs incremental code
grows.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..consensus.ledger import LedgerError, LedgerRules
from ..eras.byron import CERT_DLG, CERT_UPDATE
from ..eras.shelley import (
    CERT_DELEG, CERT_POOL, CERT_RETIRE, ISSUER_FIELD, pool_id_of,
)


class DualLedgerMismatch(AssertionError):
    """The implementation diverged from the executable spec."""


# ---------------------------------------------------------------------------
# Executable specs (plain-dict semantics, no incremental state)
# ---------------------------------------------------------------------------

def _spec_verify_witnesses(tx) -> set:
    """Signature validity straight from the reference crypto (the spec may
    use the ground-truth primitive); returns the set of witnessing vks."""
    from ..crypto import ed25519_ref
    vks = set()
    for vk, sig in tx.witnesses:
        if not ed25519_ref.verify(vk, tx.txid, sig):
            raise LedgerError("spec: invalid witness signature")
        vks.add(vk)
    return vks


class ByronSpec:
    """UTxO + heavyweight delegation, straight from the rules."""

    def __init__(self, genesis: dict, genesis_vks, initial_delegates):
        self.utxo = {(b"\x00" * 32, ix): (addr, amt)
                     for ix, (addr, amt) in enumerate(
                         sorted(genesis.items()))}
        self.genesis_vks = list(genesis_vks)
        self.delegates = list(initial_delegates)
        self.update_epoch = -1

    def apply_tx(self, tx) -> None:
        wit_vks = _spec_verify_witnesses(tx)
        for key in tx.inputs:
            if key in self.utxo and self.utxo[key][0] not in wit_vks:
                raise LedgerError("spec: spend without witness")
        for kind, arg, vk in tx.certs:
            if kind == CERT_DLG:
                gix = int.from_bytes(arg, "big")
                if not 0 <= gix < len(self.genesis_vks) \
                        or self.genesis_vks[gix] not in wit_vks:
                    raise LedgerError("spec: unwitnessed delegation")
            elif kind == CERT_UPDATE:
                if not any(v in wit_vks for v in self.genesis_vks):
                    raise LedgerError("spec: unwitnessed update")
        if len(set(tx.inputs)) != len(tx.inputs):
            raise LedgerError("spec: duplicate inputs")
        spent = 0
        for key in tx.inputs:
            if key not in self.utxo:
                raise LedgerError("spec: missing input")
            spent += self.utxo[key][1]
        if any(m < 0 for _a, m in tx.outputs):
            raise LedgerError("spec: negative output")
        if sum(m for _a, m in tx.outputs) > spent:
            raise LedgerError("spec: overspend")
        for kind, arg, vk in tx.certs:
            if kind == CERT_DLG:
                gix = int.from_bytes(arg, "big")
                if not 0 <= gix < len(self.delegates):
                    raise LedgerError("spec: unknown genesis key")
                self.delegates[gix] = vk
            elif kind == CERT_UPDATE:
                self.update_epoch = int.from_bytes(arg, "big")
            else:
                raise LedgerError("spec: unknown cert")
        for key in tx.inputs:
            del self.utxo[key]
        for ix, (addr, amt) in enumerate(tx.outputs):
            self.utxo[(tx.txid, ix)] = (addr, amt)

    def observe(self) -> dict:
        return {"utxo": dict(self.utxo),
                "delegates": tuple(self.delegates),
                "update_epoch": self.update_epoch}


class ShelleySpec:
    """UTxO + pools + delegation + per-epoch stake recomputation from
    scratch (vs the impl's incremental mark/set snapshot rotation)."""

    def __init__(self, genesis: dict, config, initial_pools,
                 initial_delegs, era: str = "shelley",
                 initial_reserves: int = 1_000_000):
        self.utxo = {(b"\x00" * 32, ix): (addr, amt, ())
                     for ix, (addr, amt) in enumerate(
                         sorted(genesis.items()))}
        self.pools = dict(initial_pools)
        self.delegs = dict(initial_delegs)
        self.config = config
        self.era = era
        self.epoch = 0
        # snapshots as plain recomputations
        self.snap_mark = self._stake()
        self.snap_set = dict(self.snap_mark)
        self.snap_go = dict(self.snap_mark)
        self.reserves = initial_reserves
        self.treasury = 0
        self.rewards: dict = {}
        self.retiring: dict = {}
        self.blocks_made: dict = {}

    def _stake(self) -> dict:
        by_addr: dict = {}
        for (_t, _i), (addr, amt, _assets) in self.utxo.items():
            by_addr[addr] = by_addr.get(addr, 0) + amt
        out: dict = {}
        for addr, pid in self.delegs.items():
            if pid in self.pools:
                out[pid] = out.get(pid, 0) + by_addr.get(addr, 0)
        return {p: s for p, s in out.items() if s > 0}

    def note_block(self, issuer_vk) -> None:
        if issuer_vk is not None:
            pid = pool_id_of(issuer_vk)
            self.blocks_made[pid] = self.blocks_made.get(pid, 0) + 1

    def tick_to(self, slot: int) -> None:
        cfg = self.config
        target = slot // cfg.epoch_length
        while self.epoch < target:
            self.epoch += 1
            # rewards: rho of reserves -> pot, tau of pot -> treasury,
            # rest split over the GO snapshot by stake x performance
            pot = self.reserves * cfg.rho.numerator // cfg.rho.denominator
            if pot:
                cut = pot * cfg.tau.numerator // cfg.tau.denominator
                distributable = pot - cut
                total_go = sum(self.snap_go.values())
                total_blocks = sum(self.blocks_made.values())
                paid = 0
                if total_go and total_blocks:
                    for pid in sorted(self.snap_go):
                        stake = self.snap_go[pid]
                        base = distributable * stake // total_go
                        expected = max(1, total_blocks * stake // total_go)
                        r = base * min(self.blocks_made.get(pid, 0),
                                       expected) // expected
                        if r:
                            self.rewards[pid] = self.rewards.get(pid, 0) + r
                            paid += r
                self.reserves -= cut + paid
                self.treasury += cut
            # rotation go <- set <- mark <- live
            self.snap_go = dict(self.snap_set)
            self.snap_set = dict(self.snap_mark)
            self.snap_mark = self._stake()
            # retirement
            due = {p for p, e in self.retiring.items() if e <= self.epoch}
            for p in due:
                self.pools.pop(p, None)
                self.retiring.pop(p, None)
            if due:
                self.delegs = {a: p for a, p in self.delegs.items()
                               if p not in due}
            self.blocks_made = {}

    def apply_tx(self, tx, slot: int) -> None:
        # feature gating (era-indexed tx admission)
        family = ("shelley", "allegra", "mary")
        ix = family.index(self.era)
        if tx.validity:
            if ix < family.index("allegra"):
                raise LedgerError("spec: validity needs allegra+")
            before, after = tx.validity
            if (before >= 0 and slot < before) or \
                    (after >= 0 and slot > after):
                raise LedgerError("spec: outside validity interval")
        if (tx.mint or any(assets for _a, _m, assets in tx.outputs)) \
                and ix < family.index("mary"):
            raise LedgerError("spec: multi-asset needs mary")
        # witnesses: signature validity + structural coverage
        wit_vks = _spec_verify_witnesses(tx)
        for key in tx.inputs:
            if key in self.utxo and self.utxo[key][0] not in wit_vks:
                raise LedgerError("spec: spend without witness")
        for kind, a, _b in tx.certs:
            if kind in (CERT_POOL, CERT_DELEG, CERT_RETIRE) \
                    and a not in wit_vks:
                raise LedgerError("spec: unwitnessed certificate")
        policies = {pool_id_of(vk) for vk in wit_vks}
        for aid, _q in tx.mint:
            if aid not in policies:
                raise LedgerError("spec: unwitnessed mint policy")
        wds = getattr(tx, "withdrawals", ())
        if len({p for p, _a in wds}) != len(wds):
            raise LedgerError("spec: duplicate withdrawals")
        for pid, _amt in wds:
            if pid not in policies:
                raise LedgerError("spec: unwitnessed withdrawal")
        if len(set(tx.inputs)) != len(tx.inputs):
            raise LedgerError("spec: duplicate inputs")
        spent = 0
        consumed: dict = {}
        for key in tx.inputs:
            if key not in self.utxo:
                raise LedgerError("spec: missing input")
            _a, amt, assets = self.utxo[key]
            spent += amt
            for aid, q in assets:
                consumed[aid] = consumed.get(aid, 0) + q
        for pid, amt in getattr(tx, "withdrawals", ()):
            if amt <= 0 or amt != self.rewards.get(pid, 0):
                raise LedgerError("spec: withdrawal != reward balance")
            spent += amt
        for aid, q in tx.mint:
            consumed[aid] = consumed.get(aid, 0) + q
        produced = 0
        produced_assets: dict = {}
        for _a, amt, assets in tx.outputs:
            if amt < 0:
                raise LedgerError("spec: negative output")
            produced += amt
            for aid, q in assets:
                if q <= 0:
                    raise LedgerError("spec: non-positive output asset")
                produced_assets[aid] = produced_assets.get(aid, 0) + q
        if produced > spent:
            raise LedgerError("spec: overspend")
        if produced_assets != {a: q for a, q in consumed.items() if q}:
            raise LedgerError("spec: asset imbalance")
        for kind, a, b in tx.certs:
            if kind == CERT_POOL:
                self.pools[pool_id_of(a)] = b
                self.retiring.pop(pool_id_of(a), None)
            elif kind == CERT_DELEG:
                if b not in self.pools:
                    raise LedgerError("spec: unregistered pool")
                self.delegs[a] = b
            elif kind == CERT_RETIRE:
                pid = pool_id_of(a)
                if pid not in self.pools:
                    raise LedgerError("spec: retiring unregistered pool")
                epoch = int.from_bytes(b, "big")
                if epoch <= self.epoch:
                    raise LedgerError("spec: retirement not in the future")
                self.retiring[pid] = epoch
            else:
                raise LedgerError("spec: unknown cert")
        for pid, _amt in getattr(tx, "withdrawals", ()):
            del self.rewards[pid]
        for key in tx.inputs:
            del self.utxo[key]
        for ix, (addr, amt, assets) in enumerate(tx.outputs):
            self.utxo[(tx.txid, ix)] = (addr, amt, assets)

    def observe(self) -> dict:
        return {"utxo": dict(self.utxo), "pools": dict(self.pools),
                "delegs": dict(self.delegs), "epoch": self.epoch,
                "snap_set": dict(self.snap_set),
                "snap_mark": dict(self.snap_mark),
                "snap_go": dict(self.snap_go),
                "reserves": self.reserves, "treasury": self.treasury,
                "rewards": dict(self.rewards),
                "retiring": dict(self.retiring),
                "blocks_made": dict(self.blocks_made)}


# ---------------------------------------------------------------------------
# The lockstep wrapper
# ---------------------------------------------------------------------------

def _observe_byron_impl(state) -> dict:
    return {"utxo": {(t, i): (a, m) for t, i, a, m in state.utxo},
            "delegates": tuple(state.delegates),
            "update_epoch": state.update_epoch}


def _observe_shelley_impl(state) -> dict:
    return {"utxo": {(t, i): (a, m, assets)
                     for t, i, a, m, assets in state.utxo},
            "pools": dict(state.pools),
            "delegs": dict(state.delegs),
            "epoch": state.epoch,
            "snap_set": {p: s for p, s, _v in state.snap_set},
            "snap_mark": {p: s for p, s, _v in state.snap_mark},
            "snap_go": {p: s for p, s, _v in state.snap_go},
            "reserves": state.reserves, "treasury": state.treasury,
            "rewards": dict(state.rewards),
            "retiring": dict(state.retiring),
            "blocks_made": dict(state.blocks_made)}


@dataclass
class DualResult:
    impl_error: Optional[Exception]
    spec_error: Optional[Exception]


class DualLedger:
    """Run the production LedgerRules and the spec in lockstep
    (Dual.hs agreeOnError + state comparison after every block)."""

    def __init__(self, impl: LedgerRules, impl_state, spec,
                 observe_impl, era: str):
        self.impl = impl
        self.state = impl_state
        self.spec = spec
        self.observe_impl = observe_impl
        self.era = era

    def _compare(self) -> None:
        a = self.observe_impl(self.state)
        b = self.spec.observe()
        if a != b:
            keys = [k for k in a if a[k] != b.get(k)]
            raise DualLedgerMismatch(
                f"impl/spec divergence in {keys}: "
                f"impl={ {k: a[k] for k in keys} } "
                f"spec={ {k: b.get(k) for k in keys} }")

    def apply_block(self, block, backend=None) -> DualResult:
        """Apply to both; errors must AGREE (both reject or both accept),
        and accepted states must observe equal.  The impl rejects blocks
        atomically, so the spec runs on a copy that is committed only on
        success — a rejected block must leave BOTH sides untouched."""
        import copy
        impl_err = spec_err = None
        ticked = self.impl.tick(self.state, block.slot)
        try:
            new_state = self.impl.apply_block(ticked, block,
                                              backend=backend)
        except LedgerError as e:
            impl_err = e
        spec_try = copy.deepcopy(self.spec)
        if self.era == "shelley":
            try:
                spec_try.tick_to(block.slot)
                for tx in block.body:
                    spec_try.apply_tx(tx, block.slot)
                # block-production accounting (BlocksMade), mirroring the
                # impl's header-issuer bookkeeping
                header = getattr(block, "header", None)
                if header is not None and hasattr(header, "get"):
                    spec_try.note_block(header.get(ISSUER_FIELD))
            except LedgerError as e:
                spec_err = e
        else:
            try:
                for tx in block.body:
                    spec_try.apply_tx(tx)
            except LedgerError as e:
                spec_err = e
        if (impl_err is None) != (spec_err is None):
            raise DualLedgerMismatch(
                f"impl error={impl_err!r} but spec error={spec_err!r}")
        if impl_err is None:
            self.state = new_state
            self.spec = spec_try
            self._compare()
        return DualResult(impl_err, spec_err)


def dual_byron(genesis: dict, genesis_vks, initial_delegates):
    from ..eras.byron import ByronLedger
    impl = ByronLedger(genesis, genesis_vks, initial_delegates)
    spec = ByronSpec(genesis, genesis_vks, initial_delegates)
    return DualLedger(impl, impl.initial_state(), spec,
                      _observe_byron_impl, era="byron")


def dual_shelley(genesis: dict, config, initial_pools, initial_delegs,
                 era: str = "shelley", initial_reserves: int = 1_000_000):
    from ..eras.shelley import ShelleyLedger
    impl = ShelleyLedger(genesis, config, initial_pools, initial_delegs,
                         era=era, initial_reserves=initial_reserves)
    spec = ShelleySpec(genesis, config, initial_pools, initial_delegs,
                       era=era, initial_reserves=initial_reserves)
    return DualLedger(impl, impl.initial_state(), spec,
                      _observe_shelley_impl, era="shelley")
