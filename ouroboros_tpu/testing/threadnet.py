"""ThreadNet — N full nodes in the deterministic simulator.

Reference: ouroboros-consensus-test/src/Test/ThreadNet/General.hs:204,230
(`runTestNetwork` inside `runSimOrThrow`) + Network.hs:275-344 (mesh of
real NodeKernels over in-memory channels), instantiated for mock Praos as
in ouroboros-consensus-mock-test/test/Test/ThreadNet/Praos.hs — the
reference's cheapest full-stack configuration and BASELINE.md config #1.

Each node is the real stack: MockFS → ImmutableDB/VolatileDB/LedgerDB →
ChainDB → NodeKernel with mempool, forging loop, batched-window ChainSync
clients, BlockFetch decision logic — connected by mux bearers with
configurable delay.  The umbrella property (`prop_general`, General.hs:408)
maps to ThreadNetResult checks: convergence, chain growth, no unexpected
thread failures.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from .. import simharness as sim
from ..chain.block import Point
from ..consensus.header_validation import AnnTip, HeaderState
from ..consensus.headers import ProtocolBlock
from ..consensus.ledger import ExtLedgerRules, ExtLedgerState
from ..consensus.mempool import Mempool
from ..consensus.protocols.praos import (
    HotKey, Praos, PraosConfig, PraosNode, PraosState, praos_forge_fields,
)
from ..crypto import ed25519_ref, kes as kes_mod
from ..crypto.backend import OpensslBackend
from ..ledgers.mock import MockLedger, MockLedgerState, Tx
from ..node import BlockForging, BlockchainTime, NodeKernel, connect_nodes
from ..storage import MockFS
from ..storage.chaindb import ChainDB
from ..utils import cbor


@dataclass
class NodeKeys:
    vrf_sk: bytes
    vrf_vk: bytes
    kes_seed: bytes
    kes_vk: bytes
    payment_sk: bytes
    payment_vk: bytes


def praos_node_keys(i: int, kes_depth: int, seed: bytes = b"threadnet"
                    ) -> NodeKeys:
    def h(tag: bytes) -> bytes:
        return hashlib.blake2b(seed + tag + i.to_bytes(4, "big"),
                               digest_size=32).digest()
    vrf_sk = h(b"vrf")
    kes_seed = h(b"kes")
    pay_sk = h(b"pay")
    return NodeKeys(
        vrf_sk=vrf_sk, vrf_vk=ed25519_ref.public_key(vrf_sk),
        kes_seed=kes_seed, kes_vk=kes_mod.vk_of(kes_depth, kes_seed),
        payment_sk=pay_sk, payment_vk=ed25519_ref.public_key(pay_sk))


@dataclass
class ThreadNetConfig:
    n_nodes: int = 3
    n_slots: int = 30
    slot_length: float = 1.0
    k: int = 10
    f: float = 0.6                   # active slot coefficient
    epoch_length: int = 100
    kes_depth: int = 7
    slots_per_kes_period: int = 10
    seed: int = 0
    link_delay: float = 0.05         # bearer one-way delay, in slots units
    join_slots: Optional[Sequence[int]] = None   # node i joins at slot[i]
    topology: str = "mesh"           # "mesh" | "ring" | "line"
    chain_sync_window: int = 8
    coin_per_node: int = 1000
    # txs submitted at (slot, node, tx_factory(keys, ledger_state)) hooks
    tx_plan: tuple = ()
    # per-node handshake network magic (default: all 0 — one network)
    network_magics: Optional[Sequence[int]] = None
    # (slot, node_ix) pairs: stop the node at `slot` and restart it from
    # its own on-disk state (NodeRestarts.hs analog — the restarted node
    # re-opens its ChainDB, replays, reconnects, and catches up)
    restart_plan: tuple = ()


@dataclass
class ThreadNetResult:
    chains: list                     # final current_chain per node
    ledgers: list                    # final ExtLedgerState per node
    keys: list                       # NodeKeys per node
    trace: list = field(default_factory=list)
    failures: list = field(default_factory=list)

    # -- prop_general checks (General.hs:408) --------------------------------
    def common_prefix_ok(self, k: int) -> bool:
        """Every pair of final chains forks at most k blocks from either
        head (the common-prefix / bounded-fork-length property)."""
        for i in range(len(self.chains)):
            for j in range(i + 1, len(self.chains)):
                a, b = self.chains[i], self.chains[j]
                isect = a.intersect(b)
                if isect is None:
                    isect_bn = a.anchor_block_no
                else:
                    blk = a.lookup(isect.hash)
                    isect_bn = blk.block_no if blk is not None \
                        else a.anchor_block_no
                for c in (a, b):
                    if c.head_block_no - isect_bn > k:
                        return False
        return True

    def max_fork_depth(self) -> int:
        """Deepest divergence among final chains: max over pairs of
        (head height - intersection height).  prop_general bounds this by
        the protocol-specific expectation (Util/Expectations.hs) — for
        honest mock Praos, end-of-run slot battles only (a few blocks)."""
        worst = 0
        for i in range(len(self.chains)):
            for j in range(i + 1, len(self.chains)):
                a, b = self.chains[i], self.chains[j]
                isect = a.intersect(b)
                if isect is None:
                    isect_bn = min(a.anchor_block_no, b.anchor_block_no)
                else:
                    blk = a.lookup(isect.hash)
                    isect_bn = blk.block_no if blk is not None \
                        else a.anchor_block_no
                worst = max(worst, a.head_block_no - isect_bn,
                            b.head_block_no - isect_bn)
        return worst

    def min_length(self) -> int:
        return min(c.head_block_no + 1 for c in self.chains)

    def max_length(self) -> int:
        return max(c.head_block_no + 1 for c in self.chains)


class PraosNetworkFactory:
    """Builds the per-node stacks for a mock-Praos network; reused by
    run_threadnet and by node-to-client / tooling tests that need one
    full node outside the ThreadNet driver."""

    def __init__(self, cfg: ThreadNetConfig):
        self.cfg = cfg
        self.keys = [praos_node_keys(i, cfg.kes_depth)
                     for i in range(cfg.n_nodes)]
        self.protocol_cfg = PraosConfig(
            nodes=tuple(PraosNode(k.vrf_vk, k.kes_vk, stake=1)
                        for k in self.keys),
            k=cfg.k, f=cfg.f, epoch_length=cfg.epoch_length,
            kes_depth=cfg.kes_depth,
            slots_per_kes_period=cfg.slots_per_kes_period)
        self.genesis = {k.payment_vk: cfg.coin_per_node for k in self.keys}
        self.backend = OpensslBackend()

    # -- codecs ---------------------------------------------------------------
    @staticmethod
    def block_decode(raw: bytes) -> ProtocolBlock:
        return ProtocolBlock.decode(cbor.loads(raw), tx_decode=Tx.decode)

    @staticmethod
    def header_decode_obj(obj):
        from ..consensus.headers import ProtocolHeader
        return ProtocolHeader.decode(obj)

    @staticmethod
    def block_decode_obj(obj):
        return ProtocolBlock.decode(obj, tx_decode=Tx.decode)

    @staticmethod
    def enc_state(ext: ExtLedgerState):
        dep: PraosState = ext.header.chain_dep_state
        tip = ext.header.tip
        return [list(ext.ledger.utxo), ext.ledger.slot,
                ext.ledger.tip.encode(),
                None if tip is None else [tip.slot, tip.block_no, tip.hash,
                                          int(tip.is_ebb)],
                [dep.epoch, dep.eta, list(dep.pending)]]

    @staticmethod
    def dec_state(obj) -> ExtLedgerState:
        utxo = tuple((bytes(e[0]), int(e[1]), bytes(e[2]), int(e[3]))
                     for e in obj[0])
        led = MockLedgerState(utxo, int(obj[1]), Point.decode(obj[2]))
        tip = None if obj[3] is None else AnnTip(
            int(obj[3][0]), int(obj[3][1]), bytes(obj[3][2]),
            bool(obj[3][3]) if len(obj[3]) > 3 else False)
        dep = PraosState(int(obj[4][0]), bytes(obj[4][1]),
                         tuple(bytes(p) for p in obj[4][2]))
        return ExtLedgerState(led, HeaderState(tip, dep))

    def make_node(self, i: int, fs=None,
                  label: Optional[str] = None) -> NodeKernel:
        """Build node i's full stack; pass its previous MockFS to model a
        RESTART — ChainDB.open then recovers from the on-disk state.
        Restarts must also pass a FRESH label: peer ids derive from it,
        and reusing the old one would collide the neighbors' per-peer
        state with the dead connection's."""
        cfg, keys = self.cfg, self.keys
        protocol = Praos(self.protocol_cfg)
        ledger = MockLedger(self.genesis)
        ext_rules = ExtLedgerRules(protocol, ledger)
        fs = fs if fs is not None else MockFS()
        db = ChainDB.open(fs, ext_rules, self.enc_state, self.dec_state,
                          self.block_decode, backend=self.backend)
        mempool = Mempool(ledger,
                          lambda db=db: (db.current_ledger.ledger,
                                         db.tip_point()),
                          backend=self.backend)
        hot_key = HotKey(kes_mod.KesSignKey(cfg.kes_depth,
                                            keys[i].kes_seed))
        forging = BlockForging(
            issuer=i, can_be_leader=(i, keys[i].vrf_sk),
            forge=lambda protocol, proof, hdr, hk=hot_key:
                praos_forge_fields(protocol, hk, proof, hdr))
        btime = BlockchainTime(cfg.slot_length)
        kern = NodeKernel(db, ledger, mempool, btime, [forging],
                          label=label or f"node{i}", backend=self.backend,
                          chain_sync_window=cfg.chain_sync_window,
                          header_decode=self.header_decode_obj,
                          block_decode_obj=self.block_decode_obj,
                          tx_decode=Tx.decode)
        if cfg.network_magics is not None:
            kern.network_magic = cfg.network_magics[i]
        kern.fs = fs                      # restartable: same disk next time
        return kern

    def forge_at(self, i: int, slot: int, ext_state) -> ProtocolBlock:
        """Forge node i's empty block at `slot` on ext_state's tip (test
        helper for out-of-band blocks, e.g. clock-skew scenarios).  Node i
        must lead the slot (use f=1.0 configs)."""
        from ..chain.block import GENESIS_HASH
        from ..consensus.headers import ProtocolHeader, body_hash_of
        protocol = Praos(self.protocol_cfg)
        ticked = protocol.tick_chain_dep_state(
            ext_state.header.chain_dep_state, None, slot)
        pi = protocol.check_is_leader((i, self.keys[i].vrf_sk), slot,
                                      ticked, None)
        assert pi is not None, f"node {i} does not lead slot {slot}"
        ann = ext_state.header.tip
        prev_hash = ann.hash if ann else GENESIS_HASH
        block_no = ann.block_no + 1 if ann else 0
        hdr = ProtocolHeader(slot=slot, block_no=block_no,
                             prev_hash=prev_hash,
                             body_hash=body_hash_of(()), issuer=i)
        hot_key = HotKey(kes_mod.KesSignKey(self.cfg.kes_depth,
                                            self.keys[i].kes_seed))
        return ProtocolBlock(praos_forge_fields(protocol, hot_key, pi, hdr),
                             ())

    def forge_chain_from(self, i: int, ext_state, n: int) -> list:
        """n connected empty blocks from ext_state's tip, one per slot."""
        protocol = Praos(self.protocol_cfg)
        ledger = MockLedger(self.genesis)
        rules = ExtLedgerRules(protocol, ledger)
        out = []
        slot = (ext_state.header.tip.slot + 1
                if ext_state.header.tip else 0)
        st = ext_state
        while len(out) < n:
            blk = self.forge_at(i, slot, st)
            st = rules.tick_then_reapply(st, blk)
            out.append(blk)
            slot += 1
        return out


def run_threadnet(cfg: ThreadNetConfig) -> ThreadNetResult:
    """Run the network to n_slots and collect final chains (runTestNetwork)."""
    factory = PraosNetworkFactory(cfg)
    keys = factory.keys
    kernels: list[NodeKernel] = []
    make_node = factory.make_node

    def edges() -> list[tuple[int, int]]:
        n = cfg.n_nodes
        if cfg.topology == "mesh":
            return [(i, j) for i in range(n) for j in range(i + 1, n)]
        if cfg.topology == "ring":
            return [(i, (i + 1) % n) for i in range(n)] if n > 2 else \
                   [(0, 1)]
        if cfg.topology == "line":
            return [(i, i + 1) for i in range(n - 1)]
        raise ValueError(cfg.topology)

    result = ThreadNetResult([], [], keys)

    async def main():
        join = cfg.join_slots or [0] * cfg.n_nodes
        started: dict[int, NodeKernel] = {}
        wired: set[tuple[int, int]] = set()

        async def start_node(i: int):
            at = join[i] * cfg.slot_length
            if at > sim.now():
                await sim.sleep(at - sim.now())
            kern = make_node(i)
            kernels.append(kern)
            started[i] = kern
            kern.start()
            for a, b in edges():
                if a in started and b in started and (a, b) not in wired:
                    wired.add((a, b))
                    connect_nodes(started[a], started[b],
                                  delay=cfg.link_delay * cfg.slot_length)

        starters = [sim.spawn(start_node(i), label=f"start-{i}")
                    for i in range(cfg.n_nodes)]
        for s in starters:
            await s.wait()

        # plan tasks are supervised (polled at snapshot time below): a
        # fire-and-forget fork would swallow a failed submit/restart and
        # the run would pass on a net that never saw its planned events
        plan_tasks: list = []
        for slot, node_ix, tx_factory in cfg.tx_plan:
            async def submit(slot=slot, node_ix=node_ix,
                             tx_factory=tx_factory):
                at = slot * cfg.slot_length
                if at > sim.now():
                    await sim.sleep(at - sim.now())
                kern = started[node_ix]
                tx = tx_factory(keys, kern.chain_db.current_ledger.ledger)
                kern.mempool.try_add_txs([tx])
            plan_tasks.append(sim.spawn(submit(), label=f"tx@{slot}"))

        for slot, node_ix in cfg.restart_plan:
            async def restart(slot=slot, node_ix=node_ix):
                at = slot * cfg.slot_length
                if at > sim.now():
                    await sim.sleep(at - sim.now())
                old = started[node_ix]
                old.stop()
                fs = old.fs
                await sim.sleep(0.5 * cfg.slot_length)   # downtime
                # recover from disk, under a FRESH label: peer ids derive
                # from labels, and reusing the old one would collide the
                # neighbors' per-peer state with the dead connection's
                kern = make_node(node_ix, fs=fs,
                                 label=f"{old.label}r")
                kernels.append(kern)
                started[node_ix] = kern
                kern.start()
                for a, b in edges():
                    if node_ix in (a, b) and a in started and b in started:
                        connect_nodes(started[a], started[b],
                                      delay=cfg.link_delay
                                      * cfg.slot_length)
            plan_tasks.append(sim.spawn(restart(),
                                        label=f"restart-{node_ix}@{slot}"))

        await sim.sleep(cfg.n_slots * cfg.slot_length - sim.now()
                        + 2 * cfg.slot_length)
        for t in plan_tasks:
            try:
                if not t.done:
                    # poll() returns None for blocked AND for done-with-
                    # None; a plan task still parked at snapshot time is
                    # a planned event the net never saw — a failure
                    result.failures.append(
                        ("plan", t.label, "still blocked at snapshot"))
                else:
                    t.poll()
            except BaseException as e:
                result.failures.append(("plan", t.label, e))
        # settle: let in-flight messages drain with the clock stopped for
        # forging (no new slots matter; we just stop the world)
        for kern in started.values():
            result.chains.append(kern.chain_db.current_chain.copy())
            result.ledgers.append(kern.chain_db.current_ledger)
            for t in kern._threads:
                try:
                    t.poll()
                except sim.AsyncCancelled:
                    pass
                except BaseException as e:
                    result.failures.append((kern.label, t.label, e))
            kern.stop()

    sim.run(main(), seed=cfg.seed)
    return result


# ---------------------------------------------------------------------------
# Chaos ThreadNet — the Praos network under a seeded FaultPlan
# ---------------------------------------------------------------------------
#
# Reference shape: the io-sim fault exploration of the reference test suites
# (attenuated bearers / AbsBearerInfo in ouroboros-network-framework's sim
# tests) composed with ThreadNet's prop_general checks.  Nodes are wired
# through diffusion.py's subscription layer (NOT the static mesh), so a
# connection killed by a fault or watchdog is *suspended* by the error
# policy (demotion) and *redialled* after backoff (re-promotion) — the
# recovery loop this harness exists to exercise.

from ..network.error_policy import (          # noqa: E402  (section import)
    default_node_policies,
)
from ..node.diffusion import SimNetwork, run_sim_diffusion  # noqa: E402
from ..node.watchdog import NodeTimeLimits    # noqa: E402
from ..observe import netmetrics as _netmetrics             # noqa: E402
from ..observe.propagation import FleetTelemetry            # noqa: E402
from ..simharness import FaultPlan, FaultSpec, Partition    # noqa: E402


def chaos_error_policies(scale: float = 1.0) -> list:
    """The REAL policy set (default_node_policies) with durations scaled
    to chaos-sim time — the production 200 s/60 s windows would outlast a
    40-slot run."""
    return default_node_policies(violation=8.0 * scale,
                                 transport=4.0 * scale,
                                 unknown=6.0 * scale)


def chaos_time_limits() -> NodeTimeLimits:
    """Watchdog limits scaled to the chaos net's 1 s slots (same ratios as
    the production defaults in node/watchdog.py)."""
    # must_reply stays ~7x the expected block interval (reference ratio:
    # 135 s against ~20 s blocks) — tighter and a healthy-but-quiet
    # producer gets spuriously killed during the settle window
    return NodeTimeLimits(
        chain_sync_short=3.0, chain_sync_must_reply=20.0,
        keep_alive_timeout=3.0, block_fetch_busy=6.0,
        fetch_deadline_floor=1.5, fetch_deadline_mult=4.0,
        handshake_timeout=3.0)


@dataclass
class ChaosConfig:
    """One chaos run: a ThreadNetConfig + the hostility applied to it.

    The FaultSpec/Partition fields (not a FaultPlan instance) keep the
    config pure data — run_chaos_threadnet builds a FRESH plan per run, so
    replaying the same config replays the identical fault schedule."""
    net: ThreadNetConfig = field(default_factory=ThreadNetConfig)
    spec: FaultSpec = field(default_factory=FaultSpec)
    partitions: tuple = ()               # Partition over node labels
    base_backoff: float = 2.0
    keepalive_interval: float = 2.0
    settle_slots: int = 4
    time_limits: NodeTimeLimits = field(default_factory=chaos_time_limits)
    # slot after which per-message hostility stops (None = hostile through
    # the settle window too).  Default: faults run for the measured
    # n_slots, then the settle window is clean — the ThreadNet
    # partition-heals-then-net-converges shape, so the final-chain
    # common-prefix check judges recovery, not mid-fault luck.
    fault_until_slot: Optional[int] = -1     # -1 -> net.n_slots
    # multiplier on chaos_error_policies' suspension windows: the max
    # escalated backoff must fit inside the settle window or a peer
    # suspended in the hostile tail never rejoins before the snapshot
    error_scale: float = 1.0


@dataclass
class ChaosResult(ThreadNetResult):
    """ThreadNetResult + the observability a chaos run is judged on."""
    seed: int = 0
    fault_events: list = field(default_factory=list)   # plan.events
    workers: list = field(default_factory=list)        # SubscriptionWorkers
    race_report: Optional[object] = None   # RaceReport under explore=K
    # the merged FleetTelemetry report (ISSUE 14): adoption quantiles,
    # per-edge delivery latency, partition healing, per-peer mux bytes —
    # byte-identical (sort_keys JSON) across replays of one seed
    fleet: Optional[dict] = None

    # -- trace views ---------------------------------------------------------
    def _events(self, label: str) -> list:
        # trace_event(payload, label) records the label in SimEvent.kind
        # (the `label` field is the emitting thread's, always "user" here)
        return [e for e in self.trace if e.kind == label]

    def watchdog_events(self) -> list:
        """Every per-state timeout + the kills it caused."""
        return self._events("watchdog")

    def suspensions(self) -> list:
        """(time, worker, addr, kind, duration, fail_count) demotions."""
        return [(e.time, e.payload[0], *e.payload[2:])
                for e in self._events("subscription")
                if e.payload[1] == "suspend"]

    def demoted_then_repromoted(self) -> list:
        """Addresses that were suspended (demoted) and later redialled
        (re-promoted) by the subscription layer — the recovery loop's
        end-to-end evidence, readable from the trace alone."""
        suspended_at: dict = {}
        recovered = []
        for e in self._events("subscription"):
            worker, kind = e.payload[0], e.payload[1]
            addr = e.payload[2]
            key = (worker, addr)
            if kind == "suspend":
                suspended_at.setdefault(key, e.time)
            elif kind == "dial" and key in suspended_at \
                    and e.time > suspended_at[key] and addr not in recovered:
                recovered.append(addr)
        return recovered

    def trace_tail(self, n: int = 40) -> str:
        """The reproduction blurb chaos test failures print: seed + the
        last n sim-trace events."""
        tail = "\n".join(repr(e) for e in self.trace[-n:])
        return (f"fault plan seed={self.seed} — rerun with this seed to "
                f"reproduce; sim trace tail:\n{tail}")


# TVar labels whose races are tolerated during chaos exploration, with
# the justification reviewable next to the suppression (the ouro-lint
# baseline discipline applied to dynamic findings).  Patterns are
# fnmatch globs over the TVar label.
#
# Everything here is an ORDER-INSENSITIVE access pattern: in the
# cooperative runtime a sync block is atomic regardless of schedule, so
# an unordered pair is only a bug when the two orders produce different
# outcomes.  Monotone counters, one-way latches and re-validated peeks
# commute; anything NOT matching these globs blocks the exploration
# gate (tests/test_races.py).
CHAOS_RACE_TOLERATED = {
    "current-slot": "monotonic slot counter: readers peek the current "
                    "slot and tolerate being one tick stale by design "
                    "(the reference reads the slot clock non-atomically "
                    "too); torn reads are impossible in the cooperative "
                    "sim",
    "*-fetch-wakeup": "edge-triggered poke counter: concurrent pokes "
                      "coalesce and the fetch-logic loop re-reads the "
                      "full decision state after every wake, so a lost "
                      "increment only costs one extra (idempotent) "
                      "decision pass",
    "*-chain-version": "monotonic version counter poked from the ChainDB "
                       "writer thread; followers re-validate against the "
                       "real chain after waking, so stale peeks are "
                       "self-healing",
    "mempool-version": "same monotone version-counter shape as "
                       "chain-version: watchers re-snapshot the mempool "
                       "after every wake",
    "chaindb-add-queue": "wake counter for the single add-block writer "
                         "thread: the runner drains the whole queue "
                         "after every wake and re-checks before "
                         "blocking, so a coalesced increment is "
                         "absorbed by the drain loop",
    "fetch-req-*": "block_fetch._queued's documented non-transactional "
                   "peek of the per-peer request queue: the decision "
                   "loop re-runs on every fetch-wakeup poke, so a "
                   "stale snapshot costs one extra decision pass, "
                   "never a lost request",
    "*.closed": "mux teardown latch: one-way False->True flips commute "
                "(concurrent stop() calls are idempotent) and readers "
                "racing the flip either see open and get woken by the "
                "notify, or see closed",
    "*.chanver": "mux ingress version counter: monotone, bumped per "
                 "delivered SDU; channel readers re-check decodability "
                 "under STM after every wake",
}


def _chaos_setup(cfg: ChaosConfig):
    """Fresh per-run state + the program coroutine factory.  Exploration
    re-runs the SAME config under perturbed schedules, and every schedule
    must get its own kernels/plan/result — sim programs are not
    re-runnable."""
    factory = PraosNetworkFactory(cfg.net)
    net = cfg.net
    until_slot = net.n_slots if cfg.fault_until_slot == -1 \
        else cfg.fault_until_slot
    plan = FaultPlan(net.seed, cfg.spec, cfg.partitions,
                     until=None if until_slot is None
                     else until_slot * net.slot_length)
    result = ChaosResult([], [], factory.keys, seed=net.seed)

    def neighbors(i: int) -> list:
        if net.topology == "mesh":
            return [j for j in range(net.n_nodes) if j != i]
        if net.topology == "ring":
            return sorted({(i - 1) % net.n_nodes, (i + 1) % net.n_nodes}
                          - {i})
        if net.topology == "line":
            return [j for j in (i - 1, i + 1) if 0 <= j < net.n_nodes]
        raise ValueError(net.topology)

    async def main():
        # fresh fleet-accounting scope: MuxIO totals born in THIS run are
        # what the fleet report folds, so two replays of one seed report
        # identical per-peer bytes
        _netmetrics.reset_run_scope()
        fleet = FleetTelemetry(partitions=cfg.partitions)
        network = SimNetwork(
            link_delay=net.link_delay * net.slot_length,
            fault_plan=plan)
        kernels = [factory.make_node(i) for i in range(net.n_nodes)]
        # every address must be listening before any worker dials, or the
        # startup order would masquerade as connection failures
        for i, kern in enumerate(kernels):
            kern.propagation = fleet.tracker(kern.label)
            network.listen(f"addr{i}", kern)
        worker_threads = []
        for i, kern in enumerate(kernels):
            kern.time_limits = cfg.time_limits
            kern.keepalive_interval = cfg.keepalive_interval
            kern.start()
            d = run_sim_diffusion(
                kern, network, f"addr{i}",
                ip_targets=[f"addr{j}" for j in neighbors(i)],
                valency=len(neighbors(i)),
                error_policies=chaos_error_policies(cfg.error_scale),
                base_backoff=cfg.base_backoff, seed=net.seed)
            result.workers.extend(d.workers)
            worker_threads.extend(d.threads)
        await sim.sleep(net.n_slots * net.slot_length
                        + cfg.settle_slots * net.slot_length)
        for kern in kernels:
            result.chains.append(kern.chain_db.current_chain.copy())
            result.ledgers.append(kern.chain_db.current_ledger)
        for t in worker_threads:
            try:
                t.poll()
            except sim.AsyncCancelled:
                pass
            except BaseException as e:   # a THROW verdict or worker bug
                result.failures.append(("subscription", t.label, e))
        result.fleet = fleet.report()
        for kern in kernels:
            kern.stop()

    return plan, result, main


def run_chaos_threadnet(cfg: ChaosConfig, explore: int = 0,
                        tolerate=None) -> ChaosResult:
    """Run the Praos network under cfg's FaultPlan, wired through the
    subscription/diffusion layer so faulted peers are demoted (error-policy
    suspension) and re-promoted (redial) instead of staying dead.

    Deterministic end to end: the plan, the scheduler, the subscription
    jitter and every watchdog all derive from cfg.net.seed, so two runs of
    the same config produce byte-identical sim traces.

    explore=K additionally attaches the happens-before race detector
    (simharness/race.py) to the measured run — which IS exploration
    schedule 0, the production FIFO schedule — and re-runs the same
    config under K-1 further seeded schedule perturbations, returning
    the RaceReport on ``result.race_report``.  `tolerate` overrides the
    default CHAOS_RACE_TOLERATED label globs (each documented above)."""
    plan, result, main = _chaos_setup(cfg)
    det0 = sim.RaceDetector(schedule_index=0) if explore > 0 else None
    measured = sim.Sim(seed=cfg.net.seed, collect_trace=True, race=det0)
    try:
        measured.run(main())
    except BaseException as e:
        # crash-proof evidence (ISSUE 9): when the flight recorder is
        # armed, a failing chaos run dumps the sim trace tail alongside
        # whatever spans/metric deltas the ring already holds.  All
        # timestamps are VIRTUAL sim time, so the same seed dumps
        # byte-identical files on every replay of the failure.
        from ..observe import flight as _flight
        if _flight.FLIGHT.armed:
            # the sim has already exited (its runtime is detached), so
            # each event carries its OWN virtual time — stamping with
            # monotonic_now() here would leak wall clock into the dump
            for ev in getattr(measured, "_trace", [])[-256:]:
                _flight.FLIGHT.note(ev, t=ev.time)
            _flight.FLIGHT.dump_on_failure(
                f"chaos threadnet seed={cfg.net.seed}: {e!r}")
        raise
    result.trace = measured._trace
    result.fault_events = list(plan.events)
    if explore > 0:
        def make_program():
            _plan, _result, fresh_main = _chaos_setup(cfg)
            return fresh_main()
        controller = sim.ScheduleController(
            make_program, k=explore, seed=cfg.net.seed,
            tolerate=tuple(CHAOS_RACE_TOLERATED
                           if tolerate is None else tolerate))
        # the measured FIFO run doubles as schedule 0: re-running it
        # would be byte-identical wasted wall-clock
        result.race_report = controller.explore(pre_collected=[det0],
                                                start=1)
    return result
