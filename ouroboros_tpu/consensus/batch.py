"""Batched window validation — the point of the framework.

The reference validates strictly sequentially (`ledgerDbPushMany` fold,
LedgerDB/InMemory.hs:429-449; per-header validate in the ChainSync client,
MiniProtocol/ChainSync/Client.hs:792).  Per SURVEY.md §2 "The TPU-relevant
gap", every VRF/KES/Ed25519 proof in a window of headers/blocks is
*independent* once the cheap sequential inputs (nonces, ticked states) are
derived.  This module does the split:

  pass 1 (host, sequential, cheap)  envelope checks + tick + reupdate fold,
                                    collecting proof obligations per header
  pass 2 (device, one batch)        all proofs verified together
  result                            valid prefix + states, or first failure

This is the `lax.scan` (sequential state) + vmapped-verify (parallel proofs)
decomposition of SURVEY.md §7 P3, with the scan on host because chain state
is pointer-heavy, and the FLOP-heavy group math on device.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from ..crypto.backend import CryptoBackend, default_backend
from .header_validation import (
    HeaderError, HeaderState, validate_envelope, revalidate_header,
)
from .ledger import (
    ExtLedgerRules, ExtLedgerState, LedgerError, OutsideForecastRange,
)
from .protocol import ConsensusProtocol, _verify_mixed


@dataclass
class BatchValidationResult:
    """Valid prefix of the window.

    states[i] is the state *after* headers[i]; len(states) == n_valid.
    error explains why headers[n_valid] failed (None if all valid).
    """
    states: list
    n_valid: int
    error: Optional[Exception]

    @property
    def all_valid(self) -> bool:
        return self.error is None

    @property
    def final_state(self):
        return self.states[-1] if self.states else None


def _seq_header_pass(protocol: ConsensusProtocol, headers: Sequence[Any],
                     header_state: HeaderState,
                     ledger_view_for: Callable[[int, Any], Any]):
    """Pass 1 (host, sequential, cheap): envelope + tick + reupdate fold,
    collecting proof obligations per header.  Shared by the direct
    batched path below and the VerifyService-coalesced path
    (crypto/batching.validate_headers_coalesced) so the two can never
    drift.  Returns (states, proofs, owner, seq_error, n_seq)."""
    states: list[HeaderState] = []
    proofs: list = []
    owner: list[int] = []          # proofs[j] belongs to headers[owner[j]]
    seq_error: Optional[Exception] = None
    n_seq = 0                      # headers that passed the sequential pass

    st = header_state
    for i, h in enumerate(headers):
        try:
            view = ledger_view_for(i, h)
            validate_envelope(h, st, protocol)
            ticked = protocol.tick_chain_dep_state(
                st.chain_dep_state, view, h.slot)
            protocol.sequential_checks(ticked, h, view)
            reqs = protocol.extract_proofs(ticked, h, view)
            st = revalidate_header(protocol, view, h, st)
        except OutsideForecastRange as e:
            # not a validation failure: the caller must wait for the chain
            # to advance (ChainSync forecast-horizon waiting)
            seq_error = e
            break
        except Exception as e:
            seq_error = e if isinstance(e, HeaderError) else HeaderError(str(e))
            break
        proofs.extend(reqs)
        owner.extend([i] * len(reqs))
        states.append(st)
        n_seq += 1
    return states, proofs, owner, seq_error, n_seq


def _merge_header_verdicts(headers: Sequence[Any], states: list,
                           proofs: list, owner: list, ok: Sequence,
                           seq_error: Optional[Exception],
                           n_seq: int) -> BatchValidationResult:
    """Fold the proof verdict vector back into the valid prefix (the
    other half shared with the coalesced path)."""
    first_bad = n_seq
    bad_proof: Optional[int] = None
    for j, good in enumerate(ok):
        if not good and owner[j] < first_bad:
            first_bad, bad_proof = owner[j], j

    if bad_proof is not None:
        err: Optional[Exception] = HeaderError(
            f"proof {type(proofs[bad_proof]).__name__} failed for header "
            f"index {first_bad} (slot {headers[first_bad].slot})")
    else:
        err = seq_error
    return BatchValidationResult(states[:first_bad], first_bad, err)


def validate_headers_batched(
        protocol: ConsensusProtocol,
        headers: Sequence[Any],
        header_state: HeaderState,
        ledger_view_for: Callable[[int, Any], Any],
        backend: Optional[CryptoBackend] = None) -> BatchValidationResult:
    """Validate a window of headers with one device batch for all proofs.

    Equivalent to folding validate_header, but ~window-size× fewer device
    round trips.  `ledger_view_for(i, header)` supplies the ledger view for
    header i (from forecasts during sync, or the tip view during replay).
    """
    backend = backend or default_backend()
    protocol.prefetch_window(headers, backend)
    states, proofs, owner, seq_error, n_seq = _seq_header_pass(
        protocol, headers, header_state, ledger_view_for)

    # one device batch for every proof in the window
    ok = _verify_mixed(backend, proofs) if proofs else []
    return _merge_header_verdicts(headers, states, proofs, owner, ok,
                                  seq_error, n_seq)


def _seq_block_step(protocol: ConsensusProtocol, ledger, st: ExtLedgerState,
                    b: Any) -> tuple[list, ExtLedgerState]:
    """One block of the sequential pass: envelope + cheap checks + proof
    extraction + optimistic reapply.  Shared by the synchronous and the
    pipelined drivers.  Raises on any sequential failure."""
    header = getattr(b, "header", b)
    view = ledger.forecast_view(st.ledger, header.slot)
    validate_envelope(header, st.header, protocol)
    ticked_dep = protocol.tick_chain_dep_state(
        st.header.chain_dep_state, view, header.slot)
    protocol.sequential_checks(ticked_dep, header, view)
    ticked_ledger = ledger.tick(st.ledger, b.slot)
    ledger.sequential_checks(ticked_ledger, b)
    reqs = (protocol.extract_proofs(ticked_dep, header, view)
            + ledger.extract_proofs(ticked_ledger, b))
    return reqs, ExtLedgerState(
        ledger.reapply_block(ticked_ledger, b),
        revalidate_header(protocol, view, header, st.header))


def validate_blocks_batched(
        ext_rules: ExtLedgerRules,
        blocks: Sequence[Any],
        ext_state: ExtLedgerState,
        backend: Optional[CryptoBackend] = None) -> BatchValidationResult:
    """Full-block analog: header proofs + body witness proofs (the
    reference's BBODY Ed25519 multi-verify) in one batch.  The replay/
    candidate-validation hot path (ChainSel.hs:775-808, OnDisk.hs:277),
    batched."""
    backend = backend or default_backend()
    protocol, ledger = ext_rules.protocol, ext_rules.ledger
    protocol.prefetch_window([getattr(b, "header", b) for b in blocks],
                             backend)
    states: list[ExtLedgerState] = []
    proofs: list = []
    owner: list[int] = []
    seq_error: Optional[Exception] = None
    n_seq = 0

    st = ext_state
    for i, b in enumerate(blocks):
        try:
            reqs, st = _seq_block_step(protocol, ledger, st, b)
        except OutsideForecastRange as e:
            # not a validation failure: the caller must retry once the
            # chain advances (the reference never marks such a block
            # invalid — same special case as validate_headers_batched)
            seq_error = e
            break
        except Exception as e:
            seq_error = (e if isinstance(e, (HeaderError, LedgerError))
                         else LedgerError(str(e)))
            break
        proofs.extend(reqs)
        owner.extend([i] * len(reqs))
        states.append(st)
        n_seq += 1

    ok = _verify_mixed(backend, proofs) if proofs else []
    first_bad = n_seq
    bad_proof = None
    for j, good in enumerate(ok):
        if not good and owner[j] < first_bad:
            first_bad, bad_proof = owner[j], j

    if bad_proof is not None:
        err: Optional[Exception] = LedgerError(
            f"proof {type(proofs[bad_proof]).__name__} failed for block "
            f"index {first_bad} (slot {blocks[first_bad].slot})")
    else:
        err = seq_error
    return BatchValidationResult(states[:first_bad], first_bad, err)


@dataclass
class ReplayResult:
    """Outcome of a pipelined replay: final state only (a mainnet-scale
    replay cannot keep per-block states), global valid-block count, first
    error.

    On OutsideForecastRange — retry-later, not a validation failure —
    final_state is the state after the valid prefix, so the caller can
    resume the replay from there once the chain advances; on a genuine
    validation failure final_state is None."""
    final_state: Any
    n_valid: int
    error: Optional[Exception]

    @property
    def all_valid(self) -> bool:
        return self.error is None


def replay_blocks_pipelined(
        ext_rules: ExtLedgerRules,
        blocks,
        ext_state: ExtLedgerState,
        backend: Optional[CryptoBackend] = None,
        window: int = 512,
        total_blocks=None,
        tracker=None,
        on_window=None) -> ReplayResult:
    """Producer/consumer-pipelined replay: a background producer thread
    runs window w+1's sequential pass, request packing and async submit
    WHILE the caller thread blocks on window w's device results — host
    and device time genuinely overlap instead of adding (the r5 version
    interleaved both halves on one thread, so they could not).  Window
    w's device call also computes the VRF betas window w+2's sequential
    pass will need, installed at drain time; the producer's permit gate
    keeps it exactly within that beta-carry distance
    (consensus/pipeline.py has the protocol).

    `blocks` may be any iterable — windows are consumed with a bounded
    look-ahead, so a mainnet-scale replay streams without buffering the
    chain.

    The sequential pass advances optimistically via reapply (no crypto);
    if a window's proof batch later fails, the replay aborts with the
    failing block's global index — the db-analyser/LgrDB replay semantics
    (OnDisk.hs:277), where any invalid block invalidates the run.

    The two in-flight windows are double-buffered on device: each
    window's input arrays are donated to its fused program
    (JaxBackend._window_composite), so on the warm path XLA reuses the
    previous window's buffers instead of allocating fresh ones, and the
    cross-window precomputation cache (crypto/precompute.py) means a
    warm window ships no per-key decompression or table-build work at
    all — only the ladders themselves.  On backends with
    `supports_window_fold` the drain is a device-folded WindowVerdict
    (one scalar pair) instead of a per-proof vector.

    A ShardedJaxBackend (parallel/sharded_verify.py) rides this same
    driver unchanged (ISSUE 11): the producer pads each window to the
    per-shard bucket shape, the window composite shard_maps the packed
    cores over the mesh, and the fold verdict's min-reduction already
    spans shards — first-error-wins is preserved because the failing
    request INDEX, not a per-shard flag, is what crosses the link.
    `bench.py --mesh N` and the multichip dryrun are the measured
    entry points.

    `on_window(state, n_done, point)` fires after each window is FULLY
    verified — the streaming engine's snapshot seam (identical contract
    on the threaded and the synchronous fallback drivers); `tracker`
    shares one pipeline ProgressTracker across stages.

    Falls back to the synchronous windowed driver on backends without
    submit_window."""
    import itertools

    from ..chain.block import Point

    backend = backend or default_backend()
    submit = getattr(backend, "submit_window", None)

    if submit is None:
        block_iter = iter(blocks)
        st = ext_state
        done = 0
        while True:
            w = list(itertools.islice(block_iter, window))
            if not w:
                break
            # the synchronous validate IS this driver's in-flight
            # window: bracketing it keeps the shared tracker honest —
            # the streaming engine's prefetch thread genuinely overlaps
            # it (disk_hidden accrues), and the live progress gauges
            # advance per window instead of freezing for the whole run
            if tracker is not None:
                tracker.window_submitted()
            n_ok = 0
            try:
                res = validate_blocks_batched(ext_rules, w, st,
                                              backend=backend)
                n_ok = res.n_valid
            finally:
                if tracker is not None:
                    tracker.window_drained(n_ok)
            done += res.n_valid
            # hook parity with the threaded driver: a window that died
            # on a PROOF failure yields no checkpoint (the threaded
            # drain cannot attribute a partial prefix), while a
            # retry-later horizon wait still checkpoints its verified
            # prefix on both drivers
            if on_window is not None and res.n_valid \
                    and (res.all_valid
                         or isinstance(res.error, OutsideForecastRange)):
                last = getattr(w[res.n_valid - 1], "header",
                               w[res.n_valid - 1])
                on_window(res.states[-1], done, Point(last.slot,
                                                      last.hash))
            if not res.all_valid:
                resume = (res.final_state or st
                          if isinstance(res.error, OutsideForecastRange)
                          else None)
                return ReplayResult(resume, done, res.error)
            st = res.final_state
        return ReplayResult(st, done, None)

    from .pipeline import replay_threaded
    return replay_threaded(ext_rules, blocks, ext_state, backend,
                           window=window, total_blocks=total_blocks,
                           tracker=tracker,
                           on_window=on_window)  # total from len() too
