"""PBFT: delegate signatures with a windowed per-signer threshold.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Protocol/PBFT.hs:226-302
(update = verify issuer is a genesis delegate, append signer to a window of
the last `windowSize` signers, reject when one signer exceeds
`threshold × windowSize`), window state in PBFT/State.hs.  The signature
check is the batchable proof; the window arithmetic is the cheap
sequential check.
"""
from __future__ import annotations

from ...crypto import ed25519_ref
from ...crypto.backend import Ed25519Req
from ..protocol import ConsensusProtocol, ProtocolError

SIG_FIELD = "pbft_sig"


class PBft(ConsensusProtocol):
    """Config: delegate vks, signature threshold, window size.

    ChainDepState = tuple of recent issuer indices (newest last), ≤ window.
    """

    def __init__(self, delegate_vks: list[bytes], threshold: float = 0.22,
                 window: int = 100, k: int = 5):
        self.delegate_vks = list(delegate_vks)
        self.threshold = threshold
        self.window = window
        self.security_param = k

    @property
    def n(self) -> int:
        return len(self.delegate_vks)

    def slot_leader(self, slot: int) -> int:
        return slot % self.n

    def _limit(self) -> int:
        # strictly-greater-than comparison in the reference (PBFT.hs:279)
        return int(self.threshold * self.window)

    # -- state ----------------------------------------------------------------
    def initial_chain_dep_state(self):
        return ()

    def reupdate_chain_dep_state(self, ticked, header, ledger_view):
        signers = ticked + (header.issuer,)
        return signers[-self.window:]

    # -- checks ---------------------------------------------------------------
    def sequential_checks(self, ticked, header, ledger_view):
        if not (0 <= header.issuer < self.n):
            raise ProtocolError(
                f"PBFT: issuer {header.issuer} is not a genesis delegate")
        if header.get(SIG_FIELD) is None:
            raise ProtocolError("PBFT: header missing signature")
        signers = (ticked + (header.issuer,))[-self.window:]
        count = sum(1 for s in signers if s == header.issuer)
        if count > max(1, self._limit()):
            raise ProtocolError(
                f"PBFT: signer {header.issuer} signed {count} of last "
                f"{len(signers)} blocks, exceeds threshold "
                f"{self.threshold}×{self.window}")

    def extract_proofs(self, ticked, header, ledger_view):
        sig = header.get(SIG_FIELD)
        if sig is None:
            return []
        return [Ed25519Req(vk=self.delegate_vks[header.issuer],
                           msg=header.bytes_dropping(SIG_FIELD), sig=sig)]

    # -- leadership -----------------------------------------------------------
    def check_is_leader(self, can_be_leader, slot, ticked, ledger_view):
        return True if self.slot_leader(slot) == can_be_leader else None


def pbft_sign_header(sk: bytes, header):
    sig = ed25519_ref.sign(sk, header.bytes_dropping(SIG_FIELD))
    return header.with_fields(**{SIG_FIELD: sig})
