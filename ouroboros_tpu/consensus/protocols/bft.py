"""BFT: round-robin leadership with Ed25519 header signatures.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Protocol/BFT.hs —
leader of slot s is node (s mod n); every header carries a DSIGN signature
by its slot's leader; ChainDepState is trivial.
"""
from __future__ import annotations

from ...crypto import ed25519_ref
from ...crypto.backend import Ed25519Req
from ..protocol import ConsensusProtocol, ProtocolError

SIG_FIELD = "bft_sig"


class Bft(ConsensusProtocol):
    """Config = ordered list of node verification keys."""

    def __init__(self, node_vks: list[bytes], k: int = 5):
        self.node_vks = list(node_vks)
        self.security_param = k

    @property
    def n(self) -> int:
        return len(self.node_vks)

    def slot_leader(self, slot: int) -> int:
        return slot % self.n

    # -- state ----------------------------------------------------------------
    def initial_chain_dep_state(self):
        return ()

    def reupdate_chain_dep_state(self, ticked, header, ledger_view):
        return ()

    # -- checks ---------------------------------------------------------------
    def sequential_checks(self, ticked, header, ledger_view):
        expected = self.slot_leader(header.slot)
        if header.issuer != expected:
            raise ProtocolError(
                f"BFT: slot {header.slot} led by node {expected}, "
                f"header issued by {header.issuer}")
        if header.get(SIG_FIELD) is None:
            raise ProtocolError("BFT: header missing signature")

    def extract_proofs(self, ticked, header, ledger_view):
        sig = header.get(SIG_FIELD)
        if sig is None:
            return []
        return [Ed25519Req(vk=self.node_vks[self.slot_leader(header.slot)],
                           msg=header.bytes_dropping(SIG_FIELD), sig=sig)]

    # -- leadership -----------------------------------------------------------
    def check_is_leader(self, can_be_leader, slot, ticked, ledger_view):
        """can_be_leader = our node index (BftCanBeLeader analog)."""
        return True if self.slot_leader(slot) == can_be_leader else None


def bft_sign_header(sk: bytes, header):
    """Attach the BFT signature (forging side)."""
    sig = ed25519_ref.sign(sk, header.bytes_dropping(SIG_FIELD))
    return header.with_fields(**{SIG_FIELD: sig})
