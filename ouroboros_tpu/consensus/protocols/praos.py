"""Mock Praos: VRF leader election + KES header signatures + epoch nonces.

Reference: ouroboros-consensus-mock/src/Ouroboros/Consensus/Mock/Protocol/
Praos.hs:60-126 (PraosFields {praosCreator, praosRho (VRF cert), praosY,
praosSignature (KES)}; leader iff VRF output below a stake-scaled threshold
φ_f(σ) = 1 − (1−f)^σ; epoch nonce η evolved from the VRF outputs of the
previous epoch).  The KES/VRF verifications are the batched proofs
(SURVEY.md §2 gap); nonce evolution and the threshold comparison are the
cheap sequential pass.

HotKey evolution mirrors ouroboros-consensus-shelley/src/Ouroboros/
Consensus/Shelley/Protocol/HotKey.hs:48-149.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, replace
from typing import Optional, Sequence

from ...crypto import kes as kes_mod, vrf_ref
from ...crypto.backend import KesReq, VrfReq
from ..protocol import ConsensusProtocol, ProtocolError

VRF_FIELD = "praos_rho"
KES_FIELD = "praos_kes_sig"


@dataclass(frozen=True)
class PraosNode:
    """Registered keys + stake for one node (the mock stake distribution)."""
    vrf_vk: bytes
    kes_vk: bytes
    stake: int


@dataclass(frozen=True)
class PraosConfig:
    nodes: tuple
    k: int = 5
    f: float = 0.5                   # active slot coefficient
    epoch_length: int = 50
    kes_depth: int = 7               # Sum7 — 128 periods
    slots_per_kes_period: int = 10

    @property
    def total_stake(self) -> int:
        return sum(n.stake for n in self.nodes)


@dataclass(frozen=True)
class PraosState:
    """ChainDepState: current epoch, its nonce, and the VRF outputs
    accumulated toward the next nonce."""
    epoch: int
    eta: bytes
    pending: tuple                   # β values contributed this epoch

    @classmethod
    def genesis(cls) -> "PraosState":
        return cls(0, hashlib.blake2b(b"praos-eta0", digest_size=32).digest(),
                   ())


def _phi(f: float, stake_frac: float) -> float:
    """Leader probability φ_f(σ) = 1 − (1−f)^σ — independent aggregation
    property of Praos (Mock/Protocol/Praos.hs leader check)."""
    return 1.0 - (1.0 - f) ** stake_frac


def _leader_value(beta: bytes) -> int:
    return int.from_bytes(beta[:32], "big")


def _alpha(eta: bytes, slot: int) -> bytes:
    """VRF input for a slot: H(η ‖ slot)."""
    return hashlib.blake2b(eta + slot.to_bytes(8, "big"),
                           digest_size=32).digest()


class Praos(ConsensusProtocol):
    def __init__(self, config: PraosConfig):
        self.config = config
        self.security_param = config.k
        from ...crypto.backend import GLOBAL_BETA_CACHE
        self._betas = GLOBAL_BETA_CACHE

    # -- epochs ---------------------------------------------------------------
    def epoch_of(self, slot: int) -> int:
        return slot // self.config.epoch_length

    def initial_chain_dep_state(self) -> PraosState:
        return PraosState.genesis()

    def tick_chain_dep_state(self, state: PraosState, ledger_view,
                             slot: int) -> PraosState:
        """Cross epoch boundaries: fold pending β values into the next η."""
        target = self.epoch_of(slot)
        while state.epoch < target:
            h = hashlib.blake2b(digest_size=32)
            h.update(state.eta)
            h.update((state.epoch + 1).to_bytes(8, "big"))
            for beta in state.pending:
                h.update(beta)
            state = PraosState(state.epoch + 1, h.digest(), ())
        return state

    def reupdate_chain_dep_state(self, ticked: PraosState, header,
                                 ledger_view) -> PraosState:
        beta = self._betas.get(header.get(VRF_FIELD))
        return replace(ticked, pending=ticked.pending + (beta[:32],))

    def vrf_proofs_of(self, headers) -> list:
        proofs = [h.get(VRF_FIELD) for h in headers]
        return [p for p in proofs if p is not None]

    # -- validation -----------------------------------------------------------
    def threshold(self, issuer: int) -> int:
        node = self.config.nodes[issuer]
        frac = node.stake / self.config.total_stake
        return int(_phi(self.config.f, frac) * float(1 << 256))

    def kes_period_of(self, slot: int) -> int:
        return slot // self.config.slots_per_kes_period

    def sequential_checks(self, ticked: PraosState, header, ledger_view):
        cfg = self.config
        if not (0 <= header.issuer < len(cfg.nodes)):
            raise ProtocolError(f"Praos: unknown issuer {header.issuer}")
        pi = header.get(VRF_FIELD)
        sig = header.get(KES_FIELD)
        if pi is None or sig is None:
            raise ProtocolError("Praos: header missing VRF proof or KES sig")
        try:
            beta = self._betas.get(pi)
        except Exception as e:
            raise ProtocolError(f"Praos: malformed VRF proof: {e}") from e
        if _leader_value(beta) >= self.threshold(header.issuer):
            raise ProtocolError(
                f"Praos: issuer {header.issuer} VRF output above stake "
                f"threshold at slot {header.slot} — not a slot leader")
        period = self.kes_period_of(header.slot)
        if period >= kes_mod.total_periods(cfg.kes_depth):
            raise ProtocolError(
                f"Praos: KES period {period} beyond key lifetime")

    def extract_proofs(self, ticked: PraosState, header, ledger_view):
        cfg = self.config
        node = cfg.nodes[header.issuer]
        pi = header.get(VRF_FIELD)
        sig = header.get(KES_FIELD)
        if pi is None or sig is None:
            return []
        return [
            VrfReq(vk=node.vrf_vk,
                   alpha=_alpha(ticked.eta, header.slot), proof=pi),
            KesReq(depth=cfg.kes_depth, vk=node.kes_vk,
                   period=self.kes_period_of(header.slot),
                   msg=header.bytes_dropping(KES_FIELD), sig_bytes=sig),
        ]

    # -- leadership -----------------------------------------------------------
    def check_is_leader(self, can_be_leader, slot: int, ticked: PraosState,
                        ledger_view) -> Optional[bytes]:
        """can_be_leader = (issuer_index, vrf_sk).  Returns the VRF proof π
        as the IsLeader evidence (praosRho analog)."""
        issuer, vrf_sk = can_be_leader
        pi = vrf_ref.prove(vrf_sk, _alpha(ticked.eta, slot))
        beta = vrf_ref.proof_to_hash(pi)
        if _leader_value(beta) < self.threshold(issuer):
            return pi
        return None


class HotKey:
    """Evolving KES signing key with period tracking (HotKey.hs:48-149)."""

    def __init__(self, key: kes_mod.KesSignKey):
        self.key = key

    @property
    def period(self) -> int:
        return self.key.period

    def sign_at(self, period: int, msg: bytes) -> bytes:
        """Evolve forward to `period` (forward-secure: never backwards) and
        sign."""
        if period < self.key.period:
            raise ValueError(
                f"KES key already evolved past period {period} "
                f"(at {self.key.period})")
        while self.key.period < period:
            self.key.evolve()
        return self.key.sign(msg).to_bytes()


def praos_forge_fields(protocol: Praos, hot_key: HotKey, is_leader_pi: bytes,
                       header):
    """Attach PraosFields: VRF proof first, then the KES signature over the
    header including the proof (Mock/Protocol/Praos.hs forgePraosFields)."""
    h1 = header.with_fields(**{VRF_FIELD: is_leader_pi})
    period = protocol.kes_period_of(header.slot)
    sig = hot_key.sign_at(period, h1.bytes_dropping(KES_FIELD))
    return h1.with_fields(**{KES_FIELD: sig})
