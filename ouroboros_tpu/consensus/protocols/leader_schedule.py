"""LeaderSchedule + ModChainSel — protocol combinators for tests.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Protocol/
LeaderSchedule.hs (WithLeaderSchedule: a static slot -> [node] map replaces
the underlying protocol's leader election, so test cases are inspectable and
shrinkable) and ModChainSel.hs (ModChainSel: swap the SelectView /
chain-ordering of an underlying protocol, delegating everything else).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Optional, Sequence

from ..protocol import ConsensusProtocol, ProtocolError


@dataclass(frozen=True)
class LeaderSchedule:
    """Slot -> list of leader node ids (LeaderSchedule.hs newtype)."""
    slots: Mapping[int, Sequence[int]]

    def leaders_of(self, slot: int) -> Sequence[int]:
        if slot not in self.slots:
            raise ProtocolError(f"LeaderSchedule: missing slot {slot}")
        return self.slots[slot]

    def slots_for(self, node_id: int) -> set:
        """The slots a given node leads (leaderScheduleFor)."""
        return {s for s, ls in self.slots.items() if node_id in ls}

    def merge(self, other: "LeaderSchedule") -> "LeaderSchedule":
        """Semigroup append: union of per-slot leader lists, left-biased
        dedup (LeaderSchedule.hs Semigroup instance)."""
        out = {s: list(ls) for s, ls in self.slots.items()}
        for s, rs in other.slots.items():
            ls = out.setdefault(s, [])
            ls.extend(n for n in rs if n not in ls)
        return LeaderSchedule(out)


class WithLeaderSchedule(ConsensusProtocol):
    """Extension of protocol `p` by a static leader schedule: leadership is
    read off the schedule; chain-dep state becomes trivial; chain selection
    still delegates to `p` (LeaderSchedule.hs ConsensusProtocol instance)."""

    def __init__(self, inner: ConsensusProtocol, schedule: LeaderSchedule,
                 node_id: int):
        self.inner = inner
        self.schedule = schedule
        self.node_id = node_id
        self.security_param = inner.security_param
        self.accepts_ebb = getattr(inner, "accepts_ebb", False)

    def initial_chain_dep_state(self):
        return ()

    def tick_chain_dep_state(self, state, ledger_view, slot):
        return ()

    def update_chain_dep_state(self, ticked, header, ledger_view,
                               backend=None):
        return ()

    def reupdate_chain_dep_state(self, ticked, header, ledger_view):
        return ()

    def check_is_leader(self, can_be_leader, slot, ticked, ledger_view):
        return () if self.node_id in self.schedule.leaders_of(slot) else None

    def select_view(self, header):
        return self.inner.select_view(header)

    def prefer_candidate(self, ours, candidate):
        return self.inner.prefer_candidate(ours, candidate)


class ModChainSel(ConsensusProtocol):
    """Swap chain selection of an underlying protocol: `view` projects a
    header to the new SelectView; everything else delegates
    (ModChainSel.hs)."""

    def __init__(self, inner: ConsensusProtocol,
                 view: Callable[[Any], Any],
                 prefer: Optional[Callable[[Any, Any], bool]] = None):
        self.inner = inner
        self.view = view
        self.prefer = prefer
        self.security_param = inner.security_param
        self.accepts_ebb = getattr(inner, "accepts_ebb", False)

    def initial_chain_dep_state(self):
        return self.inner.initial_chain_dep_state()

    def tick_chain_dep_state(self, state, ledger_view, slot):
        return self.inner.tick_chain_dep_state(state, ledger_view, slot)

    def update_chain_dep_state(self, ticked, header, ledger_view,
                               backend=None):
        return self.inner.update_chain_dep_state(ticked, header, ledger_view,
                                                 backend=backend)

    def reupdate_chain_dep_state(self, ticked, header, ledger_view):
        return self.inner.reupdate_chain_dep_state(ticked, header,
                                                   ledger_view)

    def sequential_checks(self, ticked, header, ledger_view):
        return self.inner.sequential_checks(ticked, header, ledger_view)

    def extract_proofs(self, ticked, header, ledger_view):
        return self.inner.extract_proofs(ticked, header, ledger_view)

    def check_is_leader(self, can_be_leader, slot, ticked, ledger_view):
        return self.inner.check_is_leader(can_be_leader, slot, ticked,
                                          ledger_view)

    def select_view(self, header):
        return self.view(header)

    def prefer_candidate(self, ours, candidate):
        if self.prefer is not None:
            return self.prefer(ours, candidate)
        return candidate > ours
