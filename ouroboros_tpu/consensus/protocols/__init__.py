"""Protocol instantiations: BFT, PBFT, mock Praos, plus the LeaderSchedule
and ModChainSel combinators.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Protocol/
{BFT,PBFT,LeaderSchedule,ModChainSel}.hs and ouroboros-consensus-mock/src/
Ouroboros/Consensus/Mock/Protocol/Praos.hs.
"""
from .bft import Bft, bft_sign_header
from .leader_schedule import LeaderSchedule, ModChainSel, WithLeaderSchedule
from .pbft import PBft, pbft_sign_header
from .praos import (
    Praos, PraosConfig, PraosNode, PraosState, HotKey, praos_forge_fields,
)

__all__ = [
    "Bft", "bft_sign_header",
    "PBft", "pbft_sign_header",
    "Praos", "PraosConfig", "PraosNode", "PraosState", "HotKey",
    "praos_forge_fields",
    "LeaderSchedule", "WithLeaderSchedule", "ModChainSel",
]
