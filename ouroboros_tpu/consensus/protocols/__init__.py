"""Protocol instantiations: BFT, PBFT, mock Praos.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Protocol/{BFT,PBFT}.hs
and ouroboros-consensus-mock/src/Ouroboros/Consensus/Mock/Protocol/Praos.hs.
"""
from .bft import Bft, bft_sign_header
from .pbft import PBft, pbft_sign_header
from .praos import (
    Praos, PraosConfig, PraosNode, PraosState, HotKey, praos_forge_fields,
)

__all__ = [
    "Bft", "bft_sign_header",
    "PBft", "pbft_sign_header",
    "Praos", "PraosConfig", "PraosNode", "PraosState", "HotKey",
    "praos_forge_fields",
]
