"""Ledger abstraction and the extended ledger state.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Ledger/{Basics,
Abstract}.hs (`IsLedger`/`ApplyBlock`: applyChainTick, applyLedgerBlock,
reapplyLedgerBlock), Ledger/Extended.hs:52,142-163 (`ExtLedgerState` =
ledger × header-state and its ApplyBlock instance — "the single seam through
which all block validation flows"), Ledger/SupportsProtocol.hs (ledger-view
projection + forecast), Forecast.hs.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..chain.block import Point
from .header_validation import (
    HeaderState, revalidate_header, validate_header,
)
from .protocol import ConsensusProtocol


class LedgerError(Exception):
    """Block failed ledger rules (applyLedgerBlock failure)."""


class OutsideForecastRange(Exception):
    """Requested slot beyond the ledger view forecast horizon
    (Forecast.hs OutsideForecastRange)."""


class LedgerRules:
    """IsLedger + ApplyBlock + LedgerSupportsProtocol in one trait.

    State values are immutable; every method returns a new state.
    """

    def initial_state(self) -> Any:
        raise NotImplementedError

    def tip(self, state: Any) -> Point:
        raise NotImplementedError

    # -- applying blocks ------------------------------------------------------
    def tick(self, state: Any, slot: int) -> Any:
        """Time-based state evolution, no block (applyChainTick)."""
        return state

    def apply_block(self, ticked: Any, block: Any, backend=None) -> Any:
        """Full checks incl. tx witness crypto; raises LedgerError."""
        raise NotImplementedError

    def reapply_block(self, ticked: Any, block: Any) -> Any:
        """Known-valid block, skip expensive checks (reapplyLedgerBlock)."""
        return self.apply_block(ticked, block)

    # -- the batching seam (tx-witness analog of protocol.extract_proofs) ----
    def sequential_checks(self, ticked: Any, block: Any) -> None:
        """Cheap structural body checks that must run even on the batched
        path (e.g. witness presence); raises LedgerError."""

    def extract_proofs(self, ticked: Any, block: Any) -> list:
        """Independent crypto obligations of the block body (the reference's
        BBODY Ed25519 witness multi-verify — Shelley/Ledger/Ledger.hs:279).
        Default: none (mock ledgers check structurally)."""
        return []

    def tx_proofs(self, state: Any, tx: Any) -> Optional[list]:
        """Independent crypto obligations of ONE tx — the mempool
        admission unit (extract_proofs at tx granularity).  The adaptive
        batching service pre-verifies these coalesced with other
        threads' traffic, then apply_tx runs with the verdicts honored
        (Mempool.try_add_txs_async).  None = unknown: witness crypto
        stays inside apply_tx and the service path degrades to the
        plain synchronous admission."""
        return None

    # -- protocol support -----------------------------------------------------
    def ledger_view(self, state: Any) -> Any:
        """Projection consumed by the consensus protocol
        (LedgerSupportsProtocol.protocolLedgerView)."""
        return None

    def forecast_view(self, state: Any, slot: int) -> Any:
        """Ledger view at a *future* slot; raises OutsideForecastRange when
        `slot` is beyond the stability horizon (ledgerViewForecastAt)."""
        return self.ledger_view(state)


@dataclass(frozen=True)
class ExtLedgerState:
    """Ledger state × header state (Ledger/Extended.hs:52)."""
    ledger: Any
    header: HeaderState


class ExtLedgerRules:
    """ApplyBlock for ExtLedgerState (Extended.hs:142-163): ledger apply +
    validateHeader, combined.  All chain validation flows through here."""

    def __init__(self, protocol: ConsensusProtocol, ledger: LedgerRules):
        self.protocol = protocol
        self.ledger = ledger

    def initial_state(self) -> ExtLedgerState:
        return ExtLedgerState(self.ledger.initial_state(),
                              HeaderState.genesis(self.protocol))

    def tip(self, ext: ExtLedgerState) -> Point:
        return ext.header.tip_point

    def tick_then_apply(self, ext: ExtLedgerState, block: Any,
                        backend=None) -> ExtLedgerState:
        """Full validation: header crypto + ledger rules (ApplyVal path).
        The header validates against the view forecast AT ITS SLOT — for
        era-composed ledgers this is the cross-era view when the block
        sits past a transition."""
        ticked_ledger = self.ledger.tick(ext.ledger, block.slot)
        view = self.ledger.forecast_view(ext.ledger, block.slot)
        header = getattr(block, "header", block)
        new_header = validate_header(self.protocol, view, header, ext.header,
                                     backend=backend)
        new_ledger = self.ledger.apply_block(ticked_ledger, block,
                                             backend=backend)
        return ExtLedgerState(new_ledger, new_header)

    def tick_then_reapply(self, ext: ExtLedgerState,
                          block: Any) -> ExtLedgerState:
        """Known-valid block: no crypto (ReapplyVal path; used for replay)."""
        ticked_ledger = self.ledger.tick(ext.ledger, block.slot)
        view = self.ledger.forecast_view(ext.ledger, block.slot)
        header = getattr(block, "header", block)
        new_header = revalidate_header(self.protocol, view, header,
                                       ext.header)
        new_ledger = self.ledger.reapply_block(ticked_ledger, block)
        return ExtLedgerState(new_ledger, new_header)
