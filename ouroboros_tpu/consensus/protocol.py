"""ConsensusProtocol — the protocol abstraction.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Protocol/Abstract.hs:50-178
(`ConsensusProtocol p` with associated types ChainDepState / IsLeader /
CanBeLeader / SelectView / LedgerView / ValidateView; methods checkIsLeader,
tickChainDepState, updateChainDepState, reupdateChainDepState,
protocolSecurityParam; preferCandidate at :178).

TPU-first redesign: associated types become duck-typed values; the crucial
addition is `extract_proofs`, which splits `updateChainDepState` into

    sequential cheap part  (nonce evolution, window bookkeeping — host)
  + independent proofs     (VRF / KES / Ed25519 — device batch)

so a window of headers is verified in ONE batched device call
(consensus/batch.py drives it; SURVEY.md §7 P3: "scan + vmapped-verify").
`update_chain_dep_state` remains the reference-shaped all-in-one entry used
by non-batched callers; it must equal extract_proofs + verify + reupdate.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

from ..crypto.backend import CryptoBackend, default_backend


class ProtocolError(Exception):
    """ValidationErr analog — raised by update_chain_dep_state."""


class ConsensusProtocol:
    """Base class; subclasses are *configured instances* (config is self).

    security_param -- k: max rollback depth (protocolSecurityParam).
    """

    security_param: int = 2160

    # Whether this protocol's era admits epoch-boundary blocks; consulted by
    # validate_envelope (the reference gates EBBs per era via
    # ValidateEnvelope — only Byron has them).
    accepts_ebb: bool = False

    # -- chain-dependent state ------------------------------------------------
    def initial_chain_dep_state(self) -> Any:
        raise NotImplementedError

    def tick_chain_dep_state(self, state: Any, ledger_view: Any,
                             slot: int) -> Any:
        """Advance state to `slot` with no header (tickChainDepState)."""
        return state

    def update_chain_dep_state(self, ticked: Any, header: Any,
                               ledger_view: Any,
                               backend: Optional[CryptoBackend] = None) -> Any:
        """Apply header with full crypto checks (updateChainDepState).

        Default implementation = extract proofs, verify them now (batch of
        one), then reupdate; protocols only override when their check is not
        expressible as independent proofs.
        """
        backend = backend or default_backend()
        self.sequential_checks(ticked, header, ledger_view)
        reqs = self.extract_proofs(ticked, header, ledger_view)
        if reqs:
            ok = _verify_mixed(backend, reqs)
            if not all(ok):
                bad = ok.index(False)
                raise ProtocolError(
                    f"{type(self).__name__}: proof {bad} "
                    f"({type(reqs[bad]).__name__}) failed for header "
                    f"slot={header.slot}")
        return self.reupdate_chain_dep_state(ticked, header, ledger_view)

    def reupdate_chain_dep_state(self, ticked: Any, header: Any,
                                 ledger_view: Any) -> Any:
        """Re-apply a known-valid header, no crypto (reupdateChainDepState)."""
        raise NotImplementedError

    # -- the batching seam ----------------------------------------------------
    def sequential_checks(self, ticked: Any, header: Any,
                          ledger_view: Any) -> None:
        """Cheap host-side state-DEPENDENT checks (e.g. PBFT's windowed
        signer threshold, Praos' leader-value threshold).  Raised errors are
        validation failures.  Runs in the sequential pass of the batch
        driver; must not do expensive crypto."""

    def extract_proofs(self, ticked: Any, header: Any,
                       ledger_view: Any) -> list:
        """Independent proof obligations of this header given ticked state.

        Returns Ed25519Req/VrfReq/KesReq items (crypto/backend.py).  MUST be
        state-independent once `ticked` is known, so a window of headers can
        be verified as one device batch.
        """
        return []

    def vrf_proofs_of(self, headers: Sequence[Any]) -> list:
        """VRF proofs whose outputs (betas) the sequential pass will need
        for these headers.  Drives both prefetch_window and the pipelined
        replay driver (which computes window w+1's betas inside window w's
        device call)."""
        return []

    def prefetch_window(self, headers: Sequence[Any],
                        backend: CryptoBackend) -> None:
        """Hook run by the batch driver before the sequential pass of a
        window: batch-compute the headers' VRF betas in one device call
        instead of per-header host EC math during the fold."""
        from ..crypto.backend import GLOBAL_BETA_CACHE
        proofs = self.vrf_proofs_of(headers)
        if proofs:
            GLOBAL_BETA_CACHE.prefetch(proofs, backend)

    # -- leadership -----------------------------------------------------------
    def check_is_leader(self, can_be_leader: Any, slot: int, ticked: Any,
                        ledger_view: Any) -> Optional[Any]:
        """IsLeader proof if we lead `slot`, else None (checkIsLeader)."""
        return None

    # -- chain ordering -------------------------------------------------------
    def select_view(self, header: Any) -> Any:
        """Projection used to compare chains (SelectView); totally ordered.

        Default: block number — longest chain (Abstract.hs SelectView default
        = BlockNo)."""
        return header.block_no

    def prefer_candidate(self, ours: Any, candidate: Any) -> bool:
        """True iff candidate select-view is strictly better (preferCandidate,
        Abstract.hs:178)."""
        return candidate > ours


class NullProtocol(ConsensusProtocol):
    """Trivial protocol: no leadership checks, no proofs — test scaffolding."""

    def __init__(self, k: int = 5):
        self.security_param = k

    def initial_chain_dep_state(self):
        return ()

    def reupdate_chain_dep_state(self, ticked, header, ledger_view):
        return ()

    def check_is_leader(self, can_be_leader, slot, ticked, ledger_view):
        return True


def _verify_mixed(backend: CryptoBackend, reqs: Sequence) -> list[bool]:
    """Dispatch a mixed list of proof requests through the backend's fused
    mixed-batch path (KES hash-paths reduced to Ed25519 leaves on host, one
    Ed25519 batch + one VRF batch), preserving order."""
    return backend.verify_mixed(reqs)
