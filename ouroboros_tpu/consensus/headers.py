"""Protocol-carrying header: concrete header + named protocol fields.

The reference attaches protocol evidence to headers via per-era header types
(e.g. mock Praos' `PraosFields` with VRF certs + KES signature,
ouroboros-consensus-mock/src/Ouroboros/Consensus/Mock/Protocol/Praos.hs;
BFT's `BftFields` DSIGN signature, Protocol/BFT.hs).  Here one generic
header type carries an ordered tuple of (name, value) protocol fields;
signatures cover the CBOR encoding with the signature fields dropped
(`bytes_dropping`), matching the reference's sign-the-header-minus-signature
convention.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace
from typing import Any, Optional, Sequence

from ..chain.block import GENESIS_HASH
from ..utils import cbor


@dataclass(frozen=True)
class ProtocolHeader:
    """HasHeader + protocol evidence fields."""
    slot: int
    block_no: int
    prev_hash: bytes
    body_hash: bytes
    issuer: int = 0                     # index into the ledger view's keys
    fields: tuple = ()                  # ((name, bytes-or-int), ...)

    _cache: dict = field(default_factory=dict, repr=False, hash=False,
                         compare=False)

    def encode(self, drop: Sequence[str] = ()):
        fs = [[k, v] for k, v in self.fields if k not in drop]
        return [self.slot, self.block_no, self.prev_hash, self.body_hash,
                self.issuer, fs]

    @classmethod
    def decode(cls, obj) -> "ProtocolHeader":
        fs = tuple((str(k) if isinstance(k, str) else bytes(k).decode(),
                    bytes(v) if isinstance(v, (bytes, bytearray)) else int(v))
                   for k, v in obj[5])
        return cls(int(obj[0]), int(obj[1]), bytes(obj[2]), bytes(obj[3]),
                   int(obj[4]), fs)

    def bytes_dropping(self, *drop: str) -> bytes:
        """Serialisation with the named fields removed — what gets signed.

        When the header was decoded from stored bytes (ProtocolBlock.
        from_bytes), the result is assembled from raw-byte spans instead
        of re-encoding — re-encoding was ~40% of the replay host pass."""
        sp = self._cache.get("spans")
        if sp is not None:
            raw, helems, fpairs = sp
            keep = [s for k, s in fpairs if k not in drop]
            return (cbor._head(4, 6)
                    + raw[helems[0][0]:helems[4][1]]
                    + cbor._head(4, len(keep))
                    + b"".join(raw[a:b] for a, b in keep))
        return cbor.dumps(self.encode(drop))

    @property
    def bytes(self) -> bytes:
        c = self._cache
        b = c.get("bytes")
        if b is None:
            b = c["bytes"] = cbor.dumps(self.encode())
        return b

    @property
    def hash(self) -> bytes:
        c = self._cache
        if "h" not in c:
            c["h"] = hashlib.blake2b(self.bytes, digest_size=32).digest()
        return c["h"]

    def get(self, name: str, default: Any = None) -> Any:
        for k, v in self.fields:
            if k == name:
                return v
        return default

    def with_fields(self, **kw) -> "ProtocolHeader":
        merged = dict(self.fields)
        merged.update(kw)
        return replace(self, fields=tuple(sorted(merged.items())),
                       _cache={})


@dataclass(frozen=True)
class ProtocolBlock:
    """Block = protocol header + opaque tx body tuple."""
    header: ProtocolHeader
    body: tuple = ()

    @property
    def slot(self) -> int:
        return self.header.slot

    @property
    def block_no(self) -> int:
        return self.header.block_no

    @property
    def hash(self) -> bytes:
        return self.header.hash

    @property
    def prev_hash(self) -> bytes:
        return self.header.prev_hash

    def encode(self):
        return [self.header.encode(), [t.encode() if hasattr(t, "encode")
                                       else t for t in self.body]]

    @classmethod
    def decode(cls, obj, tx_decode=None) -> "ProtocolBlock":
        """tx_decode: per-ledger body-item decoder (default: raw values)."""
        body = tuple(tx_decode(t) if tx_decode else t for t in obj[1])
        return cls(ProtocolHeader.decode(obj[0]), body)

    @classmethod
    def from_bytes(cls, raw: bytes, tx_decode=None,
                   tx_body_elems: int | None = None) -> "ProtocolBlock":
        """Decode AND retain raw-byte spans so the hot sequential pass
        (header hash, KES signing bytes, tx ids) never re-encodes.

        tx_body_elems: when set, each tx item is a list whose first
        tx_body_elems elements form the tx BODY (ShelleyTx: 6 body
        fields + witnesses) — the body encoding is assembled from spans
        and stashed in the tx's _cache for txid."""
        obj = cbor.loads(raw)
        block = cls.decode(obj, tx_decode=tx_decode)
        try:
            outer = cbor.list_spans(raw, 0)          # [header, [txs]]
            hspan = outer[0]
            helems = cbor.list_spans(raw, hspan[0])
            fpairs_sp = cbor.list_spans(raw, helems[5][0])
            hdr = block.header
            hdr._cache["bytes"] = raw[hspan[0]:hspan[1]]
            hdr._cache["spans"] = (
                raw, helems,
                list(zip((k for k, _v in hdr.fields), fpairs_sp)))
            if tx_body_elems is not None and block.body:
                for tx, tsp in zip(block.body,
                                   cbor.list_spans(raw, outer[1][0])):
                    telems = cbor.list_spans(raw, tsp[0])
                    body_raw = (cbor._head(4, tx_body_elems) + raw[
                        telems[0][0]:telems[tx_body_elems - 1][1]])
                    cache = getattr(tx, "_cache", None)
                    if cache is not None:
                        cache["body_bytes"] = body_raw
        except (cbor.CBORError, IndexError):
            pass        # spans are an optimisation; decode stands alone
        return block

    @property
    def bytes(self) -> bytes:
        return cbor.dumps(self.encode())


def body_hash_of(body: Sequence) -> bytes:
    enc = [t.encode() if hasattr(t, "encode") else t for t in body]
    return hashlib.blake2b(cbor.dumps(enc), digest_size=32).digest()


def make_header(prev: Optional[ProtocolHeader], slot: int, body: Sequence,
                issuer: int) -> ProtocolHeader:
    """Unsigned header extending `prev`; protocols add evidence fields."""
    if prev is None:
        prev_hash, block_no = GENESIS_HASH, 0
    else:
        prev_hash, block_no = prev.hash, prev.block_no + 1
    return ProtocolHeader(slot=slot, block_no=block_no, prev_hash=prev_hash,
                          body_hash=body_hash_of(body), issuer=issuer)
