"""Header validation: envelope checks + chain-dep-state update.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/HeaderValidation.hs —
`HeaderState` {tip, chainDep} (:154), envelope checks (blockNo/slot monotone,
prevHash link; :278 `ValidateEnvelope`), `validateHeader` = envelope +
`updateChainDepState` (:413-432), `revalidateHeader` (:436, re-apply without
crypto), `HeaderError` (:351); `HeaderStateHistory.hs` for ChainSync
rollback support.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..chain.block import GENESIS_HASH, Point, point_of
from .protocol import ConsensusProtocol


class HeaderError(Exception):
    """Envelope or protocol-level header validation failure."""


class HeaderEnvelopeError(HeaderError):
    """blockNo / slot / prevHash relation violated (HeaderError:351)."""


@dataclass(frozen=True)
class AnnTip:
    """Annotated tip of the validated header chain (HeaderValidation.hs:97).

    is_ebb mirrors the reference's TipInfoIsEBB: a Byron EBB's successor is
    allowed to occupy the same slot (minimumNextSlotNo)."""
    slot: int
    block_no: int
    hash: bytes
    is_ebb: bool = False

    @property
    def point(self) -> Point:
        return Point(self.slot, self.hash)


@dataclass(frozen=True)
class HeaderState:
    """State needed to validate the next header (HeaderValidation.hs:154)."""
    tip: Optional[AnnTip]          # None = genesis
    chain_dep_state: Any

    @classmethod
    def genesis(cls, protocol: ConsensusProtocol) -> "HeaderState":
        return cls(None, protocol.initial_chain_dep_state())

    @property
    def tip_point(self) -> Point:
        return self.tip.point if self.tip else Point.genesis()


def validate_envelope(header: Any, header_state: HeaderState,
                      protocol: ConsensusProtocol) -> None:
    """The cheap structural checks (HeaderValidation.hs:278-349):
    block number increments, slot strictly increases, prev hash links.

    Epoch-boundary blocks (header field "ebb", the Byron-era quirk of
    Block/EBB.hs + the era-specific `ValidateEnvelope` instances) share
    their predecessor's block number instead of incrementing it; only
    protocols declaring `accepts_ebb` admit them (Shelley-family eras have
    none), and an EBB's successor may share the EBB's slot
    (minimumNextSlotNo)."""
    tip = header_state.tip
    is_ebb = _is_ebb(header)
    if is_ebb and not getattr(protocol, "accepts_ebb", False):
        raise HeaderEnvelopeError(
            "EBB header in an era whose protocol admits no EBBs")
    if tip is None:
        expected_block_no, min_slot, expected_prev = 0, 0, GENESIS_HASH
    else:
        expected_block_no = tip.block_no if is_ebb else tip.block_no + 1
        # only the REAL block following an EBB may share its slot; an EBB
        # can never reuse its predecessor's slot
        min_slot = tip.slot if (tip.is_ebb and not is_ebb) else tip.slot + 1
        expected_prev = tip.hash
    if header.block_no != expected_block_no:
        raise HeaderEnvelopeError(
            f"unexpected block number {header.block_no}, "
            f"expected {expected_block_no}")
    if header.slot < min_slot:
        raise HeaderEnvelopeError(
            f"slot {header.slot} not after tip slot {min_slot - 1}")
    if header.prev_hash != expected_prev:
        raise HeaderEnvelopeError(
            f"prev hash mismatch at slot {header.slot}: "
            f"{header.prev_hash.hex()[:16]} != {expected_prev.hex()[:16]}")


def _is_ebb(header: Any) -> bool:
    return bool(header.get("ebb", 0)) if hasattr(header, "get") else False


def ann_tip_of(header: Any) -> AnnTip:
    return AnnTip(header.slot, header.block_no, header.hash, _is_ebb(header))


def validate_header(protocol: ConsensusProtocol, ledger_view: Any,
                    header: Any, header_state: HeaderState,
                    backend=None) -> HeaderState:
    """Envelope + full crypto chain-dep update (validateHeader, :413-432)."""
    validate_envelope(header, header_state, protocol)
    ticked = protocol.tick_chain_dep_state(
        header_state.chain_dep_state, ledger_view, header.slot)
    try:
        new_dep = protocol.update_chain_dep_state(
            ticked, header, ledger_view, backend=backend)
    except Exception as e:
        raise HeaderError(f"chain-dep update failed: {e}") from e
    return HeaderState(ann_tip_of(header), new_dep)


def revalidate_header(protocol: ConsensusProtocol, ledger_view: Any,
                      header: Any, header_state: HeaderState) -> HeaderState:
    """Re-apply a previously-validated header, no crypto (revalidateHeader,
    :436)."""
    validate_envelope(header, header_state, protocol)
    ticked = protocol.tick_chain_dep_state(
        header_state.chain_dep_state, ledger_view, header.slot)
    new_dep = protocol.reupdate_chain_dep_state(ticked, header, ledger_view)
    return HeaderState(ann_tip_of(header), new_dep)


class HeaderStateHistory:
    """Bounded history of HeaderStates supporting rollback-to-point
    (HeaderStateHistory.hs) — used by the ChainSync client when the server
    rolls back."""

    def __init__(self, k: int, initial: HeaderState):
        self.k = k
        self._states: list[HeaderState] = [initial]   # oldest..newest

    @property
    def current(self) -> HeaderState:
        return self._states[-1]

    def append(self, state: HeaderState) -> None:
        self._states.append(state)
        # keep k states *past* the anchor so any rollback ≤ k succeeds
        if len(self._states) > self.k + 1:
            del self._states[0:len(self._states) - (self.k + 1)]

    def rewind(self, point: Point) -> bool:
        """Roll back so `current` has tip == point. False if too deep."""
        for i in range(len(self._states) - 1, -1, -1):
            if self._states[i].tip_point == point:
                del self._states[i + 1:]
                return True
        return False
