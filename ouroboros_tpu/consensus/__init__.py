"""Consensus core — protocol abstraction, header/ledger validation, batching.

Rebuilds the seams of /root/reference/ouroboros-consensus (SURVEY.md §2 L5)
TPU-first: the `ConsensusProtocol` class (Protocol/Abstract.hs:50) grows an
explicit proof-extraction hook so that a *window* of headers can have its
VRF/KES/Ed25519 proofs verified as one device batch (the reference verifies
strictly sequentially — SURVEY.md §2 "The TPU-relevant gap").
"""
from .protocol import ConsensusProtocol, NullProtocol
from .header_validation import (
    HeaderError, HeaderState, HeaderStateHistory, validate_header,
    revalidate_header,
)
from .ledger import (
    LedgerError, LedgerRules, ExtLedgerState, ExtLedgerRules,
    OutsideForecastRange,
)
from .batch import validate_headers_batched, BatchValidationResult
from .mempool import Mempool, MempoolReader, MempoolSnapshot

__all__ = [
    "Mempool", "MempoolReader", "MempoolSnapshot",
    "ConsensusProtocol", "NullProtocol",
    "HeaderError", "HeaderState", "HeaderStateHistory", "validate_header",
    "revalidate_header",
    "LedgerError", "LedgerRules", "ExtLedgerState", "ExtLedgerRules",
    "OutsideForecastRange",
    "validate_headers_batched", "BatchValidationResult",
]
