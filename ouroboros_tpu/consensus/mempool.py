"""Mempool — validated pending transactions, revalidated on tip change.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Mempool/API.hs:53-155
(`Mempool` {tryAddTxs, removeTxs, syncWithLedger, getSnapshot(For)}, ticket-
based zero-copy reader at :285), Mempool/Impl.hs (TVar `InternalState`
revalidated against the ledger tip on change), Mempool/TxSeq.hs (`TxSeq`
finger-tree with `TicketNo`).  Capacity defaults to twice the max block
body size (Impl.hs capacity policy).

TPU-first note: per-tx admission stays on the host CPU path (batch-of-one
witness checks — txs arrive one at a time from the network), while the bulk
witness verification happens when a *block* containing these txs is
validated through consensus/batch.py as one device batch.  Re-validation on
tip change reuses ledger.apply_tx and never re-runs witness crypto for txs
that merely moved to a new tip (witnesses sign the txid, which is
tip-independent) — mirroring the reference's revalidateTxsFor using
reapply.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from ..chain.block import Point
from ..crypto.backend import default_backend as _default_backend
from ..observe import metrics as _metrics
from ..observe.spans import monotonic_now as _now
from ..utils import cbor
from .ledger import LedgerError, LedgerRules

# arrival instrumentation (ISSUE 9): a caught-up node's mempool sees a
# firehose of batch-of-1 tx admissions — these three histograms make the
# batch-of-1 vs batch-of-N trade measurable BEFORE the adaptive batching
# service exists (ROADMAP item 3).  Handles pre-bound (OBS002); sizes
# and latencies are timing/traffic-shaped, so all three are unstable.
_ARRIVAL_TXS = _metrics.histogram("mempool.arrival_txs", stable=False)
_ADMIT_SECS = _metrics.latency_histogram("mempool.admit_secs")
_INTERARRIVAL = _metrics.latency_histogram("mempool.interarrival_secs")


@dataclass(frozen=True)
class MempoolEntry:
    """One tx with its admission ticket (TxSeq.hs `TxTicket`)."""
    ticket: int
    tx: Any
    size: int

    @property
    def txid(self) -> bytes:
        return self.tx.txid


@dataclass(frozen=True)
class MempoolSnapshot:
    """Point-in-time view (API.hs `MempoolSnapshot`): the validated tx
    sequence and the ledger state *after* applying all of them."""
    entries: tuple              # MempoolEntry, ticket-ordered
    ledger_state: Any
    tip_point: Point
    slot: int

    @property
    def txs(self) -> list:
        return [e.tx for e in self.entries]

    @property
    def tx_ids(self) -> list:
        return [e.txid for e in self.entries]

    def entries_after(self, ticket: int) -> list:
        """Zero-copy reader support (API.hs:285 snapshotTxsAfter)."""
        return [e for e in self.entries if e.ticket > ticket]

    def has_tx(self, txid: bytes) -> bool:
        return any(e.txid == txid for e in self.entries)


def _tx_size(tx: Any) -> int:
    enc = tx.encode() if hasattr(tx, "encode") else tx
    return len(cbor.dumps(enc))


class Mempool:
    """The mempool implementation (Impl.hs).

    get_ledger -- () -> (ledger_state, tip_point): the current ledger tip,
                  normally ChainDB.current_ledger().ledger + tip_point.
    capacity_bytes -- admission bound; reference default is 2x the max
                  block body size.
    """

    def __init__(self, ledger_rules: LedgerRules,
                 get_ledger: Callable[[], tuple],
                 capacity_bytes: int = 2 * 65536,
                 backend=None, verify_service=None):
        self.rules = ledger_rules
        self.get_ledger = get_ledger
        self.capacity_bytes = capacity_bytes
        self.backend = backend
        # adaptive batching service (crypto/batching.py): when attached,
        # try_add_txs_async coalesces witness checks with every other
        # protocol thread's single-proof traffic
        self.verify_service = verify_service
        self._entries: list[MempoolEntry] = []
        self._last_arrival: Optional[float] = None
        self._next_ticket = 1
        base, tip = get_ledger()
        self._base_state = base          # ledger state at tip, no mempool txs
        self._state = base               # after all mempool txs
        self._tip_point = tip
        # version TVar for blocking readers (TxSubmission outbound); plain
        # int fallback outside the sim
        try:
            from ..simharness.stm import TVar
            self.version: Optional[Any] = TVar(0, label="mempool-version")
        except Exception:                                  # pragma: no cover
            self.version = None
        self._version_int = 0

    # -- internals ------------------------------------------------------------
    @property
    def bytes_used(self) -> int:
        return sum(e.size for e in self._entries)

    def _bump(self) -> None:
        self._version_int += 1
        if self.version is not None:
            try:
                self.version.set_notify(self._version_int)
            except Exception:
                # outside the sim: keep the raw value fresh for polling
                self.version._value = self._version_int

    # -- API (API.hs:53-155) --------------------------------------------------
    def try_add_txs(self, txs: Sequence[Any],
                    backend=None) -> tuple[list, list]:
        """Validate and admit txs against the current mempool state.

        Returns (added_txids, [(tx, error)rejected]).  Stops admitting (but
        keeps rejecting-on-validity) when capacity is reached, like
        tryAddTxs's MempoolCapacityBytesOverride behaviour.  `backend`
        overrides the mempool's own for this call (the service admission
        path passes a PrecheckedBackend carrying coalesced verdicts).
        """
        observing = _metrics.enabled()
        if observing:
            t0 = _now()
            _ARRIVAL_TXS.observe(len(txs))
            if self._last_arrival is not None:
                _INTERARRIVAL.observe(t0 - self._last_arrival)
            self._last_arrival = t0
        added, rejected = [], []
        for tx in txs:
            size = _tx_size(tx)
            if self.bytes_used + size > self.capacity_bytes:
                rejected.append((tx, LedgerError("mempool full")))
                continue
            if any(e.txid == tx.txid for e in self._entries):
                rejected.append((tx, LedgerError("duplicate tx")))
                continue
            try:
                new_state = self.rules.apply_tx(
                    self._state, tx,
                    backend=backend if backend is not None
                    else self.backend)
            except LedgerError as e:
                rejected.append((tx, e))
                continue
            self._entries.append(MempoolEntry(self._next_ticket, tx, size))
            self._next_ticket += 1
            self._state = new_state
            added.append(tx.txid)
        if added:
            self._bump()
        if observing:
            _ADMIT_SECS.observe(_now() - t0)
        return added, rejected

    async def try_add_txs_async(self, txs: Sequence[Any]
                                ) -> tuple[list, list]:
        """try_add_txs with the witness crypto routed through the
        attached VerifyService (ROADMAP item 3: the batch-of-1 firehose
        coalesced into device batches across ALL submitting threads).

        Each tx's proofs (rules.tx_proofs) are verified through the
        service first — blocking on back-pressure like any other caller
        — then the synchronous admission runs with those verdicts
        honored via a PrecheckedBackend, so a verdict is never computed
        twice and admission semantics (capacity, duplicates, ordering)
        are IDENTICAL to the direct path.  Degrades to plain
        try_add_txs when no service is attached or the ledger does not
        expose tx-level proofs."""
        if self.verify_service is None:
            return self.try_add_txs(txs)
        reqs: list = []
        for tx in txs:
            p = self.rules.tx_proofs(self._state, tx)
            if p is None:                    # ledger can't pre-extract:
                return self.try_add_txs(txs)  # plain path for the batch
            reqs.extend(p)
        from ..crypto.batching import PrecheckedBackend, verdict_map
        verdicts = await verdict_map(self.verify_service, reqs)
        return self.try_add_txs(
            txs, backend=PrecheckedBackend(
                self.backend or _default_backend(), verdicts))

    def remove_txs(self, txids: Sequence[bytes]) -> None:
        """Drop the named txs and revalidate the remainder (removeTxs)."""
        drop = set(txids)
        keep = [e for e in self._entries if e.txid not in drop]
        if len(keep) != len(self._entries):
            self._revalidate(keep)
            self._bump()

    def sync_with_ledger(self) -> list:
        """Re-fetch the ledger tip and revalidate every tx against it
        (syncWithLedger).  Returns txids dropped as now-invalid (typically:
        included in the new tip block, or double-spent by it)."""
        base, tip = self.get_ledger()
        if tip == self._tip_point:
            return []
        self._base_state, self._tip_point = base, tip
        before = {e.txid for e in self._entries}
        self._revalidate(self._entries)
        dropped = [t for t in before
                   if not any(e.txid == t for e in self._entries)]
        self._bump()
        return dropped

    def _apply_all(self, state: Any, candidates: Sequence[MempoolEntry]
                   ) -> tuple[list, Any]:
        """Fold apply_tx over entries, dropping now-invalid ones — the
        shared core of syncWithLedger and getSnapshotFor revalidation."""
        kept: list[MempoolEntry] = []
        for e in candidates:
            try:
                state = self.rules.apply_tx(state, e.tx,
                                            backend=self.backend)
            except LedgerError:
                continue
            kept.append(e)
        return kept, state

    def _revalidate(self, candidates: Sequence[MempoolEntry]) -> None:
        self._entries, self._state = self._apply_all(self._base_state,
                                                     candidates)

    def get_snapshot(self) -> MempoolSnapshot:
        return MempoolSnapshot(tuple(self._entries), self._state,
                               self._tip_point, self._state_slot())

    def get_snapshot_for(self, slot: int, ticked_ledger: Any
                         ) -> MempoolSnapshot:
        """Snapshot revalidated against a *ticked* state for forging at
        `slot` (getSnapshotFor): the forge path must only include txs valid
        in the block being made."""
        kept, state = self._apply_all(ticked_ledger, self._entries)
        return MempoolSnapshot(tuple(kept), state, self._tip_point, slot)

    def _state_slot(self) -> int:
        return getattr(self._state, "slot", -1)

    def reader(self) -> "MempoolReader":
        return MempoolReader(self)


class MempoolReader:
    """Cursor over the mempool for TxSubmission outbound
    (TxSubmission/Mempool/Reader.hs): next_ids advances a ticket cursor,
    lookup resolves an id to the tx if still present."""

    def __init__(self, mempool: Mempool):
        self.mempool = mempool
        self.cursor = 0                  # last ticket handed out

    def next_ids(self, n: int) -> list[tuple[bytes, int]]:
        out = []
        for e in self.mempool.get_snapshot().entries_after(self.cursor):
            if len(out) >= n:
                break
            out.append((e.txid, e.size))
            self.cursor = e.ticket
        return out

    def lookup(self, txid: bytes) -> Optional[Any]:
        for e in self.mempool.get_snapshot().entries:
            if e.txid == txid:
                return e.tx
        return None
