"""Threaded producer/consumer replay — true host/device overlap.

The r5 software pipeline (consensus/batch.py) kept two windows in
flight, but the sequential pass, request packing, and dispatch all ran
on ONE Python thread: while that thread sat inside a blocking drain
(the packed result transfer plus result folding), no host-sequential
work advanced, so host-seq and device time simply ADDED in the bench
breakdown (BENCH_r05: 0.87s + 3.79s).  SURVEY.md hard parts #3 says the
split is legal — nonce evolution is sequential, but proofs are
state-independent once seeds are derived — so this module puts the host
half on its own thread:

    producer (background thread)      consumer (caller thread)
    ------------------------------    ------------------------------
    window w+1: seq pass              window w: blocking drain
               (nonce evolution,        (ONE packed transfer; with
                envelope checks,         fold=True just a verdict
                proof extraction)        scalar + betas)
               request packing          install carried betas
               key-cache prefetch       release one permit
               async submit  ───────►   first error wins, oldest-first

Coordination protocol (mirrored 1:1 by the sim model explored under
ouro-race in tests/test_replay_pipeline.py):

  * one Condition guards {pending, submitted, drained, stop, done};
  * the producer acquires a PERMIT before each window's sequential
    pass: it waits until ``submitted - drained < DEPTH`` — exactly the
    beta-carry distance.  Window w's submit ships window w+2's betas,
    which the consumer installs when draining w, immediately before the
    producer's sequential pass for w+2 reads them.  Running further
    ahead would silently fall back to per-proof host EC math;
  * the consumer drains oldest-first outside the lock (the blocking
    device wait must not hold it), installs betas, then releases the
    permit;
  * on a drain error the consumer sets ``stop``; the producer observes
    it at the next permit check, so at most one more window is ever
    submitted, and the consumer discards the leftovers with
    finish_window so no device work is leaked;
  * the producer NEVER touches the result: seq counts, the final state
    and any sequential error hand over through the shared state after
    ``done``, and an unexpected producer exception re-raises on the
    caller thread (``crash``).

Scheduling cannot change the outcome: drains are processed in
submission order and the first error wins, so ReplayResult is
byte-identical to the synchronous driver on any chain, valid or not —
tests/test_replay_pipeline.py pins this.

Shared-cache discipline: the producer owns all point-cache fills and
beta-cache reads; the consumer owns beta-cache writes and KES hash-path
outcome writes.  Individual dict operations are GIL-atomic and every
value is a pure function of its key, so a racing read at worst
recomputes; the caches' LRU bookkeeping (recency touches, capacity
eviction) additionally tolerates a concurrent eviction from the other
thread — see precompute._insert / VrfBetaCache._store.  Span trees are per-thread (observe/spans.py): the producer's
``window.host_seq``/``window.submit`` roots and the consumer's
``window.drain`` roots overlap in wall time — which is the point — and
bench.py's ``overlap`` section measures exactly that hiding.
"""
from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Any, Optional

from ..chain.block import Point
from ..crypto.backend import GLOBAL_BETA_CACHE, WindowVerdict
from ..observe import flight as _flight
from ..observe import metrics as _metrics
from ..observe import spans as _spans
from .header_validation import HeaderError
from .ledger import LedgerError, OutsideForecastRange

#: max windows submitted-but-not-drained while a sequential pass runs —
#: the beta-carry distance (window w's device call computes w+2's betas)
DEPTH = 2

# load-bearing thread accounting (always on): a replay that returns with
# started != finished leaked its producer — bench --smoke asserts the
# pair equal after the pipelined parity probe
_STARTED = _metrics.counter("pipeline.producers_started", always=True)
_FINISHED = _metrics.counter("pipeline.producers_finished", always=True)
# observational: windows through the pipeline / producer permit stalls
_WINDOWS = _metrics.counter("pipeline.windows")
_STALLS = _metrics.counter("pipeline.producer_stalls")
# queue-latency instrumentation (ISSUE 9): submit→drain covers the full
# async residence of a window — dispatch queue + device + transfer —
# the quantity the adaptive batching service will trade off against
# coalescing gain.  Handles pre-bound here (OBS002): observe() is two
# hot-loop calls per window.
_SUBMIT_DRAIN = _metrics.latency_histogram("pipeline.submit_drain_secs")
_WINDOW_BLOCKS = _metrics.histogram("pipeline.window_blocks")

# replay progress gauges (rendered live by tools/obsreport.py --live via
# the scrape endpoint).  blocks_done / windows_in_flight / total are
# deterministic end-state for a fixed workload (stable); rate/ETA/
# hidden-fraction are measured seconds (unstable).
_P_BLOCKS = _metrics.gauge("replay.progress.blocks_done")
_P_TOTAL = _metrics.gauge("replay.progress.total_blocks")
_P_INFLIGHT = _metrics.gauge("replay.progress.windows_in_flight")
_P_RATE = _metrics.gauge("replay.progress.blocks_per_sec", stable=False)
_P_ETA = _metrics.gauge("replay.progress.eta_secs", stable=False)
_P_HIDDEN = _metrics.gauge("replay.progress.hidden_frac", stable=False)
# mesh attribution (ISSUE 11): devices the in-flight windows shard over
# (1 off-mesh) and the lane padding waste the per-shard bucket rounding
# cost this replay — both read straight off the backend, published so a
# live scrape of a sharded replay names its mesh
_P_DEVICES = _metrics.gauge("replay.progress.devices")
_P_PAD_WASTE = _metrics.gauge("replay.progress.padding_waste_frac")
# streaming-replay disk overlap (ISSUE 15): disk+decode seconds the
# prefetch thread spent while >= 1 window was in flight on device —
# published live so a scrape of a streaming replay shows whether the
# read-ahead is actually hiding the storage layer
_S_HIDDEN = _metrics.gauge("replay.stream.hidden_frac", stable=False)


class ProgressTracker:
    """Online progress/overlap accounting for one streaming replay,
    published through the registry after every drained window.

    Exactness without history: hidden host-seq time is the measure of
    {host sequential pass active} ∩ {≥1 window in flight}.  Both are
    on/off signals with O(1) transitions (host edges from the producer,
    in-flight edges from submit/drain), so the intersection accumulates
    in a scalar — no interval lists to keep, which matters at
    million-block scale.  The streaming replay (storage/stream.py) adds
    a third on/off signal with the same discipline: {prefetch thread
    reading/decoding} ∩ {≥1 window in flight} accumulates into
    disk_hidden_secs, so the engine can report how many storage seconds
    the read-ahead hid behind device verify.  ETA uses the blocks/sec
    observed so far; total_blocks is optional (an unbounded stream has
    progress but no ETA)."""

    __slots__ = ("t0", "total", "blocks", "host_secs", "hidden_secs",
                 "disk_secs", "disk_hidden_secs", "_lock", "_inflight",
                 "_host_since", "_both_since", "_disk_since",
                 "_disk_both_since")

    def __init__(self, total_blocks: Optional[int] = None):
        self.t0 = _spans.monotonic_now()
        self.total = total_blocks
        self.blocks = 0
        self.host_secs = 0.0
        self.hidden_secs = 0.0
        self.disk_secs = 0.0
        self.disk_hidden_secs = 0.0
        self._lock = threading.Lock()
        self._inflight = 0
        self._host_since: Optional[float] = None
        self._both_since: Optional[float] = None
        self._disk_since: Optional[float] = None
        self._disk_both_since: Optional[float] = None
        _P_TOTAL.set(total_blocks if total_blocks is not None else 0)
        _P_BLOCKS.set(0)
        _P_INFLIGHT.set(0)

    # -- producer edges ------------------------------------------------------
    def host_begin(self) -> None:
        now = _spans.monotonic_now()
        with self._lock:
            self._host_since = now
            if self._inflight:
                self._both_since = now

    def host_end(self) -> None:
        now = _spans.monotonic_now()
        with self._lock:
            if self._host_since is not None:
                self.host_secs += now - self._host_since
                self._host_since = None
            if self._both_since is not None:
                self.hidden_secs += now - self._both_since
                self._both_since = None

    # -- prefetch-thread edges (streaming replay) ----------------------------
    def disk_begin(self) -> None:
        now = _spans.monotonic_now()
        with self._lock:
            self._disk_since = now
            if self._inflight:
                self._disk_both_since = now

    def disk_end(self) -> None:
        now = _spans.monotonic_now()
        with self._lock:
            if self._disk_since is not None:
                self.disk_secs += now - self._disk_since
                self._disk_since = None
            if self._disk_both_since is not None:
                self.disk_hidden_secs += now - self._disk_both_since
                self._disk_both_since = None

    # -- consumer edges ------------------------------------------------------
    def window_submitted(self) -> None:
        now = _spans.monotonic_now()
        with self._lock:
            self._inflight += 1
            if self._inflight == 1:
                if self._host_since is not None:
                    self._both_since = now
                if self._disk_since is not None:
                    self._disk_both_since = now

    def window_drained(self, n_blocks: int) -> None:
        now = _spans.monotonic_now()
        with self._lock:
            self._inflight -= 1
            if self._inflight == 0:
                if self._both_since is not None:
                    self.hidden_secs += now - self._both_since
                    self._both_since = None
                if self._disk_both_since is not None:
                    self.disk_hidden_secs += now - self._disk_both_since
                    self._disk_both_since = None
            self.blocks += n_blocks
            blocks, inflight = self.blocks, self._inflight
            host, hidden = self.host_secs, self.hidden_secs
            disk, disk_hidden = self.disk_secs, self.disk_hidden_secs
        elapsed = now - self.t0
        rate = blocks / elapsed if elapsed > 0 else 0.0
        _P_BLOCKS.set(blocks)
        _P_INFLIGHT.set(inflight)
        _P_RATE.set(round(rate, 3))
        if self.total and rate > 0:
            _P_ETA.set(round(max(0, self.total - blocks) / rate, 3))
        _P_HIDDEN.set(round(hidden / host, 4) if host > 0 else 0.0)
        if disk > 0:
            _S_HIDDEN.set(round(disk_hidden / disk, 4))


class _Shared:
    """Producer/consumer handoff state; every field below is guarded by
    ``cond`` except the producer-private ones it publishes only before
    setting ``done``."""

    __slots__ = ("cond", "pending", "submitted", "drained", "stop",
                 "done", "crash", "seq_error", "seq_done", "final_state",
                 "progress")

    def __init__(self):
        self.cond = threading.Condition()
        # (start, sub, reqs, owner, n_seq, t_submit, state_after, point)
        self.pending: deque = deque()
        self.progress: Optional[ProgressTracker] = None
        self.submitted = 0
        self.drained = 0
        self.stop = False               # consumer: error seen, stop producing
        self.done = False               # producer: no more submissions
        self.crash: Optional[BaseException] = None
        self.seq_error: Optional[Exception] = None
        self.seq_done = 0               # blocks past the sequential pass
        self.final_state: Any = None


def _produce(shared: _Shared, ext_rules, block_iter, ext_state, backend,
             window: int, fold: bool) -> None:
    """Producer body: sequential pass + packing + async submit per
    window, permit-gated to the beta-carry depth."""
    protocol, ledger = ext_rules.protocol, ext_rules.ledger
    submit = backend.submit_window

    def next_window():
        w = list(itertools.islice(block_iter, window))
        return w or None

    try:
        # bounded look-ahead: ahead[0] = current window, ahead[1:] = the
        # two windows whose beta proofs may already be in flight
        ahead: deque = deque()
        for _ in range(3):
            w = next_window()
            if w is None:
                break
            ahead.append(([getattr(b, "header", b) for b in w], w))
        if ahead:
            # windows 0 and 1 ride a plain prefetch; window w's device
            # call then carries window w+2's betas
            protocol.prefetch_window(
                [h for hs, _w in list(ahead)[:2] for h in hs], backend)

        st = ext_state
        while ahead:
            with shared.cond:
                if not (shared.stop
                        or shared.submitted - shared.drained < DEPTH):
                    _STALLS.inc()
                    with _spans.span("producer.stall", cat="stall"):
                        shared.cond.wait_for(
                            lambda: shared.stop or
                            shared.submitted - shared.drained < DEPTH)
                if shared.stop:
                    return
            headers_w, blk_window = ahead.popleft()
            nxt = next_window()
            if nxt is not None:
                ahead.append(([getattr(b, "header", b) for b in nxt],
                              nxt))
            reqs: list = []
            owner: list[int] = []
            seq_error: Optional[Exception] = None
            n_seq_w = 0
            progress = shared.progress
            if progress is not None:
                progress.host_begin()
            with _spans.span("window.host_seq", cat="host-seq"):
                for i, b in enumerate(blk_window):
                    try:
                        rs, st = _seq_block_step(protocol, ledger, st, b)
                    except OutsideForecastRange as e:
                        # retry-later, never invalid (see
                        # validate_blocks_batched)
                        seq_error = e
                        break
                    except Exception as e:
                        seq_error = (e if isinstance(e, (HeaderError,
                                                         LedgerError))
                                     else LedgerError(str(e)))
                        break
                    reqs.extend(rs)
                    owner.extend([i] * len(rs))
                    n_seq_w += 1
            if progress is not None:
                progress.host_end()
            # carry betas for the window TWO ahead (ahead[1] after the
            # pop): the consumer installs them at drain time, which the
            # permit above orders before that window's sequential pass
            next_proofs = (protocol.vrf_proofs_of(ahead[1][0])
                           if len(ahead) > 1 and seq_error is None else ())
            next_proofs = [p for p in next_proofs
                           if p not in GLOBAL_BETA_CACHE]
            sub = (submit(reqs, next_proofs, fold=True) if fold
                   else submit(reqs, next_proofs))
            _WINDOWS.inc()
            _WINDOW_BLOCKS.observe(n_seq_w)
            if progress is not None:
                progress.window_submitted()
            # the window's post-prefix state + tip point ride the entry:
            # once this window DRAINS clean, `st` is fully verified up to
            # `pt` — the consumer hands the pair to on_window (the
            # streaming engine's snapshot seam).  A window that died on
            # a genuine sequential validation failure carries NO point:
            # its prefix precedes an invalid block and both drivers
            # refuse to checkpoint it (retry-later horizon waits DO
            # checkpoint — their prefix is on the canonical chain)
            pt = (Point(headers_w[n_seq_w - 1].slot,
                        headers_w[n_seq_w - 1].hash)
                  if n_seq_w and (seq_error is None
                                  or isinstance(seq_error,
                                                OutsideForecastRange))
                  else None)
            with shared.cond:
                shared.pending.append(
                    (shared.seq_done, sub, reqs, owner, n_seq_w,
                     _spans.monotonic_now(), st, pt))
                shared.submitted += 1
                shared.seq_done += n_seq_w
                shared.cond.notify_all()
            if seq_error is not None:
                shared.seq_error = seq_error
                break
        shared.final_state = st
    except BaseException as e:      # submit/seq machinery broke: hand the
        shared.crash = e            # exception to the caller thread
    finally:
        with shared.cond:
            shared.done = True
            shared.cond.notify_all()


def _drain(backend, entry) -> tuple:
    """Finish one window's device call; install its carried betas.
    Returns (error, n_valid): error None when every proof held, else
    n_valid is the global index of the first bad block."""
    start, sub, reqs, owner, n_seq_w, t_submit, _st, _pt = entry
    # named distinctly from jax_backend's inner "window.drain" span:
    # bench._rep_overlap pairs submits and drains positionally by name,
    # and a second same-named interval per drain would break the zip.
    # This outer span exists for EVERY async backend (the flight
    # recorder must show drains even on stub/CPU backends); phase
    # totals stay correct because self-time attribution subtracts the
    # nested inner span.
    with _spans.span("pipeline.drain", cat="device"):
        ok, betas = backend.finish_window(sub)
    _SUBMIT_DRAIN.observe(_spans.monotonic_now() - t_submit)
    if betas:
        GLOBAL_BETA_CACHE.store_many(betas.keys(), betas.values())
    if isinstance(ok, WindowVerdict):
        # device-folded form: the first failing request index directly
        # (owner maps are non-decreasing, so the first bad request is
        # also the first bad block)
        bad, first_bad = ok.first_bad, n_seq_w
        if bad is not None:
            first_bad = owner[bad]
    else:
        first_bad, bad = n_seq_w, None
        for j, good in enumerate(ok):
            if not good and owner[j] < first_bad:
                first_bad, bad = owner[j], j
    if bad is not None:
        return LedgerError(
            f"proof {type(reqs[bad]).__name__} failed for block "
            f"{start + first_bad}"), start + first_bad
    return None, start + n_seq_w


def replay_threaded(ext_rules, blocks, ext_state, backend,
                    window: int = 512,
                    total_blocks: Optional[int] = None,
                    tracker: Optional[ProgressTracker] = None,
                    on_window=None):
    """Run the producer/consumer pipeline to completion; returns the
    same ReplayResult the synchronous driver would (batch.py re-exports
    this as the submit_window path of replay_blocks_pipelined).

    `total_blocks` (len(blocks) when the caller knows it) feeds the
    progress tracker's ETA; a streaming replay without it still reports
    blocks/sec, windows in flight and the hidden fraction.  `tracker`
    lets a caller share one ProgressTracker with other pipeline stages
    (the streaming engine's prefetch thread feeds its disk signal into
    the same tracker).  `on_window(state, n_done, point)` runs on the
    consumer thread after each window drains CLEAN: `state` is the
    fully verified state after that window's prefix and `point` its tip
    — the snapshot seam.  An exception it raises stops the replay
    through the normal first-error-wins teardown (producer joined,
    in-flight windows discarded via finish_window) and re-raises on the
    caller."""
    from .batch import ReplayResult

    if total_blocks is None and hasattr(blocks, "__len__"):
        total_blocks = len(blocks)
    fold = bool(getattr(backend, "supports_window_fold", False))
    # the sharded backend (parallel/sharded_verify.py) drives this SAME
    # driver: the producer's packing pads window w+1 to the per-shard
    # bucket shape (backend._pad rounds to a mesh multiple) while window
    # w's sharded composite drains, and the fold verdict is already the
    # cross-shard minimum — nothing here branches on mesh size, but the
    # mesh is attributed for live observers
    _P_DEVICES.set(int(getattr(backend, "n_shards", 1)))
    stats_fn = getattr(backend, "padding_stats", None)
    pad0 = stats_fn() if stats_fn is not None else None
    shared = _Shared()
    shared.progress = (tracker if tracker is not None
                       else ProgressTracker(total_blocks))
    t = threading.Thread(
        target=_run_producer,
        args=(shared, ext_rules, iter(blocks), ext_state, backend,
              window, fold),
        name="ouro-replay-producer", daemon=True)
    _STARTED.inc()
    t.start()
    error: Optional[Exception] = None
    n_ok = 0
    try:
        while True:
            with shared.cond:
                shared.cond.wait_for(
                    lambda: shared.pending or shared.done)
                if not shared.pending:
                    break               # done and fully drained
                entry = shared.pending.popleft()
            err, n = _drain(backend, entry)      # blocking, lock NOT held
            with shared.cond:
                shared.drained += 1
                shared.cond.notify_all()
            shared.progress.window_drained(entry[4])
            if err is not None:
                error, n_ok = err, n
                break
            if on_window is not None and entry[7] is not None:
                # every proof up to entry's tip point has now held —
                # entry[6] is a durable resume point.  A hook failure
                # (snapshot write error, a test's injected kill) rides
                # the consumer-exception path below: producer joined,
                # leftovers discarded, exception re-raised
                on_window(entry[6], n, entry[7])
    finally:
        # wake a permit-blocked producer and wait it out — the pipeline
        # must never leak its thread, least of all on an error path
        with shared.cond:
            shared.stop = True
            shared.cond.notify_all()
        t.join()
        # discard anything submitted after the first error (or after a
        # consumer-side exception): the async device work must complete
        for entry in shared.pending:
            backend.finish_window(entry[1])
        shared.pending.clear()
        if stats_fn is not None:
            # THIS replay's windows only (since=): a long-lived backend
            # must not smear earlier replays' padding into the gauge
            _P_PAD_WASTE.set(
                stats_fn(since=pad0).get("waste_frac", 0.0))
    if shared.crash is not None:
        # unhandled producer error: the flight ring holds the last
        # spans/metric deltas before the crash — dump before re-raising
        _flight.FLIGHT.dump_on_failure(
            f"replay producer crash: {shared.crash!r}")
        raise shared.crash
    if error is not None:
        # ReplayResult failure (first error wins): a crash-proof record
        # of the moments before the bad window, for offline triage
        _flight.FLIGHT.dump_on_failure(
            f"replay failed at block {n_ok}: {error}")
        return ReplayResult(None, n_ok, error)
    if shared.seq_error is not None:
        # the valid prefix (incl. the drained proofs) is fully verified:
        # resumable when the error is retry-later
        resume = (shared.final_state
                  if isinstance(shared.seq_error, OutsideForecastRange)
                  else None)
        if resume is None:
            # genuine sequential validation failure (retry-later horizon
            # waits are normal operation, not flight-dump material)
            _flight.FLIGHT.dump_on_failure(
                f"replay failed at block {shared.seq_done}: "
                f"{shared.seq_error}")
        return ReplayResult(resume, shared.seq_done, shared.seq_error)
    return ReplayResult(shared.final_state, shared.seq_done, None)


def _run_producer(*args) -> None:
    try:
        _produce(*args)
    finally:
        _FINISHED.inc()


# placed at the bottom to avoid a circular import at module load
# (batch.py imports replay_threaded; we only need its seq step)
from .batch import _seq_block_step  # noqa: E402
