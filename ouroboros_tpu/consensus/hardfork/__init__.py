"""HardFork combinator — era composition.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/HardFork/ (SURVEY.md
§2 L5 "HardFork Combinator"): n-ary era composition with cross-era state
translation, era-tagged blocks, and the slot↔epoch↔wallclock time
interpreter.  Rebuilt idiomatically: eras are first-class Python objects
with translation hooks; the Telescope GADT machinery collapses to an
(era_index, inner_state) pair because Python is untyped anyway.
"""
from .history import Bound, EraParams, EraSummary, PastHorizon, Summary
from .combinator import (
    Era, HardForkLedger, HardForkProtocol, HardForkState, era_of_slot,
    hard_fork_rules,
)

__all__ = [
    "Bound", "EraParams", "EraSummary", "PastHorizon", "Summary",
    "Era", "HardForkLedger", "HardForkProtocol", "HardForkState",
    "era_of_slot", "hard_fork_rules",
]
