"""The era combinator: one protocol/ledger over a sequence of eras.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/HardFork/Combinator/
— protocol instance (Protocol.hs:91), ledger instance + cross-era
forecasting (Ledger.hs), era translations (the `CanHardFork` record,
ouroboros-consensus-cardano/src/.../CanHardFork.hs:365-422), era-tagged
headers (Block/NestedContent.hs), `Degenerate` single-era shortcut
(Degenerate.hs).

Idiomatic collapse of the SOP/Telescope machinery: era-indexed state is
`HardForkState(era, inner, transitions)` where `transitions` records the
epoch at which each past era ended — exactly the info the reference's
`Telescope` + `TransitionInfo` carry — and the `Summary` of §history is
derived from it on demand.

The era of a block is carried in an explicit header field (`hfc_era`),
validated against the slot's era from the summary — the envelope check the
reference performs via era-tagged decoding.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Callable, Optional, Sequence

from ..ledger import ExtLedgerRules, LedgerError, LedgerRules
from ..protocol import ConsensusProtocol, ProtocolError
from .history import EraParams, PastHorizon, Summary

ERA_FIELD = "hfc_era"


@dataclass(frozen=True)
class Era:
    """One era + its exit: how the ledger decides the transition and how
    state crosses the boundary (the CanHardFork translations)."""
    name: str
    protocol: ConsensusProtocol
    ledger: LedgerRules
    params: EraParams
    # inner ledger state -> first epoch of the NEXT era (None: not decided)
    transition_epoch: Optional[Callable[[Any], Optional[int]]] = None
    # state translations applied at the boundary (identity by default)
    translate_ledger: Callable[[Any], Any] = lambda s: s
    translate_chain_dep: Callable[[Any], Any] = lambda s: s


@dataclass(frozen=True)
class HardForkState:
    """(era index, inner state, recorded era-end epochs)."""
    era: int
    inner: Any
    transitions: tuple = ()          # transitions[i] = epoch era i ended at

    def state_hash(self) -> bytes:
        """Era-tagged digest over the inner ledger state (for replay-parity
        checks across backends)."""
        import hashlib

        from ...utils import cbor
        return hashlib.blake2b(
            cbor.dumps([self.era, list(self.transitions),
                        self.inner.state_hash()]),
            digest_size=32).digest()


@dataclass(frozen=True)
class HardForkLedgerView:
    """What the combinator protocol needs from the combinator ledger."""
    era: int
    inner: Any
    summary: Summary


# Summary construction is pure in (era params, transition epochs), and the
# transition tuple only changes when a transition is decided or crossed —
# so summaries are memoised per transition tuple (the History/Caching.hs
# EpochInfo cache role).  Keyed on the era-params identity so distinct
# ledgers don't share entries.
_SUMMARY_CACHE: dict = {}
_SUMMARY_CACHE_MAX = 256


def _effective_transitions(eras: Sequence[Era], state: HardForkState,
                           inner_ledger_state: Optional[Any]) -> tuple:
    """Recorded transitions plus (if decided) the current era's pending
    transition read from the inner ledger state."""
    transitions = tuple(state.transitions)
    if inner_ledger_state is not None and state.era < len(eras) - 1:
        fn = eras[state.era].transition_epoch
        pending = fn(inner_ledger_state) if fn is not None else None
        if pending is not None:
            transitions = transitions + (pending,)
    return transitions


def _summary(eras: Sequence[Era], state: HardForkState,
             inner_ledger_state: Optional[Any] = None) -> Summary:
    transitions = _effective_transitions(eras, state, inner_ledger_state)
    key = (tuple(e.params for e in eras), transitions)   # frozen dataclass
    s = _SUMMARY_CACHE.get(key)
    if s is None:
        params = [e.params for e in eras[:len(transitions) + 1]]
        s = Summary.from_era_params(params, list(transitions))
        if len(_SUMMARY_CACHE) >= _SUMMARY_CACHE_MAX:
            _SUMMARY_CACHE.clear()
        _SUMMARY_CACHE[key] = s
    return s


def era_of_slot(eras: Sequence[Era], state: HardForkState,
                inner_ledger_state: Any, slot: int) -> int:
    s = _summary(eras, state, inner_ledger_state)
    try:
        return s.era_index_of_slot(slot)
    except PastHorizon:
        return len(s.eras) - 1       # open final era extends


class HardForkLedger(LedgerRules):
    """LedgerRules over HardForkState (Combinator/Ledger.hs)."""

    def __init__(self, eras: Sequence[Era]):
        self.eras = list(eras)

    def initial_state(self) -> HardForkState:
        return HardForkState(0, self.eras[0].ledger.initial_state(), ())

    def tip(self, state: HardForkState):
        return self.eras[state.era].ledger.tip(state.inner)

    def summary(self, state: HardForkState) -> Summary:
        return _summary(self.eras, state, state.inner)

    def _cross(self, state: HardForkState, target_era: int,
               summary: Summary) -> HardForkState:
        """Tick across era boundaries, translating state (CanHardFork)."""
        while state.era < target_era:
            era = self.eras[state.era]
            boundary = summary.eras[state.era].end
            # tick the old era's ledger up to its boundary, then translate
            inner = era.ledger.tick(state.inner, boundary.slot)
            nxt = era.translate_ledger(inner)
            state = HardForkState(state.era + 1, nxt,
                                  state.transitions + (boundary.epoch,))
        return state

    def tick(self, state: HardForkState, slot: int) -> HardForkState:
        summary = self.summary(state)
        target = era_of_slot(self.eras, state, state.inner, slot)
        state = self._cross(state, target, summary)
        inner = self.eras[state.era].ledger.tick(state.inner, slot)
        return replace(state, inner=inner)

    def _check_block_era(self, state: HardForkState, block) -> None:
        header = getattr(block, "header", block)
        tagged = header.get(ERA_FIELD)
        if tagged is None:
            raise LedgerError("block missing era tag")
        if tagged != state.era:
            raise LedgerError(
                f"block tagged era {tagged} but slot {block.slot} is in "
                f"era {state.era} ({self.eras[state.era].name})")

    def apply_block(self, ticked: HardForkState, block,
                    backend=None) -> HardForkState:
        self._check_block_era(ticked, block)
        inner = self.eras[ticked.era].ledger.apply_block(
            ticked.inner, block, backend=backend)
        return replace(ticked, inner=inner)

    def reapply_block(self, ticked: HardForkState, block) -> HardForkState:
        inner = self.eras[ticked.era].ledger.reapply_block(ticked.inner,
                                                           block)
        return replace(ticked, inner=inner)

    def sequential_checks(self, ticked: HardForkState, block) -> None:
        self._check_block_era(ticked, block)
        self.eras[ticked.era].ledger.sequential_checks(ticked.inner, block)

    def extract_proofs(self, ticked: HardForkState, block) -> list:
        return self.eras[ticked.era].ledger.extract_proofs(ticked.inner,
                                                           block)

    def apply_tx(self, state: HardForkState, tx, backend=None
                 ) -> HardForkState:
        """Mempool injection (Combinator/InjectTxs.hs): txs apply in the
        current era.  A tx of an earlier era that survives in a mempool
        across the boundary is rejected as a LedgerError (the reference
        translates txs when possible; our tx types do not cross), so
        mempool revalidation drops it instead of crashing."""
        era = self.eras[state.era]
        try:
            inner = era.ledger.apply_tx(state.inner, tx, backend=backend)
        except LedgerError:
            raise
        except Exception as e:
            raise LedgerError(
                f"tx not applicable in era {era.name}: {e}") from e
        return replace(state, inner=inner)

    def ledger_view(self, state: HardForkState) -> HardForkLedgerView:
        inner_view = self.eras[state.era].ledger.ledger_view(state.inner)
        return HardForkLedgerView(state.era, inner_view,
                                  self.summary(state))

    def forecast_view(self, state: HardForkState,
                      slot: int) -> HardForkLedgerView:
        """Cross-era forecasting (Combinator/Ledger.hs): when `slot` lands
        past a decided transition, tick (translating state across the
        boundary) and produce the NEW era's view — the view a header of
        that era validates against."""
        target = era_of_slot(self.eras, state, state.inner, slot)
        if target == state.era:
            inner_view = self.eras[state.era].ledger.forecast_view(
                state.inner, slot)
            return HardForkLedgerView(state.era, inner_view,
                                      self.summary(state))
        crossed = self.tick(state, slot)
        inner_view = self.eras[crossed.era].ledger.ledger_view(
            crossed.inner)
        return HardForkLedgerView(crossed.era, inner_view,
                                  self.summary(crossed))


class HardForkProtocol(ConsensusProtocol):
    """ConsensusProtocol over HardForkState (Combinator/Protocol.hs:91)."""

    def __init__(self, eras: Sequence[Era]):
        self.eras = list(eras)
        self.security_param = max(e.protocol.security_param for e in eras)
        # Envelope-level EBB admission: true if ANY era has EBBs; the exact
        # era is enforced by the era tag + each protocol's own checks.
        self.accepts_ebb = any(getattr(e.protocol, "accepts_ebb", False)
                               for e in eras)

    def initial_chain_dep_state(self) -> HardForkState:
        return HardForkState(0, self.eras[0].protocol
                             .initial_chain_dep_state(), ())

    def _target_era(self, view: HardForkLedgerView, slot: int) -> int:
        try:
            return view.summary.era_index_of_slot(slot)
        except PastHorizon:
            return len(view.summary.eras) - 1

    def tick_chain_dep_state(self, state: HardForkState,
                             ledger_view: HardForkLedgerView,
                             slot: int) -> HardForkState:
        target = self._target_era(ledger_view, slot)
        while state.era < target:
            era = self.eras[state.era]
            boundary = ledger_view.summary.eras[state.era].end
            inner = era.protocol.tick_chain_dep_state(
                state.inner, ledger_view.inner, boundary.slot)
            state = HardForkState(state.era + 1,
                                  era.translate_chain_dep(inner),
                                  state.transitions + (boundary.epoch,))
        inner = self.eras[state.era].protocol.tick_chain_dep_state(
            state.inner, ledger_view.inner, slot)
        return replace(state, inner=inner)

    def sequential_checks(self, ticked: HardForkState, header,
                          ledger_view: HardForkLedgerView) -> None:
        tagged = header.get(ERA_FIELD)
        if tagged is None:
            raise ProtocolError("header missing era tag")
        if tagged != ticked.era:
            raise ProtocolError(
                f"header tagged era {tagged}, expected {ticked.era}")
        era_protocol = self.eras[ticked.era].protocol
        # the combinator-level accepts_ebb is the union over eras; enforce
        # the CURRENT era's admission here (protocols that predate the ebb
        # field would otherwise grant the block_no non-increment exemption)
        if header.get("ebb") and not getattr(era_protocol, "accepts_ebb",
                                             False):
            raise ProtocolError(
                f"EBB header in era {self.eras[ticked.era].name}, which "
                f"admits no EBBs")
        era_protocol.sequential_checks(ticked.inner, header,
                                       ledger_view.inner)

    def extract_proofs(self, ticked: HardForkState, header,
                       ledger_view: HardForkLedgerView) -> list:
        return self.eras[ticked.era].protocol.extract_proofs(
            ticked.inner, header, ledger_view.inner)

    def vrf_proofs_of(self, headers) -> list:
        """Collect VRF proofs per era tag (betas land in the shared
        process-wide cache, so a flat list suffices)."""
        by_era: dict = {}
        for h in headers:
            tag = h.get(ERA_FIELD)
            if isinstance(tag, int) and 0 <= tag < len(self.eras):
                by_era.setdefault(tag, []).append(h)
        proofs: list = []
        for tag, hs in by_era.items():
            proofs.extend(self.eras[tag].protocol.vrf_proofs_of(hs))
        return proofs

    def reupdate_chain_dep_state(self, ticked: HardForkState, header,
                                 ledger_view: HardForkLedgerView
                                 ) -> HardForkState:
        inner = self.eras[ticked.era].protocol.reupdate_chain_dep_state(
            ticked.inner, header, ledger_view.inner)
        return replace(ticked, inner=inner)

    def check_is_leader(self, can_be_leader, slot: int,
                        ticked: HardForkState,
                        ledger_view: HardForkLedgerView):
        """can_be_leader: dict era_index -> inner can_be_leader (a node may
        hold credentials for a subset of eras)."""
        inner_cbl = can_be_leader.get(ticked.era) \
            if isinstance(can_be_leader, dict) else can_be_leader
        if inner_cbl is None:
            return None
        proof = self.eras[ticked.era].protocol.check_is_leader(
            inner_cbl, slot, ticked.inner, ledger_view.inner)
        if proof is None:
            return None
        return (ticked.era, proof)


def hard_fork_rules(eras: Sequence[Era]) -> ExtLedgerRules:
    """The composed ExtLedgerRules (Degenerate.hs when len(eras)==1)."""
    return ExtLedgerRules(HardForkProtocol(eras), HardForkLedger(eras))


def hfc_forge(eras: Sequence[Era], era_forges: dict):
    """BlockForging.forge for the combinator: tag the header with its era,
    then dispatch to the era's forge function.

    era_forges: era_index -> forge(inner_protocol, inner_proof, header).
    The is-leader proof from HardForkProtocol.check_is_leader is
    (era, inner_proof)."""
    def forge(protocol: HardForkProtocol, proof, header):
        era_ix, inner_proof = proof
        tagged = header.with_fields(**{ERA_FIELD: era_ix})
        return era_forges[era_ix](eras[era_ix].protocol, inner_proof,
                                  tagged)
    return forge
