"""Era history: slot ↔ epoch ↔ wallclock translation across eras.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/HardFork/History/
{EraParams,Summary,Qry}.hs — `EraParams` {epoch size, slot length, safe
zone}, `Bound` (aligned time/slot/epoch triple), `EraSummary` [start,end),
`Summary` = non-empty era list, and the `Qry` interpreter.  The reference
compiles queries to a small DSL and interprets them against the summary;
here the summary answers directly — same totality properties: queries past
the final era's end raise PastHorizon.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence


class PastHorizon(Exception):
    """Query beyond the known era summary (Qry.hs `PastHorizon`)."""


@dataclass(frozen=True)
class EraParams:
    """EraParams.hs: the shape of slots/epochs within one era."""
    epoch_size: int                  # slots per epoch
    slot_length: float               # seconds
    safe_zone: int = 0               # slots after the tip with era certainty


@dataclass(frozen=True)
class Bound:
    """An era boundary, aligned on all three scales (Summary.hs `Bound`)."""
    time: float
    slot: int
    epoch: int


@dataclass(frozen=True)
class EraSummary:
    """One era's extent: [start, end) with end None = open (final era)."""
    start: Bound
    end: Optional[Bound]
    params: EraParams

    def contains_slot(self, slot: int) -> bool:
        return slot >= self.start.slot and \
            (self.end is None or slot < self.end.slot)

    def contains_time(self, t: float) -> bool:
        return t >= self.start.time and \
            (self.end is None or t < self.end.time)

    def next_bound(self, end_epoch: int) -> Bound:
        """The aligned bound where this era ends at `end_epoch`."""
        n_epochs = end_epoch - self.start.epoch
        n_slots = n_epochs * self.params.epoch_size
        return Bound(self.start.time + n_slots * self.params.slot_length,
                     self.start.slot + n_slots,
                     end_epoch)


class Summary:
    """Non-empty era list; the query interpreter (Summary.hs, Qry.hs)."""

    def __init__(self, eras: Sequence[EraSummary]):
        assert eras, "summary must be non-empty"
        self.eras = list(eras)

    @classmethod
    def from_era_params(cls, params: Sequence[EraParams],
                        transitions: Sequence[int]) -> "Summary":
        """Build from per-era params + transition epochs (era i ends at
        transitions[i]); the final era is open-ended."""
        assert len(transitions) == len(params) - 1
        eras: list[EraSummary] = []
        start = Bound(0.0, 0, 0)
        for i, p in enumerate(params):
            if i < len(transitions):
                era = EraSummary(start, None, p)
                end = era.next_bound(transitions[i])
                eras.append(EraSummary(start, end, p))
                start = end
            else:
                eras.append(EraSummary(start, None, p))
        return cls(eras)

    def _era_for_slot(self, slot: int) -> EraSummary:
        for e in self.eras:
            if e.contains_slot(slot):
                return e
        raise PastHorizon(f"slot {slot} beyond summary")

    def _era_for_time(self, t: float) -> EraSummary:
        for e in self.eras:
            if e.contains_time(t):
                return e
        raise PastHorizon(f"time {t} beyond summary")

    def _era_for_epoch(self, epoch: int) -> EraSummary:
        for e in self.eras:
            if epoch >= e.start.epoch and \
                    (e.end is None or epoch < e.end.epoch):
                return e
        raise PastHorizon(f"epoch {epoch} beyond summary")

    # -- the queries (Qry.hs) ------------------------------------------------
    def slot_to_epoch(self, slot: int) -> tuple[int, int]:
        """(epoch, slot offset within the epoch)."""
        e = self._era_for_slot(slot)
        d = slot - e.start.slot
        return (e.start.epoch + d // e.params.epoch_size,
                d % e.params.epoch_size)

    def epoch_to_first_slot(self, epoch: int) -> int:
        e = self._era_for_epoch(epoch)
        return e.start.slot + (epoch - e.start.epoch) * e.params.epoch_size

    def slot_to_wallclock(self, slot: int) -> float:
        e = self._era_for_slot(slot)
        return e.start.time + (slot - e.start.slot) * e.params.slot_length

    def wallclock_to_slot(self, t: float) -> int:
        e = self._era_for_time(t)
        return e.start.slot + int((t - e.start.time) / e.params.slot_length)

    def slot_length_at(self, slot: int) -> float:
        return self._era_for_slot(slot).params.slot_length

    def era_index_of_slot(self, slot: int) -> int:
        for i, e in enumerate(self.eras):
            if e.contains_slot(slot):
                return i
        raise PastHorizon(f"slot {slot} beyond summary")
