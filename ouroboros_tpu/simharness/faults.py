"""Deterministic fault injection — seeded network hostility for sim tests.

Reference behaviour being reproduced: io-sim based fault exploration in the
reference test suites (ouroboros-network-framework's sim tests drive
`AbsBearerInfo`/attenuated channels: per-direction delay, error-at-byte and
SDU corruption — testlib/Ouroboros/Network/ConnectionManager/Experiments
and Simulation/Network/Snocket.hs attenuations), plus the ThreadNet
restart/partition plans of Test/ThreadNet/General.hs.

A :class:`FaultPlan` is a *seeded* description of network hostility:

- latency jitter          (extra per-message delay, uniform in [0, jitter])
- message drops           (an SDU/message silently vanishes)
- byte corruption         (one byte of an SDU payload is flipped)
- mid-stream disconnects  (the link dies; every later op raises LinkDown)
- silent stalls           (the link goes quiet for `stall_for` seconds)
- scheduled partitions    (messages between node groups dropped in a window)

Wrap any bearer or Channel with ``plan.wrap_bearer(...)`` /
``plan.wrap_channel(...)`` and an existing sim test runs under faults with
NO other changes.  Every decision draws from a per-edge RNG derived from
``(seed, src, dst)`` via blake2b, so the fault schedule is a pure function
of the plan — same seed, same program: identical faults, identical sim
trace (the determinism the chaos-threadnet replay check relies on).

Every injected fault emits a ``sim.trace_event(("fault", kind, edge, ...))``
so a chaos run is debuggable from the trace alone.
"""
from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

from . import core as sim


class LinkDown(ConnectionError):
    """Fault-injected mid-stream disconnect: the link is gone for good
    (until the subscription/governor layer dials a fresh connection)."""


@dataclass(frozen=True)
class FaultSpec:
    """Per-message fault probabilities + magnitudes for one plan."""
    jitter: float = 0.0          # max extra delay per message (seconds)
    drop_prob: float = 0.0       # P(message silently dropped)
    corrupt_prob: float = 0.0    # P(one payload byte flipped)
    disconnect_prob: float = 0.0  # P(link dies at this message)
    stall_prob: float = 0.0      # P(link goes quiet before this message)
    stall_for: float = 5.0       # silent-stall duration (seconds)

    def any_active(self) -> bool:
        return any((self.jitter, self.drop_prob, self.corrupt_prob,
                    self.disconnect_prob, self.stall_prob))


@dataclass(frozen=True)
class Partition:
    """A scheduled partition: during [start, end) messages crossing between
    different groups are dropped.  Nodes named in no group are unaffected
    (they can still talk to everyone)."""
    start: float
    end: float
    groups: Tuple[Tuple[str, ...], ...]

    def severs(self, t: float, src: str, dst: str) -> bool:
        if not (self.start <= t < self.end):
            return False
        gsrc = gdst = None
        for i, g in enumerate(self.groups):
            if src in g:
                gsrc = i
            if dst in g:
                gdst = i
        return gsrc is not None and gdst is not None and gsrc != gdst


class _EdgeState:
    """Mutable per-direction link state: its RNG stream and health."""

    __slots__ = ("rng", "down", "stalled_until")

    def __init__(self, rng: random.Random):
        self.rng = rng
        self.down = False
        self.stalled_until = 0.0


class FaultPlan:
    """A seeded fault schedule applied to the links it wraps.

    One plan may wrap many links; each (src, dst) direction gets its own
    blake2b-derived RNG stream, so adding or removing one link never
    perturbs the fault schedule of another (schedule stability under
    topology edits, same idea as per-peer key derivation in threadnet.py).
    """

    def __init__(self, seed: int, spec: FaultSpec = FaultSpec(),
                 partitions: Sequence[Partition] = (),
                 until: Optional[float] = None):
        self.seed = seed
        self.spec = spec
        self.partitions = tuple(partitions)
        # per-message hostility stops at `until` (sim seconds); partitions
        # keep their own explicit windows.  None = hostile forever.
        self.until = until
        self._edges: Dict[Tuple[str, str], _EdgeState] = {}
        # (time, kind, "src->dst") summary of every injected fault, for
        # test assertions that don't want to grep the sim trace
        self.events: list = []

    def _edge(self, src: str, dst: str) -> _EdgeState:
        key = (src, dst)
        st = self._edges.get(key)
        if st is None:
            h = hashlib.blake2b(f"{self.seed}:{src}->{dst}".encode(),
                                digest_size=8).digest()
            st = _EdgeState(random.Random(int.from_bytes(h, "big")))
            self._edges[key] = st
        return st

    def _note(self, kind: str, src: str, dst: str, detail: Any = None):
        now = sim.current_sim().time
        self.events.append((now, kind, f"{src}->{dst}"))
        sim.trace_event((kind, f"{src}->{dst}", detail), label="fault")

    def partition_severs(self, src: str, dst: str) -> bool:
        now = sim.current_sim().time
        return any(p.severs(now, src, dst) for p in self.partitions)

    async def perturb(self, src: str, dst: str, payload: Any,
                      corrupt) -> Tuple[bool, Any]:
        """Apply the plan to one outbound message on edge src->dst.

        Returns (deliver, payload'); raises LinkDown on a (possibly
        previously) injected disconnect.  `corrupt(payload, rng)` produces
        the corrupted variant (byte-level for bearers, None to disable for
        message channels)."""
        st = self._edge(src, dst)
        if st.down:
            raise LinkDown(f"fault-injected link down: {src}->{dst}")
        if self.partition_severs(src, dst):
            self._note("partition-drop", src, dst)
            return False, payload
        if self.until is not None and sim.current_sim().time >= self.until:
            return True, payload
        spec, rng = self.spec, st.rng
        if spec.disconnect_prob and rng.random() < spec.disconnect_prob:
            st.down = True
            self._note("disconnect", src, dst)
            raise LinkDown(f"fault-injected disconnect: {src}->{dst}")
        if spec.stall_prob and rng.random() < spec.stall_prob:
            self._note("stall", src, dst, spec.stall_for)
            await sim.sleep(spec.stall_for)
        if spec.drop_prob and rng.random() < spec.drop_prob:
            self._note("drop", src, dst)
            return False, payload
        if corrupt is not None and spec.corrupt_prob \
                and rng.random() < spec.corrupt_prob:
            payload = corrupt(payload, rng)
            self._note("corrupt", src, dst)
        if spec.jitter:
            delay = rng.random() * spec.jitter
            if delay > 0.0:
                self._note("jitter", src, dst, round(delay, 6))
                await sim.sleep(delay)
        return True, payload

    # -- wrappers ------------------------------------------------------------
    def wrap_bearer(self, bearer, src: str, dst: str) -> "FaultyBearer":
        """Wrap a mux bearer (write(SDU)/read()/sdu_size): faults apply to
        the src->dst write direction; reads pass through (the other
        direction is wrapped on the peer's side).

        Wrapping is how a FRESH connection is born, so it heals a
        previously fault-killed edge: a LinkDown poisons one link, not the
        address — the redial the reconnect policy pays for gets a live
        wire (the docstring contract on LinkDown)."""
        self._edge(src, dst).down = False
        return FaultyBearer(bearer, self, src, dst)

    def wrap_channel(self, channel, src: str, dst: str) -> "FaultyChannel":
        """Wrap a message-level Channel: drops lose exactly one message
        (no byte-stream framing to tear), corruption is disabled.  Like
        wrap_bearer, a fresh wrap heals a fault-killed edge."""
        self._edge(src, dst).down = False
        return FaultyChannel(channel, self, src, dst)


class FaultyBearer:
    """A mux bearer with the plan applied to writes.

    Dropping or corrupting an SDU tears the byte stream exactly the way a
    hostile relay would: the peer sees a codec error or an unbounded stall
    — precisely the failure modes the node's watchdogs must convert into
    a clean peer kill."""

    def __init__(self, inner, plan: FaultPlan, src: str, dst: str):
        self._inner = inner
        self._plan = plan
        self._src = src
        self._dst = dst

    @property
    def sdu_size(self) -> int:
        return self._inner.sdu_size

    @staticmethod
    def _corrupt_sdu(sdu, rng: random.Random):
        payload = sdu.payload
        if not payload:
            return sdu
        i = rng.randrange(len(payload))
        flipped = bytes([payload[i] ^ (1 + rng.randrange(255))])
        from ..network.mux import SDU
        return SDU(sdu.timestamp, sdu.mode, sdu.num,
                   payload[:i] + flipped + payload[i + 1:])

    async def write(self, sdu) -> None:
        deliver, sdu = await self._plan.perturb(
            self._src, self._dst, sdu, self._corrupt_sdu)
        if deliver:
            await self._inner.write(sdu)

    async def read(self):
        # reads fail once the edge died (symmetric teardown: a dead link
        # is dead in both call directions on this endpoint)
        if self._plan._edge(self._src, self._dst).down:
            raise LinkDown(
                f"fault-injected link down: {self._src}->{self._dst}")
        return await self._inner.read()

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close:
            close()


class FaultyChannel:
    """A message-level Channel under the plan (drops/jitter/stalls/
    disconnects; no byte corruption at this granularity)."""

    def __init__(self, inner, plan: FaultPlan, src: str, dst: str):
        self._inner = inner
        self._plan = plan
        self._src = src
        self._dst = dst
        self.label = getattr(inner, "label", f"{src}->{dst}")

    async def send(self, item) -> None:
        deliver, item = await self._plan.perturb(
            self._src, self._dst, item, None)
        if deliver:
            await self._inner.send(item)

    async def recv(self):
        if self._plan._edge(self._src, self._dst).down:
            raise LinkDown(
                f"fault-injected link down: {self._src}->{self._dst}")
        return await self._inner.recv()

    async def wait_ready(self, timeout: float) -> bool:
        # a fault-killed link reports ready IMMEDIATELY so the caller's
        # recv raises LinkDown now — same contract as MuxChannel on a
        # closed mux: transport death must not masquerade as peer
        # silence and burn the whole watchdog limit
        if self._plan._edge(self._src, self._dst).down:
            return True
        return await self._inner.wait_ready(timeout)
