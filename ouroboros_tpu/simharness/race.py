"""ouro-race — happens-before race detection + schedule exploration.

The reference io-sim's signature correctness tool is ``exploreRaces`` /
IOSimPOR (io-sim:src/Control/Monad/IOSimPOR/*): systematic schedule
perturbation that surfaces races the one default deterministic schedule
never exercises.  This module is the Python-rebuild analog, split the
same way the reference splits it:

- **Instrumentation** (`RaceDetector`): every TVar read/write, every
  ``atomically`` commit, thread fork/join and timer event is recorded
  against per-thread *vector clocks* (FastTrack-style happens-before,
  PAPERS.md).  An access pair on the same TVar is a race when the two
  accesses are causally unordered, at least one is a write, and at least
  one happened *outside* an atomic block (committed transactions
  serialize on the vars they touch, so tx/tx pairs are ordered by
  construction — exactly GHC-STM semantics).
- **Exploration** (`ScheduleController` / `explore_races`): re-run the
  same program under K seeded schedule perturbations.  Schedule 0 is the
  production FIFO schedule; later schedules insert preemption points at
  every yield/STM boundary by picking the next runnable thread at
  random (seeded) or in reversed (LIFO) order, which flips the commit
  order of racy pairs so *both* directions of an unordered pair get
  exercised.
- **Repro** (`Race.trace`): each race carries a minimized two-thread
  interleaving — only the two racing threads' events on the racing
  TVar, plus their fork points — enough to replay the schedule by hand.

Happens-before edges modeled:
  fork          parent -> child (child starts with the parent's clock)
  join          target's final clock -> waiter (Async.wait)
  commit        a transaction acquires the clocks of every TVar it read
                or wrote and releases its own to every TVar it wrote
                (commit serialization on conflicting vars)
  set_notify    a non-transactional write releases the writer's clock to
                the TVar (the wake-up edge to blocked STM readers) but
                acquires nothing — so it *races* with any unordered
                access, which is the point of the CONC001 discipline
  timer         a timer callback runs with the clock its creator had at
                registration; timer writes (new_timeout flips) propagate
                that clock but are exempt from race checks — timers are
                scheduler-mediated sync primitives, racing with one's
                own timeout is the *purpose* of a timeout

Deterministic end to end: same program factory + same seed + same K
produce a byte-identical ``RaceReport.render()``.
"""
from __future__ import annotations

import hashlib
from collections import deque
from dataclasses import dataclass, field
from fnmatch import fnmatchcase
from typing import Any, Callable, Iterable, Optional

__all__ = [
    "Race", "RaceDetector", "RaceReport", "ScheduleController",
    "explore_races",
]


# ---------------------------------------------------------------------------
# Vector clocks
# ---------------------------------------------------------------------------

class VClock:
    """Sparse vector clock over thread ids (plus timer pseudo-ids)."""

    __slots__ = ("c",)

    def __init__(self, c: Optional[dict] = None):
        self.c = dict(c) if c else {}

    def tick(self, tid) -> None:
        self.c[tid] = self.c.get(tid, 0) + 1

    def copy(self) -> "VClock":
        return VClock(self.c)

    def join(self, other: "VClock") -> None:
        for tid, n in other.c.items():
            if self.c.get(tid, 0) < n:
                self.c[tid] = n

    def leq(self, other: "VClock") -> bool:
        """self happens-before-or-equals other."""
        for tid, n in self.c.items():
            if n > other.c.get(tid, 0):
                return False
        return True

    def __repr__(self):
        return "VC" + repr(sorted(self.c.items()))


# ---------------------------------------------------------------------------
# Access records / per-var state
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _Access:
    seq: int
    tid: Any
    label: str
    kind: str           # "read" | "write"
    atomic: bool
    clock: VClock       # immutable snapshot
    timer: bool = False  # scheduler-mediated timer write: never races


class _VarState:
    __slots__ = ("name", "clock", "last_writes", "reads_since")

    def __init__(self, name: str):
        self.name = name
        self.clock = VClock()          # released clocks (commits/notifies)
        self.last_writes: list = []    # _Access of the latest write "front"
        self.reads_since: list = []    # reads since the latest write front


@dataclass(frozen=True)
class Race:
    """One detected race: an unordered access pair on the same TVar."""
    var: str                 # TVar label (or normalized id when unlabeled)
    kind: str                # "write-write" | "read-write"
    a_thread: str
    b_thread: str
    schedule: int            # schedule index it was first observed under
    trace: tuple             # minimized two-thread interleaving lines

    @property
    def key(self):
        return (self.var, self.kind, frozenset((self.a_thread,
                                                self.b_thread)))

    def render(self) -> str:
        head = (f"RACE {self.kind} on TVar[{self.var}] between "
                f"{self.a_thread!r} and {self.b_thread!r} "
                f"(schedule {self.schedule})")
        body = "\n".join(f"    {line}" for line in self.trace)
        return head + ("\n" + body if body else "")


class RaceDetector:
    """Happens-before detector attached to one Sim run.

    The Sim scheduler drives the hooks; user code never calls them.  All
    state is per-run: normalized var names are assigned in first-access
    order, so reports never leak the process-global TVar id counter and
    stay byte-identical across repeated explorations.
    """

    TRACE_WINDOW = 4096      # rolling event window repro traces draw from
    REPRO_MAX = 24           # cap on minimized-interleaving length

    def __init__(self, schedule_index: int = 0):
        self.schedule_index = schedule_index
        self.races: dict = {}             # Race.key -> Race
        self._clocks: dict = {}           # tid -> VClock
        self._vars: dict = {}             # tvar id -> _VarState
        self._var_seq = 0
        self._seq = 0
        self._events: deque = deque(maxlen=self.TRACE_WINDOW)
        self._ctx_tid: Any = None         # current thread (set by Sim)
        self._ctx_label: str = "sim"
        self._timer_clocks: dict = {}     # token -> VClock snapshot
        self._timer_depth = 0
        self._next_timer = 0

    # -- context (Sim scheduler) --------------------------------------------
    def set_ctx(self, tid, label: str) -> None:
        self._ctx_tid, self._ctx_label = tid, label

    def begin_timer(self, token: int) -> None:
        self._timer_depth += 1
        self._saved_ctx = (self._ctx_tid, self._ctx_label)
        self.set_ctx(("timer", token), f"timer-{token}")
        self._clocks[("timer", token)] = \
            self._timer_clocks.get(token, VClock()).copy()

    def end_timer(self) -> None:
        self._timer_depth -= 1
        self.set_ctx(*self._saved_ctx)

    @property
    def _in_timer(self) -> bool:
        return self._timer_depth > 0

    def _clock(self, tid=None) -> VClock:
        tid = tid if tid is not None else self._ctx_tid
        vc = self._clocks.get(tid)
        if vc is None:
            vc = self._clocks[tid] = VClock()
            vc.tick(tid)
        return vc

    # -- structural edges ----------------------------------------------------
    def on_fork(self, parent_tid, child_tid, child_label: str) -> None:
        if parent_tid is not None:
            parent = self._clock(parent_tid)
            parent.tick(parent_tid)
            child = parent.copy()
        else:
            child = VClock()
        child.tick(child_tid)
        self._clocks[child_tid] = child
        self._log(child_tid, child_label, "fork", "", "")

    def on_join(self, waiter_tid, waiter_label: str, target_tid,
                target_label: str) -> None:
        target = self._clocks.get(target_tid)
        if target is not None:
            w = self._clock(waiter_tid)
            w.join(target)
            w.tick(waiter_tid)
        self._log(waiter_tid, waiter_label, "join", target_label, "")

    def on_timer_create(self) -> int:
        token = self._next_timer
        self._next_timer += 1
        self._timer_clocks[token] = self._clock().copy()
        return token

    # -- TVar accesses -------------------------------------------------------
    def _var(self, tvar) -> _VarState:
        vs = self._vars.get(tvar._id)
        if vs is None:
            name = tvar.label or f"v{self._var_seq}"
            self._var_seq += 1
            vs = self._vars[tvar._id] = _VarState(name)
        return vs

    def on_commit(self, tid, label: str, read_vars: dict,
                  written: dict) -> None:
        """Transaction commit: acquire every accessed var's clock (commit
        serialization), then record the accesses, then release to the
        written vars."""
        vc = self._clock(tid)
        touched = {**read_vars, **written}
        for tvar in touched.values():
            vc.join(self._var(tvar).clock)
        vc.tick(tid)
        for vid, tvar in read_vars.items():
            if vid not in written:
                self._access(tvar, "read", atomic=True)
        for tvar in written.values():
            self._access(tvar, "write", atomic=True)
            vs = self._var(tvar)
            vs.clock.join(vc)
        self._log(tid, label, "commit",
                  ",".join(sorted(self._var(t).name
                                  for t in touched.values())), "")

    def on_raw_write(self, tvar) -> None:
        """Non-transactional write (TVar.set_notify, timer flips)."""
        vc = self._clock()
        vc.tick(self._ctx_tid)
        if self._in_timer:
            # timers are scheduler-mediated: propagate the creator's
            # clock (the wake-up edge) but do not race-check
            self._record_only(tvar, "write")
        else:
            self._access(tvar, "write", atomic=False)
        self._var(tvar).clock.join(vc)

    def on_peek(self, tvar) -> None:
        """Non-transactional read (TVar.value)."""
        if self._ctx_tid is None:
            return          # outside any scheduled step: nothing to order
        vc = self._clock()
        vc.tick(self._ctx_tid)
        self._access(tvar, "read", atomic=False)

    # -- core check ----------------------------------------------------------
    def _access(self, tvar, kind: str, atomic: bool) -> None:
        vs = self._var(tvar)
        self._seq += 1
        acc = _Access(self._seq, self._ctx_tid, self._ctx_label, kind,
                      atomic, self._clock().copy())
        self._log(acc.tid, acc.label,
                  ("tx-" if atomic else "") + kind, vs.name, "")
        against = vs.last_writes if kind == "read" \
            else vs.last_writes + vs.reads_since
        for prev in against:
            if prev.tid == acc.tid:
                continue
            if prev.timer:
                continue    # timer writes never race (both directions:
                            # polling one's own timeout flag is the
                            # documented purpose of registerDelay)
            if prev.atomic and acc.atomic:
                continue    # committed transactions serialize
            if prev.clock.leq(acc.clock):
                continue    # ordered: prev happens-before acc
            self._report(vs, prev, acc)
        if kind == "write":
            vs.last_writes = [acc]
            vs.reads_since = []
        else:
            vs.reads_since.append(acc)
            if len(vs.reads_since) > 64:     # bound: keep the newest reads
                del vs.reads_since[0]

    def _record_only(self, tvar, kind: str) -> None:
        vs = self._var(tvar)
        self._seq += 1
        self._log(self._ctx_tid, self._ctx_label, "timer-" + kind,
                  vs.name, "")
        # a timer write still supersedes the write front — clearing the
        # stale pre-timer accesses — but carries timer=True so LATER
        # accesses never race against it either (the exemption must be
        # two-sided, or polling one's own timeout flag reports a race)
        acc = _Access(self._seq, self._ctx_tid, self._ctx_label, kind,
                      True, self._clock().copy(), timer=True)
        if kind == "write":
            vs.last_writes = [acc]
            vs.reads_since = []

    def _report(self, vs: _VarState, a: _Access, b: _Access) -> None:
        kind = "write-write" if a.kind == "write" and b.kind == "write" \
            else "read-write"
        race = Race(var=vs.name, kind=kind, a_thread=a.label,
                    b_thread=b.label, schedule=self.schedule_index,
                    trace=self._minimize(vs.name, a, b))
        self.races.setdefault(race.key, race)

    # -- repro ---------------------------------------------------------------
    def _log(self, tid, label, op, var, detail) -> None:
        self._events.append((tid, label, op, var, detail))

    def _minimize(self, var_name: str, a: _Access, b: _Access) -> tuple:
        """The two racing threads' events on the racing var, plus their
        fork points — the smallest interleaving that still shows the
        unordered pair."""
        tids = {a.tid, b.tid}
        lines = []
        for tid, label, op, var, _detail in self._events:
            if tid not in tids:
                continue
            if op == "fork" or var == var_name or op == "join":
                lines.append(f"[{label}] {op}"
                             + (f" {var}" if var else ""))
        lines.append(f"=> unordered: [{a.label}] {a.kind}"
                     f"{' (atomic)' if a.atomic else ''} vs "
                     f"[{b.label}] {b.kind}"
                     f"{' (atomic)' if b.atomic else ''} on {var_name}")
        return tuple(lines[-self.REPRO_MAX:])


# ---------------------------------------------------------------------------
# Schedule exploration
# ---------------------------------------------------------------------------

def _derived_seed(seed: int, index: int) -> int:
    h = hashlib.blake2b(b"ouro-race:%d:%d" % (seed, index),
                        digest_size=8).digest()
    return int.from_bytes(h, "big")


@dataclass
class RaceReport:
    """Outcome of a K-schedule exploration.  `races` block; `tolerated`
    (label matched a tolerate glob) are visible but non-blocking, the
    same split as the ouro-lint baseline."""
    seed: int
    k: int
    races: list = field(default_factory=list)
    tolerated: list = field(default_factory=list)
    failures: list = field(default_factory=list)   # (schedule, repr(exc))
    schedules_run: int = 0

    @property
    def found(self) -> bool:
        return bool(self.races)

    def render(self) -> str:
        out = [f"ouro-race: seed={self.seed} k={self.k} "
               f"schedules={self.schedules_run} races={len(self.races)} "
               f"tolerated={len(self.tolerated)} "
               f"failures={len(self.failures)}"]
        for r in self.races:
            out.append(r.render())
        for r in self.tolerated:
            out.append("tolerated: " + r.render())
        for sched, err in self.failures:
            out.append(f"schedule {sched} failed: {err}")
        return "\n".join(out)


class ScheduleController:
    """Re-run one sim program under K seeded schedule perturbations.

    Schedule 0 is the production FIFO schedule (so the baseline behavior
    is always covered); schedules 1..K-1 perturb at every preemption
    point (yield / sleep / STM boundary — every spot the cooperative
    scheduler makes a choice) with a seeded random pick, and every
    fourth schedule runs LIFO, which reverses the commit order of racy
    pairs relative to FIFO."""

    def __init__(self, make_program: Callable[[], Any], k: int = 16,
                 seed: int = 0, tolerate: Iterable[str] = ()):
        if k < 1:
            raise ValueError("need at least one schedule")
        self.make_program = make_program
        self.k = k
        self.seed = seed
        self.tolerate = tuple(tolerate)

    def _mode(self, index: int) -> str:
        if index == 0:
            return "fifo"
        return "lifo" if index % 4 == 3 else "random"

    def run_schedule(self, index: int):
        """Run one perturbed schedule; returns (detector, exc_or_None)."""
        from .core import Sim
        det = RaceDetector(schedule_index=index)
        sim = Sim(seed=_derived_seed(self.seed, index),
                  schedule_mode=self._mode(index), race=det)
        try:
            sim.run(self.make_program())
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as exc:
            # BaseException, not Exception: AsyncCancelled (the most
            # timing-dependent failure shape a perturbation provokes)
            # must land in report.failures, not abort the exploration
            # and lose every schedule already collected
            return det, exc
        return det, None

    def explore(self, pre_collected=(), start: int = 0) -> RaceReport:
        """Run schedules [start, k) and fold in `pre_collected`
        detectors from runs the caller already made (e.g. the measured
        FIFO run run_chaos_threadnet performs anyway — re-running it as
        schedule 0 would be byte-identical wasted work)."""
        report = RaceReport(seed=self.seed, k=self.k)
        seen: set = set()

        def harvest(det):
            for race in det.races.values():
                if race.key in seen:
                    continue
                seen.add(race.key)
                if any(fnmatchcase(race.var, pat)
                       for pat in self.tolerate):
                    report.tolerated.append(race)
                else:
                    report.races.append(race)

        for det in pre_collected:
            report.schedules_run += 1
            harvest(det)
        for index in range(start, self.k):
            det, exc = self.run_schedule(index)
            report.schedules_run += 1
            if exc is not None:
                report.failures.append((index, f"{type(exc).__name__}: "
                                        f"{exc}"))
            harvest(det)
        report.races.sort(key=lambda r: (r.var, r.kind, r.a_thread,
                                         r.b_thread))
        report.tolerated.sort(key=lambda r: (r.var, r.kind, r.a_thread,
                                             r.b_thread))
        return report


def explore_races(make_program: Callable[[], Any], k: int = 16,
                  seed: int = 0,
                  tolerate: Iterable[str] = ()) -> RaceReport:
    """exploreRaces analog: run `make_program()` under K seeded schedule
    perturbations and report every unordered TVar access pair.

    make_program must return a FRESH coroutine (and fresh program state)
    per call — each schedule is an independent run."""
    return ScheduleController(make_program, k=k, seed=seed,
                              tolerate=tolerate).explore()
