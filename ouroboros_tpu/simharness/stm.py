"""Software transactional memory for the sim harness.

MonadSTM analog (io-sim-classes/src/Control/Monad/Class/MonadSTM.hs:91-162;
execAtomically: io-sim/src/Control/Monad/IOSim/Internal.hs:1300).

Because the sim runtime is single-threaded and cooperative, a transaction is
atomic by construction; this module provides the read/write-set tracking that
implements ``retry`` (block until a read var changes) and ``orElse``
(nested-transaction rollback), plus the derived structures the reference uses
everywhere: TQueue, TBQueue, TMVar (strict, as in MonadSTM/Strict.hs).

Transactions are *plain functions* (not coroutines) receiving a ``Tx`` handle:

    async def producer(q):
        await atomically(lambda tx: q.put(tx, item))
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

from . import runtime as _runtime

__all__ = ["TVar", "Tx", "Retry", "retry", "TQueue", "TBQueue", "TMVar"]

_tvar_ids = itertools.count()


class Retry(Exception):
    """Raised by a transaction to block until a read TVar changes."""


def retry():
    raise Retry()


class TVar:
    """Transactional variable. Read/write only through a Tx inside atomically.

    ``value`` property gives a non-transactional peek (for assertions/tracing
    only — analogous to readTVarIO).
    """

    __slots__ = ("_id", "_value", "label")

    def __init__(self, value: Any = None, label: str = ""):
        self._id = next(_tvar_ids)
        self._value = value
        self.label = label

    @property
    def value(self) -> Any:
        det = _runtime.active_detector()
        if det is not None:
            det.on_peek(self)
        return self._value

    def set_notify(self, value: Any) -> None:
        """Runtime-internal: write outside a transaction and wake STM
        waiters.  For non-sim-thread producers (timer callbacks, registration
        hooks); user code should write through atomically()."""
        det = _runtime.active_detector()
        if det is not None:
            det.on_raw_write(self)
        self._value = value
        _runtime.current().stm_notify([self._id])

    def __repr__(self):
        return f"<TVar {self._id}{' ' + self.label if self.label else ''}={self._value!r}>"


class Tx:
    """In-flight transaction: tracks read set and buffered writes."""

    __slots__ = ("_sim", "read_vars", "_writes")

    def __init__(self, sim):
        self._sim = sim
        # id -> TVar: one store per read serves both the retry read-set
        # (keys) and the race detector's commit hook, which needs the
        # objects (their labels) — no extra cost on the STM hot path
        self.read_vars: dict[int, TVar] = {}
        self._writes: dict[int, tuple[TVar, Any]] = {}

    @property
    def read_set(self):
        """TVar ids read so far (retry registration uses this view)."""
        return self.read_vars.keys()

    def read(self, tvar: TVar) -> Any:
        self.read_vars[tvar._id] = tvar
        if tvar._id in self._writes:
            return self._writes[tvar._id][1]
        return tvar._value

    def write(self, tvar: TVar, value: Any) -> None:
        self._writes[tvar._id] = (tvar, value)

    def modify(self, tvar: TVar, fn: Callable[[Any], Any]) -> Any:
        v = fn(self.read(tvar))
        self.write(tvar, v)
        return v

    def check(self, cond: bool) -> None:
        """STM 'check': retry unless cond holds."""
        if not cond:
            retry()

    def or_else(self, first: Callable[["Tx"], Any],
                second: Callable[["Tx"], Any]) -> Any:
        """Run first; if it retries, roll back its writes and run second.

        orElse analog (MonadSTM.hs; io-sim Internal.hs:1300 region).  The
        read sets of both branches accumulate (a change to either read set
        should wake a blocked orElse), matching GHC STM semantics; only the
        writes of a retried branch are rolled back.
        """
        saved_writes = dict(self._writes)
        try:
            return first(self)
        except Retry:
            self._writes = saved_writes
            return second(self)

    # called by the scheduler
    def commit(self) -> list[int]:
        written = []
        for vid, (tvar, value) in self._writes.items():
            tvar._value = value
            written.append(vid)
        return written

    def rollback(self) -> None:
        self._writes.clear()


# ---------------------------------------------------------------------------
# Derived transactional structures (MonadSTM derived API)
# ---------------------------------------------------------------------------

def _rev(cons):
    out = None
    while cons is not None:
        head, cons = cons
        out = (head, out)
    return out


class TQueue:
    """Unbounded FIFO queue (TQueue analog).

    Two-stack cons-list representation (front to pop from, back to push to),
    as in the reference TQueue — amortized O(1) per operation with purely
    immutable values, so transaction rollback stays free.
    """

    def __init__(self, label: str = ""):
        lbl = label or "tqueue"
        self._front = TVar(None, label=lbl + ".front")
        self._back = TVar(None, label=lbl + ".back")
        self._count = TVar(0, label=lbl + ".count")

    def put(self, tx: Tx, item: Any) -> None:
        tx.write(self._back, (item, tx.read(self._back)))
        tx.write(self._count, tx.read(self._count) + 1)

    def _pop(self, tx: Tx):
        front = tx.read(self._front)
        if front is None:
            front = _rev(tx.read(self._back))
            if front is None:
                return _NO_ITEM
            tx.write(self._back, None)
        head, rest = front
        tx.write(self._front, rest)
        tx.write(self._count, tx.read(self._count) - 1)
        return head

    def get(self, tx: Tx) -> Any:
        item = self._pop(tx)
        if item is _NO_ITEM:
            retry()
        return item

    def try_get(self, tx: Tx) -> Optional[Any]:
        item = self._pop(tx)
        return None if item is _NO_ITEM else item

    def size(self, tx: Tx) -> int:
        return tx.read(self._count)


_NO_ITEM = object()


class TBQueue(TQueue):
    """Bounded FIFO queue (TBQueue analog) — put blocks when full."""

    def __init__(self, capacity: int, label: str = ""):
        super().__init__(label=label or "tbqueue")
        self.capacity = capacity

    def put(self, tx: Tx, item: Any) -> None:
        if tx.read(self._count) >= self.capacity:
            retry()
        super().put(tx, item)

    def try_put(self, tx: Tx, item: Any) -> bool:
        if tx.read(self._count) >= self.capacity:
            return False
        super().put(tx, item)
        return True


_EMPTY = object()


class TMVar:
    """Transactional MVar (TMVar analog): full-or-empty box."""

    def __init__(self, value: Any = _EMPTY, label: str = ""):
        self._box = TVar(value, label=label or "tmvar")

    def take(self, tx: Tx) -> Any:
        v = tx.read(self._box)
        if v is _EMPTY:
            retry()
        tx.write(self._box, _EMPTY)
        return v

    def try_take(self, tx: Tx) -> Optional[Any]:
        v = tx.read(self._box)
        if v is _EMPTY:
            return None
        tx.write(self._box, _EMPTY)
        return v

    def put(self, tx: Tx, value: Any) -> None:
        if tx.read(self._box) is not _EMPTY:
            retry()
        tx.write(self._box, value)

    def read_(self, tx: Tx) -> Any:
        v = tx.read(self._box)
        if v is _EMPTY:
            retry()
        return v

    def is_empty(self, tx: Tx) -> bool:
        return tx.read(self._box) is _EMPTY
