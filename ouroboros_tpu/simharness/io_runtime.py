"""IoRuntime — the real-IO interpreter of the simharness interface.

The production half of the io-sim-classes story (SURVEY.md §1): everything
in ouroboros_tpu is written against the simharness facade; `Sim` interprets
it deterministically with a virtual clock, this runtime interprets it over
asyncio with the wall clock and real sockets.  The STM stays atomic for
the same reason as in the sim — asyncio is cooperative and single-threaded,
so a transaction function that never awaits runs atomically; `retry` blocks
on per-TVar wakeup events.

Usage:
    from ouroboros_tpu.simharness.io_runtime import io_run
    io_run(main())          # instead of sim.run(main())
"""
from __future__ import annotations

import asyncio
import time as _time
from typing import Any, Coroutine, Optional

from . import runtime as _runtime
from .core import AsyncCancelled
from .stm import Retry, Tx


class IoAsync:
    """Async-handle mirror of core.Async over an asyncio.Task."""

    _next_tid = [1]

    def __init__(self, task: asyncio.Task, label: str):
        self._task = task
        self.label = label
        self.tid = IoAsync._next_tid[0]
        IoAsync._next_tid[0] += 1

    @property
    def done(self) -> bool:
        return self._task.done()

    async def wait(self) -> Any:
        try:
            return await asyncio.shield(self._task)
        except asyncio.CancelledError as e:
            if self._task.cancelled():
                raise AsyncCancelled() from e
            raise

    def cancel(self) -> None:
        self._task.cancel()

    async def cancel_wait(self) -> None:
        self.cancel()
        try:
            await self._task
        except (asyncio.CancelledError, Exception):
            pass

    def poll(self) -> Optional[Any]:
        if not self._task.done():
            return None
        if self._task.cancelled():
            raise AsyncCancelled()
        exc = self._task.exception()
        if exc is not None:
            raise exc
        return self._task.result()


class IoRuntime:
    """The asyncio-backed runtime."""

    def __init__(self):
        self._t0 = _time.monotonic()
        self._tvar_waiters: dict[int, set] = {}     # tvar id -> {Event}
        self.trace: list = []
        self.collect_trace = False

    # -- time -----------------------------------------------------------------
    def now(self) -> float:
        return _time.monotonic() - self._t0

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(max(0.0, seconds))

    async def yield_(self) -> None:
        await asyncio.sleep(0)

    # -- threads --------------------------------------------------------------
    def spawn(self, coro: Coroutine, label: str = "") -> IoAsync:
        task = asyncio.get_event_loop().create_task(coro, name=label)
        return IoAsync(task, label)

    async def timeout(self, seconds: float, coro) -> tuple[bool, Any]:
        try:
            return True, await asyncio.wait_for(coro, seconds)
        except asyncio.TimeoutError:
            return False, None

    # -- STM ------------------------------------------------------------------
    async def atomically(self, tx_fn) -> Any:
        while True:
            tx = Tx(self)
            try:
                result = tx_fn(tx)
            except Retry:
                read_ids = list(tx.read_set)
                tx.rollback()
                if not read_ids:
                    raise RuntimeError(
                        "STM retry with empty read set would block forever")
                await self._wait_tvars(read_ids)
                continue
            except BaseException:
                tx.rollback()
                raise
            written = tx.commit()
            if written:
                self.stm_notify(written)
            return result

    async def _wait_tvars(self, tvar_ids: list[int]) -> None:
        event = asyncio.Event()
        for vid in tvar_ids:
            self._tvar_waiters.setdefault(vid, set()).add(event)
        try:
            await event.wait()
        finally:
            for vid in tvar_ids:
                ws = self._tvar_waiters.get(vid)
                if ws is not None:
                    ws.discard(event)
                    if not ws:
                        del self._tvar_waiters[vid]

    def stm_notify(self, tvar_ids) -> None:
        for vid in tvar_ids:
            for event in self._tvar_waiters.get(vid, ()):
                event.set()

    # -- misc -----------------------------------------------------------------
    def trace_event(self, payload: Any, label: str = "user") -> None:
        if self.collect_trace:
            self.trace.append((self.now(), label, payload))

    def new_timeout(self, seconds: float):
        from .stm import TVar
        tv = TVar(False, label=f"io-timeout+{seconds}")

        def fire():
            tv._value = True
            self.stm_notify([tv._id])
        asyncio.get_event_loop().call_later(seconds, fire)
        return tv


def io_run(main: Coroutine, debug: bool = False) -> Any:
    """Run `main` under the IO runtime (the production `sim.run`)."""
    rt = IoRuntime()

    async def entry():
        prev = _runtime.current_or_none()
        _runtime.set_current(rt)
        try:
            return await main
        finally:
            _runtime.set_current(prev)

    return asyncio.run(entry(), debug=debug)
