"""Deterministic async runtime with virtual clock — the io-sim analog.

Reference behaviour being reproduced (see /root/reference):
- io-sim/src/Control/Monad/IOSim.hs:4-40   (runSim / runSimTrace / Trace)
- io-sim/src/Control/Monad/IOSim/Internal.hs:682,1085 (schedule/reschedule)
- io-sim/src/Control/Monad/IOSim/Internal.hs:1300 (execAtomically: STM with
  retry/orElse), :1095-1112 (timer firing), IOSim.hs:108 (deadlock detection)
- io-sim-classes typeclasses (MonadSTM/MonadAsync/MonadFork/MonadTimer/...)

Idiomatic rebuild, not a translation: user code is plain Python ``async def``
coroutines; blocking primitives are awaitables that yield effect records to a
trampoline scheduler.  The runtime is single-threaded and cooperative, so STM
transactions are atomic by construction; the STM machinery only needs read-set
tracking to implement ``retry`` wake-ups.  The scheduler is seeded and fully
deterministic: same seed, same program -> identical schedule and trace.

Simulation semantics matching io-sim:
- the run ends when the *main* thread terminates (other threads discarded);
- when no thread is runnable the clock jumps to the next timer;
- no runnable thread + no timer + main alive  =>  Deadlock.
"""
from __future__ import annotations

import heapq
import random
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Coroutine, Optional

__all__ = [
    "run", "run_trace", "spawn", "now", "sleep", "yield_", "atomically",
    "trace_event", "mask", "Async", "Deadlock", "AsyncCancelled",
    "SimEvent", "Trace", "current_sim", "timeout", "new_timeout", "Sim",
]


class Deadlock(Exception):
    """No runnable threads, no pending timers, main not finished.

    io-sim analog: deadlock detection (io-sim/src/Control/Monad/IOSim.hs:108).
    """


class AsyncCancelled(BaseException):
    """Delivered into a thread by Async.cancel (MonadAsync cancel analog)."""


@dataclass(frozen=True)
class SimEvent:
    time: float
    tid: int
    label: str
    kind: str          # "fork" | "stop" | "fail" | "delay" | "wake" | "stm" | user label
    payload: Any = None

    def __repr__(self) -> str:
        return f"@{self.time:.6f} [{self.tid}:{self.label}] {self.kind} {self.payload!r}"


Trace = list  # list[SimEvent]


class _Eff:
    """Awaitable effect record interpreted by the scheduler."""
    __slots__ = ("kind", "payload")

    def __init__(self, kind: str, payload: Any = None):
        self.kind = kind
        self.payload = payload

    def __await__(self):
        result = yield self
        return result


_RUNNABLE, _BLOCKED, _DONE, _FAILED = "runnable", "blocked", "done", "failed"


class _Thread:
    __slots__ = (
        "tid", "label", "coro", "state", "resume_value", "resume_exc",
        "result", "exc", "waiters", "blocked_on", "mask_depth",
        "pending_cancel", "stm_tx_fn", "block_epoch",
    )

    def __init__(self, tid: int, label: str, coro: Coroutine):
        self.tid = tid
        self.label = label
        self.coro = coro
        self.state = _RUNNABLE
        self.resume_value: Any = None
        self.resume_exc: Optional[BaseException] = None
        self.result: Any = None
        self.exc: Optional[BaseException] = None
        self.waiters: list[tuple["_Thread", int]] = []
        self.blocked_on: Any = None
        self.mask_depth = 0
        self.pending_cancel = False
        self.stm_tx_fn: Any = None   # pending STM transaction to re-run on wake
        # Incremented on every block; wakers capture the epoch at registration
        # so a stale waker (old timer, old STM registration, old waiter entry)
        # cannot wake the thread out of a *later* block.
        self.block_epoch = 0

    @property
    def masked(self) -> bool:
        return self.mask_depth > 0

    def block(self, on: Any) -> int:
        self.state = _BLOCKED
        self.blocked_on = on
        self.block_epoch += 1
        return self.block_epoch

    def __repr__(self):
        return f"<Thread {self.tid}:{self.label} {self.state} blocked_on={self.blocked_on}>"


class Async:
    """Handle to a forked thread (MonadAsync's Async analog).

    io-sim-classes/src/Control/Monad/Class/MonadAsync.hs:98.
    """

    __slots__ = ("_thread", "_sim")

    def __init__(self, thread: _Thread, sim: "Sim"):
        self._thread = thread
        self._sim = sim

    @property
    def tid(self) -> int:
        return self._thread.tid

    @property
    def label(self) -> str:
        return self._thread.label

    @property
    def done(self) -> bool:
        return self._thread.state in (_DONE, _FAILED)

    async def wait(self) -> Any:
        """Wait for completion; re-raises the thread's exception if it failed."""
        return await _Eff("wait", self._thread)

    def cancel(self) -> None:
        """Deliver AsyncCancelled at the target's next unmasked suspension."""
        self._sim._cancel(self._thread)

    async def cancel_wait(self) -> None:
        self.cancel()
        try:
            await self.wait()
        except AsyncCancelled as e:
            # Only swallow the *target's* death; a fresh AsyncCancelled not
            # identical to the target's exc is the caller's own cancellation.
            if not self.done or self._thread.exc is not e:
                raise
        except Exception:   # target's own failure is reaped silently
            pass

    def poll(self) -> Optional[Any]:
        """Non-blocking: result if done, raises if failed, None if running."""
        t = self._thread
        if t.state == _FAILED:
            raise t.exc
        if t.state == _DONE:
            return t.result
        return None


_current_sim: Optional["Sim"] = None


def current_sim() -> "Sim":
    if _current_sim is None:
        raise RuntimeError("not inside a simulation (use simharness.run)")
    return _current_sim


class Sim:
    def __init__(self, seed: int = 0, collect_trace: bool = False,
                 explore_schedules: bool = False,
                 schedule_mode: Optional[str] = None, race=None):
        self.time = 0.0
        self._next_tid = 0
        self._timer_seq = 0
        self._run_queue: deque[_Thread] = deque()
        self._timers: list[tuple[float, int, Callable[[], None]]] = []
        self._threads: dict[int, _Thread] = {}
        self._trace: Trace = []
        self._collect = collect_trace
        self._rng = random.Random(seed)
        # schedule perturbation (ouro-race exploration): "fifo" is the
        # production schedule; "random"/"lifo" insert a preemption choice
        # at every scheduler step.  explore_schedules is the legacy
        # spelling of "random".
        if schedule_mode is None:
            schedule_mode = "random" if explore_schedules else "fifo"
        if schedule_mode not in ("fifo", "random", "lifo"):
            raise ValueError(f"unknown schedule_mode {schedule_mode!r}")
        self._mode = schedule_mode
        # happens-before race detector (simharness/race.py), or None.
        # TVar hooks reach it through runtime.active_detector().
        self._race = race
        self._main: Optional[_Thread] = None
        self._current: Optional[_Thread] = None
        # tvar id -> [(thread, epoch), ...] blocked on an STM retry
        self._stm_waiters: dict[int, list[tuple[_Thread, int]]] = {}

    # -- tracing ------------------------------------------------------------
    def now(self) -> float:
        return self.time

    def _ev(self, thread: Optional[_Thread], kind: str, payload: Any = None):
        if self._collect:
            tid = thread.tid if thread else -1
            label = thread.label if thread else "sim"
            self._trace.append(SimEvent(self.time, tid, label, kind, payload))

    # -- thread management --------------------------------------------------
    def _new_thread(self, coro: Coroutine, label: str) -> _Thread:
        tid = self._next_tid
        self._next_tid += 1
        t = _Thread(tid, label or f"thread-{tid}", coro)
        self._threads[tid] = t
        self._run_queue.append(t)
        self._ev(t, "fork")
        if self._race is not None:
            parent = self._current.tid if self._current is not None else None
            self._race.on_fork(parent, t.tid, t.label)
        return t

    def spawn(self, coro: Coroutine, label: str = "") -> Async:
        return Async(self._new_thread(coro, label), self)

    def _wake(self, thread: _Thread, value: Any = None,
              exc: Optional[BaseException] = None,
              epoch: Optional[int] = None):
        if thread.state != _BLOCKED:
            return
        if epoch is not None and epoch != thread.block_epoch:
            return   # stale waker from an earlier block of this thread
        thread.state = _RUNNABLE
        thread.blocked_on = None
        thread.resume_value = value
        thread.resume_exc = exc
        if exc is not None:
            thread.stm_tx_fn = None   # exception overrides pending STM re-run
        self._run_queue.append(thread)
        self._ev(thread, "wake")

    def _cancel(self, thread: _Thread):
        if thread.state in (_DONE, _FAILED):
            return
        thread.pending_cancel = True
        if thread.state == _BLOCKED and not thread.masked:
            thread.pending_cancel = False
            self._wake(thread, exc=AsyncCancelled())

    # -- timers -------------------------------------------------------------
    def _add_timer(self, delay: float, fn: Callable[[], None]) -> int:
        self._timer_seq += 1
        if self._race is not None:
            # the callback runs with the clock its creator has NOW (the
            # registration point) so HB flows through registerDelay-style
            # wakeups; see race.py "timer" edge
            token = self._race.on_timer_create()

            def fn(inner=fn, token=token, race=self._race):
                race.begin_timer(token)
                try:
                    inner()
                finally:
                    race.end_timer()
        heapq.heappush(self._timers, (self.time + max(delay, 0.0),
                                      self._timer_seq, fn))
        return self._timer_seq

    # -- STM integration (stm.py calls these) -------------------------------
    def stm_block(self, thread: _Thread, tvar_ids, epoch: int):
        for vid in tvar_ids:
            waiters = self._stm_waiters.setdefault(vid, [])
            if waiters:
                # prune stale registrations (earlier blocks of any thread) so
                # never-written tvars don't accumulate dead entries unboundedly
                waiters[:] = [(t, ep) for t, ep in waiters
                              if ep == t.block_epoch and t.state == _BLOCKED]
            waiters.append((thread, epoch))

    def stm_notify(self, tvar_ids):
        for vid in tvar_ids:
            for t, ep in self._stm_waiters.pop(vid, ()):
                # epoch check drops registrations left under *other* tvars by
                # an earlier wake of the same thread
                self._wake(t, epoch=ep)  # stm_tx_fn set -> re-run transaction

    # -- main loop ----------------------------------------------------------
    def run(self, main: Coroutine, label: str = "main") -> Any:
        global _current_sim
        from . import runtime as _runtime
        prev, _current_sim = _current_sim, self
        prev_rt = _runtime.current_or_none()
        _runtime.set_current(self)
        try:
            self._main = self._new_thread(main, label)
            while True:
                if self._main.state == _DONE:
                    return self._main.result
                if self._main.state == _FAILED:
                    raise self._main.exc
                if not self._run_queue:
                    if self._timers:
                        t, _, fn = heapq.heappop(self._timers)
                        self.time = max(self.time, t)
                        fn()
                        continue
                    blocked = [t for t in self._threads.values()
                               if t.state == _BLOCKED]
                    raise Deadlock(
                        "deadlock: no runnable threads, no timers; blocked: "
                        + ", ".join(f"{t.tid}:{t.label} on {t.blocked_on}"
                                    for t in blocked))
                if self._mode == "random" and len(self._run_queue) > 1:
                    # O(n) pick is fine: exploration mode is for tests
                    i = self._rng.randrange(len(self._run_queue))
                    self._run_queue.rotate(-i)
                    thread = self._run_queue.popleft()
                    self._run_queue.rotate(i)
                elif self._mode == "lifo" and len(self._run_queue) > 1:
                    thread = self._run_queue.pop()
                else:
                    thread = self._run_queue.popleft()
                if thread.state != _RUNNABLE:
                    continue
                self._step(thread)
        finally:
            # Close coroutines of threads outliving the simulation so their
            # finally/__aexit__ blocks run and GC sees no un-awaited frames.
            # Runs BEFORE restoring _current_sim (cleanup may use sim APIs);
            # cleanup exceptions never replace the simulation's result.
            # The race detector detaches first: teardown accesses happen
            # outside any schedule with a stale thread ctx — recording
            # them would misattribute them to the last-stepped thread
            # and fabricate (or mask) races
            self._race = None
            interrupt: Optional[BaseException] = None
            for t in self._threads.values():
                if t.state not in (_DONE, _FAILED):
                    try:
                        t.coro.close()
                    except Exception as exc:
                        self._ev(t, "cleanup-error", repr(exc))
                    except BaseException as exc:  # KeyboardInterrupt etc.
                        self._ev(t, "cleanup-error", repr(exc))
                        interrupt = interrupt or exc
            _current_sim = prev
            _runtime.set_current(prev_rt)
            if interrupt is not None:
                raise interrupt

    def _step(self, thread: _Thread):
        self._current = thread
        if self._race is not None:
            self._race.set_ctx(thread.tid, thread.label)
        # a pending cancellation beats a pending STM re-run: the blocked
        # transaction aborts WITHOUT committing (GHC semantics — an async
        # exception delivered to a thread blocked in `atomically` rolls the
        # transaction back), so a message that wakes a recv in the same
        # instant a timeout fires stays in the queue instead of being
        # consumed-and-dropped by the cancelled continuation
        if thread.pending_cancel and not thread.masked \
                and thread.resume_exc is None:
            thread.pending_cancel = False
            thread.stm_tx_fn = None
            thread.resume_exc = AsyncCancelled()
        if thread.stm_tx_fn is not None and thread.resume_exc is None:
            tx_fn, thread.stm_tx_fn = thread.stm_tx_fn, None
            self._run_stm(thread, tx_fn)
            return
        try:
            if thread.resume_exc is not None:
                exc, thread.resume_exc = thread.resume_exc, None
                # an exception resume supersedes any pending transaction:
                # it must not re-run if the coroutine catches and re-blocks
                thread.stm_tx_fn = None
                eff = thread.coro.throw(exc)
            else:
                val, thread.resume_value = thread.resume_value, None
                eff = thread.coro.send(val)
        except StopIteration as stop:
            thread.state = _DONE
            thread.result = stop.value
            self._ev(thread, "stop")
            self._finish(thread)
            return
        except AsyncCancelled as exc:
            thread.state = _FAILED
            thread.exc = exc
            self._ev(thread, "cancelled")
            self._finish(thread)
            return
        except BaseException as exc:  # noqa: BLE001 — thread death is data
            thread.state = _FAILED
            thread.exc = exc
            self._ev(thread, "fail", repr(exc))
            self._finish(thread)
            return
        self._handle(thread, eff)

    def _finish(self, thread: _Thread):
        for w, ep in thread.waiters:
            if self._race is not None and ep == w.block_epoch \
                    and w.state == _BLOCKED:
                self._race.on_join(w.tid, w.label, thread.tid, thread.label)
            if thread.state == _FAILED:
                self._wake(w, exc=thread.exc, epoch=ep)
            else:
                self._wake(w, value=thread.result, epoch=ep)
        thread.waiters.clear()

    def _handle(self, thread: _Thread, eff: Any):
        if not isinstance(eff, _Eff):
            raise RuntimeError(
                f"thread {thread.label} awaited a non-simharness awaitable: "
                f"{eff!r} (all blocking ops must go through simharness)")
        kind = eff.kind
        if kind == "sleep":
            ep = thread.block(f"sleep({eff.payload})")
            self._ev(thread, "delay", eff.payload)
            self._add_timer(eff.payload,
                            lambda: self._wake(thread, epoch=ep))
        elif kind == "yield":
            thread.state = _RUNNABLE
            self._run_queue.append(thread)
        elif kind == "wait":
            target: _Thread = eff.payload
            if target.state in (_DONE, _FAILED) and self._race is not None:
                self._race.on_join(thread.tid, thread.label,
                                   target.tid, target.label)
            if target.state == _DONE:
                thread.resume_value = target.result
                self._run_queue.append(thread)
            elif target.state == _FAILED:
                thread.resume_exc = target.exc
                self._run_queue.append(thread)
            else:
                ep = thread.block(f"wait({target.tid}:{target.label})")
                target.waiters.append((thread, ep))
        elif kind == "atomically":
            self._run_stm(thread, eff.payload)
        elif kind == "mask":
            thread.mask_depth = max(0, thread.mask_depth + eff.payload)
            thread.state = _RUNNABLE
            self._run_queue.append(thread)
        else:
            raise RuntimeError(f"unknown effect {kind!r}")

    # STM: run the transaction function now (atomic by construction).
    def _run_stm(self, thread: _Thread, tx_fn):
        from . import stm as _stm
        tx = _stm.Tx(self)
        try:
            result = tx_fn(tx)
        except _stm.Retry:
            read_ids = list(tx.read_set)
            tx.rollback()
            if not read_ids:
                thread.resume_exc = RuntimeError(
                    "STM retry with empty read set would block forever")
                self._run_queue.append(thread)
                return
            ep = thread.block(f"STM retry on {len(read_ids)} tvars")
            thread.stm_tx_fn = tx_fn
            self._ev(thread, "stm", "retry")
            self.stm_block(thread, read_ids, ep)
        except BaseException as exc:  # noqa: BLE001 — surfaced in the thread
            tx.rollback()
            thread.resume_exc = exc
            self._run_queue.append(thread)
        else:
            if self._race is not None and (tx.read_vars or tx._writes):
                self._race.on_commit(
                    thread.tid, thread.label, dict(tx.read_vars),
                    {vid: tvar for vid, (tvar, _v) in tx._writes.items()})
            written = tx.commit()
            if written:
                self.stm_notify(written)
            self._ev(thread, "stm", "commit")
            thread.resume_value = result
            self._run_queue.append(thread)


# ---------------------------------------------------------------------------
# User-facing API (module-level, operating on the current sim)
# ---------------------------------------------------------------------------

def run(main: Coroutine, seed: int = 0, explore_schedules: bool = False) -> Any:
    """Run a simulation to completion; returns main's result (runSimOrThrow)."""
    return Sim(seed=seed, explore_schedules=explore_schedules).run(main)


def run_trace(main: Coroutine, seed: int = 0,
              explore_schedules: bool = False) -> tuple[Any, Trace]:
    """runSimTrace analog: returns (result, trace of SimEvents)."""
    sim = Sim(seed=seed, collect_trace=True, explore_schedules=explore_schedules)
    result = sim.run(main)
    return result, sim._trace


def leaked_threads(trace: Trace) -> set:
    """Tids forked during the run that never reached a terminal event
    (stop/cancelled/fail) — the shared thread-leak gate (chaos sweeps,
    scrape-endpoint shutdown tests, bench --smoke).  One definition of
    "terminal" so a future event kind cannot silently skew one copy."""
    forked = {e.tid for e in trace if e.kind == "fork"}
    ended = {e.tid for e in trace
             if e.kind in ("stop", "cancelled", "fail")}
    return forked - ended


def spawn(coro: Coroutine, label: str = "") -> Async:
    return current_sim().spawn(coro, label)


def now() -> float:
    """Virtual monotonic clock (MonadMonotonicTime analog)."""
    return current_sim().time


async def sleep(seconds: float) -> None:
    """threadDelay analog (io-sim-classes MonadTimer.hs:38)."""
    await _Eff("sleep", float(seconds))


async def yield_() -> None:
    """Reschedule self to the back of the run queue."""
    await _Eff("yield")


async def atomically(tx_fn) -> Any:
    """Run an STM transaction; tx_fn receives a Tx handle.

    MonadSTM.atomically analog
    (io-sim-classes/src/Control/Monad/Class/MonadSTM.hs:162).
    """
    return await _Eff("atomically", tx_fn)


def trace_event(payload: Any, label: str = "user") -> None:
    """traceM analog (io-sim/src/Control/Monad/IOSim.hs:16,76)."""
    sim = current_sim()
    if sim._collect:
        sim._trace.append(SimEvent(sim.time, -1, "user", label, payload))


class mask:
    """``async with mask():`` — defer cancellation within the body. Nests.

    MonadMask analog (io-sim-classes MonadThrow.hs:176).
    """

    async def __aenter__(self):
        await _Eff("mask", +1)
        return self

    async def __aexit__(self, *exc):
        await _Eff("mask", -1)
        return False


async def timeout(seconds: float, coro: Coroutine) -> tuple[bool, Any]:
    """MonadTimer.timeout analog: (True, result) or (False, None) on expiry."""
    sim = current_sim()
    child = sim.spawn(coro, label="timeout-child")
    fired = {"v": False}

    def on_fire():
        if not child.done:
            fired["v"] = True
            child.cancel()

    sim._add_timer(seconds, on_fire)
    try:
        result = await child.wait()
        return True, result
    except AsyncCancelled as e:
        # (False, None) only for the child's own timer-induced death; the
        # caller's own cancellation (a different exception object) re-raises.
        if fired["v"] and child._thread.exc is e:
            return False, None
        raise
    finally:
        if not child.done:
            child.cancel()   # caller left early: don't leak the child


def new_timeout(seconds: float):
    """registerDelay analog: returns a TVar that flips to True at expiry."""
    from . import stm as _stm
    sim = current_sim()
    tv = _stm.TVar(False, label=f"timeout@{sim.time + seconds:.6f}")

    def fire():
        if sim._race is not None:   # timer write: HB edge, never a race
            sim._race.on_raw_write(tv)
        tv._value = True
        sim.stm_notify([tv._id])

    sim._add_timer(seconds, fire)
    return tv
