"""simharness — one async/STM interface, two interpreters.

The io-sim / io-sim-classes analog (reference: /root/reference/io-sim,
/root/reference/io-sim-classes).  All higher layers of ouroboros_tpu are
written against this facade, never against wall-clock asyncio directly —
the property that makes whole-system deterministic simulation possible
(SURVEY.md §1, §4.1) while the SAME code runs in production:

- `run(main)`     — the deterministic simulator (io-sim: virtual clock,
                    seeded scheduler, trace, deadlock detection)
- `io_run(main)`  — the asyncio-backed IO runtime (io_runtime.py), real
                    clock + real sockets

The module-level functions dispatch to whichever runtime is active.
"""
from typing import Any

from . import runtime as _runtime
from .core import (
    Async, AsyncCancelled, Deadlock, Sim, SimEvent, Trace, current_sim,
    leaked_threads, mask, run, run_trace,
)
from .core import (
    atomically as _sim_atomically,
    new_timeout as _sim_new_timeout,
    sleep as _sim_sleep,
    timeout as _sim_timeout,
    trace_event as _sim_trace_event,
    yield_ as _sim_yield,
)
from .faults import (
    FaultPlan, FaultSpec, FaultyBearer, FaultyChannel, LinkDown, Partition,
)
from .io_runtime import IoAsync, IoRuntime, io_run
from .race import (
    Race, RaceDetector, RaceReport, ScheduleController, explore_races,
)
from .stm import Retry, TBQueue, TMVar, TQueue, TVar, Tx, retry

__all__ = [
    "Async", "AsyncCancelled", "Deadlock", "Sim", "SimEvent", "Trace",
    "IoAsync", "IoRuntime", "io_run",
    "FaultPlan", "FaultSpec", "FaultyBearer", "FaultyChannel", "LinkDown",
    "Partition",
    "Race", "RaceDetector", "RaceReport", "ScheduleController",
    "explore_races",
    "atomically", "current_sim", "leaked_threads", "mask", "new_timeout",
    "now", "run", "run_trace", "sleep", "spawn", "timeout", "trace_event",
    "yield_",
    "Retry", "TBQueue", "TMVar", "TQueue", "TVar", "Tx", "retry",
]


def _rt():
    return _runtime.current()


def spawn(coro, label: str = ""):
    return _rt().spawn(coro, label)


def now() -> float:
    return _rt().now()


async def sleep(seconds: float) -> None:
    rt = _rt()
    if isinstance(rt, Sim):
        await _sim_sleep(seconds)
    else:
        await rt.sleep(seconds)


async def yield_() -> None:
    rt = _rt()
    if isinstance(rt, Sim):
        await _sim_yield()
    else:
        await rt.yield_()


async def atomically(tx_fn) -> Any:
    rt = _rt()
    if isinstance(rt, Sim):
        return await _sim_atomically(tx_fn)
    return await rt.atomically(tx_fn)


async def timeout(seconds: float, coro):
    rt = _rt()
    if isinstance(rt, Sim):
        return await _sim_timeout(seconds, coro)
    return await rt.timeout(seconds, coro)


def trace_event(payload, label: str = "user") -> None:
    rt = _runtime.current_or_none()
    if rt is None:
        return
    if isinstance(rt, Sim):
        _sim_trace_event(payload, label)
    else:
        rt.trace_event(payload, label)


def new_timeout(seconds: float):
    rt = _rt()
    if isinstance(rt, Sim):
        return _sim_new_timeout(seconds)
    return rt.new_timeout(seconds)


async def wait_pred(pred, timeout: float) -> bool:
    """Block until `pred(tx)` is true (returns True) or `timeout` elapses
    (returns False) — one STM transaction, nothing consumed, no task
    cancellation involved.  The building block for non-destructive channel
    polling (Channel/MuxChannel.wait_ready)."""
    tv = new_timeout(timeout)

    def tx_fn(tx):
        if pred(tx):
            return True
        if tx.read(tv):
            return False
        retry()
    return await atomically(tx_fn)
