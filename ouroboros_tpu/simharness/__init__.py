"""simharness — deterministic async runtime + virtual clock + STM.

The io-sim / io-sim-classes analog (reference: /root/reference/io-sim,
/root/reference/io-sim-classes).  All higher layers of ouroboros_tpu are
written against this interface, never against wall-clock asyncio — the
property that makes whole-system deterministic simulation possible
(SURVEY.md §1, §4.1).
"""
from .core import (
    Async, AsyncCancelled, Deadlock, Sim, SimEvent, Trace,
    atomically, current_sim, mask, new_timeout, now, run, run_trace,
    sleep, spawn, timeout, trace_event, yield_,
)
from .stm import Retry, TBQueue, TMVar, TQueue, TVar, Tx, retry

__all__ = [
    "Async", "AsyncCancelled", "Deadlock", "Sim", "SimEvent", "Trace",
    "atomically", "current_sim", "mask", "new_timeout", "now", "run",
    "run_trace", "sleep", "spawn", "timeout", "trace_event", "yield_",
    "Retry", "TBQueue", "TMVar", "TQueue", "TVar", "Tx", "retry",
]
