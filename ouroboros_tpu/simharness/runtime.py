"""Runtime registry: which runtime (Sim or IoRuntime) is active.

The io-sim-classes move (SURVEY.md §1 "the defining architectural move"):
all node code is written against the simharness facade, and the facade
dispatches to the active runtime — the deterministic simulator for tests,
the asyncio-backed IO runtime for production.  One implementation, two
interpreters, like `IOLike`'s IO/IOSim instances.
"""
from __future__ import annotations

from typing import Optional

_current = None


def current():
    if _current is None:
        raise RuntimeError("not inside a simulation or IO runtime")
    return _current


def current_or_none():
    return _current


def set_current(rt) -> None:
    global _current
    _current = rt


def active_detector():
    """The active runtime's happens-before race detector, or None.

    Sim carries one only while an ouro-race exploration is attached
    (simharness/race.py); the IO runtime never does.  TVar's peek and
    set_notify hooks call this on every access, so it must stay a pair
    of attribute reads — no isinstance, no raising."""
    return getattr(_current, "_race", None)
