"""Chain primitives: Point, Tip, headers/blocks.

Reference: ouroboros-network/src/Ouroboros/Network/Block.hs (HasHeader,
Point, Tip) and Testing/ConcreteBlock.hs (the concrete block used by
network-layer tests).  SlotNo/BlockNo are plain ints.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional, Protocol, Sequence, runtime_checkable

from ..utils import cbor

GENESIS_HASH = b"\x00" * 32


@dataclass(frozen=True, order=True)
class Point:
    """A point on a chain: (slot, header hash); origin = Point.genesis()."""
    slot: int
    hash: bytes

    @classmethod
    def genesis(cls) -> "Point":
        return cls(-1, GENESIS_HASH)

    @property
    def is_genesis(self) -> bool:
        return self.slot < 0

    def encode(self):
        """Reference wire grammar: origin = [], other points = [slot, hash]
        (ouroboros-network/test/messages.cddl:152-155)."""
        if self.is_genesis:
            return []
        return [self.slot, self.hash]

    @classmethod
    def decode(cls, obj) -> "Point":
        if len(obj) == 0:
            return cls.genesis()
        return cls(int(obj[0]), bytes(obj[1]))


@dataclass(frozen=True)
class Tip:
    """Tip of a chain as advertised by ChainSync: point + block number."""
    point: Point
    block_no: int

    @classmethod
    def genesis(cls) -> "Tip":
        return cls(Point.genesis(), -1)

    def encode(self):
        """tip = [point, uint] (messages.cddl:36); the genesis tip's
        block number is clamped to 0 on the wire (uint), recovered as
        Tip.genesis() on decode since origin admits no real block."""
        return [self.point.encode(), max(self.block_no, 0)]

    @classmethod
    def decode(cls, obj) -> "Tip":
        p = Point.decode(obj[0])
        if p.is_genesis:
            return cls.genesis()
        return cls(p, int(obj[1]))


@runtime_checkable
class HasHeader(Protocol):
    """Anything with (slot, block_no, hash, prev_hash) — headers and blocks."""
    slot: int
    block_no: int

    @property
    def hash(self) -> bytes: ...

    @property
    def prev_hash(self) -> bytes: ...


def point_of(b) -> Point:
    return Point(b.slot, b.hash)


@dataclass(frozen=True)
class BlockHeader:
    """Concrete test header (ConcreteBlock.hs analog).

    body_hash commits to the block body; signature/proof fields are attached
    by the consensus layer's header wrapper (consensus/headers.py)."""
    slot: int
    block_no: int
    prev_hash: bytes
    body_hash: bytes
    issuer: bytes = b""

    _hash_cache: dict = field(default_factory=dict, repr=False, hash=False,
                              compare=False)

    def encode(self):
        return [self.slot, self.block_no, self.prev_hash, self.body_hash,
                self.issuer]

    @classmethod
    def decode(cls, obj) -> "BlockHeader":
        return cls(int(obj[0]), int(obj[1]), bytes(obj[2]), bytes(obj[3]),
                   bytes(obj[4]))

    @property
    def bytes(self) -> bytes:
        return cbor.dumps(self.encode())

    @property
    def hash(self) -> bytes:
        c = self._hash_cache
        if "h" not in c:
            c["h"] = hashlib.blake2b(self.bytes, digest_size=32).digest()
        return c["h"]


@dataclass(frozen=True)
class Block:
    """Concrete test block: header + opaque tx list."""
    header: BlockHeader
    body: tuple = ()

    @property
    def slot(self) -> int:
        return self.header.slot

    @property
    def block_no(self) -> int:
        return self.header.block_no

    @property
    def hash(self) -> bytes:
        return self.header.hash

    @property
    def prev_hash(self) -> bytes:
        return self.header.prev_hash

    def encode(self):
        return [self.header.encode(), list(self.body)]

    @classmethod
    def decode(cls, obj) -> "Block":
        return cls(BlockHeader.decode(obj[0]),
                   tuple(bytes(t) if isinstance(t, (bytes, bytearray))
                         else t for t in obj[1]))

    @property
    def bytes(self) -> bytes:
        return cbor.dumps(self.encode())


def body_hash(body: Sequence) -> bytes:
    return hashlib.blake2b(cbor.dumps(list(body)), digest_size=32).digest()


def make_block(prev: Optional[Block], slot: int, body: Sequence = (),
               issuer: bytes = b"") -> Block:
    """Chain-extend helper for tests and the mock ledger."""
    if prev is None:
        prev_hash, block_no = GENESIS_HASH, 0
    else:
        prev_hash, block_no = prev.hash, prev.block_no + 1
    hdr = BlockHeader(slot=slot, block_no=block_no, prev_hash=prev_hash,
                      body_hash=body_hash(body), issuer=issuer)
    return Block(hdr, tuple(body))
