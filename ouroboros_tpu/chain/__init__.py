"""chain — chain primitives: Point/Tip, blocks, AnchoredFragment, Chain.

Reference: ouroboros-network Block.hs / AnchoredFragment.hs / MockChain/*.
"""
from .block import (GENESIS_HASH, Block, BlockHeader, HasHeader, Point, Tip,
                    body_hash, make_block, point_of)
from .chain import Chain, ChainProducerState
from .fragment import AnchoredFragment

__all__ = ["GENESIS_HASH", "Block", "BlockHeader", "HasHeader", "Point",
           "Tip", "body_hash", "make_block", "point_of", "Chain",
           "ChainProducerState", "AnchoredFragment"]
