"""AnchoredFragment — the workhorse chain-suffix type.

Reference: ouroboros-network/src/Ouroboros/Network/AnchoredFragment.hs (built
on AnchoredSeq.hs's finger tree).  A fragment is a contiguous run of
headers/blocks anchored at a Point (exclusive); the anchor is where the
fragment attaches to the rest of the chain.  Python rebuild uses a list +
hash index: O(1) head/lookup, O(n) copy on rollback — fragments are bounded
by k (=security parameter) in all uses, so this is the right simplicity
trade (SURVEY.md §5 "long-context": k-bounded suffix).
"""
from __future__ import annotations

from typing import Generic, Iterable, Optional, Sequence, TypeVar

from .block import Point, point_of

B = TypeVar("B")   # anything HasHeader


class AnchoredFragment(Generic[B]):
    __slots__ = ("anchor", "anchor_block_no", "_blocks", "_index")

    def __init__(self, anchor: Point, blocks: Iterable[B] = (),
                 anchor_block_no: int = -1):
        self.anchor = anchor
        self.anchor_block_no = anchor_block_no
        self._blocks: list[B] = list(blocks)
        self._index = {b.hash: i for i, b in enumerate(self._blocks)}

    # -- construction --------------------------------------------------------
    @classmethod
    def from_genesis(cls) -> "AnchoredFragment[B]":
        return cls(Point.genesis())

    def copy(self) -> "AnchoredFragment[B]":
        new = type(self).__new__(type(self))
        new.anchor = self.anchor
        new.anchor_block_no = self.anchor_block_no
        new._blocks = list(self._blocks)
        new._index = dict(self._index)
        return new

    # -- queries -------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._blocks)

    def __iter__(self):
        return iter(self._blocks)

    @property
    def blocks(self) -> Sequence[B]:
        return self._blocks

    @property
    def head(self) -> Optional[B]:
        return self._blocks[-1] if self._blocks else None

    @property
    def head_point(self) -> Point:
        return point_of(self._blocks[-1]) if self._blocks else self.anchor

    @property
    def head_block_no(self) -> int:
        return self._blocks[-1].block_no if self._blocks \
            else self.anchor_block_no

    def contains_point(self, p: Point) -> bool:
        if p == self.anchor:
            return True
        i = self._index.get(p.hash)
        return i is not None and self._blocks[i].slot == p.slot

    def lookup(self, h: bytes) -> Optional[B]:
        i = self._index.get(h)
        return self._blocks[i] if i is not None else None

    def points(self) -> list[Point]:
        """All points, newest first (for ChainSync intersection finding)."""
        return [point_of(b) for b in reversed(self._blocks)] + [self.anchor]

    def select_points(self, offsets: Sequence[int]) -> list[Point]:
        """Points at the given offsets back from the head (0 = head) —
        O(len(offsets)), not O(fragment)."""
        n = len(self._blocks)
        out = []
        for o in offsets:
            if o < n:
                out.append(point_of(self._blocks[n - 1 - o]))
            elif o == n:
                out.append(self.anchor)
        return out

    # -- modification --------------------------------------------------------
    def add_block(self, b: B) -> None:
        """Extend at the head; validates the prev-hash link (the genesis
        anchor's hash is the all-zero GENESIS_HASH, so the check is total)."""
        expect = self._blocks[-1].hash if self._blocks else self.anchor.hash
        if b.prev_hash != expect:
            raise ValueError("block does not link onto fragment head")
        self._index[b.hash] = len(self._blocks)
        self._blocks.append(b)

    def _rebuild(self, anchor: Point, blocks,
                 anchor_block_no: int) -> "AnchoredFragment[B]":
        """Construct a fragment of the same (sub)class without going through
        the subclass __init__ (subclasses may narrow its signature)."""
        new = type(self).__new__(type(self))
        AnchoredFragment.__init__(new, anchor, blocks, anchor_block_no)
        return new

    def rollback(self, p: Point) -> Optional["AnchoredFragment[B]"]:
        """Fragment truncated so head == p; None if p not on the fragment.
        Preserves the subclass (Chain.rollback returns a Chain)."""
        if p == self.anchor:
            return self._rebuild(self.anchor, (), self.anchor_block_no)
        i = self._index.get(p.hash)
        if i is None or self._blocks[i].slot != p.slot:
            return None
        return self._rebuild(self.anchor, self._blocks[:i + 1],
                             self.anchor_block_no)

    def truncate_to(self, p: Point) -> bool:
        """In-place rollback so head == p; False if p not on the fragment."""
        if p == self.anchor:
            self._blocks.clear()
            self._index.clear()
            return True
        i = self._index.get(p.hash)
        if i is None or self._blocks[i].slot != p.slot:
            return False
        for b in self._blocks[i + 1:]:
            del self._index[b.hash]
        del self._blocks[i + 1:]
        return True

    def drop_newest(self, n: int) -> "AnchoredFragment[B]":
        keep = len(self._blocks) - n
        return self._rebuild(self.anchor, self._blocks[:max(keep, 0)],
                             self.anchor_block_no)

    def anchor_newer_than(self, k: int) -> "AnchoredFragment[B]":
        """Re-anchor so at most k newest blocks remain (the k-suffix)."""
        if len(self._blocks) <= k:
            return self
        cut = len(self._blocks) - k
        new_anchor_blk = self._blocks[cut - 1]
        return self._rebuild(point_of(new_anchor_blk), self._blocks[cut:],
                             new_anchor_blk.block_no)

    # -- comparisons ---------------------------------------------------------
    def intersect(self, other: "AnchoredFragment[B]") -> Optional[Point]:
        """Most recent common point, or None if unrelated.  Probes the
        hash index directly — no per-call set construction."""
        for b in reversed(other._blocks):
            if b.hash in self._index or b.hash == self.anchor.hash:
                return point_of(b)
        if other.anchor.hash in self._index \
                or other.anchor.hash == self.anchor.hash \
                or other.anchor == self.anchor:
            return other.anchor
        return None

    def after_point(self, p: Point) -> Optional[list[B]]:
        """Blocks strictly after point p; None if p not on fragment."""
        if p == self.anchor:
            return list(self._blocks)
        i = self._index.get(p.hash)
        if i is None:
            return None
        return self._blocks[i + 1:]
