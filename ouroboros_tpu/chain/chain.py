"""Chain + ChainProducerState — producer-side follower bookkeeping.

Reference: ouroboros-network/src/Ouroboros/Network/MockChain/Chain.hs:94 and
MockChain/ProducerState.hs:22-171.  ChainProducerState tracks, per follower,
the read pointer on the producer's chain; the ChainSync server is driven off
it (next_change / rollback semantics).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Optional

from .block import Point, point_of
from .fragment import AnchoredFragment


class Chain(AnchoredFragment):
    """A genesis-anchored fragment (the mock whole-chain type)."""

    def __init__(self, blocks=()):
        super().__init__(Point.genesis(), blocks)


@dataclass
class _FollowerState:
    # next_to_send: index into chain of next block to send; None => must
    # first send a rollback to `point`
    point: Point
    needs_rollback: bool


class ChainProducerState:
    """Producer chain + per-follower read pointers (ProducerState.hs:22)."""

    def __init__(self, chain: Optional[Chain] = None):
        self.chain: Chain = chain or Chain()
        self._followers: dict[int, _FollowerState] = {}
        self._ids = itertools.count()
        # bumped on every chain change; ChainSync servers block on it
        from ..simharness import TVar
        self.version = TVar(0, label="producer.version")

    def _bump(self) -> None:
        from ..simharness import core
        if core._current_sim is not None:
            self.version.set_notify(self.version.value + 1)
        else:
            self.version._value += 1

    # -- follower management -------------------------------------------------
    def new_follower(self, intersection: Point = None) -> int:
        fid = next(self._ids)
        pt = intersection if intersection is not None else Point.genesis()
        self._followers[fid] = _FollowerState(pt, needs_rollback=True)
        return fid

    def remove_follower(self, fid: int) -> None:
        self._followers.pop(fid, None)

    def set_follower_point(self, fid: int, p: Point) -> bool:
        if not self.chain.contains_point(p):
            return False
        self._followers[fid] = _FollowerState(p, needs_rollback=True)
        return True

    # -- chain updates ---------------------------------------------------------
    def add_block(self, b) -> None:
        self.chain.add_block(b)
        self._bump()

    def rollback(self, p: Point) -> bool:
        new_chain = self.chain.copy()
        if not new_chain.truncate_to(p):
            return False
        self.chain = new_chain
        for fs in self._followers.values():
            if not self.chain.contains_point(fs.point):
                fs.point = p
                fs.needs_rollback = True
        self._bump()
        return True

    def switch_fork(self, p: Point, new_blocks) -> bool:
        if not self.rollback(p):
            return False
        for b in new_blocks:
            self.chain.add_block(b)
        return True

    # -- the ChainSync server's pull API --------------------------------------
    def follower_instruction(self, fid: int):
        """Returns ("rollback", Point) | ("forward", block) | None (idle).

        Mirrors ProducerState.hs's followerInstruction."""
        fs = self._followers[fid]
        if fs.needs_rollback:
            fs.needs_rollback = False
            return ("rollback", fs.point)
        nxt = self.chain.after_point(fs.point)
        if nxt is None:   # pointer fell off (shouldn't happen: rollback fixes)
            fs.point = self.chain.anchor
            fs.needs_rollback = False
            return ("rollback", fs.point)
        if not nxt:
            return None
        b = nxt[0]
        fs.point = point_of(b)
        return ("forward", b)
