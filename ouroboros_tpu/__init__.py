"""ouroboros_tpu — a TPU-native rebuild of the Ouroboros network/consensus stack.

Reference: dizgotti/ouroboros-network (Haskell). This package re-designs the
same capability surface TPU-first:

- ``simharness``  — deterministic async runtime + virtual clock + STM
                    (io-sim / io-sim-classes analog)
- ``crypto``      — batched Ed25519 / ECVRF / KES / Blake2b verification,
                    JAX device kernels + pure CPU reference backend
- ``chain``       — Point/Tip/HasHeader, AnchoredFragment (chain types)
- ``network``     — typed protocols, mux, handshake, mini-protocols,
                    block-fetch decision logic, peer selection, diffusion
- ``storage``     — HasFS, ImmutableDB, VolatileDB, LedgerDB, ChainDB
- ``consensus``   — ConsensusProtocol, header validation, ledger, mempool,
                    node kernel, forging; batched-validation seam
- ``parallel``    — device mesh + sharded batch-verify (ICI-scaled)
- ``hfc``         — era composition / time translation (hard-fork combinator)
"""

__version__ = "0.1.0"
