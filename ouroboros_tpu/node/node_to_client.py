"""Node-to-client: local chainsync (blocks), state queries, tx submission.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/MiniProtocol/
LocalStateQuery/Server.hs (acquire against LedgerDB past states),
LocalTxSubmission/Server.hs (submit → mempool), consensus
Network/NodeToClient.hs (app assembly; local protocol numbers: chainsync=5,
txsubmission=6, statequery=7 — ouroboros-network NodeToNode.hs:382-391),
and cardano-client/src/Cardano/Client/Subscription.hs:57 (`subscribe`:
follow the chain with client callbacks).

The local chainsync rolls FULL BLOCKS forward (node-to-client serves
blocks, not headers).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from .. import simharness as sim
from ..chain.block import Point
from ..network import node_to_node as n2n
from ..network.mux import INITIATOR, RESPONDER, CodecChannel, Mux, bearer_pair
from ..network.protocols import chainsync as cs_proto
from ..network.protocols import handshake as hs_proto
from ..network.protocols import localstatequery as lsq_proto
from ..network.protocols import localtxsubmission as ltx_proto
from ..network.typed import CLIENT, SERVER, Session
from ..utils import cbor
from .chain_sync import chain_sync_server

NODE_TO_CLIENT_V1 = 1


# -- queries (Shelley/Ledger/Query.hs analog: a small closed query algebra) --
def answer_query(kernel, ext_state, query):
    """Answer a query against an acquired ExtLedgerState."""
    kind = query[0] if isinstance(query, (list, tuple)) else query
    if kind == "tip":
        return ext_state.header.tip_point.encode()
    if kind == "slot":
        return getattr(ext_state.ledger, "slot", None)
    if kind == "state-hash":
        return ext_state.ledger.state_hash()
    if kind == "utxo":
        return [list(e) for e in getattr(ext_state.ledger, "utxo", ())]
    if kind == "protocol-state":
        dep = ext_state.header.chain_dep_state
        return repr(dep)
    raise ValueError(f"unknown query {query!r}")


def serve_node_to_client(kernel, mux_r: Mux, label: str = "local") -> list:
    """Spawn the responder-side local protocol servers on an existing mux
    (mkApps for node-to-client, Network/NodeToClient.hs)."""
    threads = []

    async def run():
        versions = hs_proto.Versions().add(NODE_TO_CLIENT_V1,
                                           {"magic": kernel.network_magic})
        hs = Session(hs_proto.SPEC, SERVER,
                     CodecChannel(mux_r.channel(n2n.HANDSHAKE_NUM,
                                                RESPONDER),
                                  hs_proto.CODEC))
        res = await hs_proto.server_accept(hs, versions,
                                           policy=n2n.accept_same_magic)
        if res[0] != "accepted":
            return "refused"

        blk_dec = kernel.block_decode_obj
        cs_codec = cs_proto.make_codec(blk_dec) if blk_dec \
            else cs_proto.CODEC
        cs_srv = Session(
            cs_proto.SPEC, SERVER,
            CodecChannel(mux_r.channel(n2n.LOCAL_CHAINSYNC_NUM, RESPONDER),
                         cs_codec))
        threads.append(sim.spawn(
            chain_sync_server(cs_srv, kernel.chain_db,
                              content_of=lambda b: b),
            label=f"{label}.local-cs"))

        def acquire_state(point: Optional[Point]):
            db = kernel.chain_db
            if point is None:
                return db.current_ledger
            return db.ledger_db.state_at(point)

        lsq_srv = Session(
            lsq_proto.SPEC, SERVER,
            CodecChannel(mux_r.channel(n2n.LOCAL_STATEQUERY_NUM, RESPONDER),
                         lsq_proto.CODEC))
        threads.append(sim.spawn(
            lsq_proto.server(lsq_srv, acquire_state,
                             lambda st, q: answer_query(kernel, st, q)),
            label=f"{label}.local-lsq"))

        def try_add(tx_bytes: bytes) -> Optional[str]:
            if kernel.mempool is None or kernel.tx_decode is None:
                return "node has no mempool"
            tx = kernel.tx_decode(cbor.loads(tx_bytes))
            added, rejected = kernel.mempool.try_add_txs([tx])
            if added:
                return None
            return str(rejected[0][1]) if rejected else "rejected"

        ltx_srv = Session(
            ltx_proto.SPEC, SERVER,
            CodecChannel(mux_r.channel(n2n.LOCAL_TXSUBMISSION_NUM,
                                       RESPONDER),
                         ltx_proto.CODEC))
        threads.append(sim.spawn(
            ltx_proto.server(ltx_srv, try_add),
            label=f"{label}.local-ltx"))
        return "accepted"

    # threads[0] is the accept thread; awaiting it yields the handshake
    # outcome ("accepted"/"refused") — diffusion's local server holds or
    # releases the connection on it
    threads.insert(0, sim.spawn(run(), label=f"{label}.local-accept"))
    kernel._threads.extend(threads)
    return threads


@dataclass
class LocalClient:
    """A connected node-to-client handle (the wallet's end)."""
    mux: Mux
    chain_sync: Session
    state_query: Session
    tx_submission: Session
    version: int

    async def query(self, query, point: Optional[Point] = None):
        """Acquire → query → release, keeping the session open for the
        next query (query_once's MsgDone would retire it)."""
        sess = self.state_query
        await sess.send(lsq_proto.MsgAcquire(point))
        reply = await sess.recv()
        if isinstance(reply, lsq_proto.MsgFailure):
            return None
        await sess.send(lsq_proto.MsgQuery(query))
        result = (await sess.recv()).result
        await sess.send(lsq_proto.MsgRelease())
        return result

    async def submit_tx(self, tx) -> Optional[str]:
        """Submit one tx, keeping the session open for more (the submit()
        helper's MsgDone would retire it)."""
        sess = self.tx_submission
        await sess.send(ltx_proto.MsgSubmitTx(cbor.dumps(tx.encode())))
        reply = await sess.recv()
        return None if isinstance(reply, ltx_proto.MsgAcceptTx) \
            else reply.reason


async def connect_local_client(kernel, delay: float = 0.0,
                               network_magic: Optional[int] = None,
                               label: str = "wallet") -> Optional[LocalClient]:
    """Dial a node's node-to-client surface: negotiate, then expose typed
    sessions (connectTo + Subscription.subscribe's connection phase)."""
    bc, bn = bearer_pair(sdu_size=12288, delay=delay)
    mux_c = Mux(bc, f"{label}.mux-c")
    mux_n = Mux(bn, f"{label}.mux-n")
    mux_c.start()
    mux_n.start()
    serve_node_to_client(kernel, mux_n, label=label)

    magic = kernel.network_magic if network_magic is None else network_magic
    versions = hs_proto.Versions().add(NODE_TO_CLIENT_V1, {"magic": magic})
    hs = Session(hs_proto.SPEC, CLIENT,
                 CodecChannel(mux_c.channel(n2n.HANDSHAKE_NUM, INITIATOR),
                              hs_proto.CODEC))
    res = await hs_proto.client_propose(hs, versions)
    if res[0] != "accepted":
        return None

    blk_dec = kernel.block_decode_obj
    cs_codec = cs_proto.make_codec(blk_dec) if blk_dec else cs_proto.CODEC
    return LocalClient(
        mux=mux_c,
        chain_sync=Session(
            cs_proto.SPEC, CLIENT,
            CodecChannel(mux_c.channel(n2n.LOCAL_CHAINSYNC_NUM, INITIATOR),
                         cs_codec)),
        state_query=Session(
            lsq_proto.SPEC, CLIENT,
            CodecChannel(mux_c.channel(n2n.LOCAL_STATEQUERY_NUM, INITIATOR),
                         lsq_proto.CODEC)),
        tx_submission=Session(
            ltx_proto.SPEC, CLIENT,
            CodecChannel(mux_c.channel(n2n.LOCAL_TXSUBMISSION_NUM,
                                       INITIATOR),
                         ltx_proto.CODEC)),
        version=res[1])


async def subscribe(client: LocalClient, on_block: Callable[[Any], None],
                    points=(), until_blocks: Optional[int] = None) -> None:
    """Follow the node's chain, calling on_block per rolled-forward block
    (cardano-client Subscription.subscribe:57).  Stops after until_blocks
    rolls (None = forever)."""
    sess = client.chain_sync
    pts = tuple(points) or (Point.genesis(),)
    await sess.send(cs_proto.MsgFindIntersect(pts))
    reply = await sess.recv()
    if isinstance(reply, cs_proto.MsgIntersectNotFound):
        raise RuntimeError("no intersection for subscription")
    seen = 0
    while until_blocks is None or seen < until_blocks:
        await sess.send(cs_proto.MsgRequestNext())
        msg = await sess.recv()
        if isinstance(msg, cs_proto.MsgAwaitReply):
            msg = await sess.recv()
        if isinstance(msg, cs_proto.MsgRollForward):
            on_block(msg.header)        # local variant: this IS the block
            seen += 1
        # MsgRollBackward: restart from the new point (callbacks decide)
    await sess.send(cs_proto.MsgDone())
