"""Node layer — the NodeKernel and its hot loops.

Rebuilds /root/reference/ouroboros-consensus's node tier (SURVEY.md §2 L5:
NodeKernel.hs, MiniProtocol/ChainSync/Client.hs, BlockFetch logic) the TPU
way: the ChainSync client validates headers in *batched windows* (one device
call per window instead of per header), and block forging/fetching run as
simharness threads coordinated through STM TVars exactly like the
reference's IOLike threads.
"""
from .blockchain_time import BlockchainTime
from .kernel import BlockForging, NodeKernel, connect_nodes
from .chain_sync import CandidateState, ChainSyncClientError
from .run import (
    NodeHandle, RunNodeArgs, WrongNetworkError, check_db_marker, run_node,
    was_clean_shutdown,
)

__all__ = [
    "BlockchainTime", "BlockForging", "NodeKernel", "connect_nodes",
    "CandidateState", "ChainSyncClientError",
    "NodeHandle", "RunNodeArgs", "WrongNetworkError", "check_db_marker",
    "run_node", "was_clean_shutdown",
]
