"""Real-socket node networking — serve and dial over TCP/Unix sockets.

Reference: the Snocket + Socket layer (ouroboros-network-framework/src/
Ouroboros/Network/{Snocket.hs:163,Socket.hs:187} — `connectToNode` runs the
handshake then the mux over the accepted fd; `withServerNode` accepts and
runs responders).  Runs ONLY under the IO runtime (simharness.io_run);
in-sim tests use the in-memory bearers.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from .. import simharness as sim
from ..network.mux import Mux
from ..network.socket_bearer import SocketBearer
from .kernel import NodeKernel, _run_initiator, _run_responder


async def serve_node(kernel: NodeKernel, host: str = "127.0.0.1",
                     port: int = 0) -> tuple[asyncio.AbstractServer, int]:
    """withServerNode: accept connections, run the responder application
    on each (handshake first, then mini-protocols)."""
    async def on_conn(reader, writer):
        peername = writer.get_extra_info("peername")
        peer_id = f"{kernel.label}<-{peername}"
        bearer = SocketBearer(reader, writer)
        mux = Mux(bearer, f"{peer_id}.mux")
        mux.start()
        try:
            outcome = await _run_responder(kernel, mux, peer_id)
            if outcome != "refused":
                # hold the fd while the responder protocols run; the
                # demuxer's end (EOF/error) is the connection-down signal
                await mux.wait_closed()
        finally:
            mux.stop()
            bearer.close()

    server = await asyncio.start_server(on_conn, host, port)
    actual_port = server.sockets[0].getsockname()[1]
    return server, actual_port


def dial_node(kernel: NodeKernel, host: str, port: int):
    """connectToNode: dial, then run the initiator application.  Returns
    the connection runner handle (completes when the connection ends)."""
    async def conn():
        reader, writer = await asyncio.open_connection(host, port)
        bearer = SocketBearer(reader, writer)
        peer_id = f"{kernel.label}->{host}:{port}"
        mux = Mux(bearer, f"{peer_id}.mux")
        mux.start()
        try:
            await _run_initiator(kernel, mux, peer_id)
        finally:
            mux.stop()
            bearer.close()

    return sim.spawn(conn(), label=f"{kernel.label}-dial-{host}:{port}")
