"""BlockchainTime — wall-clock slot ticking.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/BlockchainTime/
{API.hs,WallClock/Default.hs,Simple.hs}: a `BlockchainTime` exposes the
current slot as an STM view, advanced by a background thread watching the
(virtual) clock.  Fixed slot length only — the HFC-aware version layers era
translation on top (WallClock/HardFork.hs).
"""
from __future__ import annotations

from .. import simharness as sim
from ..simharness import Retry, TVar


class BlockchainTime:
    """Current-slot TVar driven by the simharness virtual clock.

    Slot s spans [s*slot_length, (s+1)*slot_length).  `start()` spawns the
    ticker thread; `wait_slot_after(prev)` blocks (STM retry) until the
    current slot exceeds `prev` — the knownSlotWatcher pattern the forging
    loop uses (NodeKernel.hs:344-351).
    """

    def __init__(self, slot_length: float = 1.0):
        self.slot_length = slot_length
        self.current: TVar = TVar(self._slot_of_now(), label="current-slot")
        self._ticker = None

    def _slot_of_now(self) -> int:
        try:
            return int(sim.now() / self.slot_length)
        except Exception:
            return 0                     # outside the sim: epoch start

    def start(self, label: str = "btime") -> None:
        self._ticker = sim.spawn(self._tick_loop(), label=label)

    def stop(self) -> None:
        if self._ticker is not None:
            self._ticker.cancel()
            self._ticker = None

    async def _tick_loop(self) -> None:
        while True:
            nxt = self.current.value + 1
            at = nxt * self.slot_length
            delay = at - sim.now()
            if delay > 0:
                await sim.sleep(delay)
            # max() guards against float truncation (int(k*L/L) can be
            # k-1): the slot always advances, so this loop cannot spin
            # without yielding, and the TVar is monotone
            self.current.set_notify(
                max(nxt, int(sim.now() / self.slot_length)))

    async def wait_slot_after(self, prev: int) -> int:
        """Block until the current slot is > prev; return it."""
        def tx_fn(tx):
            s = tx.read(self.current)
            if s <= prev:
                raise Retry()
            return s
        return await sim.atomically(tx_fn)


class HardForkBlockchainTime(BlockchainTime):
    """Slot ticking through the era summary — slot length may change at
    era boundaries (BlockchainTime/WallClock/HardFork.hs:
    hardForkBlockchainTime interprets the HFC time summary).

    get_summary() is re-read every tick so a transition decided by the
    ledger mid-run takes effect (the reference re-runs the Qry against the
    current ledger state the same way).
    """

    def __init__(self, get_summary):
        self.get_summary = get_summary
        try:
            now = sim.now()
        except RuntimeError:             # outside the sim: epoch start
            now = 0.0
        self.current = TVar(get_summary().wallclock_to_slot(now),
                            label="current-slot")
        self._ticker = None

    async def _tick_loop(self) -> None:
        while True:
            summary = self.get_summary()
            nxt = self.current.value + 1
            at = summary.slot_to_wallclock(nxt)
            delay = at - sim.now()
            if delay > 0:
                await sim.sleep(delay)
            # max(nxt, ...) keeps the slot monotone and always advancing:
            # float truncation can compute nxt-1, and a transition decided
            # during the sleep can remap the wallclock to an earlier slot
            # — neither may regress the TVar or stall this loop
            self.current.set_notify(
                max(nxt,
                    self.get_summary().wallclock_to_slot(sim.now())))
