"""BlockchainTime — wall-clock slot ticking.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/BlockchainTime/
{API.hs,WallClock/Default.hs,Simple.hs}: a `BlockchainTime` exposes the
current slot as an STM view, advanced by a background thread watching the
(virtual) clock.  Fixed slot length only — the HFC-aware version layers era
translation on top (WallClock/HardFork.hs).
"""
from __future__ import annotations

from .. import simharness as sim
from ..simharness import Retry, TVar


class BlockchainTime:
    """Current-slot TVar driven by the simharness virtual clock.

    Slot s spans [s*slot_length, (s+1)*slot_length).  `start()` spawns the
    ticker thread; `wait_slot_after(prev)` blocks (STM retry) until the
    current slot exceeds `prev` — the knownSlotWatcher pattern the forging
    loop uses (NodeKernel.hs:344-351).
    """

    def __init__(self, slot_length: float = 1.0):
        self.slot_length = slot_length
        self.current: TVar = TVar(self._slot_of_now(), label="current-slot")
        self._ticker = None

    def _slot_of_now(self) -> int:
        try:
            return int(sim.now() / self.slot_length)
        except Exception:
            return 0                     # outside the sim: epoch start

    def start(self, label: str = "btime") -> None:
        self._ticker = sim.spawn(self._tick_loop(), label=label)

    def stop(self) -> None:
        if self._ticker is not None:
            self._ticker.cancel()
            self._ticker = None

    async def _tick_loop(self) -> None:
        while True:
            nxt = self.current.value + 1
            at = nxt * self.slot_length
            delay = at - sim.now()
            if delay > 0:
                await sim.sleep(delay)
            self.current.set_notify(int(sim.now() / self.slot_length))

    async def wait_slot_after(self, prev: int) -> int:
        """Block until the current slot is > prev; return it."""
        def tx_fn(tx):
            s = tx.read(self.current)
            if s <= prev:
                raise Retry()
            return s
        return await sim.atomically(tx_fn)
