"""Node orchestration — the `run` entry point with crash-recovery policy.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/Node.hs:203-301
(`run`/`runWith`: checked DB open -> ChainDB -> blockchain time ->
NodeKernel -> applications), Node/DbMarker.hs (magic file guarding against
pointing a node at another network's DB), Node/Recovery.hs:6-50 (the
clean-shutdown marker: present -> fast open; absent -> the previous run
crashed, so deep-validate every chunk), Node/DbLock.hs (double-open
guard — utils/registry.FileLock, used by callers with on-disk DBs).

The assembly is sim-first: `run_node` builds markers + ChainDB + kernel
over any FsApi and returns a handle whose `stop()` records the clean
shutdown; `was_clean_shutdown` decides the validation depth the same way
stdWithCheckedDB does.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from .. import simharness as sim
from ..storage.chaindb import ChainDB
from ..storage.fs import FsApi
from ..storage.ledgerdb import DiskPolicy
from ..consensus.mempool import Mempool
from .blockchain_time import BlockchainTime
from .kernel import NodeKernel

MARKER_FILE = ("dbmarker",)            # DbMarker.hs `protocolMagicId`
CLEAN_FILE = ("clean_shutdown",)       # Recovery.hs marker


class WrongNetworkError(Exception):
    """The DB belongs to a different network magic (DbMarker.hs)."""


def check_db_marker(fs: FsApi, network_magic: int) -> None:
    """Create-or-verify the magic marker (DbMarker.hs lockDbMarkerFile)."""
    if fs.exists(MARKER_FILE):
        raw = fs.read_file(MARKER_FILE)
        try:
            found = int(raw.decode().strip())
        except (UnicodeDecodeError, ValueError) as e:
            raise WrongNetworkError(
                f"DB marker is corrupt ({raw[:32]!r}); refusing to open "
                f"— remove it only if this DB really is for magic "
                f"{network_magic}") from e
        if found != network_magic:
            raise WrongNetworkError(
                f"DB marker has magic {found}, node runs {network_magic}")
    else:
        fs.write_file(MARKER_FILE, str(network_magic).encode())


def was_clean_shutdown(fs: FsApi) -> bool:
    """True when the previous run stopped cleanly (Recovery.hs:6-50);
    consumed by run_node — a crash means every chunk gets revalidated."""
    return fs.exists(CLEAN_FILE)


@dataclass
class RunNodeArgs:
    """The RunNodeArgs/ProtocolInfo bundle (Node.hs:130-170)."""
    fs: FsApi
    ext_rules: Any
    encode_state: Callable
    decode_state: Callable
    block_decode: Callable
    btime: BlockchainTime
    forgings: Sequence = ()
    label: str = "node"
    network_magic: int = 0
    backend: Any = None
    chain_sync_window: int = 32
    header_decode: Optional[Callable] = None
    block_decode_obj: Optional[Callable] = None
    tx_decode: Optional[Callable] = None
    with_mempool: bool = True
    chunk_size: int = 100
    max_blocks_per_file: int = 50
    disk_policy: DiskPolicy = field(default_factory=DiskPolicy)


@dataclass
class NodeHandle:
    kernel: NodeKernel
    fs: FsApi
    deep_validated: bool

    def stop(self) -> None:
        """Clean shutdown: stop threads, then record the marker — the next
        open skips deep validation (Recovery.hs)."""
        self.kernel.stop()
        self.fs.write_file(CLEAN_FILE, b"1")


def run_node(args: RunNodeArgs) -> NodeHandle:
    """The `run` assembly (Node.hs:203-301):

    1. DbMarker check (right network), clean-shutdown marker decides the
       validation depth, then the marker is REMOVED — only a clean stop()
       rewrites it, so a crash leaves it absent.
    2. ChainDB.open (snapshot + replay + initial chain selection).
    3. NodeKernel with mempool + forging + background pipeline, started.

    On-disk callers additionally hold utils.registry.FileLock around the
    DB directory (DbLock.hs); MockFS sims have no cross-process opens."""
    check_db_marker(args.fs, args.network_magic)
    clean = was_clean_shutdown(args.fs)
    if clean:
        args.fs.remove(CLEAN_FILE)
    db = ChainDB.open(
        args.fs, args.ext_rules, args.encode_state, args.decode_state,
        args.block_decode, chunk_size=args.chunk_size,
        max_blocks_per_file=args.max_blocks_per_file,
        backend=args.backend, disk_policy=args.disk_policy,
        validate_chunks=not clean)       # crash -> deep validation
    mempool = None
    if args.with_mempool:
        mempool = Mempool(args.ext_rules.ledger,
                          lambda db=db: (db.current_ledger.ledger,
                                         db.tip_point()),
                          backend=args.backend)
    kernel = NodeKernel(
        db, args.ext_rules.ledger, mempool, args.btime,
        list(args.forgings), label=args.label, backend=args.backend,
        chain_sync_window=args.chain_sync_window,
        header_decode=args.header_decode,
        block_decode_obj=args.block_decode_obj,
        tx_decode=args.tx_decode)
    kernel.network_magic = args.network_magic
    kernel.start()
    sim.trace_event(("node-run", args.label,
                     "fast-open" if clean else "deep-validation"))
    return NodeHandle(kernel, args.fs, deep_validated=not clean)
