"""NodeKernel — ties ChainDB, mempool, forging, and peers together.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/NodeKernel.hs:87
(`NodeKernel` record), :139-175 (initNodeKernel forks block-forging threads
+ BlockFetch logic + candidate-fragment map), :344-496 (the forging loop:
slot tick → checkShouldForge → mempool snapshot → forgeBlock →
addBlockAsync), plus the connection assembly of Network/NodeToNode.hs
(mkApps: per-protocol handlers over one mux bearer, protocol numbers
chainsync=2 blockfetch=3 txsubmission=4 — NodeToNode.hs:211,382).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from .. import simharness as sim
from ..chain.block import GENESIS_HASH
from ..consensus.headers import ProtocolBlock, ProtocolHeader, body_hash_of
from ..consensus.mempool import Mempool
from ..network.mux import (
    INITIATOR, RESPONDER, CodecChannel, Mux, bearer_pair,
)
from ..network import node_to_node as n2n
from ..network.deltaq import PeerGSVTracker
from ..network.protocols import blockfetch as bf_proto
from ..network.protocols import chainsync as cs_proto
from ..network.protocols import handshake as hs_proto
from ..network.protocols import keepalive as ka_proto
from ..network.protocols import txsubmission as tx_proto
from ..network.typed import CLIENT, PipelinedSession, SERVER, Session
from ..observe import metrics as _metrics
from ..simharness import TVar
from .block_fetch import (
    PeerFetchState, block_fetch_client, block_fetch_server, fetch_logic_loop,
)
from .blockchain_time import BlockchainTime
from .chain_sync import CandidateState, chain_sync_client, chain_sync_server
from .tx_submission import (TxInboundProtocolError, tx_inbound_loop,
                            tx_outbound_loop)
from .watchdog import KeepAliveTimeout, NodeTimeLimits, WatchdogTimeout

# protocol numbers per NodeToNode.hs:211-212 (handshake=0, chainsync=2,
# blockfetch=3, txsubmission=4, keepalive=8)
CHAINSYNC_NUM, BLOCKFETCH_NUM, TXSUBMISSION_NUM, KEEPALIVE_NUM = 2, 3, 4, 8

# whole-negotiation latency (the net.rtt.* namespace, ISSUE 14)
_HANDSHAKE_SECS = _metrics.latency_histogram("net.rtt.handshake_secs")


@dataclass
class BlockForging:
    """One forging credential (Block/Forging.hs:81-183).

    forge(protocol, is_leader_proof, header) -> signed header."""
    issuer: int
    can_be_leader: Any
    forge: Callable


class NodeKernel:
    """One node: storage + mempool + forging + peer connections."""

    def __init__(self, chain_db, ledger_rules, mempool: Optional[Mempool],
                 btime: BlockchainTime, forgings=(), label: str = "node",
                 backend=None, chain_sync_window: int = 32,
                 header_decode=None, block_decode_obj=None, tx_decode=None,
                 tracers=None, time_limits: Optional[NodeTimeLimits] = None,
                 verify_service=None):
        from ..utils.tracer import NodeTracers
        self.chain_db = chain_db
        self.ledger_rules = ledger_rules
        self.protocol = chain_db.ext_rules.protocol
        self.mempool = mempool
        self.btime = btime
        self.forgings = list(forgings)
        self.label = label
        self.backend = backend
        # adaptive batching service (crypto/batching.py): when attached,
        # sub-window ChainSync flushes (the caught-up batch-of-1 regime)
        # and mempool admission coalesce their proofs through it instead
        # of dispatching alone
        self.verify_service = verify_service
        if mempool is not None and verify_service is not None \
                and mempool.verify_service is None:
            mempool.verify_service = verify_service
        self.chain_sync_window = chain_sync_window
        self.header_decode = header_decode
        self.block_decode_obj = block_decode_obj
        self.tx_decode = tx_decode
        # per-subsystem typed tracer bundle (Node/Tracers.hs:51-62)
        self.tracers = tracers if tracers is not None else NodeTracers.nop()

        self.candidates: Dict[object, CandidateState] = {}
        self.peer_fetch: Dict[object, PeerFetchState] = {}
        self.peer_gsv: Dict[object, PeerGSVTracker] = {}
        # block-propagation lifecycle tracker (observe/propagation.py):
        # attached by the fleet harness (threadnet) or an operator; None
        # = zero per-block bookkeeping
        self.propagation = None
        self.keepalive_interval = 10.0
        # per-state protocol watchdogs (timeLimits*; node/watchdog.py)
        self.time_limits = time_limits if time_limits is not None \
            else NodeTimeLimits()
        self.network_magic = 0
        self.fetch_wakeup = TVar(0, label=f"{label}-fetch-wakeup")
        self._fetch_v = 0
        self._threads: list = []

        # STM hook for followers / servers blocking on chain changes
        chain_db.version_tvar = TVar(chain_db.version,
                                     label=f"{label}-chain-version")
        chain_db.on_change(self._on_chain_change)

    # -- wiring ---------------------------------------------------------------
    def _on_chain_change(self) -> None:
        try:
            self.chain_db.version_tvar.set_notify(self.chain_db.version)
        except Exception:
            self.chain_db.version_tvar._value = self.chain_db.version
        prop = self.propagation
        if prop is not None:
            # stamp every newly adopted block (walk back from the head;
            # the first already-stamped hash ends the new suffix)
            for b in reversed(self.chain_db.current_chain.blocks):
                if not prop.mark("adopted", b.hash):
                    break
        if self.mempool is not None:
            self.mempool.sync_with_ledger()
        self.poke_fetch_logic()

    def poke_fetch_logic(self) -> None:
        self._fetch_v += 1
        try:
            self.fetch_wakeup.set_notify(self._fetch_v)
        except Exception:
            self.fetch_wakeup._value = self._fetch_v

    def ledger_view(self):
        return self.ledger_rules.ledger_view(self.chain_db.current_ledger.ledger)

    def forecast_view(self, slot: int):
        """View forecast at `slot` from the current tip (cross-era aware);
        raises OutsideForecastRange past the stability horizon."""
        return self.ledger_rules.forecast_view(
            self.chain_db.current_ledger.ledger, slot)

    def have_block(self, h: bytes) -> bool:
        """Stored, queued for the writer thread, or buffered as a future
        block — all count as "have" so fetch decisions never re-request
        them (the reference's getIsFetched includes cdbBlocksToAdd)."""
        db = self.chain_db
        return (db.volatile.block_info(h) is not None
                or h in db.immutable
                or h in db.future_blocks
                or any(b.hash == h for b in db._add_queue))

    def plausible_candidate(self, frag) -> bool:
        """Would we prefer this candidate over our current chain?
        (Decision.hs plausible-candidates filter; select-view comparison.)"""
        head = frag.head
        if head is None:
            return False
        cur = self.chain_db.current_chain
        cur_head = cur.head
        cur_view = (self.protocol.select_view(cur_head.header)
                    if cur_head is not None else cur.head_block_no)
        return self.protocol.prefer_candidate(
            cur_view, self.protocol.select_view(head))

    def add_fetched_block(self, block) -> None:
        """Fetched blocks go through the async queue — chain selection
        runs only on the ChainDB writer thread (addBlockAsync,
        BlockFetch.hs:169)."""
        self.chain_db.add_block_async(block)

    def new_candidate(self, peer_id) -> CandidateState:
        c = CandidateState(peer_id)
        orig = c.publish

        def publish(fragment):
            orig(fragment)
            self.poke_fetch_logic()
        c.publish = publish
        self.candidates[peer_id] = c
        return c

    def drop_peer(self, peer_id) -> None:
        self.candidates.pop(peer_id, None)
        self.peer_fetch.pop(peer_id, None)
        self.peer_gsv.pop(peer_id, None)
        self.poke_fetch_logic()

    def fetch_order_key(self, peer_id) -> float:
        """Expected time to fetch a reference-sized batch from this peer
        (the DeltaQ comparison of Decision.hs prioritisation)."""
        t = self.peer_gsv.get(peer_id)
        return t.expected_fetch_time(16 * 2048) if t is not None else 0.0

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        """Fork the background threads (initNodeKernel, NodeKernel.hs:139,
        + the ChainDB background pipeline, Background.hs:84-102)."""
        self.btime.start(label=f"{self.label}-btime")
        self.chain_db.current_slot_fn = lambda: self.btime.current.value
        self._threads.append(sim.spawn(fetch_logic_loop(self),
                                       label=f"{self.label}-fetch-logic"))
        self._threads.append(sim.spawn(self._background_loop(),
                                       label=f"{self.label}-chaindb-bg"))
        self._threads.append(sim.spawn(self.chain_db.add_block_runner(),
                                       label=f"{self.label}-add-block"))
        self._threads.append(sim.spawn(self._slot_tick_loop(),
                                       label=f"{self.label}-slot-tick"))
        for forging in self.forgings:
            self._threads.append(
                sim.spawn(self._forging_loop(forging),
                          label=f"{self.label}-forge-{forging.issuer}"))

    async def _slot_tick_loop(self) -> None:
        """Re-triage buffered future blocks as their slots arrive
        (cdbFutureBlocks rerun; Fragment/InFuture.hs clock-skew check)."""
        last = self.btime.current.value - 1
        while True:
            slot = await self.btime.wait_slot_after(last)
            last = slot
            if self.chain_db.future_blocks:
                for res in self.chain_db.on_slot_tick(slot):
                    sim.trace_event(("future-block-adopted", self.label,
                                     res.kind))

    async def _background_loop(self) -> None:
        """copyAndSnapshotRunner: whenever the chain grows past k, copy the
        excess to the ImmutableDB, GC the VolatileDB, snapshot the ledger
        (all inside ChainDB.copy_to_immutable)."""
        from .chain_sync import _wait_version_above, kernel_version_value
        while True:
            seen = kernel_version_value(self.chain_db)
            copied = self.chain_db.copy_to_immutable()
            if copied:
                sim.trace_event(("copy-to-immutable", self.label, copied))
                continue
            await _wait_version_above(self.chain_db, seen)

    def stop(self) -> None:
        self.btime.stop()
        for t in self._threads:
            t.cancel()
        self._threads.clear()

    # -- forging (NodeKernel.hs:344-496) --------------------------------------
    async def _forging_loop(self, forging: BlockForging) -> None:
        last = self.btime.current.value - 1
        while True:
            slot = await self.btime.wait_slot_after(last)
            last = slot
            try:
                self._try_forge(forging, slot)
            except Exception as e:
                sim.trace_event(("forge-error", self.label, slot, repr(e)))

    def _try_forge(self, forging: BlockForging, slot: int) -> None:
        ext = self.chain_db.current_ledger
        # forecast AT the slot (NodeKernel.hs:~400 ledger view forecast):
        # for era-composed ledgers this is the new era's view when `slot`
        # sits past a decided transition
        view = self.ledger_rules.forecast_view(ext.ledger, slot)
        ticked_dep = self.protocol.tick_chain_dep_state(
            ext.header.chain_dep_state, view, slot)
        proof = self.protocol.check_is_leader(
            forging.can_be_leader, slot, ticked_dep, view)
        if proof is None:
            return
        if self.mempool is not None:
            ticked_ledger = self.ledger_rules.tick(ext.ledger, slot)
            snap = self.mempool.get_snapshot_for(slot, ticked_ledger)
            body = tuple(snap.txs)
        else:
            body = ()
        # Build on the validated tip from the ledger state, NOT the chain
        # fragment: after copy-to-immutable empties the fragment the anchor
        # is a real block, and forging prev=GENESIS there would waste every
        # led slot on an unconnectable block.
        ann = ext.header.tip
        if ann is None:
            prev_hash, block_no = GENESIS_HASH, 0
        else:
            prev_hash, block_no = ann.hash, ann.block_no + 1
        hdr = ProtocolHeader(slot=slot, block_no=block_no,
                             prev_hash=prev_hash,
                             body_hash=body_hash_of(body),
                             issuer=forging.issuer)
        signed = forging.forge(self.protocol, proof, hdr)
        block = ProtocolBlock(signed, body)
        res = self.chain_db.add_block(block)
        sim.trace_event(("forged", self.label, slot, res.kind))
        if self.tracers.forge.active:
            from ..utils.tracer import TraceForgeEvent
            self.tracers.forge.trace(TraceForgeEvent(
                slot=slot, outcome="forged", detail=res.kind))


def connect_nodes(a: NodeKernel, b: NodeKernel, delay: float = 0.0,
                  sdu_size: int = 12288, fault_plan=None) -> None:
    """Wire a<->b with two directional connections (the ThreadNet mesh edge,
    Test/ThreadNet/Network.hs:275-344): each direction runs its own bearer,
    mux, and initiator/responder protocol set.  A FaultPlan wraps every
    bearer so the whole mesh runs under seeded network hostility."""
    _connect_directional(a, b, delay, sdu_size, fault_plan=fault_plan)
    _connect_directional(b, a, delay, sdu_size, fault_plan=fault_plan)


def _connect_directional(initiator: NodeKernel, responder: NodeKernel,
                         delay: float, sdu_size: int, fault_plan=None,
                         conn_seq: int = 0):
    """initiator runs chainsync/blockfetch clients against responder's
    servers (learning responder's chain) and offers its txs to responder's
    inbound (NodeToNode.hs initiator/responder application split).

    Version negotiation runs FIRST, on protocol 0 over the same bearer, and
    only a successful handshake starts the mini-protocols (Socket.hs:226:
    negotiate-then-multiplex).

    fault_plan: a simharness FaultPlan wrapping both bearers (each write
    direction draws from its own seeded stream).  conn_seq distinguishes
    successive redials of the same edge in thread labels."""
    peer_id = f"{initiator.label}->{responder.label}"
    tag = f"{peer_id}#{conn_seq}" if conn_seq else peer_id
    bi, br = bearer_pair(sdu_size=sdu_size, delay=delay)
    if fault_plan is not None:
        bi = fault_plan.wrap_bearer(bi, initiator.label, responder.label)
        br = fault_plan.wrap_bearer(br, responder.label, initiator.label)
    # the initiator's GSV estimate for this peer is fed passively by the
    # demuxer's per-SDU one-way delays (TraceStats.hs) on top of the
    # KeepAlive RTT probes; the label publishes the estimate as per-peer
    # net.deltaq.* gauges through the bounded-label helper
    tracker = PeerGSVTracker(label=peer_id)
    mux_i = Mux(bi, f"{tag}.mux-i", owd_observer=tracker.observe_owd)
    mux_r = Mux(br, f"{tag}.mux-r")
    mux_i.start()
    mux_r.start()

    async def run_and_teardown():
        # the dial-path contract (matching diffusion._dialer): when the
        # initiator application ends — cleanly or by a kill — its mux dies
        # with it, so redials never talk over a poisoned half-open bearer
        try:
            await _run_initiator(initiator, mux_i, peer_id, tracker)
        finally:
            mux_i.stop()

    handle = sim.spawn(run_and_teardown(), label=f"{tag}.connect-i")
    initiator._threads.append(handle)
    responder._threads.append(sim.spawn(
        _run_responder(responder, mux_r, peer_id),
        label=f"{tag}.connect-r"))
    return handle


async def _initiator_handshake(initiator: NodeKernel, mux_i, peer_id):
    """Version negotiation on protocol 0; returns the agreed version, or
    None on refusal/magic mismatch (the warm-up step every outbound
    connection — subscription-driven or governor-driven — runs first)."""
    versions = n2n.node_to_node_versions(initiator.network_magic)
    hs = Session(
        hs_proto.SPEC, CLIENT,
        CodecChannel(mux_i.channel(n2n.HANDSHAKE_NUM, INITIATOR),
                     hs_proto.CODEC))
    res = await hs_proto.client_propose(hs, versions)
    if res[0] != "accepted":
        sim.trace_event(("handshake-refused", initiator.label, peer_id,
                         res[1]))
        return None
    _, version, params = res
    if dict(params or {}).get("magic") != initiator.network_magic:
        sim.trace_event(("handshake-magic-mismatch", initiator.label,
                         peer_id, params))
        return None
    sim.trace_event(("handshake-ok", initiator.label, peer_id, version))
    return version


def _start_keepalive(initiator: NodeKernel, mux_i, peer_id, tracker):
    """The WARM-stage protocol (the reference keeps KeepAlive running on
    warm peers): RTT probes feeding the peer's GSV tracker.

    The probe doubles as the whole-connection liveness watchdog
    (timeLimitsKeepAlive): a responder silent past the reply deadline
    raises KeepAliveTimeout, and the supervisor tears the mux down —
    poisoning every mini-protocol channel so the hot set dies with
    MuxError instead of hanging, which ends the connection and feeds the
    failure to the error-policy/reconnect layer."""
    initiator.peer_gsv[peer_id] = tracker
    ka_sess = Session(
        ka_proto.SPEC, CLIENT,
        CodecChannel(mux_i.channel(KEEPALIVE_NUM, INITIATOR),
                     ka_proto.CODEC))

    async def supervised():
        try:
            await ka_proto.client_probe(
                ka_sess, None, initiator.keepalive_interval,
                on_rtt=tracker.observe_rtt,
                response_timeout=initiator.time_limits.keep_alive_timeout)
        except KeepAliveTimeout:
            sim.trace_event(("keepalive-kill", initiator.label, peer_id),
                            label="watchdog")
            mux_i.stop()
            raise

    return sim.spawn(supervised(), label=f"{peer_id}.ka-client")


async def _run_hot(initiator: NodeKernel, mux_i, peer_id, version) -> None:
    """The HOT protocol set: ChainSync (supervised, the liveness signal)
    + BlockFetch client + TxSubmission outbound.  Returns when ChainSync
    ends; cancels the satellites and releases the peer's candidate."""
    hdr_dec = initiator.header_decode
    blk_dec = initiator.block_decode_obj
    cs_codec = cs_proto.make_codec(hdr_dec) if hdr_dec else cs_proto.CODEC
    bf_codec = bf_proto.make_codec(blk_dec) if blk_dec else bf_proto.CODEC

    candidate = initiator.new_candidate(peer_id)
    initiator.peer_fetch[peer_id] = PeerFetchState(peer_id)

    satellites = []
    bf_sess = Session(
        bf_proto.SPEC, CLIENT,
        CodecChannel(mux_i.channel(BLOCKFETCH_NUM, INITIATOR), bf_codec))
    satellites.append(sim.spawn(
        _supervise_block_fetch(
            block_fetch_client(bf_sess, initiator, peer_id),
            initiator, mux_i, peer_id),
        label=f"{peer_id}.bf-client"))

    if initiator.mempool is not None and version >= n2n.NODE_TO_NODE_V2:
        tx_out = Session(
            tx_proto.SPEC, CLIENT,
            CodecChannel(mux_i.channel(TXSUBMISSION_NUM, INITIATOR),
                         tx_proto.CODEC))
        satellites.append(sim.spawn(
            _supervise_tx(tx_outbound_loop(tx_out, initiator.mempool),
                          initiator, mux_i, peer_id),
            label=f"{peer_id}.tx-out"))
    initiator._threads.extend(satellites)

    cs_sess = PipelinedSession(
        cs_proto.SPEC, CLIENT,
        CodecChannel(mux_i.channel(CHAINSYNC_NUM, INITIATOR), cs_codec),
        max_outstanding=initiator.chain_sync_window + 2)
    try:
        await _supervise_chain_sync(initiator, cs_sess, candidate, peer_id)
    finally:
        for s in satellites:
            s.cancel()
        initiator.drop_peer(peer_id)


async def _run_initiator(initiator: NodeKernel, mux_i, peer_id,
                         tracker=None) -> None:
    """The initiator-side connection runner (warm + hot in one go — the
    subscription-worker path promotes straight to hot).  Completes when
    the ChainSync client ends (the connection's liveness signal —
    Client.hs kill semantics); satellite protocols are cancelled on exit
    so subscription workers can treat completion as connection-down and
    redial."""
    # the whole negotiation runs under one deadline (the reference's
    # handshake timeout): a peer that swallows the proposal would
    # otherwise hang this dial forever while it holds a valency slot
    t0 = sim.now()
    done, version = await sim.timeout(
        initiator.time_limits.handshake_timeout,
        _initiator_handshake(initiator, mux_i, peer_id))
    if done and version is not None:
        _HANDSHAKE_SECS.observe(sim.now() - t0)
    if not done:
        sim.trace_event(("timeout", "handshake", "StConfirm", peer_id),
                        label="watchdog")
        mux_i.stop()
        raise WatchdogTimeout("handshake", "StConfirm",
                              initiator.time_limits.handshake_timeout)
    if version is None:
        return
    tracker = tracker if tracker is not None else PeerGSVTracker()
    ka = _start_keepalive(initiator, mux_i, peer_id, tracker)
    initiator._threads.append(ka)
    try:
        await _run_hot(initiator, mux_i, peer_id, version)
    finally:
        ka.cancel()


async def _run_responder(responder: NodeKernel, mux_r, peer_id) -> None:
    versions = n2n.node_to_node_versions(responder.network_magic)
    hs = Session(
        hs_proto.SPEC, SERVER,
        CodecChannel(mux_r.channel(n2n.HANDSHAKE_NUM, RESPONDER),
                     hs_proto.CODEC))
    res = await hs_proto.server_accept(hs, versions,
                                       policy=n2n.accept_same_magic)
    if res[0] != "accepted":
        sim.trace_event(("handshake-refused", responder.label, peer_id,
                         res[1]))
        return "refused"
    version = res[1]

    hdr_dec = responder.header_decode
    blk_dec = responder.block_decode_obj
    cs_codec = cs_proto.make_codec(hdr_dec) if hdr_dec else cs_proto.CODEC
    bf_codec = bf_proto.make_codec(blk_dec) if blk_dec else bf_proto.CODEC

    cs_srv = Session(
        cs_proto.SPEC, SERVER,
        CodecChannel(mux_r.channel(CHAINSYNC_NUM, RESPONDER), cs_codec))
    responder._threads.append(sim.spawn(
        chain_sync_server(cs_srv, responder.chain_db),
        label=f"{peer_id}.cs-server"))

    bf_srv = Session(
        bf_proto.SPEC, SERVER,
        CodecChannel(mux_r.channel(BLOCKFETCH_NUM, RESPONDER), bf_codec))
    responder._threads.append(sim.spawn(
        block_fetch_server(responder.chain_db)(bf_srv),
        label=f"{peer_id}.bf-server"))

    ka_srv = Session(
        ka_proto.SPEC, SERVER,
        CodecChannel(mux_r.channel(KEEPALIVE_NUM, RESPONDER),
                     ka_proto.CODEC))
    responder._threads.append(sim.spawn(
        ka_proto.server(ka_srv), label=f"{peer_id}.ka-server"))

    if responder.mempool is not None and responder.tx_decode is not None \
            and version >= n2n.NODE_TO_NODE_V2:
        tx_in = Session(
            tx_proto.SPEC, SERVER,
            CodecChannel(mux_r.channel(TXSUBMISSION_NUM, RESPONDER),
                         tx_proto.CODEC))
        responder._threads.append(sim.spawn(
            _supervise_tx(
                tx_inbound_loop(tx_in, responder.mempool,
                                responder.tx_decode),
                responder, mux_r, peer_id),
            label=f"{peer_id}.tx-in"))
    return "accepted"


async def _supervise_tx(coro, kernel, mux, peer_id) -> None:
    """Observe the TxSubmission loops: a window-contract violation is a
    protocol error, so kill the whole connection (stop the mux — every
    mini-protocol channel dies with it), matching the reference's
    ProtocolError -> bearer-teardown path (TxSubmission/Inbound.hs)."""
    try:
        await coro
    except TxInboundProtocolError as e:
        sim.trace_event(("tx-protocol-kill", kernel.label, peer_id,
                         str(e)))
        mux.stop()


async def _supervise_block_fetch(coro, kernel, mux, peer_id) -> None:
    """Observe the BlockFetch client: a watchdog-expired request means the
    peer is silent past its (DeltaQ-informed) deadline — kill the whole
    connection via mux teardown, same as the reference's per-protocol time
    limits feeding the connection-level error path."""
    from .watchdog import WatchdogTimeout
    try:
        await coro
    except WatchdogTimeout:
        sim.trace_event(("block-fetch-watchdog-kill", kernel.label,
                         peer_id), label="watchdog")
        mux.stop()


async def _supervise_chain_sync(kernel: NodeKernel, session, candidate,
                                peer_id) -> None:
    """Run the ChainSync client; on error drop the peer's candidate so
    BlockFetch stops considering it (the kill-the-connection semantics of
    Client.hs:1114), then RE-RAISE so the connection ends exceptionally:
    the reconnect layer's ErrorPolicy must see the violation and suspend
    the peer — swallowing it here would make the failure look like a
    clean session end (fail_count reset + base backoff) and the node
    would churn against a protocol-violating peer forever."""
    from .chain_sync import ChainSyncClientError
    try:
        await chain_sync_client(session, kernel, candidate,
                                window=kernel.chain_sync_window)
    except ChainSyncClientError as e:
        sim.trace_event(("chain-sync-kill", kernel.label, peer_id, str(e)))
        kernel.drop_peer(peer_id)
        raise
