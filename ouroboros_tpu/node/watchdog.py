"""Protocol watchdogs — per-state time limits on peer agency.

Reference: the `ProtocolTimeLimits` attached to every mini-protocol codec:
- ouroboros-network/src/Ouroboros/Network/Protocol/ChainSync/Codec.hs
  `timeLimitsChainSync` (StIntersect / StNext CanAwait: `shortWait` = 10 s;
  StNext MustReply: the long must-reply timeout, 135–269 s in the
  reference, randomised against eclipse timing attacks)
- .../Protocol/KeepAlive/Codec.hs `timeLimitsKeepAlive` (server reply
  within 60 s)
- .../Protocol/BlockFetch/Codec.hs `timeLimitsBlockFetch` (BFBusy /
  BFStreaming: 60 s)

A state where the PEER holds agency gets a deadline; when it expires the
peer is silent past its contract and the connection is killed — the
resulting :class:`WatchdogTimeout` flows into the ErrorPolicy layer
exactly like any other connection failure (suspend + redial).  States
where WE hold agency, and genuinely-unbounded server waits, carry no
limit (`None` = waitForever).

The wait itself uses the non-destructive ``channel.wait_ready`` poll
rather than cancelling a recv inside ``sim.timeout`` — a cancelled recv
continuation can lose pipeline bookkeeping (see Channel.wait_ready), and
a watchdog must never corrupt the very session it is guarding before the
kill decision is made.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Optional

from .. import simharness as sim
from ..observe import metrics as _metrics
from ..observe import netmetrics as _net

# one firing counter for all watchdogs; per-protocol attribution is a
# labeled series through the bounded-label helper (the name carries a
# runtime value, so it pays the same cardinality discipline as peer
# labels — OBS003).  Cold path: a firing kills the connection, so it
# happens at most once per peer lifetime.
_FIRINGS = _metrics.counter("watchdog.firings")


def _count_firing(protocol: str) -> None:
    _FIRINGS.inc()
    _net.labeled_counter("watchdog.firings_by_protocol",
                         protocol=protocol).inc()


class WatchdogTimeout(Exception):
    """A peer held agency past its per-state time limit: it is considered
    dead/adversarial and the connection must be torn down."""

    def __init__(self, protocol: str, state: str, limit: float):
        super().__init__(
            f"{protocol}: peer silent in state {state} past {limit}s limit")
        self.protocol = protocol
        self.state = state
        self.limit = limit


class KeepAliveTimeout(WatchdogTimeout):
    """The keep-alive responder missed its reply deadline — the
    whole-connection liveness signal (KeepAlive/Codec.hs 60 s limit)."""


@dataclass(frozen=True)
class ProtocolTimeLimits:
    """state -> seconds of allowed peer silence (None = wait forever)."""
    name: str
    limits: Mapping[str, Optional[float]]

    def limit_for(self, state: str) -> Optional[float]:
        return self.limits.get(state)


@dataclass(frozen=True)
class NodeTimeLimits:
    """The node's watchdog configuration, one knob set per protocol.

    Defaults mirror the reference's production values; chaos tests scale
    them down to the sim's slot length."""
    chain_sync_short: float = 10.0       # StIntersect + StNext (can-await)
    chain_sync_must_reply: float = 135.0  # StMustReply (caught-up idle)
    keep_alive_timeout: float = 60.0     # KAServer reply deadline
    block_fetch_busy: float = 60.0       # whole-request ceiling
    handshake_timeout: float = 10.0      # whole version negotiation
    # DeltaQ-informed BlockFetch deadline: a request is given
    # max(floor, mult * expected_fetch_time) capped by block_fetch_busy,
    # so a measured-fast peer is held to a measured-fast deadline
    # (Decision.hs deadline-mode expectations feeding the client).
    fetch_deadline_floor: float = 2.0
    fetch_deadline_mult: float = 4.0

    def chain_sync(self) -> ProtocolTimeLimits:
        return ProtocolTimeLimits("chain-sync", {
            "StIntersect": self.chain_sync_short,
            "StNext": self.chain_sync_short,
            "StMustReply": self.chain_sync_must_reply,
        })

    def fetch_deadline(self, tracker, est_bytes: int) -> float:
        """The per-request BlockFetch watchdog: DeltaQ expected duration
        scaled by `fetch_deadline_mult` (slack for queueing + variance),
        floored and capped.  An unmeasured peer gets the full ceiling."""
        # default False: a tracker without the `measured` attribute is
        # treated as UNmeasured (full ceiling) — failing the other way
        # would hand an optimistic-default GSV the tight deadline and
        # spuriously kill a healthy peer
        if tracker is None or not getattr(tracker, "measured", False):
            return self.block_fetch_busy
        expected = tracker.expected_fetch_time(max(est_bytes, 1))
        return min(self.block_fetch_busy,
                   max(self.fetch_deadline_floor,
                       self.fetch_deadline_mult * expected))


async def recv_with_limit(session, limits: ProtocolTimeLimits,
                          peer_id=None):
    """session.recv() guarded by the current state's time limit.

    Non-destructive: waits for a complete decodable message via
    wait_ready, then recv()s it — nothing is consumed on the timeout
    path, and the raised WatchdogTimeout carries the violated state."""
    limit = limits.limit_for(session.state)
    if limit is not None:
        ready = await session.channel.wait_ready(limit)
        if not ready:
            _count_firing(limits.name)
            sim.trace_event(("timeout", limits.name, session.state,
                             peer_id), label="watchdog")
            raise WatchdogTimeout(limits.name, session.state, limit)
    return await session.recv()


async def collect_with_limit(session, limits: ProtocolTimeLimits,
                             peer_id=None):
    """PipelinedSession.collect() under the time limit of the state the
    oldest outstanding reply is expected in (the pipelined analog of the
    reference's per-state limits — the peer owes us a reply for THAT
    state, not for the pipeline's advanced send state)."""
    state = session._outstanding[0] if session._outstanding \
        else session.state
    limit = limits.limit_for(state)
    if limit is not None:
        ready = await session.channel.wait_ready(limit)
        if not ready:
            _count_firing(limits.name)
            sim.trace_event(("timeout", limits.name, state, peer_id),
                            label="watchdog")
            raise WatchdogTimeout(limits.name, state, limit)
    return await session.collect()
