"""Consensus-side ChainSync client + server.

Reference: ouroboros-consensus/src/Ouroboros/Consensus/MiniProtocol/
ChainSync/Client.hs:418-431 (intersect, then pipelined roll-forward with
full header validation per header at :792, candidate-fragment STM publish,
kill on invalid header / too-deep rollback at :1114) and ChainSync/Server.hs
(server from a ChainDB follower).

TPU-first redesign of the client hot loop: instead of validating each
header as it arrives (the reference's per-header `validateHeader`), the
client pipelines up to `window` MsgRequestNext, buffers the roll-forwards,
and validates the whole buffer through consensus/batch.py — ONE device
batch for all VRF/KES/Ed25519 proofs in the window.  While syncing this
turns thousands of device round-trips into dozens; when caught up the
window degrades gracefully to batch-of-1.
"""
from __future__ import annotations

from typing import Optional

from .. import simharness as sim
from ..chain.block import Point, point_of
from ..chain.fragment import AnchoredFragment
from ..consensus.batch import validate_headers_batched
from ..consensus.header_validation import HeaderState, HeaderStateHistory
from ..observe import metrics as _metrics
from ..observe.spans import monotonic_now as _mono_now
from ..network.protocols.chainsync import (
    MsgAwaitReply, MsgFindIntersect, MsgIntersectFound, MsgIntersectNotFound,
    MsgRequestNext, MsgRollBackward, MsgRollForward,
)
from ..simharness import Retry, TVar
from .watchdog import collect_with_limit, recv_with_limit

# Fibonacci-ish offsets for intersection points, like the reference's
# chainSyncClient headerPoints (Client.hs mkPoints)
_OFFSETS = (0, 1, 2, 3, 5, 8, 13, 21, 34, 55, 89, 144)

# header-arrival instrumentation (ISSUE 9): while syncing the window
# fills to `window` headers per flush; caught up it degrades to
# batch-of-1 — the exact distribution the adaptive batching service
# (ROADMAP item 3) needs to see live.  Handles pre-bound (OBS002);
# virtual-time gaps under sim, wall gaps in production (unstable).
_ARRIVAL_GAP = _metrics.latency_histogram("chainsync.arrival_gap_secs")
_FLUSH_HEADERS = _metrics.histogram("chainsync.flush_headers",
                                    stable=False)


def pipeline_decision(outstanding: int, low: int, high: int,
                      caught_up: bool) -> str:
    """The low/high-watermark pipelining policy
    (Protocol/ChainSync/PipelineDecision.hs pipelineDecisionLowHighMark):
    behind the server tip, pipeline until the HIGH mark; caught up, only
    refill to the LOW mark (collect otherwise) so a quiescent tip is not
    saturated with speculative requests."""
    target = low if caught_up else high
    return "pipeline" if outstanding < target else "collect"


class ChainSyncClientError(Exception):
    """Peer sent an invalid header / rolled back too deep — disconnect and
    (for invalid headers) remember the block as bad (Client.hs:1114)."""


class CandidateState:
    """Per-peer candidate header chain published to BlockFetch
    (the candidate-fragment map entry, NodeKernel.hs:156)."""

    def __init__(self, peer_id):
        self.peer_id = peer_id
        self.fragment: Optional[AnchoredFragment] = None
        self.version = TVar(0, label=f"candidate-{peer_id}")
        self._v = 0

    def publish(self, fragment: AnchoredFragment) -> None:
        self.fragment = fragment
        self._v += 1
        try:
            self.version.set_notify(self._v)
        except Exception:
            self.version._value = self._v


async def chain_sync_client(session, kernel, candidate: CandidateState,
                            window: int = 32) -> None:
    """Pipelined ChainSync client against `session` (CLIENT role,
    PipelinedSession).  Publishes validated headers into `candidate`;
    raises ChainSyncClientError to kill the connection.
    """
    db = kernel.chain_db
    protocol = kernel.protocol
    # per-state time limits (timeLimitsChainSync): a peer silent past its
    # state's deadline is killed via WatchdogTimeout -> ErrorPolicy
    limits = kernel.time_limits.chain_sync()
    # block-propagation lifecycle tracker (ISSUE 14): records
    # first-header-seen / validated stamps when the kernel carries one
    prop = getattr(kernel, "propagation", None)

    # -- find intersection with our current chain ----------------------------
    points = db.current_chain.select_points(_OFFSETS)
    if db.current_chain.anchor not in points:
        points.append(db.current_chain.anchor)
    await session.send(MsgFindIntersect(tuple(points)))
    reply = await recv_with_limit(session, limits, peer_id=candidate.peer_id)
    if isinstance(reply, MsgIntersectNotFound):
        raise ChainSyncClientError("no intersection with peer chain")
    assert isinstance(reply, MsgIntersectFound)
    isect: Point = reply.point

    # Seed the header-state history with ALL of the ledger DB's recent
    # states up to the intersection (not just the intersection's), so a
    # legitimate rollback to a point *before* the intersection — a fork
    # whose branch point predates where we joined the peer — still rewinds
    # instead of killing the peer (the reference seeds from
    # HeaderStateHistory of the last k states for exactly this reason).
    past = db.ledger_db.past_points()
    if isect not in past:
        raise ChainSyncClientError(
            f"intersection {isect} deeper than our ledger history")
    seed_points = past[:past.index(isect) + 1]
    history = HeaderStateHistory(
        protocol.security_param, db.ledger_db.state_at(seed_points[0]).header)
    for p in seed_points[1:]:
        history.append(db.ledger_db.state_at(p).header)

    anchor_bn = _block_no_at(db, isect)
    fragment = AnchoredFragment(isect, (), anchor_block_no=anchor_bn)
    candidate.publish(fragment.copy())

    buffered: list = []          # validated-pending roll-forward headers

    async def flush() -> None:
        """Validate `buffered` as one batched window and publish.

        Views are forecast at each header's slot (cross-era aware); when
        the forecast horizon is hit the validated prefix is published and
        the rest stays buffered until the chain advances (the reference's
        forecast-horizon waiting, Client.hs:~740-790).

        A sub-window flush — the caught-up batch-of-1 regime — routes
        its proofs through the kernel's VerifyService when one is wired
        (crypto/batching.py): the window's handful of proofs coalesces
        with every other protocol thread's traffic into one device batch
        (or takes the CPU break-even fallback) instead of dispatching
        alone.  Full windows keep the direct batched path: they already
        ARE a good device batch."""
        if not buffered:
            return
        _FLUSH_HEADERS.observe(len(buffered))
        from ouroboros_tpu.consensus.ledger import OutsideForecastRange
        svc = getattr(kernel, "verify_service", None)
        if svc is not None and len(buffered) < window:
            from ouroboros_tpu.crypto.batching import (
                validate_headers_coalesced,
            )
            res = await validate_headers_coalesced(
                protocol, buffered, history.current,
                lambda i, h: kernel.forecast_view(h.slot), svc)
        else:
            res = validate_headers_batched(
                protocol, buffered, history.current,
                lambda i, h: kernel.forecast_view(h.slot),
                backend=kernel.backend)
        for st, h in zip(res.states, buffered[:res.n_valid]):
            history.append(st)
            fragment.add_block(h)
            if prop is not None:
                prop.mark("validated", h.hash, peer=candidate.peer_id)
        del buffered[:res.n_valid]
        if res.n_valid:
            if kernel.tracers.chain_sync.active:
                from ..utils.tracer import TraceChainSyncEvent
                kernel.tracers.chain_sync.trace(TraceChainSyncEvent(
                    peer_id=candidate.peer_id, event="validated",
                    slot=fragment.head_point.slot, n=res.n_valid))
            candidate.publish(fragment.copy())
        if res.error is None:
            return
        if isinstance(res.error, OutsideForecastRange):
            horizon_stalled[0] = True   # wait: headers stay buffered
            return
        del buffered[:]
        raise ChainSyncClientError(f"invalid header from peer: "
                                   f"{res.error}")

    horizon_stalled = [False]
    last_arrival = [None]        # roll-forward inter-arrival gap state
    # watermark pipelining (Protocol/ChainSync/PipelineDecision.hs
    # low/high mark): while BEHIND the server tip the pipeline fills to
    # the high mark (`window`); once caught up new requests only refill
    # to the low mark, so a quiescent tip holds few outstanding requests
    low_mark = max(1, window // 4)
    caught_up = [False]

    def _note_tip(tip) -> None:
        # count the not-yet-validated buffered headers too: a single push
        # at the tip must not flip the policy back to the high mark
        caught_up[0] = (tip is not None
                        and fragment.head_block_no + len(buffered)
                        >= tip.block_no)

    # -- pipelined follow loop ------------------------------------------------
    while True:
        while pipeline_decision(session.outstanding, low_mark, window,
                                caught_up[0]) == "pipeline":
            await session.send_pipelined(MsgRequestNext(), "StIdle")
        if horizon_stalled[0] and buffered:
            # forecast horizon hit: our own chain must advance (BlockFetch
            # adopting the validated prefix) before the rest validates —
            # poll the channel NON-destructively instead of cancelling a
            # collect() (cancellation would lose pipeline bookkeeping /
            # in-flight replies) while the peer may be quiescent at its tip
            # (Client.hs forecast waiting)
            ready = await session.channel.wait_ready(0.2)
            horizon_stalled[0] = False
            if not ready:
                await flush()
                continue
        msg = await collect_with_limit(session, limits,
                                       peer_id=candidate.peer_id)
        if isinstance(msg, MsgAwaitReply):
            # caught up: validate what we have, then wait for the next
            # server push (the collect below blocks on the channel)
            caught_up[0] = True
            await flush()
            continue
        if isinstance(msg, MsgRollForward):
            if _metrics.enabled():
                now = _mono_now()
                if last_arrival[0] is not None:
                    _ARRIVAL_GAP.observe(now - last_arrival[0])
                last_arrival[0] = now
            if prop is not None:
                prop.mark("header_seen", msg.header.hash,
                          peer=candidate.peer_id)
            buffered.append(msg.header)
            _note_tip(msg.tip)
            if len(buffered) >= window:
                await flush()
            elif session.outstanding == 0:
                await flush()
            continue
        if isinstance(msg, MsgRollBackward):
            _note_tip(msg.tip)
            await flush()
            if not history.rewind(msg.point):
                raise ChainSyncClientError(
                    f"peer rolled back beyond k to {msg.point}")
            if not fragment.truncate_to(msg.point):
                # rollback target is before the candidate's anchor but
                # within our header history: re-anchor an empty fragment
                # there (the peer's new chain branches below where we
                # joined it)
                bn = history.current.tip.block_no \
                    if history.current.tip else -1
                fragment = AnchoredFragment(msg.point, (),
                                            anchor_block_no=bn)
            candidate.publish(fragment.copy())
            continue
        raise ChainSyncClientError(f"unexpected message {msg}")


def _block_no_at(db, point: Point) -> int:
    if point.is_genesis:
        return -1
    blk = db.current_chain.lookup(point.hash)
    if blk is not None:
        return blk.block_no
    if point == db.current_chain.anchor:
        return db.current_chain.anchor_block_no
    raise ChainSyncClientError(f"intersection {point} not on our chain")


async def chain_sync_server(session, chain_db, content_of=None) -> None:
    """ChainSync server from a ChainDB follower (ChainSync/Server.hs).

    Serves the current chain — headers by default; pass
    ``content_of=lambda b: b`` for the node-to-client variant that rolls
    full blocks forward.  Blocks on the ChainDB version TVar when the
    follower is caught up (followerInstructionBlocking).
    """
    content_of = content_of or (lambda b: b.header)
    from ..network.protocols.chainsync import (
        MsgDone, MsgIntersectFound, MsgIntersectNotFound, MsgRequestNext,
    )
    follower = chain_db.new_follower()
    try:
        while True:
            msg = await session.recv()
            if isinstance(msg, MsgDone):
                return
            if isinstance(msg, MsgFindIntersect):
                found = None
                for p in msg.points:
                    if p.is_genesis or chain_db.contains_point(p):
                        found = p
                        break
                tip = _tip_of(chain_db)
                if found is None:
                    await session.send(MsgIntersectNotFound(tip))
                else:
                    follower.point = found
                    follower.needs_rollback = False
                    await session.send(MsgIntersectFound(found, tip))
                continue
            assert isinstance(msg, MsgRequestNext)
            ins = follower.instruction()
            if ins is None:
                await session.send(MsgAwaitReply())
                while True:
                    # read the version BEFORE re-checking the instruction so
                    # a block added in between is seen here, not lost to the
                    # wait below (same lost-wakeup discipline as the example
                    # server in network/protocols/chainsync.py)
                    seen = kernel_version_value(chain_db)
                    ins = follower.instruction()
                    if ins is not None:
                        break
                    await _wait_version_above(chain_db, seen)
            kind, payload = ins
            tip = _tip_of(chain_db)
            if kind == "forward":
                await session.send(MsgRollForward(content_of(payload), tip))
            else:
                await session.send(MsgRollBackward(payload, tip))
    finally:
        chain_db.remove_follower(follower)


def _tip_of(chain_db):
    from ..chain.block import Tip
    return Tip(chain_db.tip_point(), chain_db.current_chain.head_block_no)


def kernel_version_value(chain_db) -> int:
    tv = getattr(chain_db, "version_tvar", None)
    return tv.value if tv is not None else chain_db.version


async def _wait_version_above(chain_db, seen: int) -> None:
    tv = getattr(chain_db, "version_tvar", None)
    if tv is None:
        # no STM hook (ChainDB used outside a kernel): cooperative poll
        while chain_db.version == seen:
            await sim.yield_()
        return

    def tx_fn(tx):
        if tx.read(tv) == seen:
            raise Retry()
    await sim.atomically(tx_fn)
