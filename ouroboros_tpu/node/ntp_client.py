"""NTP client — wall-clock drift measurement for the node.

Reference: ntp-client/src/Network/NTP/Client.hs:35-120 (withNtpClient:
status TVar, poll loop, exponential error backoff capped at 600s, forced
re-query by setting the status back to pending) and Client/{Query,Packet}.hs
(48-byte RFC-5905 packet, offset = ((t1-t0)+(t2-t3))/2, IPv4+IPv6 racing,
`minimumOfSome` requiring a quorum of responses).

The transport is injectable (the Snocket lesson): production uses UDP
sockets under the IO runtime; tests drive the same client with a scripted
transport under the simulator.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from .. import simharness as sim

NTP_PACKET_SIZE = 48
NTP_UNIX_OFFSET = 2_208_988_800          # seconds 1900-01-01 .. 1970-01-01
_MODE_CLIENT = 3
_VERSION = 4


def _to_ntp(t: float) -> tuple[int, int]:
    """Unix seconds -> (ntp seconds, ntp fraction) 32.32 fixed point."""
    sec = int(t) + NTP_UNIX_OFFSET
    frac = int((t - int(t)) * (1 << 32))
    return sec & 0xFFFFFFFF, frac & 0xFFFFFFFF


def _from_ntp(sec: int, frac: int) -> float:
    return (sec - NTP_UNIX_OFFSET) + frac / (1 << 32)


@dataclass(frozen=True)
class NtpPacket:
    """The fields the client cares about (Packet.hs NtpPacket)."""
    params: int = (_VERSION << 3) | _MODE_CLIENT   # LI=0, VN=4, mode=client
    poll: int = 0
    origin_time: float = 0.0      # t0: when the client sent the request
    receive_time: float = 0.0     # t1: when the server received it
    transmit_time: float = 0.0    # t2: when the server sent the reply

    def encode(self) -> bytes:
        o_s, o_f = _to_ntp(self.origin_time)
        r_s, r_f = _to_ntp(self.receive_time)
        t_s, t_f = _to_ntp(self.transmit_time)
        return struct.pack(
            ">BBbb" + "II" + "I" + "IIIIIIII",
            self.params, 0, self.poll, 0,
            0, 0,                     # root delay, root dispersion
            0,                        # reference id
            0, 0,                     # reference timestamp
            o_s, o_f, r_s, r_f, t_s, t_f)

    @classmethod
    def decode(cls, raw: bytes) -> "NtpPacket":
        if len(raw) < NTP_PACKET_SIZE:
            raise ValueError(f"NTP packet too short: {len(raw)}")
        fields = struct.unpack(">BBbbIIIIIIIIIII", raw[:NTP_PACKET_SIZE])
        params, _stratum, poll = fields[0], fields[1], fields[2]
        o_s, o_f, r_s, r_f, t_s, t_f = fields[9:15]
        return cls(params=params, poll=poll,
                   origin_time=_from_ntp(o_s, o_f),
                   receive_time=_from_ntp(r_s, r_f),
                   transmit_time=_from_ntp(t_s, t_f))


def clock_offset(reply: NtpPacket, destination_time: float) -> float:
    """((t1 - t0) + (t2 - t3)) / 2 (Packet.hs clockOffsetPure)."""
    return ((reply.receive_time - reply.origin_time)
            + (reply.transmit_time - destination_time)) / 2.0


def minimum_of_some(threshold: int,
                    offsets: Sequence[float]) -> Optional[float]:
    """Smallest-magnitude offset, provided a quorum responded
    (Query.hs minimumOfSome)."""
    if len(offsets) < max(1, threshold):
        return None
    return min(offsets, key=abs)


# --- status ------------------------------------------------------------------

PENDING = "pending"          # NtpSyncPending
UNAVAILABLE = "unavailable"  # NtpSyncUnavailable


@dataclass(frozen=True)
class Drift:
    """NtpDrift: successfully measured offset (seconds; + = we are behind)."""
    offset: float


@dataclass(frozen=True)
class NtpSettings:
    """Query.hs NtpSettings."""
    servers: tuple                      # opaque server addresses
    required_results: int = 3           # ntpRequiredNumberOfResults
    response_timeout: float = 1.0       # per-query wait for replies
    poll_delay: float = 300.0           # between successful queries
    initial_error_delay: float = 5.0    # fast-retry start
    max_error_delay: float = 600.0      # backoff cap (Client.hs:118)


class NtpClient:
    """Poll-loop NTP client with an injectable transport.

    transport(server, request_bytes, timeout) -> response bytes | None.
    Servers of both address families are queried concurrently — the
    reference's IPv4/IPv6 racing (Query.hs:226-271) generalised to a list.
    """

    def __init__(self, settings: NtpSettings,
                 transport: Callable, tracer=None):
        self.settings = settings
        self.transport = transport
        self.tracer = tracer
        self.status = sim.TVar(PENDING, label="ntp.status")
        self._task = None

    def _trace(self, ev):
        if self.tracer:
            self.tracer(ev)

    # -- one query round ------------------------------------------------------
    async def query_once(self) -> object:
        """Query all servers concurrently; quorum of replies -> Drift."""
        st = self.settings

        async def one(server):
            # RFC 5905: the client puts t0 in the TRANSMIT field; the server
            # echoes it back as the reply's ORIGIN field (Packet.hs
            # mkNtpPacket does the same).
            t0 = sim.now()
            req = NtpPacket(transmit_time=t0)
            try:
                raw = await self.transport(server, req.encode(),
                                           st.response_timeout)
            except Exception as e:       # noqa: BLE001 — trace and continue
                self._trace(("ntp.send_error", server, repr(e)))
                return None
            if raw is None:
                return None
            try:
                reply = NtpPacket.decode(raw)
            except ValueError as e:
                self._trace(("ntp.bad_packet", server, str(e)))
                return None
            if abs(reply.origin_time - t0) > 1e-6:
                # origin must echo our transmit — drop spoofed/stale replies
                self._trace(("ntp.origin_mismatch", server))
                return None
            return clock_offset(reply, sim.now())

        tasks = [sim.spawn(one(s), label=f"ntp.query.{i}")
                 for i, s in enumerate(st.servers)]
        offsets = [o for o in [await t.wait() for t in tasks]
                   if o is not None]
        best = minimum_of_some(st.required_results, offsets)
        if best is None:
            self._trace(("ntp.unavailable", len(offsets)))
            return UNAVAILABLE
        self._trace(("ntp.drift", best))
        return Drift(best)

    # -- client thread --------------------------------------------------------
    async def _await_pending_with_timeout(self, t: float) -> None:
        """Sleep t seconds, woken early if someone forces a re-query by
        setting the status to PENDING (Client.hs awaitPendingWithTimeout)."""
        async def waiter():
            await sim.atomically(
                lambda tx: tx.check(tx.read(self.status) == PENDING))

        await sim.timeout(t, waiter())

    async def run(self):
        """The ntpClientThread loop: query, publish, sleep; on failure
        publish UNAVAILABLE and retry with doubling delay."""
        error_delay = self.settings.initial_error_delay
        while True:
            status = await self.query_once()
            if isinstance(status, Drift):
                await sim.atomically(
                    lambda t: t.write(self.status, status))
                await self._await_pending_with_timeout(
                    self.settings.poll_delay)
                error_delay = self.settings.initial_error_delay
            else:
                await sim.atomically(
                    lambda t: t.write(self.status, UNAVAILABLE))
                self._trace(("ntp.retry_delay", error_delay))
                await self._await_pending_with_timeout(error_delay)
                error_delay = min(2 * error_delay,
                                  self.settings.max_error_delay)

    # -- public API (NtpClient record) ----------------------------------------
    def get_status(self):
        return self.status.value

    async def query_blocking(self):
        """Force a re-query and wait for its result (ntpQueryBlocking)."""
        def force(t):
            if t.read(self.status) != PENDING:
                t.write(self.status, PENDING)
        await sim.atomically(force)

        def wait_done(t):
            s = t.read(self.status)
            t.check(s != PENDING)
            return s
        return await sim.atomically(wait_done)

    def start(self):
        self._task = sim.spawn(self.run(), label="ntp.client")
        return self._task

    def stop(self):
        if self._task is not None:
            self._task.cancel()


def udp_transport(resolve=None):
    """Production transport over real UDP sockets (IO runtime only).

    Returns an async callable (server, data, timeout) -> bytes | None.
    `server` is a (host, port) pair; resolve defaults to the identity.
    """
    import asyncio
    import socket

    async def transport(server, data, timeout):
        addr = resolve(server) if resolve else server

        def blocking_io():
            family = (socket.AF_INET6 if ":" in str(addr[0])
                      else socket.AF_INET)
            s = socket.socket(family, socket.SOCK_DGRAM)
            try:
                s.settimeout(timeout)
                s.sendto(data, addr)
                raw, _ = s.recvfrom(NTP_PACKET_SIZE)
                return raw
            except OSError:
                return None
            finally:
                s.close()

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, blocking_io)

    return transport
