"""Diffusion — compose the node's whole network surface.

Reference: ouroboros-network/src/Ouroboros/Network/Diffusion.hs:119-245
(`runDataDiffusion` composes: IOManager, snockets, local server for
wallets, IP/DNS subscription workers for outbound, accept servers for
inbound, error policies) — here over the in-sim address registry (the
Snocket seam: a socket transport plugs into `SimNetwork.dial` the same
way).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

from .. import simharness as sim
from ..network.error_policy import default_node_policies
from ..network.subscription import SubscriptionWorker
from .kernel import NodeKernel, _connect_directional


class SimNetwork:
    """Address registry standing in for the Snocket layer: maps addresses
    to listening kernels and dials by spawning directional connections."""

    def __init__(self, link_delay: float = 0.05, sdu_size: int = 12288):
        self.link_delay = link_delay
        self.sdu_size = sdu_size
        self.listeners: Dict[object, NodeKernel] = {}

    def listen(self, addr, kernel: NodeKernel) -> None:
        self.listeners[addr] = kernel

    def make_dial(self, kernel: NodeKernel):
        def dial(addr):
            target = self.listeners.get(addr)
            if target is None:
                async def fail():
                    raise ConnectionError(f"no listener at {addr}")
                return sim.spawn(fail(), label=f"dial-fail-{addr}")
            return _connect_directional(kernel, target,
                                        self.link_delay, self.sdu_size)
        return dial


@dataclass
class DiffusionArguments:
    """Diffusion.hs:119 `DiffusionArguments` analog."""
    address: object                          # our listening address
    ip_targets: Sequence = ()                # peers to maintain
    valency: int = 2
    error_policies: Optional[list] = None


@dataclass
class Diffusion:
    worker: Optional[SubscriptionWorker]
    threads: list = field(default_factory=list)


def run_data_diffusion(kernel: NodeKernel, network: SimNetwork,
                       args: DiffusionArguments) -> Diffusion:
    """Register the accept side, start outbound subscription maintenance
    (runDataDiffusion's composition, minus OS specifics)."""
    network.listen(args.address, kernel)
    worker = None
    if args.ip_targets:
        worker = SubscriptionWorker(
            targets=list(args.ip_targets),
            valency=args.valency,
            dial=network.make_dial(kernel),
            error_policies=(args.error_policies
                            if args.error_policies is not None
                            else default_node_policies()),
            label=f"{kernel.label}-subscription")
        t = sim.spawn(worker.run(), label=f"{kernel.label}-subscription")
        kernel._threads.append(t)
        return Diffusion(worker, [t])
    return Diffusion(worker)
