"""Diffusion — compose the node's whole network surface.

Reference: ouroboros-network/src/Ouroboros/Network/Diffusion.hs:119-245.
`runDataDiffusion` composes, in one record-driven call: the snocket layer,
a LOCAL server for wallets (node-to-client), per-address ACCEPT servers
for inbound node-to-node (initiator-and-responder mode only), an IP
subscription worker and per-domain DNS subscription workers for outbound,
with shared connection tables, accept limits and error policies.

This is that composition over this repo's Snocket trait, so the same
`run_data_diffusion` runs deterministically in-sim (SimSnocket) and over
real TCP/Unix sockets (TcpSnocket/UnixSnocket under the IO runtime) —
tests/test_diffusion.py drives both.

The older SimNetwork address-registry path is kept for tests that wire
kernels directly without bearers.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

from .. import simharness as sim
from ..network.error_policy import default_node_policies
from ..network.mux import Mux
from ..network.snocket import (
    AcceptLimits, ConnectionTable, Listener, Snocket, run_server,
)
from ..network.peer_selection import (
    PeerSelectionActions, PeerSelectionGovernor, PeerSelectionTargets,
)
from ..network.subscription import (
    Resolver, SubscriptionFatal, SubscriptionWorker,
    dns_subscription_targets,
)
from .kernel import NodeKernel, _connect_directional, _run_initiator, \
    _run_responder
from .node_to_client import serve_node_to_client

INITIATOR_AND_RESPONDER = "initiator-and-responder"   # DiffusionMode
INITIATOR_ONLY = "initiator-only"


@dataclass
class DiffusionArguments:
    """Diffusion.hs:119 `DiffusionArguments`: everything the node's
    network surface needs, as one typed record."""
    addresses: Sequence = ()           # listen addrs (daIPv4/daIPv6Address)
    local_address: object = None       # daLocalAddress (node-to-client)
    ip_producers: Sequence = ()        # daIpProducers dial targets
    ip_valency: int = 2
    dns_producers: Sequence = ()       # daDnsProducers domain names
    dns_valency: int = 2
    accept_limits: AcceptLimits = field(default_factory=AcceptLimits)
    mode: str = INITIATOR_AND_RESPONDER   # daDiffusionMode
    error_policies: Optional[list] = None


@dataclass
class Diffusion:
    """Handle on a running diffusion: its workers, servers and tables."""
    threads: list = field(default_factory=list)
    workers: list = field(default_factory=list)
    listeners: list = field(default_factory=list)
    tables: dict = field(default_factory=dict)

    def stop(self) -> None:
        for t in self.threads:
            t.cancel()
        for lst in self.listeners:
            lst.close()


async def _hold_connection(mux: Mux, runner) -> None:
    """Run a connection's application, then hold the bearer open until
    the mux's demuxer ends (bearer EOF/error = connection down), so
    run_server's finally can free the ConnectionTable slot and close the
    bearer (Socket.hs keeps the fd open until the application completes).
    A refused handshake releases the connection immediately."""
    try:
        outcome = await runner
        if outcome != "refused":
            await mux.wait_closed()
    finally:
        mux.stop()


def _dialer(kernel: NodeKernel, snocket: Snocket, label: str):
    """connectToNode over a snocket: dial -> mux -> initiator app.
    Returns the dial function the subscription workers drive."""
    def dial(addr):
        async def conn():
            bearer = await snocket.connect(addr)
            peer_id = f"{kernel.label}->{addr}"
            mux = Mux(bearer, f"{peer_id}.mux")
            mux.start()
            try:
                await _run_initiator(kernel, mux, peer_id)
            finally:
                mux.stop()
                close = getattr(bearer, "close", None)
                if close:
                    close()
        return sim.spawn(conn(), label=f"{label}-dial-{addr}")
    return dial


async def run_data_diffusion(kernel: NodeKernel, args: DiffusionArguments,
                             snocket: Snocket,
                             local_snocket: Optional[Snocket] = None,
                             resolver: Optional[Resolver] = None,
                             ) -> Diffusion:
    """The full composition (runDataDiffusion, Diffusion.hs:175-245):

    - local node-to-client server on args.local_address
    - accept server per args.addresses entry (responder mode only)
    - one IP subscription worker over args.ip_producers
    - one DNS subscription worker per args.dns_producers domain
    - shared remote/local connection tables + accept limits + policies
    """
    d = Diffusion()
    if args.dns_producers and resolver is None:
        raise ValueError("dns_producers given but no resolver — pass a "
                         "Resolver (DictResolver in sim, "
                         "GetAddrInfoResolver for real DNS)")
    policies = args.error_policies if args.error_policies is not None \
        else default_node_policies()
    remote_table = ConnectionTable()
    local_table = ConnectionTable()
    d.tables = {"remote": remote_table, "local": local_table}
    local_snocket = local_snocket or snocket

    # -- local server for wallets (Diffusion.hs:214 runLocalServer)
    if args.local_address is not None:
        lst = await local_snocket.listen(args.local_address)
        d.listeners.append(lst)

        async def local_handler(bearer, remote):
            mux = Mux(bearer, f"{kernel.label}.local.{remote}")
            mux.start()
            threads = serve_node_to_client(
                kernel, mux, label=f"{kernel.label}.local.{remote}")
            # threads[0] = the accept thread; its result is the handshake
            # outcome, so refused wallets release their slot immediately
            await _hold_connection(mux, threads[0].wait())

        d.threads.append(sim.spawn(
            run_server(lst, local_handler, table=local_table,
                       limits=args.accept_limits),
            label=f"{kernel.label}-local-server"))

    # -- accept servers per address (Diffusion.hs:225 runServer)
    if args.mode == INITIATOR_AND_RESPONDER:
        for addr in args.addresses:
            lst = await snocket.listen(addr)
            d.listeners.append(lst)

            async def handler(bearer, remote):
                peer_id = f"{kernel.label}<-{remote}"
                mux = Mux(bearer, f"{peer_id}.mux")
                mux.start()
                await _hold_connection(
                    mux, _run_responder(kernel, mux, peer_id))

            d.threads.append(sim.spawn(
                run_server(lst, handler, table=remote_table,
                           limits=args.accept_limits),
                label=f"{kernel.label}-server-{addr}"))

    # -- IP subscription worker (Diffusion.hs:217 runIpSubscriptionWorker)
    dial = _dialer(kernel, snocket, kernel.label)
    if args.ip_producers:
        w = SubscriptionWorker(
            targets=list(args.ip_producers), valency=args.ip_valency,
            dial=dial, error_policies=policies,
            label=f"{kernel.label}-ip-subscription")
        d.workers.append(w)
        d.threads.append(sim.spawn(
            _run_subscription(w, kernel),
            label=f"{kernel.label}-ip-subscription"))

    # -- DNS subscription workers (Diffusion.hs:220)
    for name in args.dns_producers:
        async def dns_worker(name=name):
            targets = await dns_subscription_targets(resolver, [name])
            if not targets:
                sim.trace_event(("dns-no-targets", kernel.label, name))
                return
            w = SubscriptionWorker(
                targets=targets, valency=args.dns_valency, dial=dial,
                error_policies=policies,
                label=f"{kernel.label}-dns-{name}")
            d.workers.append(w)
            await _run_subscription(w, kernel)
        d.threads.append(sim.spawn(
            dns_worker(), label=f"{kernel.label}-dns-subscription-{name}"))

    kernel._threads.extend(d.threads)
    return d


async def _run_subscription(worker: SubscriptionWorker,
                            kernel: NodeKernel) -> None:
    """Run a subscription worker under the THROW contract: a
    SubscriptionFatal verdict is fatal to the APPLICATION, not just the
    one peer (ErrorPolicy.hs `Throw`), so the whole node is stopped
    visibly — without this the worker thread dies silently reaped and the
    kernel keeps running with its connections never replenished."""
    try:
        await worker.run()
    except SubscriptionFatal as exc:
        sim.trace_event((kernel.label, "diffusion-fatal", repr(exc)),
                        label="subscription")
        try:
            raise
        finally:
            kernel.stop()


async def connect_local_client_via(snocket: Snocket, addr, kernel_info,
                                   label: str = "wallet"):
    """Wallet-side dial of a diffusion's local address: connect over the
    snocket, negotiate node-to-client, return a LocalClient
    (cardano-client Subscription.subscribe's connection phase, but over
    the diffusion's real local server rather than an in-memory pair).

    kernel_info: (network_magic, block_decode_obj) — what the client
    needs to know about the node's chain encoding."""
    from ..network import node_to_node as n2n
    from ..network.mux import INITIATOR, CodecChannel
    from ..network.protocols import chainsync as cs_proto
    from ..network.protocols import handshake as hs_proto
    from ..network.protocols import localstatequery as lsq_proto
    from ..network.protocols import localtxsubmission as ltx_proto
    from ..network.typed import CLIENT, Session
    from .node_to_client import NODE_TO_CLIENT_V1, LocalClient

    network_magic, block_decode_obj = kernel_info
    bearer = await snocket.connect(addr)
    mux_c = Mux(bearer, f"{label}.mux")
    mux_c.start()
    versions = hs_proto.Versions().add(NODE_TO_CLIENT_V1,
                                       {"magic": network_magic})
    hs = Session(hs_proto.SPEC, CLIENT,
                 CodecChannel(mux_c.channel(n2n.HANDSHAKE_NUM, INITIATOR),
                              hs_proto.CODEC))
    res = await hs_proto.client_propose(hs, versions)
    if res[0] != "accepted":
        mux_c.stop()
        close = getattr(bearer, "close", None)
        if close:
            close()
        return None
    cs_codec = cs_proto.make_codec(block_decode_obj) if block_decode_obj \
        else cs_proto.CODEC
    return LocalClient(
        mux=mux_c,
        chain_sync=Session(
            cs_proto.SPEC, CLIENT,
            CodecChannel(mux_c.channel(n2n.LOCAL_CHAINSYNC_NUM,
                                       INITIATOR), cs_codec)),
        state_query=Session(
            lsq_proto.SPEC, CLIENT,
            CodecChannel(mux_c.channel(n2n.LOCAL_STATEQUERY_NUM,
                                       INITIATOR), lsq_proto.CODEC)),
        tx_submission=Session(
            ltx_proto.SPEC, CLIENT,
            CodecChannel(mux_c.channel(n2n.LOCAL_TXSUBMISSION_NUM,
                                       INITIATOR), ltx_proto.CODEC)),
        version=res[1])


# ---------------------------------------------------------------------------
# Legacy in-sim address registry (pre-snocket wiring; kept for tests that
# connect kernels without bearers)
# ---------------------------------------------------------------------------

class SimNetwork:
    """Address registry standing in for the Snocket layer: maps addresses
    to listening kernels and dials by spawning directional connections.

    fault_plan: a simharness FaultPlan applied to every dialled
    connection's bearers — AND to the dial itself: dialling across an
    active partition is refused (the TCP-SYN-times-out analog), so
    suspension/redial cycles run at backoff speed instead of waiting out
    a full handshake watchdog."""

    def __init__(self, link_delay: float = 0.05, sdu_size: int = 12288,
                 fault_plan=None):
        self.link_delay = link_delay
        self.sdu_size = sdu_size
        self.fault_plan = fault_plan
        self.listeners: Dict[object, NodeKernel] = {}
        self._dial_seq: Dict[tuple, int] = {}

    def listen(self, addr, kernel: NodeKernel) -> None:
        self.listeners[addr] = kernel

    def make_dial(self, kernel: NodeKernel):
        def dial(addr):
            target = self.listeners.get(addr)
            if target is None:
                async def fail():
                    raise ConnectionError(f"no listener at {addr}")
                return sim.spawn(fail(), label=f"dial-fail-{addr}")
            if self.fault_plan is not None and \
                    self.fault_plan.partition_severs(kernel.label,
                                                     target.label):
                async def refused():
                    sim.trace_event(("dial-refused-partition", kernel.label,
                                     target.label), label="fault")
                    raise ConnectionError(
                        f"partitioned: {kernel.label}->{target.label}")
                return sim.spawn(refused(), label=f"dial-part-{addr}")
            key = (kernel.label, target.label)
            seq = self._dial_seq[key] = self._dial_seq.get(key, 0) + 1
            return _connect_directional(kernel, target,
                                        self.link_delay, self.sdu_size,
                                        fault_plan=self.fault_plan,
                                        conn_seq=seq)
        return dial


class GovernedConnection:
    """One governor-driven outbound connection with warm/hot staging
    (Governor.hs's cold→warm→hot ladder made concrete):

      warm  = bearer + mux + negotiated version + KeepAlive probe
      hot   = ChainSync/BlockFetch/TxSubmission client set running

    The subscription path fuses both stages (_run_initiator); here the
    governor controls each transition separately."""

    def __init__(self, kernel: NodeKernel, target: NodeKernel,
                 link_delay: float, sdu_size: int, on_down=None):
        self.kernel = kernel
        self.target = target
        self.peer_id = f"{kernel.label}->{target.label}"
        self.link_delay = link_delay
        self.sdu_size = sdu_size
        self.on_down = on_down
        self.mux_i = self.mux_r = None
        self.version = None
        self._ka = None
        self._hot = None

    async def establish(self) -> bool:
        """Cold→warm: dial, handshake, start KeepAlive."""
        from .kernel import (
            PeerGSVTracker, _initiator_handshake, _run_responder,
            _start_keepalive,
        )
        from ..network.mux import bearer_pair
        bi, br = bearer_pair(sdu_size=self.sdu_size, delay=self.link_delay)
        tracker = PeerGSVTracker(label=self.peer_id)
        self.mux_i = Mux(bi, f"{self.peer_id}.mux-i",
                         owd_observer=tracker.observe_owd)
        self.mux_r = Mux(br, f"{self.peer_id}.mux-r")
        self.mux_i.start()
        self.mux_r.start()
        self.target._threads.append(sim.spawn(
            _run_responder(self.target, self.mux_r, self.peer_id),
            label=f"{self.peer_id}.connect-r"))
        self.version = await _initiator_handshake(self.kernel, self.mux_i,
                                                  self.peer_id)
        if self.version is None:
            self.close()
            return False
        self._ka = _start_keepalive(self.kernel, self.mux_i, self.peer_id,
                                    tracker)
        return True

    def activate(self) -> bool:
        """Warm→hot: start the full client protocol set; when ChainSync
        ends (peer gone / protocol kill) the governor hears about it via
        on_down."""
        from .kernel import _run_hot
        if self.version is None or self._hot is not None:
            return False

        async def hot_then_report():
            try:
                await _run_hot(self.kernel, self.mux_i, self.peer_id,
                               self.version)
            finally:
                self._hot = None
                if self.on_down is not None:
                    self.on_down()
        self._hot = sim.spawn(hot_then_report(),
                              label=f"{self.peer_id}.hot")
        self.kernel._threads.append(self._hot)
        return True

    def deactivate(self) -> None:
        """Hot→warm: cancel the hot set, keep the connection."""
        if self._hot is not None:
            job, self._hot = self._hot, None
            job.cancel()

    def close(self) -> None:
        """→cold: tear the whole connection down."""
        self.deactivate()
        if self._ka is not None:
            self._ka.cancel()
            self._ka = None
        for m in (self.mux_i, self.mux_r):
            if m is not None:
                m.stop()


class GovernedPeerActions(PeerSelectionActions):
    """PeerSelectionActions over a SimNetwork: the governor's decisions
    become real staged connections (the runnable-governor wiring VERDICT
    r4 missing #4 asked for)."""

    def __init__(self, kernel: NodeKernel, network: SimNetwork,
                 root_peers=(), gossip_fn=None):
        self.kernel = kernel
        self.network = network
        self.root_peers = list(root_peers)
        self.gossip_fn = gossip_fn
        self.conns: Dict[object, GovernedConnection] = {}
        self.governor = None          # wired by run_governed_diffusion

    async def request_peers(self):
        return list(self.root_peers)

    async def gossip(self, addr):
        return list(self.gossip_fn(addr)) if self.gossip_fn else []

    async def connect(self, addr) -> bool:
        target = self.network.listeners.get(addr)
        if target is None or addr in self.conns:
            return addr in self.conns
        conn = GovernedConnection(
            self.kernel, target, self.network.link_delay,
            self.network.sdu_size,
            on_down=lambda a=addr: self._peer_down(a))
        if await conn.establish():
            self.conns[addr] = conn
            return True
        return False

    def _peer_down(self, addr) -> None:
        """Hot set died (connection gone): drop the stale connection so a
        re-promotion dials fresh, and feed the failure back (suspension +
        demotion) if the governor still thought the peer active."""
        was_active = (self.governor is not None
                      and addr in self.governor.active)
        conn = self.conns.pop(addr, None)
        if conn is not None:
            conn.close()
        if was_active:
            self.governor.report_failure(addr)

    async def activate(self, addr) -> bool:
        conn = self.conns.get(addr)
        return bool(conn) and conn.activate()

    async def deactivate(self, addr) -> None:
        conn = self.conns.get(addr)
        if conn:
            conn.deactivate()

    async def disconnect(self, addr) -> None:
        conn = self.conns.pop(addr, None)
        if conn:
            conn.close()


def run_governed_diffusion(kernel: NodeKernel, network: SimNetwork,
                           address, root_peers=(),
                           targets: Optional[PeerSelectionTargets] = None,
                           seed: int = 0, churn_interval: float = 0.0,
                           gossip_fn=None) -> Diffusion:
    """Governor-driven peer maintenance: instead of fixed-valency
    subscription workers, a PeerSelectionGovernor walks peers up and down
    the cold/warm/hot ladder toward declarative targets (Governor.hs:427
    as the diffusion driver)."""
    network.listen(address, kernel)
    actions = GovernedPeerActions(kernel, network, root_peers=root_peers,
                                  gossip_fn=gossip_fn)
    gov = PeerSelectionGovernor(
        targets or PeerSelectionTargets(), actions, seed=seed,
        self_addr=address)
    actions.governor = gov
    d = Diffusion()
    t = sim.spawn(gov.run(), label=f"{kernel.label}-governor")
    kernel._threads.append(t)
    d.threads.append(t)
    if churn_interval > 0:
        tc = sim.spawn(gov.run_churn(churn_interval),
                       label=f"{kernel.label}-governor-churn")
        kernel._threads.append(tc)
        d.threads.append(tc)
    d.tables["governor"] = gov
    d.tables["actions"] = actions
    return d


def run_sim_diffusion(kernel: NodeKernel, network: SimNetwork,
                      address, ip_targets=(), valency: int = 2,
                      error_policies=None, base_backoff: float = 5.0,
                      seed: int = 0) -> Diffusion:
    """SimNetwork-based composition (the pre-round-4 surface)."""
    network.listen(address, kernel)
    d = Diffusion()
    if ip_targets:
        worker = SubscriptionWorker(
            targets=list(ip_targets), valency=valency,
            dial=network.make_dial(kernel),
            error_policies=(error_policies if error_policies is not None
                            else default_node_policies()),
            base_backoff=base_backoff, seed=seed,
            label=f"{kernel.label}-subscription")
        t = sim.spawn(_run_subscription(worker, kernel),
                      label=f"{kernel.label}-subscription")
        kernel._threads.append(t)
        d.workers.append(worker)
        d.threads.append(t)
    return d
