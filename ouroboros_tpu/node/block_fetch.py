"""BlockFetch logic — the download governor.

Reference: ouroboros-network/src/Ouroboros/Network/BlockFetch/Decision.hs:
150-184,526 (pure decision pipeline: filter plausible candidates → filter
already-fetched/in-flight → prioritise → per-peer requests with in-flight
limits), BlockFetch.hs:239 (logic iteration loop re-run on STM change),
ClientState.hs (per-peer in-flight tracking), BlockFetch/Client.hs (protocol
adapter), BlockFetch/Server.hs (server from a ChainDB iterator).

The decision pipeline is a pure function over immutable snapshots
(fetch_decisions) so it is testable exactly like the reference's
property-tested `fetchDecisions`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence

from .. import simharness as sim
from ..chain.block import Point, point_of
from ..network.protocols.blockfetch import fetch_range
from ..observe import metrics as _metrics
from ..simharness import Retry, TQueue, TVar

# per-request BlockFetch latency (ISSUE 14 net.rtt.* namespace, beside
# the KeepAlive RTT in network/deltaq.py); handle pre-bound (OBS002)
_FETCH_REQUEST_SECS = _metrics.latency_histogram(
    "net.rtt.blockfetch_secs")


@dataclass(frozen=True)
class FetchRequest:
    """A contiguous run of headers to download from one peer.

    start is EXCLUSIVE (the predecessor point), matching the server's
    (from, to] streaming semantics; headers are oldest..newest."""
    peer_id: object
    start: Point
    headers: tuple
    est_bytes: int = 0               # in-flight byte accounting estimate

    @property
    def end(self) -> Point:
        return point_of(self.headers[-1])


@dataclass(frozen=True)
class FetchBudget:
    """The request-sizing limits of fetchRequestDecisions
    (Decision.hs:526): per-peer in-flight bytes (the low/high watermark
    pair collapsed to one cap), a network-wide concurrency budget, and a
    DeltaQ bound on a single request's expected duration."""
    max_blocks_per_request: int = 16
    max_in_flight_bytes_per_peer: int = 256 * 1024
    max_concurrent_peers: int = 4
    max_request_expected_secs: float = 5.0
    # deadline-mode duplicate-fetch race (Decision.hs deadline semantics):
    # a block already in flight with a slow peer may be re-requested from
    # a peer whose DeltaQ arrival estimate beats the claimant's by this
    # factor; 0 disables racing (bulk sync never duplicates)
    duplicate_speedup: float = 0.0

    @classmethod
    def bulk_sync(cls) -> "FetchBudget":
        """FetchModeBulkSync: far from the tip — few peers, big batches
        (maximise throughput; duplicate fetches are pure waste here)."""
        return cls(max_blocks_per_request=32,
                   max_in_flight_bytes_per_peer=512 * 1024,
                   max_concurrent_peers=2,
                   max_request_expected_secs=20.0)

    @classmethod
    def deadline(cls) -> "FetchBudget":
        """FetchModeDeadline: near the tip — more peers, small requests,
        tight expected-duration bound (minimise time-to-adoption; the
        block-diffusion deadline of BASELINE.md), and duplicate racing
        against clearly-slower in-flight claims."""
        return cls(max_blocks_per_request=4,
                   max_in_flight_bytes_per_peer=128 * 1024,
                   max_concurrent_peers=8,
                   max_request_expected_secs=2.0,
                   duplicate_speedup=2.0)


class PeerFetchState:
    """Per-peer fetch bookkeeping (ClientState.hs `PeerFetchStatus` +
    request queue + in-flight byte/size tracking)."""

    def __init__(self, peer_id):
        self.peer_id = peer_id
        self.queue = TQueue(label=f"fetch-req-{peer_id}")
        self.in_flight: set[bytes] = set()     # header hashes requested
        self.in_flight_bytes: int = 0          # estimated bytes outstanding
        self.avg_block_bytes: int = 2048       # refined from transfers
        # scan frontier: everything on the candidate up to this point is
        # known-stored, so decision rounds skip it (keeps a long sync from
        # rescanning the fragment from its anchor every round)
        self.done_through: Optional[Point] = None

    @property
    def busy(self) -> bool:
        return bool(self.in_flight)

    def observe_blocks(self, n_blocks: int, n_bytes: int) -> None:
        if n_blocks:
            self.avg_block_bytes = max(
                64, (self.avg_block_bytes + n_bytes // n_blocks) // 2)


def fetch_decisions(
        candidates: Dict[object, object],
        peer_states: Dict[object, PeerFetchState],
        plausible: Callable[[object], bool],
        have_block: Callable[[bytes], bool],
        max_blocks_per_request: Optional[int] = None,
        order_key: Optional[Callable[[object], float]] = None,
        budget: Optional[FetchBudget] = None,
        gsv: Optional[Callable[[object], object]] = None
        ) -> list[FetchRequest]:
    """The pure decision pipeline (Decision.hs:150-184,526).

    candidates: peer -> AnchoredFragment of validated headers (or None).
    plausible:  fragment -> would we prefer this chain over ours?
    have_block: hash -> already stored in the ChainDB?
    gsv:        peer -> PeerGSV tracker (None: no DeltaQ sizing).

    Filter plausible → filter fetched/in-flight → prioritise (longest
    candidate, then cheapest peer by DeltaQ) → size requests within the
    FetchBudget: per-peer in-flight byte cap, network concurrency budget,
    and a DeltaQ bound on each request's expected duration — a slow peer
    gets small requests (or none, when faster peers cover its candidate),
    a fast peer saturates.
    """
    # one source of truth for request sizing: an explicit
    # max_blocks_per_request overrides the budget's field
    if budget is None:
        budget = FetchBudget(
            max_blocks_per_request=max_blocks_per_request or 16)
    elif max_blocks_per_request is not None:
        from dataclasses import replace as _replace
        budget = _replace(budget,
                          max_blocks_per_request=max_blocks_per_request)
    # claimed: hash -> the claiming peer's DeltaQ arrival estimate (inf
    # when unknown).  Deadline mode races a clearly-faster peer against a
    # slow claim; bulk mode treats every claim as final.
    claimed: Dict[bytes, float] = {}
    busy_count = 0
    for peer, ps in peer_states.items():
        tracker = gsv(peer) if gsv is not None else None
        eta = (tracker.expected_fetch_time(
            max(ps.in_flight_bytes, ps.avg_block_bytes))
            if tracker is not None else float("inf"))
        for h in ps.in_flight:
            claimed[h] = min(claimed.get(h, float("inf")), eta)
        queued = _queued(ps.queue)
        for req in queued:
            for h in req.headers:
                claimed[h.hash] = min(claimed.get(h.hash, float("inf")),
                                      eta)
        if ps.busy or queued:
            busy_count += 1

    decisions: list[FetchRequest] = []
    # deterministic peer order: better candidates first, then cheaper peers
    # by DeltaQ expected fetch time (Decision.hs prioritisation), then id
    def head_key(item):
        peer, frag = item
        bn = frag.head_block_no if frag is not None and len(frag) else -1
        dq = order_key(peer) if order_key is not None else 0.0
        return (-bn, dq, str(peer))

    for peer, frag in sorted(candidates.items(), key=head_key):
        if busy_count >= budget.max_concurrent_peers:
            break                        # concurrency budget exhausted
        if frag is None or len(frag) == 0 or not plausible(frag):
            continue
        ps = peer_states.get(peer)
        if ps is None or ps.busy or _queued(ps.queue):
            continue
        # per-peer byte budget + DeltaQ request sizing
        est = ps.avg_block_bytes
        bytes_left = budget.max_in_flight_bytes_per_peer \
            - ps.in_flight_bytes
        if bytes_left < est:
            continue
        cap = min(budget.max_blocks_per_request, max(1, bytes_left // est))
        tracker = gsv(peer) if gsv is not None else None
        if tracker is not None:
            if tracker.expected_fetch_time(est) \
                    > budget.max_request_expected_secs:
                if decisions:
                    # a faster peer is already fetching this round: the
                    # slow peer loses the race entirely (Decision.hs
                    # deadline-mode peer filtering)
                    continue
                # sole source: fetch slowly (one block) rather than
                # starve — a too-slow ONLY peer must still make progress
                cap = 1
            else:
                n = 1
                while n < cap and tracker.expected_fetch_time(
                        (n + 1) * est) <= budget.max_request_expected_secs:
                    n += 1
                cap = n
        # resume the scan at the stored frontier when it is still on the
        # fragment (a rollback may have invalidated it — then rescan)
        blocks = None
        prev_point = frag.anchor
        if ps.done_through is not None:
            blocks = frag.after_point(ps.done_through)
            if blocks is not None:
                prev_point = ps.done_through
            else:
                ps.done_through = None
        if blocks is None:
            blocks = frag.blocks
        # symmetric race comparison (ADVICE r4): include OUR queue backlog
        # exactly as expected_fetch_time does for the claimant, else a
        # loaded fast peer wins duplicate races its backlog should lose
        my_eta = (tracker.expected_fetch_time(
                      max(ps.in_flight_bytes + est, est))
                  if tracker is not None else float("inf"))
        run: list = []
        start: Optional[Point] = None
        frontier_ok = True               # still in the contiguous stored prefix
        for h in blocks:
            stored = have_block(h.hash)
            other_eta = claimed.get(h.hash)
            needed = not stored and (
                other_eta is None
                # the deadline-mode duplicate race: fetch a claimed block
                # again iff our arrival beats the claim by the configured
                # factor (Decision.hs deadline-mode in-flight-with-other-
                # peers filtering)
                or (budget.duplicate_speedup > 0
                    and my_eta * budget.duplicate_speedup < other_eta))
            if needed:
                if not run:
                    start = prev_point
                run.append(h)
                if len(run) >= cap:
                    break
            elif run:
                break                    # only the first contiguous run
            elif stored and frontier_ok:
                # advance the frontier cache over the stored prefix only —
                # never past an unstored (claimed) block whose fetch may
                # still fail
                ps.done_through = point_of(h)
            # a claimed-by-another-peer block is skipped: a later run may
            # still be assignable to this peer (disjoint parallel fetch)
            if not stored:
                frontier_ok = False
            prev_point = point_of(h)
        if run:
            req = FetchRequest(peer, start, tuple(run),
                               est_bytes=len(run) * est)
            for h in run:
                claimed[h.hash] = min(claimed.get(h.hash, float("inf")),
                                      my_eta)
            decisions.append(req)
            busy_count += 1
    return decisions


def _queued(q: TQueue) -> list:
    """Non-transactional peek at queued requests (cooperative runtime —
    safe between awaits)."""
    out = []
    cons = q._back.value
    while cons is not None:
        item, cons = cons
        out.append(item)
    out.reverse()
    front = []
    cons = q._front.value
    while cons is not None:
        item, cons = cons
        front.append(item)
    return front + out


async def fetch_logic_loop(kernel) -> None:
    """The blockFetchLogic iteration thread (BlockFetch.hs:239): re-runs
    the decision pipeline whenever a candidate, the current chain, or the
    in-flight set changes, and enqueues requests to per-peer clients."""
    from ..utils.tracer import TraceFetchDecision
    prop = getattr(kernel, "propagation", None)
    while True:
        seen = kernel.fetch_wakeup.value
        # fetch MODE (BlockFetchConsensusInterface readFetchMode): far
        # behind the best candidate -> bulk sync; near the tip -> deadline
        our_bn = kernel.chain_db.current_chain.head_block_no
        best_bn = max(
            (c.fragment.head_block_no for c in kernel.candidates.values()
             if c.fragment is not None and len(c.fragment)),
            default=our_bn)
        budget = (FetchBudget.bulk_sync() if best_bn - our_bn > 16
                  else FetchBudget.deadline())
        decisions = fetch_decisions(
            {p: c.fragment for p, c in kernel.candidates.items()},
            kernel.peer_fetch,
            kernel.plausible_candidate,
            kernel.have_block,
            order_key=kernel.fetch_order_key,
            budget=budget,
            gsv=kernel.peer_gsv.get)
        for req in decisions:
            ps = kernel.peer_fetch[req.peer_id]
            ps.in_flight |= {h.hash for h in req.headers}
            ps.in_flight_bytes += req.est_bytes
            if prop is not None:
                for h in req.headers:
                    prop.mark("fetch_decided", h.hash, peer=req.peer_id)
            if kernel.tracers.fetch.active:
                kernel.tracers.fetch.trace(TraceFetchDecision(
                    peer_id=req.peer_id, n_requested=len(req.headers),
                    in_flight_bytes=ps.in_flight_bytes, reason="request"))

            def push(tx, ps=ps, req=req):
                ps.queue.put(tx, req)
            await sim.atomically(push)
        # wait for something to change
        def wait_change(tx, seen=seen):
            if tx.read(kernel.fetch_wakeup) == seen:
                raise Retry()
        await sim.atomically(wait_change)


async def block_fetch_client(session, kernel, peer_id) -> None:
    """Per-peer fetch worker: executes assigned FetchRequests over the
    BlockFetch mini-protocol and feeds blocks into the ChainDB
    (BlockFetch/Client.hs + addFetchedBlock).

    On any failure the peer's in-flight claims are released and the peer is
    dropped from fetch consideration — otherwise its claimed hashes would
    block every other peer from ever re-requesting that chain segment."""
    from .watchdog import WatchdogTimeout
    ps = kernel.peer_fetch[peer_id]
    prop = getattr(kernel, "propagation", None)
    try:
        while True:
            req = await sim.atomically(lambda tx: ps.queue.get(tx))
            try:
                t0 = sim.now()
                # whole-request watchdog (timeLimitsBlockFetch), tightened
                # by the peer's DeltaQ estimate: a measured-fast peer gets
                # a measured-fast deadline instead of the 60s ceiling
                deadline = kernel.time_limits.fetch_deadline(
                    kernel.peer_gsv.get(peer_id),
                    max(req.est_bytes, ps.avg_block_bytes))
                done, blocks = await sim.timeout(
                    deadline, fetch_range(session, req.start, req.end))
                if not done:
                    sim.trace_event(("timeout", "block-fetch", "BFBusy",
                                     peer_id), label="watchdog")
                    raise WatchdogTimeout("block-fetch", "BFBusy", deadline)
                tracker = kernel.peer_gsv.get(peer_id)
                if blocks:
                    total = sum(len(b.bytes) for b in blocks)
                    _FETCH_REQUEST_SECS.observe(sim.now() - t0)
                    if tracker is not None:
                        tracker.observe_transfer(total, sim.now() - t0)
                    ps.observe_blocks(len(blocks), total)
                for b in blocks or ():
                    if prop is not None:
                        prop.mark("body_arrived", b.hash, peer=peer_id)
                    kernel.add_fetched_block(b)
            finally:
                ps.in_flight -= {h.hash for h in req.headers}
                ps.in_flight_bytes = max(0,
                                         ps.in_flight_bytes - req.est_bytes)
            ps.done_through = req.end
            kernel.poke_fetch_logic()
    except sim.AsyncCancelled:
        raise
    except Exception as e:
        sim.trace_event(("block-fetch-kill", kernel.label, peer_id,
                         repr(e)))
        ps.in_flight.clear()
        ps.in_flight_bytes = 0
        kernel.drop_peer(peer_id)
        raise


def block_fetch_server(chain_db):
    """Server peer function streaming ranges from the ChainDB."""
    from ..network.protocols.blockfetch import server_from_blocks

    async def server(session):
        await server_from_blocks(
            session, lambda start, end: chain_db.stream_blocks(start, end))
    return server
