"""Node-side TxSubmission: blocking outbound from the mempool, inbound to
the mempool.

Reference: ouroboros-network/src/Ouroboros/Network/TxSubmission/
{Outbound,Inbound}.hs + Mempool/Reader.hs — the outbound side serves tx
ids/bodies from a mempool reader, *blocking* on the blocking id request
until new txs arrive; the inbound side windows requests, dedups, and feeds
`mempoolAddTxs`.
"""
from __future__ import annotations

from .. import simharness as sim
from ..network.protocols.txsubmission import (
    MsgReplyTxIds, MsgReplyTxs, MsgRequestTxIds, MsgRequestTxs,
)
from ..simharness import Retry
from ..utils import cbor


async def tx_outbound_loop(session, mempool) -> None:
    """CLIENT role: serve our mempool to the peer's inbound server.

    Blocking MsgRequestTxIds waits on the mempool version TVar when the
    reader is drained (Outbound.hs blocking semantics) instead of
    terminating — this is a long-lived node-to-node connection.
    """
    reader = mempool.reader()
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgRequestTxIds):
            new = reader.next_ids(msg.req)
            if not new and msg.blocking:
                while not new:
                    seen = mempool.version.value
                    new = reader.next_ids(msg.req)
                    if new:
                        break

                    def wait_change(tx, seen=seen):
                        if tx.read(mempool.version) == seen:
                            raise Retry()
                    await sim.atomically(wait_change)
            await session.send(MsgReplyTxIds(tuple(new)))
        elif isinstance(msg, MsgRequestTxs):
            txs = []
            for txid in msg.ids:
                tx = reader.lookup(txid)
                if tx is not None:
                    txs.append(cbor.dumps(tx.encode()))
            await session.send(MsgReplyTxs(tuple(txs)))
        else:
            return


async def tx_inbound_loop(session, mempool, tx_decode, window: int = 10
                          ) -> None:
    """SERVER role: pull txs from the peer into our mempool
    (Inbound.hs:52-172 — windowed acks, dedup via the mempool itself)."""
    ack = 0
    while True:
        await session.send(MsgRequestTxIds(True, ack, window))
        reply = await session.recv()
        if not isinstance(reply, MsgReplyTxIds):
            return
        ids = [i for i, _ in reply.ids_and_sizes]
        ack = len(ids)
        if not ids:
            continue
        # skip txs we already have (dedup before fetching bodies); one
        # snapshot for the whole window, not one per id
        have = set(mempool.get_snapshot().tx_ids)
        want = [i for i in ids if i not in have]
        if want:
            await session.send(MsgRequestTxs(tuple(want)))
            reply = await session.recv()
            txs = [tx_decode(cbor.loads(raw)) for raw in reply.txs]
            mempool.try_add_txs(txs)
