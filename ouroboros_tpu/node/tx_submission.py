"""Node-side TxSubmission: blocking outbound from the mempool, windowed
inbound to the mempool.

Reference: ouroboros-network/src/Ouroboros/Network/TxSubmission/
{Outbound,Inbound}.hs + Mempool/Reader.hs — the outbound side serves tx
ids/bodies from a mempool reader, *blocking* on the blocking id request
until new txs arrive; the inbound side (Inbound.hs:52-172) keeps a
bounded FIFO of unacknowledged ids, acks strictly in order as txs are
processed, budgets the bodies it requests, dedups against the mempool,
and treats any window violation by the peer as a protocol error that
tears the connection down — an over-announcing or re-announcing peer
cannot grow node memory unboundedly.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from .. import simharness as sim
from ..network.protocols.txsubmission import (
    MsgReplyTxIds, MsgReplyTxs, MsgRequestTxIds, MsgRequestTxs,
)
from ..simharness import Retry
from ..utils import cbor


class TxInboundProtocolError(Exception):
    """Peer violated the TxSubmission window contract; the caller must
    drop the connection (the reference throws ProtocolErrorXxx from
    Inbound.hs and the mux tears the bearer down)."""


@dataclass
class TxInboundPolicy:
    """Bounds of the inbound window (Inbound.hs txSubmissionInbound
    arguments; numbers are the node defaults' shape, not a copy)."""
    max_unacked: int = 10          # FIFO bound on unacknowledged ids
    max_ids_per_req: int = 3       # new ids per MsgRequestTxIds
    max_txs_per_req: int = 2       # bodies per MsgRequestTxs
    max_bytes_in_flight: int = 100_000   # advertised-size budget per fetch
    max_tx_size: int = 65_536      # reject absurd advertised sizes


async def tx_outbound_loop(session, mempool,
                           max_window: int = 100) -> None:
    """CLIENT role: serve our mempool to the peer's inbound server.

    Blocking MsgRequestTxIds waits on the mempool version TVar when the
    reader is drained (Outbound.hs blocking semantics) instead of
    terminating — this is a long-lived node-to-node connection.

    Keeps the peer honest the way Outbound.hs does: acks may only cover
    ids we actually sent, and the requested window is bounded — a peer
    asking for an absurd window is a protocol violation, not an
    allocation.
    """
    reader = mempool.reader()
    unacked: deque = deque()
    while True:
        msg = await session.recv()
        if isinstance(msg, MsgRequestTxIds):
            if msg.ack > len(unacked) or msg.req > max_window:
                raise TxInboundProtocolError(
                    f"outbound: bad ack/req {msg.ack}/{msg.req} "
                    f"(unacked {len(unacked)})")
            for _ in range(msg.ack):
                unacked.popleft()
            if len(unacked) + msg.req > max_window:
                raise TxInboundProtocolError(
                    "outbound: window overflow requested")
            new = reader.next_ids(msg.req)
            if not new and msg.blocking:
                while not new:
                    seen = mempool.version.value
                    new = reader.next_ids(msg.req)
                    if new:
                        break

                    def wait_change(tx, seen=seen):
                        if tx.read(mempool.version) == seen:
                            raise Retry()
                    await sim.atomically(wait_change)
            unacked.extend(i for i, _s in new)
            await session.send(MsgReplyTxIds(tuple(new)))
        elif isinstance(msg, MsgRequestTxs):
            txs = []
            for txid in msg.ids:
                if txid not in unacked:
                    raise TxInboundProtocolError(
                        "outbound: tx requested outside the window")
                tx = reader.lookup(txid)
                if tx is not None:
                    txs.append(cbor.dumps(tx.encode()))
            await session.send(MsgReplyTxs(tuple(txs)))
        else:
            return


async def tx_inbound_loop(session, mempool, tx_decode,
                          policy: TxInboundPolicy | None = None,
                          window: int | None = None) -> None:
    """SERVER role: pull txs from the peer into our mempool with the
    reference's full window discipline (Inbound.hs:52-172):

    - `unacked` is a bounded FIFO of advertised ids; acks cover exactly
      the processed PREFIX (the peer drops that many from its own queue).
    - ids already in the mempool are processed immediately (dedup) —
      acked without fetching a body.
    - body requests are budgeted by count and by advertised size.
    - violations (more ids than requested, an id re-announced while
      still unacknowledged, empty non-blocking reply abuse, oversize
      advertisements, bodies that hash to an id we never asked for)
      raise TxInboundProtocolError — the connection dies, memory stays
      bounded by max_unacked + the fetch budget.
    """
    from dataclasses import replace
    policy = policy or TxInboundPolicy()
    if window is not None:       # legacy knob: cap ids per request
        policy = replace(policy, max_ids_per_req=window)
    unacked: deque = deque()      # ids in announce order
    done: set = set()             # processed (fetched/deduped) ids
    sizes: dict = {}              # id -> advertised size, not yet fetched
    ack = 0
    while True:
        in_window = len(unacked)
        req = min(policy.max_ids_per_req, policy.max_unacked - in_window)
        blocking = in_window == 0 and not sizes
        if req > 0:
            await session.send(MsgRequestTxIds(blocking, ack, req))
            ack = 0
            reply = await session.recv()
            if not isinstance(reply, MsgReplyTxIds):
                return
            if len(reply.ids_and_sizes) > req:
                raise TxInboundProtocolError(
                    f"peer sent {len(reply.ids_and_sizes)} ids for a "
                    f"window of {req}")
            if blocking and not reply.ids_and_sizes:
                raise TxInboundProtocolError(
                    "empty reply to a blocking id request")
            have = set(mempool.get_snapshot().tx_ids)
            pending = set(unacked)
            for txid, size in reply.ids_and_sizes:
                if txid in pending:
                    raise TxInboundProtocolError(
                        "id re-announced while still unacknowledged")
                if size > policy.max_tx_size:
                    raise TxInboundProtocolError(
                        f"advertised tx size {size} exceeds limit")
                pending.add(txid)
                unacked.append(txid)
                if txid in have or txid in done:
                    done.add(txid)       # dedup: ack without fetching
                else:
                    sizes[txid] = size
        # budgeted body fetch: oldest-first so acks can advance
        batch: list = []
        budget = policy.max_bytes_in_flight
        for txid in unacked:
            if len(batch) >= policy.max_txs_per_req or budget <= 0:
                break
            if txid in sizes and txid not in done:
                if sizes[txid] <= budget or not batch:
                    batch.append(txid)
                    budget -= sizes[txid]
        if batch:
            await session.send(MsgRequestTxs(tuple(batch)))
            reply = await session.recv()
            if not isinstance(reply, MsgReplyTxs):
                return
            requested = set(batch)
            txs = []
            for raw in reply.txs:
                tx = tx_decode(cbor.loads(raw))
                if tx.txid not in requested:
                    raise TxInboundProtocolError(
                        "peer sent a tx body we did not request")
                txs.append(tx)
            if txs:
                mempool.try_add_txs(txs)
            # requested-but-missing ids are done too: the peer's mempool
            # evicted them (Outbound.hs filters); we must still ack
            for txid in batch:
                done.add(txid)
                sizes.pop(txid, None)
        # advance the ack prefix
        while unacked and unacked[0] in done:
            done.discard(unacked.popleft())
            ack += 1
