#!/usr/bin/env python
"""Perf probe: repeated idle measurements of the Ed25519/VRF device paths.

Times each (path, shape) with R repetitions and prints median + min/max —
the measurement discipline VERDICT r3 asked for, in a standalone tool so
kernel work can be steered by medians instead of single-shot noise.
"""
import argparse
import hashlib
import statistics
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.abspath(__file__)) + "/..")


def timed(fn, reps):
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        vals.append(time.perf_counter() - t0)
    return vals


def report(name, n, vals):
    med = statistics.median(vals)
    spread = (max(vals) - min(vals)) / med if med else 0
    print(f"{name:28s} n={n:5d}  median {n / med:9.1f}/s   "
          f"min {n / max(vals):9.1f}/s  max {n / min(vals):9.1f}/s  "
          f"spread {100 * spread:.0f}%", flush=True)
    return n / med


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--n-ed", type=int, default=4096)
    ap.add_argument("--n-vrf", type=int, default=2048)
    ap.add_argument("--skip-vrf", action="store_true")
    ap.add_argument("--skip-xla", action="store_true")
    args = ap.parse_args()

    import numpy as np
    import jax.numpy as jnp
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from ouroboros_tpu.crypto import ed25519_jax as EJ
    from ouroboros_tpu.crypto import ed25519_ref, vrf_ref
    from ouroboros_tpu.crypto import pallas_kernels as PK
    from ouroboros_tpu.crypto import vrf_jax

    n = args.n_ed
    sk = hashlib.sha256(b"probe").digest()
    key = Ed25519PrivateKey.from_private_bytes(sk)
    vk = ed25519_ref.public_key(sk)
    msgs = [b"m%06d" % i for i in range(n)]
    sigs = [key.sign(m) for m in msgs]
    arrays, parse_ok = EJ.prepare_bytes_batch([vk] * n, msgs, sigs)
    arrs = [jnp.asarray(a) for a in arrays]

    # --- Ed25519 XLA path
    if not args.skip_xla:
        def run_xla():
            ok = np.asarray(EJ.verify_full_kernel(*arrs))
            assert ok.sum() == n, ok.sum()
        run_xla()   # compile
        report("ed25519 XLA", n, timed(run_xla, args.reps))

    # --- Ed25519 pallas path
    yA, signA, yR, signR, s_bits, k_bits = arrs

    def run_pallas():
        ok = np.asarray(PK.ed25519_verify_pallas(
            yA, signA, yR, signR, s_bits, k_bits, n))
        assert ok.sum() == n, ok.sum()
    run_pallas()    # compile
    report("ed25519 pallas", n, timed(run_pallas, args.reps))

    if args.skip_vrf:
        return
    # --- VRF
    nv = args.n_vrf
    vsk = hashlib.sha256(b"probe-vrf").digest()
    vvk = vrf_ref.public_key(vsk)
    alphas = [b"a%d" % i for i in range(nv)]
    proofs = [vrf_ref.prove(vsk, a) for a in alphas]

    if not args.skip_xla:
        def run_vrf_xla():
            st = vrf_jax._submit([vvk] * nv, alphas, proofs, nv, runner=None)
            oks, _ = vrf_jax._finish(*st, nv)
            assert all(oks)
        run_vrf_xla()
        report("vrf XLA", nv, timed(run_vrf_xla, args.reps))

    def run_vrf_pallas():
        st = vrf_jax._submit([vvk] * nv, alphas, proofs, nv,
                             runner=PK.vrf_verify_pallas)
        oks, _ = vrf_jax._finish(*st, nv)
        assert all(oks)
    run_vrf_pallas()
    report("vrf pallas", nv, timed(run_vrf_pallas, args.reps))


if __name__ == "__main__":
    main()
